"""Elastic resume: restore a checkpoint across a data-axis resize.

A checkpoint written at data-axis size N must be usable by a session
whose data axis is M — the surviving-hosts path after a permanent host
loss (supervisor fall-through), or a deliberate shrink/grow between
runs.  Params and tree-shaped optimizer state are already
topology-portable (checkpoints store the LOGICAL layout; Orbax reshards
on restore).  The one piece that is NOT is ZeRO-1's flat bucket-major
optimizer state (arXiv:2004.13336, PR 2): each bucket's moments are a
flat vector zero-padded to a multiple of N so it slices into N equal
shards — at M the pad length changes and a naive restore
shape-mismatches.

The reshard is exact, not approximate: bucket MEMBERSHIP is a pure
function of ``(catalog, bucket_bytes, dtype, group)`` and never of the
axis size (``kernel/synchronization/bucketing.py``), so the first
``total`` elements of every flat vector — the real moments — are
identical at any N.  Elastic restore therefore (1) regathers each
bucket at the checkpoint's bucketing, (2) re-plans buckets for the new
axis (same membership, new ``padded_total``), and (3) truncates the old
zero pad and re-pads to the new shard divisor before reslicing 1/M.
Padded-tail moments are zeros by construction (gradient pads are zeros,
so Adam's mu/nu stay zero there), which is what makes truncation
lossless.

Sync state (compressor residuals) is per-device-shaped and does NOT
survive a resize; it reinitializes, which only matters for compressed
runs (documented as approximate in docs/resilience.md).  Run
:func:`preflight_elastic` (or the ``elastic/axis-resize`` analysis rule
via the CLI) before building the resized session to validate the plan —
ZeRO-1 reshard legality, ``sync/ring-degenerate`` on the shrunken axis,
and the HBM re-estimate at 1/M — before any tracing happens.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence

from autodist_tpu.utils import logging


class ElasticResumeError(RuntimeError):
    """The checkpoint cannot be resharded into this session exactly."""


# -- bucket layout (de)serialization ----------------------------------------

def bucket_layout(buckets: Sequence) -> List[dict]:
    """Serializable description of a ZeRO-1 bucket plan — what
    ``Saver.save`` records in ``autodist_meta.json`` so a later session
    can reshard without re-deriving the writer's plan."""
    out = []
    for b in buckets:
        out.append({
            "key": b.key, "dtype": str(b.dtype), "total": int(b.total),
            "padded_total": int(b.padded_total),
            "vars": [{"name": v.name, "shape": list(v.shape)}
                     for v in b.vars],
        })
    return out


def layout_mismatch(old_layout: Sequence[dict],
                    new_buckets: Sequence) -> Optional[str]:
    """Why the checkpoint's bucket layout cannot map 1:1 onto this
    session's plan (None when it can — possibly after re-padding).
    Membership must match exactly: a drifted ``bucket_bytes`` or a
    changed variable catalog reshuffles offsets inside the flat vectors
    and no slicing rule can recover the moments."""
    old = {d["key"]: d for d in old_layout}
    new = {b.key: b for b in new_buckets}
    if set(old) != set(new):
        return (f"bucket keys differ: checkpoint has {sorted(old)}, "
                f"session plans {sorted(new)} (bucket_bytes or variable "
                "catalog changed)")
    for key, d in old.items():
        b = new[key]
        if str(b.dtype) != d["dtype"]:
            return f"bucket {key}: dtype {d['dtype']} != {b.dtype}"
        if int(b.total) != int(d["total"]):
            return (f"bucket {key}: element count {d['total']} != "
                    f"{b.total}")
        old_vars = [(v["name"], tuple(v["shape"])) for v in d["vars"]]
        new_vars = [(v.name, tuple(v.shape)) for v in b.vars]
        if old_vars != new_vars:
            return (f"bucket {key}: member variables differ "
                    f"({old_vars} != {new_vars})")
    return None


def needs_reshard(old_layout: Sequence[dict],
                  new_buckets: Sequence) -> bool:
    """True when any bucket's padded length changed — the only case the
    plain (Orbax-resharded) restore cannot handle."""
    new = {b.key: b for b in new_buckets}
    return any(int(d["padded_total"]) != int(new[d["key"]].padded_total)
               for d in old_layout if d["key"] in new)


# -- pytree plumbing ---------------------------------------------------------

def _path_keys(path) -> List[str]:
    keys = []
    for entry in path:
        k = getattr(entry, "key", None)
        if k is None:
            k = getattr(entry, "name", None)
        if k is None and hasattr(entry, "idx"):
            k = entry.idx
        keys.append(str(k))
    return keys

def _bucket_key_for(path, bucket_keys) -> Optional[str]:
    """The bucket a leaf belongs to: the leaf sits under the ``zero1``
    subtree and some path entry names a planned bucket key."""
    keys = _path_keys(path)
    if "zero1" not in keys:
        return None
    for k in keys:
        if k in bucket_keys:
            return k
    return None


def old_shaped_opt_target(opt_target, old_layout: Sequence[dict],
                          new_buckets: Sequence, mesh):
    """Rewrite a session's optimizer restore target so ZeRO-1 flat
    leaves carry the CHECKPOINT's padded shapes (replicated), leaving
    every other leaf — the topology-portable tree state — untouched."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    old_pad = {d["key"]: int(d["padded_total"]) for d in old_layout}
    new_pad = {b.key: int(b.padded_total) for b in new_buckets}
    replicated = NamedSharding(mesh, P())

    def swap(path, t):
        key = _bucket_key_for(path, old_pad)
        if key is None or tuple(t.shape) != (new_pad[key],):
            return t   # scalars (opt counts) and non-bucket leaves
        return jax.ShapeDtypeStruct((old_pad[key],), t.dtype,
                                    sharding=replicated)

    return jax.tree_util.tree_map_with_path(swap, opt_target)


def reshard_opt_state(restored_opt, old_layout: Sequence[dict],
                      session):
    """Truncate each flat bucket leaf to its real ``total`` and re-pad
    to the session's shard divisor, placing the result with the
    session's ZeRO-1 shardings.  Exact: only zero padding is dropped or
    added."""
    import jax
    import numpy as np

    old = {d["key"]: d for d in old_layout}
    new_pad = {b.key: int(b.padded_total) for b in session.zero1_buckets}
    shardings = session._step.opt_shardings

    def fix(path, leaf, sh):
        key = _bucket_key_for(path, old)
        if key is None:
            return leaf
        d = old[key]
        if tuple(np.shape(leaf)) != (int(d["padded_total"]),):
            return leaf   # per-bucket scalars pass through
        total = int(d["total"])
        arr = np.asarray(leaf)
        out = np.zeros((new_pad[key],), arr.dtype)
        out[:total] = arr[:total]
        return jax.device_put(out, sh)

    return jax.tree_util.tree_map_with_path(fix, restored_opt, shardings)


# -- data-loader shard remapping ---------------------------------------------

def remap_data_state(state: Optional[dict], old_hosts: int,
                     new_hosts: int) -> Optional[dict]:
    """Translate a saved ``DataLoader.state()`` across a host-count
    change.  The epoch index (and with it the shuffle stream) is
    preserved; the within-epoch offset is only meaningful against the
    OLD per-host shard (different hosts hold different rows at a
    different count), so a mid-epoch offset resets to the epoch start —
    the data path is epoch-exact, not batch-exact, across a resize
    (params/opt stay bit-exact; this is documented in
    docs/resilience.md)."""
    if state is None or old_hosts == new_hosts:
        return state
    out = dict(state)
    if int(state.get("offset", 0)):
        logging.warning(
            "elastic resume: dropping within-epoch offset %s — shard "
            "layout changed (%d -> %d hosts), so epoch %s replays from "
            "its start on the new shards", state.get("offset"), old_hosts,
            new_hosts, state.get("epoch"))
        out["offset"] = 0
    return out


# -- the one-call entry point ------------------------------------------------

def preflight_elastic(session, meta: dict, context: str = "elastic",
                      resource_spec=None) -> None:
    """Re-run the static analysis passes against the (possibly shrunken)
    mesh with the checkpoint's provenance attached — ZeRO-1 reshard
    legality (``elastic/*`` rules), the full schedule verifier on the
    new mesh (``schedule/*`` rules: ring hop chains, bucket leg order,
    and the happens-before race detector are re-checked EXACTLY, not
    just HBM and ring degeneracy — an elastic resize changes hop counts
    and leg order), and the liveness HBM watermark at the new 1/M
    (``memory/watermark*``; its budget rules fire when
    ``resource_spec`` carries ``hbm_gb``) — raising
    ``StrategyValidationError`` before any restore or tracing.  The
    checkpoint's recorded ``schedule_fingerprint`` rides along so a
    same-mesh resume with a drifted sync config is flagged
    (``schedule/fingerprint-drift``)."""
    from autodist_tpu.analysis import analyze, log_report

    compiled = session._step.compiled_strategy
    report = analyze(compiled, session._gi, resource_spec=resource_spec,
                     elastic={"from_axes": meta.get("mesh_axes") or {},
                              "buckets": meta.get("zero1_buckets"),
                              "schedule_fingerprint":
                                  meta.get("schedule_fingerprint")})
    log_report(report, context)
    report.raise_for_errors()


def elastic_restore(session, path: str, validate: bool = True) -> int:
    """Restore ``path`` into ``session`` across a topology change.

    Thin orchestration over :class:`~autodist_tpu.checkpoint.Saver`
    (whose ``restore`` performs the actual reshard when needed), adding
    the pre-flight analysis gate.  Returns the restored step."""
    from autodist_tpu.checkpoint.saver import Saver

    meta = Saver.read_meta(path)
    if validate:
        preflight_elastic(session, meta, context=f"elastic:{path}")
    return Saver(session).restore(path)
