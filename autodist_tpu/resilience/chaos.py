"""Deterministic fault injection ("chaos") for recovery testing.

Every recovery path in the resilience stack — supervisor relaunch,
heartbeat wedge detection, checkpoint verify fallback, preemption
checkpointing — needs a REPRODUCIBLE failure to exercise it.  This
module injects faults as pure functions of ``(event spec, process
index, attempt, step)``: the same spec always fails the same process at
the same step of the same attempt, so a multiprocess CPU test replays a
TPU-pod failure timeline exactly.

The spec rides the ``AUTODIST_CHAOS`` env var (shipped to workers like
any other coordinator env) as ``;``-separated events::

    kill@step=6,proc=1,attempt=0            # worker 1 exits 43 at step 6
    kill@step=6,proc=1,attempt=0,code=9     # ... with exit code 9
    kill@step=1,proc=0,stage=1              # only stage1's worker 0 (MPMD)
    kill@step=6,during=save                 # die INSIDE the next Saver.save
    preempt@step=5,signal=SIGTERM           # deliver a preemption notice
    preempt@step=5,grace=2.5                # ... with a 2.5s grace deadline
    storage_stall@step=4,seconds=3          # checkpoint writes block 3s
    drop_heartbeats@step=3,proc=2           # beacons stop (wedge drill)
    hang@step=6,proc=1                      # one process blocks in the step
    hang@step=6,proc=1,leg=g0@-1/reduce     # ... wedged "in" a named leg
    hang@step=6,proc=1,seconds=5            # ... unblocking after 5s
    corrupt_ckpt@step=4,item=params,path=/ckpt/dir   # truncate a step dir
    nan_grad@step=3,bucket=all_reduce:float32:g0:0   # NaN into a bucket
    inf_grad@step=3,var=l0/w                # Inf into one grad leaf
    loss_spike@step=9,factor=1e6            # spike the MONITORED loss
    kill_replica@replica=0,tokens=5         # serving: die mid-decode
    slow_replica@replica=1,seconds=0.05     # serving: per-step latency
    drop_response@replica=0,count=2         # serving: sever 2 responses
    stale_stats@replica=0                   # serving: freeze /v1/stats

Recovery-tier drills (docs/resilience.md): ``preempt@...,grace=<s>``
stamps ``AUTODIST_PREEMPT_GRACE_S`` before delivering the signal, so
``fit``'s deadline decision (persistent save vs peer-tier emergency
snapshot) runs under the injected budget.  ``storage_stall`` makes
every subsequent ``Saver.save``/``wait`` block first (the slow-disk
drill the deadline decision exists for).  ``kill@...,during=save`` arms
a pre-save hook instead of dying at the step boundary: the process
os._exits INSIDE the next save, leaving the partial step dir the
verify/latest_step machinery must skip.  Per the "kills leave
evidence" rule, every injection is journaled BEFORE it executes.

``hang`` (docs/observability.md "Flight recorder") is the
deterministic LIVE-WEDGE drill: the matched process blocks inside the
step (the heartbeat daemon keeps beating, so beacon age stays fresh —
exactly the WEDGED-in-a-collective signature only ``step_timeout``
can catch), after stamping a flight-recorder cursor for ``leg=<id>``
(default: a ``"hang"`` phase cursor) so the monitor's verdict and the
crash bundle localize to the planted leg and process.  ``seconds=``
bounds the block (default: forever — the supervisor's terminate path
ends it).

Filters (``step``/``proc``/``attempt``/``stage``) all default to
"any"; an event fires at most once per process.  ``proc`` matches the
JAX process index (or ``AUTODIST_PROCESS_ID`` before the runtime is
up); ``attempt`` matches ``AUTODIST_ATTEMPT``, which the job
supervisor stamps on every relaunch — so ``attempt=0`` means "fail the
first try, let the retry succeed", the canonical recovery drill.
``stage`` matches the MPMD pipeline stage a process runs
(``AUTODIST_STAGE``, which :class:`~autodist_tpu.parallel.mpmd.runner.
StageRunner` stamps on construction): ``stage=1`` and ``stage=stage1``
both mean "only the stage-1 program's workers" — the spelling is
normalized through the schedule IR's shared ``stage_name`` helper, the
same one the partitioner and ``stage_of`` use.  Note ``proc`` is a
WITHIN-stage index under MPMD (each stage program is its own
jax.distributed world), so ``kill@step=1,proc=0,stage=1`` kills
exactly one worker of one stage — the cross-slice recovery drill in
tests/integration/mpmd_train.py.

Numerics events (docs/numerics.md) drive the PR 5 guard/rollback tests
through this same path, but fire differently from the host-side
actions above: ``nan_grad``/``inf_grad`` are consumed at TRACE time by
the numerics guard (:func:`grad_injections`) and compiled into the step
— the poison lands in the named gradient bucket (``bucket=<key>``) or
variable (``var=<name>``) when the step's on-device counter matches, so
detection is exact on every sync path.  They require
``capture(numerics=...)``.  ``loss_spike`` is consumed by the host-side
:class:`~autodist_tpu.numerics.StepHealthMonitor`: it multiplies the
OBSERVED loss once (``factor=``, default 1e6) without touching the real
trajectory — the synthetic detector drill behind the
rollback-vs-oracle parity test.

Serving events (docs/serving.md "Fault tolerance") are consumed by a
:class:`ServingChaos` inside the replica's :class:`~autodist_tpu.
serving.server.EngineServer`, not by ``on_step``: a replica has no
training step, so the firing clock is serving progress — ``requests=``
(completion submissions so far) and ``tokens=`` (generated tokens so
far, the "mid-decode" trigger), both defaulting to fire on the first
driver tick.  ``replica=`` filters on the replica index (the trailing
integer of ``AUTODIST_REPLICA_NAME``, or ``AUTODIST_REPLICA``
explicitly); the other filters keep their meaning.  ``kill_replica``
os._exits (code=, default 43) — the router's token-exact in-flight
recovery drill; ``slow_replica`` injects ``seconds=`` of latency into
every subsequent driver iteration (the straggler behind hedged
requests); ``drop_response`` severs the next ``count=`` completion
responses after a request fully decodes (the retry-idempotence drill);
``stale_stats`` freezes the ``/v1/stats`` payload at its arming-time
snapshot, so the router's load scores go stale.  Per the "kills leave
evidence" rule, every serving injection is journaled BEFORE it
executes.
"""
from __future__ import annotations

import os
import signal as _signal
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from autodist_tpu.utils import logging

ACTIONS = ("kill", "preempt", "drop_heartbeats", "corrupt_ckpt",
           "storage_stall", "hang", "nan_grad", "inf_grad", "loss_spike",
           "kill_replica", "slow_replica", "drop_response",
           "stale_stats")

#: events NOT executed by ChaosMonkey.on_step: grad injections compile
#: into the step (numerics guard), loss_spike rides the health monitor.
GRAD_ACTIONS = ("nan_grad", "inf_grad")
MONITOR_ACTIONS = ("loss_spike",)
#: ... and serving events ride the replica's ServingChaos (the
#: EngineServer driver loop), clocked by serving progress, not steps.
SERVING_ACTIONS = ("kill_replica", "slow_replica", "drop_response",
                   "stale_stats")

DEFAULT_KILL_CODE = 43   # distinct from crashes (1) and supervised aborts


@dataclass
class ChaosEvent:
    """One planned fault."""

    action: str
    step: Optional[int] = None      # fire at this step (None = first check)
    proc: Optional[int] = None      # only this process index (None = all)
    attempt: Optional[int] = None   # only this supervisor attempt
    stage: Optional[str] = None     # only this MPMD pipeline stage
    replica: Optional[int] = None   # only this serving replica index
    args: Dict[str, str] = field(default_factory=dict)
    fired: bool = False

    def matches(self, step: int, proc: Optional[int],
                attempt: Optional[int],
                stage: Optional[str] = None) -> bool:
        if self.fired:
            return False
        if self.proc is not None and proc is not None and self.proc != proc:
            return False
        if self.attempt is not None and attempt is not None \
                and self.attempt != attempt:
            return False
        if self.stage is not None and stage is not None \
                and self.stage != stage:
            return False
        return self.step is None or step >= self.step


def _norm_stage(v: str) -> str:
    """One spelling for stage filters: ``1`` → ``stage1`` via the
    schedule IR's shared :func:`stage_name` helper (the same canonical
    form ``stage_of``, the partitioner, and ``AUTODIST_STAGE`` use)."""
    from autodist_tpu.kernel.synchronization.schedule_ir import stage_name

    v = v.strip()
    return stage_name(int(v)) if v.isdigit() else v


def parse_chaos(spec: str) -> List[ChaosEvent]:
    """Parse the ``AUTODIST_CHAOS`` grammar (see module docstring)."""
    events: List[ChaosEvent] = []
    for raw in (spec or "").split(";"):
        raw = raw.strip()
        if not raw:
            continue
        action, _, rest = raw.partition("@")
        action = action.strip()
        if action not in ACTIONS:
            raise ValueError(f"unknown chaos action {action!r}; "
                             f"expected one of {ACTIONS}")
        ev = ChaosEvent(action=action)
        for kv in filter(None, (p.strip() for p in rest.split(","))):
            if "=" not in kv:
                raise ValueError(f"bad chaos arg {kv!r} in {raw!r} "
                                 "(use key=value)")
            k, v = kv.split("=", 1)
            k = k.strip()
            if k == "step":
                ev.step = int(v)
            elif k == "proc":
                ev.proc = int(v)
            elif k == "attempt":
                ev.attempt = int(v)
            elif k == "stage":
                ev.stage = _norm_stage(v)
            elif k == "replica":
                ev.replica = int(v)
            else:
                ev.args[k] = v.strip()
        events.append(ev)
    return events


class ChaosMonkey:
    """Executes planned faults at step boundaries.

    Drive it from the training loop (``ChaosCallback``) or call
    :meth:`on_step` manually from a script.  All matching is
    deterministic; ``heartbeats_enabled`` is the flag a
    :class:`~autodist_tpu.resilience.heartbeat.HeartbeatWriter` consults
    once a ``drop_heartbeats`` event has fired.
    """

    def __init__(self, events: List[ChaosEvent],
                 process_index: Optional[int] = None,
                 attempt: Optional[int] = None,
                 stage: Optional[str] = None):
        self._events = list(events)
        self._proc = process_index
        self._attempt = attempt
        self._stage = stage
        self._heartbeats = True
        self._exit = os._exit            # patchable seam for unit tests

    @classmethod
    def from_env(cls, process_index: Optional[int] = None) -> "ChaosMonkey":
        from autodist_tpu.const import ENV

        events = parse_chaos(ENV.AUTODIST_CHAOS.val)
        return cls(events, process_index=process_index,
                   attempt=ENV.AUTODIST_ATTEMPT.val)

    @property
    def events(self) -> List[ChaosEvent]:
        return list(self._events)

    @property
    def heartbeats_enabled(self) -> bool:
        return self._heartbeats

    def _process_index(self) -> Optional[int]:
        if self._proc is not None:
            return self._proc
        try:    # after rendezvous the runtime knows; before it, env does
            import jax
            return jax.process_index()
        except Exception:
            pid = os.environ.get("AUTODIST_PROCESS_ID")
            return int(pid) if pid is not None else None

    def _stage_name(self) -> Optional[str]:
        """Which MPMD pipeline stage this process runs, if any — the
        ``AUTODIST_STAGE`` identity a StageRunner stamps at startup."""
        if self._stage is not None:
            return self._stage
        return os.environ.get("AUTODIST_STAGE") or None

    def on_step(self, step: int) -> None:
        """Fire every event matching this completed step (each once).
        Numerics events (``nan_grad``/``inf_grad``/``loss_spike``) are
        consumed elsewhere (trace-time injection / the health monitor)
        and are skipped here."""
        if not self._events:
            return
        proc = self._process_index()
        stage = self._stage_name()
        for ev in self._events:
            if ev.action in GRAD_ACTIONS or ev.action in MONITOR_ACTIONS \
                    or ev.action in SERVING_ACTIONS:
                continue
            if ev.matches(int(step), proc, self._attempt, stage):
                ev.fired = True
                self._fire(ev, step)

    def _fire(self, ev: ChaosEvent, step: int) -> None:
        logging.warning("CHAOS: firing %s at step %d (proc=%s attempt=%s)",
                        ev.action, step, self._process_index(),
                        self._attempt)
        # Journal the injection BEFORE executing it: a `kill` os._exit
        # leaves no later chance, and the post-mortem timeline must show
        # the fault was DELIBERATE (docs/observability.md).
        from autodist_tpu.telemetry import emit_event
        emit_event("chaos/" + ev.action, step=int(step),
                   proc=self._process_index(), attempt=self._attempt,
                   args=dict(ev.args))
        if ev.action == "kill":
            code = int(ev.args.get("code", DEFAULT_KILL_CODE))
            if ev.args.get("during") == "save":
                # Die INSIDE the next Saver.save instead of here: the
                # stranded-partial-save drill.  The injection was
                # journaled above (arming), so the evidence exists even
                # though the hook itself cannot journal after os._exit.
                from autodist_tpu.checkpoint import saver as saver_mod

                saver_mod.add_pre_save_hook(
                    lambda path, _exit=self._exit, _code=code: (
                        logging.warning(
                            "CHAOS: kill during=save firing inside "
                            "save of %s", path),
                        _exit(_code)))
            else:
                # os._exit: no atexit, no orbax flush — a real
                # SIGKILL-grade death, which is the point.
                self._exit(code)
        elif ev.action == "preempt":
            grace = ev.args.get("grace")
            if grace is not None:
                # The deadline drill: fit's preemption decision reads
                # the grace budget from env at notice time.
                os.environ["AUTODIST_PREEMPT_GRACE_S"] = str(float(grace))
            sig = getattr(_signal, ev.args.get("signal", "SIGTERM"))
            os.kill(os.getpid(), sig)
        elif ev.action == "storage_stall":
            from autodist_tpu.checkpoint import saver as saver_mod

            saver_mod.set_storage_stall(
                float(ev.args.get("seconds", 1.0)))
        elif ev.action == "hang":
            # The live-wedge drill: stamp where we "are", then block the
            # step loop while the beacon daemon keeps beating.  The
            # journal entry above plus the planted cursor are exactly
            # the evidence the WEDGED verdict + hang localization need.
            from autodist_tpu.telemetry import flightrec

            leg = ev.args.get("leg")
            slot = int(ev.args.get("slot", flightrec.END_OF_STEP))
            flightrec.record_cursor(
                leg or "hang", kind="leg" if leg else "phase",
                slot=slot, event="enter", step=int(step))
            seconds = float(ev.args.get("seconds", 0.0))
            deadline = None if seconds <= 0 \
                else time.monotonic() + seconds
            logging.warning(
                "CHAOS: hang — blocking in the step%s%s",
                f" at leg {leg}" if leg else "",
                f" for {seconds:g}s" if deadline else " (forever)")
            while deadline is None or time.monotonic() < deadline:
                time.sleep(0.1)
        elif ev.action == "drop_heartbeats":
            self._heartbeats = False
        elif ev.action == "corrupt_ckpt":
            path = ev.args.get("path")
            if not path:
                raise ValueError("corrupt_ckpt needs path=<checkpoint dir>")
            corrupt_checkpoint(path, item=ev.args.get("item", "params"),
                               mode=ev.args.get("mode", "truncate"))


class ChaosCallback:
    """``fit`` callback driving a :class:`ChaosMonkey` at step ends
    (duck-typed to :class:`autodist_tpu.fit.Callback`)."""

    def __init__(self, monkey: ChaosMonkey):
        self.monkey = monkey

    def on_train_begin(self, session) -> None: ...

    def on_epoch_begin(self, epoch: int) -> None: ...

    def on_step_end(self, step: int, metrics) -> None:
        self.monkey.on_step(step)

    def on_epoch_end(self, epoch: int, logs) -> None: ...

    def on_train_end(self, history) -> None: ...


def replica_index_from_env() -> Optional[int]:
    """This process's serving-replica index: ``AUTODIST_REPLICA``
    explicitly, else the trailing integer of ``AUTODIST_REPLICA_NAME``
    (the pool names replicas ``replica-<i>``)."""
    raw = os.environ.get("AUTODIST_REPLICA")
    if raw is not None:
        return int(raw)
    name = os.environ.get("AUTODIST_REPLICA_NAME", "")
    tail = name.rsplit("-", 1)[-1] if "-" in name else name
    return int(tail) if tail.isdigit() else None


class ServingChaos:
    """Serving-plane fault injection, consumed by the replica's
    :class:`~autodist_tpu.serving.server.EngineServer`.

    The firing clock is serving progress, not training steps: the
    server's driver loop calls :meth:`on_tick` with its cumulative
    submission and generated-token counts, and an event fires once
    when both its ``requests=`` and ``tokens=`` thresholds are met
    (both default 0 — fire on the first tick).  ``kill_replica``
    os._exits immediately; the other actions ARM behavior the server
    consults: :attr:`slow_s` (injected per-iteration driver latency),
    :meth:`take_drop` (sever the next N completion responses),
    :attr:`stats_stale` (freeze the ``/v1/stats`` snapshot).  Every
    injection is journaled before it executes."""

    def __init__(self, events: List[ChaosEvent],
                 replica: Optional[int] = None,
                 attempt: Optional[int] = None):
        self._events = [ev for ev in events
                        if ev.action in SERVING_ACTIONS]
        self._replica = replica
        self._attempt = attempt
        self.slow_s = 0.0
        self.stats_stale = False
        self._drop_pending = 0
        self._exit = os._exit            # patchable seam for unit tests

    @classmethod
    def from_env(cls, replica: Optional[int] = None) -> "ServingChaos":
        from autodist_tpu.const import ENV

        events = parse_chaos(ENV.AUTODIST_CHAOS.val)
        if replica is None:
            replica = replica_index_from_env()
        return cls(events, replica=replica,
                   attempt=ENV.AUTODIST_ATTEMPT.val)

    def __bool__(self) -> bool:
        return bool(self._events)

    @property
    def events(self) -> List[ChaosEvent]:
        return list(self._events)

    def _matches(self, ev: ChaosEvent, requests: int,
                 generated: int) -> bool:
        if ev.fired:
            return False
        if ev.replica is not None and self._replica is not None \
                and ev.replica != self._replica:
            return False
        if ev.attempt is not None and self._attempt is not None \
                and ev.attempt != self._attempt:
            return False
        if requests < int(ev.args.get("requests", 0)):
            return False
        return generated >= int(ev.args.get("tokens", 0))

    def on_tick(self, *, requests: int = 0, generated: int = 0) -> None:
        """Fire every event whose progress thresholds this tick meets
        (each once).  Called from the server's driver loop."""
        for ev in self._events:
            if self._matches(ev, int(requests), int(generated)):
                ev.fired = True
                self._fire(ev, int(requests), int(generated))

    def _fire(self, ev: ChaosEvent, requests: int,
              generated: int) -> None:
        logging.warning(
            "CHAOS: firing %s (replica=%s requests=%d generated=%d)",
            ev.action, self._replica, requests, generated)
        # Journal BEFORE executing — a kill_replica os._exit leaves no
        # later chance, and the post-mortem timeline must show the
        # fault was deliberate (same rule as ChaosMonkey._fire).
        from autodist_tpu.telemetry import emit_event
        emit_event("chaos/" + ev.action, replica=self._replica,
                   requests=requests, generated=generated,
                   args=dict(ev.args))
        if ev.action == "kill_replica":
            # os._exit: no atexit, no socket shutdown — connected
            # clients see a mid-stream hangup, which is the point (the
            # router's partial-token recovery drill).
            self._exit(int(ev.args.get("code", DEFAULT_KILL_CODE)))
        elif ev.action == "slow_replica":
            self.slow_s = float(ev.args.get("seconds", 0.05))
        elif ev.action == "drop_response":
            self._drop_pending += int(ev.args.get("count", 1))
        elif ev.action == "stale_stats":
            self.stats_stale = True

    def take_drop(self) -> bool:
        """Consume one armed response drop (the handler severs the
        connection instead of writing the completion)."""
        if self._drop_pending > 0:
            self._drop_pending -= 1
            return True
        return False


def corrupt_checkpoint(path: str, item: str = "params",
                       mode: str = "truncate") -> List[str]:
    """Damage one item of a checkpoint step dir, deterministically.

    ``path`` is a ``step_N`` dir (or a checkpoint root, in which case
    the NEWEST step dir is hit).  ``mode="truncate"`` zero-lengths every
    regular file under the item (caught by ``Saver.verify(deep=True)``
    checksum comparison); ``mode="delete"`` removes the item dir
    entirely (caught by the shallow verify ``latest_step`` runs).
    Returns the paths touched.
    """
    from autodist_tpu.checkpoint.saver import Saver

    if not os.path.isdir(os.path.join(path, item)):
        latest = Saver.latest_checkpoint(path)
        if latest is None:
            raise FileNotFoundError(f"no checkpoint step under {path}")
        path = latest
    target = os.path.join(path, item)
    touched: List[str] = []
    if mode == "delete":
        import shutil

        shutil.rmtree(target)
        touched.append(target)
    elif mode == "truncate":
        for root, _, files in os.walk(target):
            for name in files:
                p = os.path.join(root, name)
                with open(p, "w"):
                    pass   # truncate to zero bytes
                touched.append(p)
    else:
        raise ValueError(f"unknown corrupt mode {mode!r}")
    logging.warning("CHAOS: corrupted checkpoint item %s (%s, %d paths)",
                    target, mode, len(touched))
    return touched


# -- numerics events (PR 5 guard/rollback drills) ----------------------------

def _env_events_for(actions, process_index: Optional[int] = None
                    ) -> List[ChaosEvent]:
    """Parse ``AUTODIST_CHAOS`` and keep the ``actions`` events that
    apply to THIS process/attempt.  proc/attempt filtering happens here
    — eagerly — because these events are consumed at trace time or by a
    long-lived monitor, not at a step boundary."""
    from autodist_tpu.const import ENV

    spec = ENV.AUTODIST_CHAOS.val
    if not spec:
        return []
    attempt = ENV.AUTODIST_ATTEMPT.val
    if process_index is None:
        try:
            import jax
            process_index = jax.process_index()
        except Exception:
            pid = os.environ.get("AUTODIST_PROCESS_ID")
            process_index = int(pid) if pid is not None else None
    stage = os.environ.get("AUTODIST_STAGE") or None
    out = []
    for ev in parse_chaos(spec):
        if ev.action not in actions:
            continue
        if ev.proc is not None and process_index is not None \
                and ev.proc != process_index:
            continue
        if ev.attempt is not None and attempt is not None \
                and ev.attempt != attempt:
            continue
        if ev.stage is not None and stage is not None and ev.stage != stage:
            continue
        out.append(ev)
    return out


def grad_injections(process_index: Optional[int] = None) -> List[ChaosEvent]:
    """The ``nan_grad``/``inf_grad`` events for this process/attempt —
    consumed at trace time by the numerics guard, which compiles the
    poison into the step (see ``numerics/guard.py`` and
    docs/numerics.md)."""
    return _env_events_for(GRAD_ACTIONS, process_index)


def loss_spike_events(process_index: Optional[int] = None
                      ) -> List[ChaosEvent]:
    """The ``loss_spike`` events for this process/attempt — consumed by
    the host-side :class:`~autodist_tpu.numerics.StepHealthMonitor`."""
    return _env_events_for(MONITOR_ACTIONS, process_index)
