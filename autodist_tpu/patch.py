"""Implicit program capture: zero-code-change adoption inside ``ad.scope()``.

Parity target: reference ``PatchTensorFlow`` (``autodist/patch.py:40-116``)
— at import time the reference monkeypatches every TF optimizer's
``__init__``/``apply_gradients`` so a plain training script is captured into
the default GraphItem without calling any AutoDist API
(``autodist/graph_item.py:72-108``).

The JAX analog intercepts the two calls every plain optax training script
makes anyway:

* **optimizer construction** — every public optax factory
  (``optax.adam``, ``optax.chain``, …) is wrapped while a scope is active;
  the *last* ``GradientTransformation`` built inside the scope is recorded
  (matching the reference's one-optimizer-per-graph assumption,
  ``graph_item.py:94-108``).  Its ``init`` is additionally wrapped so
  ``opt.init(params)`` records the parameter pytree.
* **gradient construction** — ``jax.grad`` / ``jax.value_and_grad`` called
  inside the scope record the differentiated function as the loss_fn
  (with its ``has_aux`` flag) — the analog of the reference capturing
  grad→target pairs from ``apply_gradients``.

With those three facts (params, optimizer, loss_fn) the facade can assemble
a :class:`~autodist_tpu.graph_item.GraphItem` without an explicit
``capture()`` call::

    with ad.scope():
        opt = optax.adamw(1e-3)          # recorded
        opt_state = opt.init(params)     # params recorded
        vg = jax.value_and_grad(loss_fn) # loss_fn recorded
    sess = ad.create_distributed_session()   # implicit GraphItem

Constraints (documented divergence from the reference, which captured the
whole graph): the implicitly-captured ``loss_fn`` must have the framework
signature ``loss_fn(params, batch) -> loss`` (or ``-> (loss, aux)`` with
``has_aux=True``).  Variable annotations (sparse/pipeline/expert vars,
remat) need the explicit ``capture()`` — a plain script has nowhere to hang
them.  Patching is reversible and scope-bounded; disable it entirely with
``AUTODIST_PATCH=False`` (the analog of the reference's ``AUTODIST_PATCH_TF``
gate, ``autodist/const.py:78``).
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, List, Optional, Tuple

from autodist_tpu.utils import logging


@dataclass
class CaptureRecord:
    """What implicit capture has seen so far inside the active scope."""

    params: Any = None
    optimizer: Any = None
    loss_fn: Optional[Callable] = None
    has_aux: bool = False
    # provenance, for error messages
    optimizer_factory: str = ""

    def missing(self) -> List[str]:
        out = []
        if self.params is None:
            out.append("params (call opt.init(params) inside ad.scope())")
        if self.optimizer is None:
            out.append("optimizer (build it via optax.* inside ad.scope())")
        if self.loss_fn is None:
            out.append("loss_fn (call jax.value_and_grad(loss_fn) or "
                       "jax.grad(loss_fn) inside ad.scope())")
        return out

    def complete(self) -> bool:
        return not self.missing()


def _contains_tracer(tree: Any) -> bool:
    import jax

    return any(isinstance(leaf, jax.core.Tracer)
               for leaf in jax.tree_util.tree_leaves(tree))


class PatchOptax:
    """Scope-bounded monkeypatching of the optax + jax.grad entry points.

    The reference patched classes once at import (``patch.py:80-88``); here
    patching is installed on scope entry and fully reverted on exit so the
    capture machinery can never leak into unrelated code.
    """

    _record: Optional[CaptureRecord] = None
    _saved_optax: List[Tuple[str, Any]] = []
    _saved_jax: List[Tuple[str, Any]] = []

    # -- lifecycle ---------------------------------------------------------
    @classmethod
    def active_record(cls) -> Optional[CaptureRecord]:
        return cls._record

    @classmethod
    def patch(cls, record: Optional[CaptureRecord] = None) -> CaptureRecord:
        """Install the interception wrappers; idempotent per scope."""
        if cls._record is not None:
            return cls._record
        cls._record = record or CaptureRecord()
        cls._patch_optax_factories()
        cls._patch_grad_functions()
        return cls._record

    @classmethod
    def unpatch(cls) -> Optional[CaptureRecord]:
        """Restore every patched attribute; returns the finished record."""
        import jax
        import optax

        for name, orig in cls._saved_optax:
            setattr(optax, name, orig)
        for name, orig in cls._saved_jax:
            setattr(jax, name, orig)
        cls._saved_optax = []
        cls._saved_jax = []
        record, cls._record = cls._record, None
        return record

    # -- optimizer capture -------------------------------------------------
    @classmethod
    def _patch_optax_factories(cls) -> None:
        import optax

        base = optax.GradientTransformation

        def wrap_factory(name: str, fn: Callable) -> Callable:
            def wrapper(*args, **kwargs):
                out = fn(*args, **kwargs)
                rec = cls._record
                if rec is not None and isinstance(out, base):
                    out = cls._recording_transformation(out, rec)
                    rec.optimizer = out
                    rec.optimizer_factory = name
                    logging.debug("implicit capture: optimizer optax.%s", name)
                return out

            wrapper.__name__ = getattr(fn, "__name__", name)
            wrapper.__autodist_wrapped__ = fn
            return wrapper

        for name in dir(optax):
            if name.startswith("_"):
                continue
            fn = getattr(optax, name)
            # Wrap plain callables only — classes (incl. the namedtuple types
            # themselves) and modules stay untouched.
            if not callable(fn) or isinstance(fn, type):
                continue
            if hasattr(fn, "__autodist_wrapped__"):  # already wrapped
                continue
            cls._saved_optax.append((name, fn))
            setattr(optax, name, wrap_factory(name, fn))

    @classmethod
    def _recording_transformation(cls, tx, rec: CaptureRecord):
        """Return ``tx`` with its ``init`` wrapped to record the params
        pytree (skipping tracer pytrees — an ``init`` under ``jit`` has no
        concrete values to capture)."""

        orig_init = tx.init

        def init(params):
            if cls._record is rec and not _contains_tracer(params):
                rec.params = params
                logging.debug("implicit capture: params via %s.init",
                              rec.optimizer_factory or "optimizer")
            return orig_init(params)

        return tx._replace(init=init)

    # -- loss_fn capture ---------------------------------------------------
    @classmethod
    def _patch_grad_functions(cls) -> None:
        import jax

        def wrap(name: str, fn: Callable) -> Callable:
            def wrapper(fun=None, *args, **kwargs):
                rec = cls._record
                if rec is not None and callable(fun):
                    # Record the UNWRAPPED user function: the compiled step
                    # re-derives jax.value_and_grad from it (NOT the manual
                    # capture(grad_fn=...) path, which is explicit-only).
                    if (rec.loss_fn is not None
                            and rec.loss_fn is not fun):
                        # last-write-wins (the one-optimizer convention),
                        # but loudly: a diagnostic jax.grad inside the
                        # scope would otherwise silently become the
                        # training objective.
                        logging.warning(
                            "implicit capture: loss_fn %r replaces "
                            "previously recorded %r — the LAST "
                            "jax.grad/value_and_grad inside ad.scope() "
                            "wins; use explicit capture() if that is not "
                            "the training loss",
                            getattr(fun, "__name__", fun),
                            getattr(rec.loss_fn, "__name__", rec.loss_fn))
                    rec.loss_fn = fun
                    # has_aux may arrive positionally: (fun, argnums,
                    # has_aux, ...).
                    rec.has_aux = bool(args[1]) if len(args) >= 2 \
                        else bool(kwargs.get("has_aux", False))
                    logging.debug("implicit capture: loss_fn %r via jax.%s",
                                  getattr(fun, "__name__", fun), name)
                return fn(fun, *args, **kwargs)

            wrapper.__name__ = name
            wrapper.__autodist_wrapped__ = fn
            return wrapper

        for name in ("grad", "value_and_grad"):
            fn = getattr(jax, name)
            if hasattr(fn, "__autodist_wrapped__"):
                continue
            cls._saved_jax.append((name, fn))
            setattr(jax, name, wrap(name, fn))
