"""Dynamic loss scaling: configuration, pure rules, and the step-state
transition.

Mixed-precision step-skipping/loss-scaling is table stakes for
large-scale TPU training (the MLPerf TPU-v3 report, arXiv:1909.09756);
the mechanism here is the standard one: multiply the loss by ``scale``
before the backward pass (so small gradients survive the low-precision
exponent range), divide the *reduced* gradients by ``scale`` before
clipping and the optimizer update, and adapt ``scale`` dynamically —
back off when a step produced non-finite gradients (the skipped-step
signal from the fused guard), grow after ``growth_interval`` consecutive
clean steps.  All factors are powers of two by default, so scaling and
unscaling are EXACT in floating point — enabling the guard on an
all-f32 program does not perturb the trajectory.

Everything that *decides* here (activation, wire saturation) is a pure
function of dtypes and config — no jax — so the static analyzer
(``analysis/precision.py`` ``numerics/*`` rules) shares the exact rule
the runtime applies (the ``bucket_drop_reason`` pattern).  The state
transition (:func:`update_state`) is traced into the step.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Sequence

import numpy as np

#: dtypes considered "low-precision" for loss-scale auto-enablement.
#: float16's 5-bit exponent underflows real gradients without scaling;
#: bfloat16 keeps f32's range, but scaling is exact (powers of two) and
#: protects the f32 master copy of a bf16-compute program, so auto
#: enables for both — the lint (numerics/no-loss-scale) mirrors this set.
LOW_PRECISION_DTYPES = ("float16", "bfloat16")

#: safety headroom between the largest loss-scaled gradient the rule
#: assumes (|g| * scale with |g| up to this factor) and the wire dtype's
#: finite max — the numerics/loss-scale-saturates-wire rule.
WIRE_HEADROOM = 1e4


@dataclass(frozen=True)
class LossScale:
    """Loss-scale configuration (the optimizer-state-like *state* it
    drives is a plain dict of scalars carried in the step's sync state
    and checkpointed with it).

    ``dynamic=False`` freezes the scale at ``init`` (no growth/backoff;
    non-finite steps still skip).  Defaults are the standard dynamic
    schedule: start high, halve on overflow, double after
    ``growth_interval`` clean steps, clamped to [min_scale, max_scale].
    """

    init: float = 2.0 ** 15
    growth_factor: float = 2.0
    backoff_factor: float = 0.5
    growth_interval: int = 2000
    min_scale: float = 1.0
    max_scale: float = 2.0 ** 24
    dynamic: bool = True

    def __post_init__(self):
        if self.init <= 0:
            raise ValueError(f"loss scale init must be > 0, got {self.init}")
        if self.growth_factor < 1 or self.backoff_factor > 1 \
                or self.backoff_factor <= 0:
            raise ValueError(
                "loss scale needs growth_factor >= 1 and 0 < backoff_factor "
                f"<= 1, got {self.growth_factor}/{self.backoff_factor}")
        if self.growth_interval < 1:
            raise ValueError("growth_interval must be >= 1")


def is_low_precision(dtype) -> bool:
    return str(np.dtype(dtype) if dtype != "bfloat16" else dtype) \
        in LOW_PRECISION_DTYPES or str(dtype) in LOW_PRECISION_DTYPES


def resolve_loss_scale(spec, dtypes: Sequence[str]) -> Optional[LossScale]:
    """The effective loss-scale config for a program whose parameters /
    gradient buckets carry ``dtypes``.

    ``spec`` is the :class:`NumericsConfig.loss_scale` value: ``"auto"``
    (enable the default dynamic schedule iff any dtype is low-precision),
    ``None``/``"none"``/``"off"`` (disabled), a number (STATIC scale at
    that value), or a :class:`LossScale`.  Returns None when scaling is
    inactive (the step then runs with scale == 1 exactly).
    """
    if spec is None or spec in ("none", "off", False):
        return None
    if isinstance(spec, LossScale):
        return spec
    if spec == "auto" or spec is True:
        if any(is_low_precision(d) for d in dtypes):
            return LossScale()
        return None
    if isinstance(spec, (int, float)):
        return LossScale(init=float(spec), dynamic=False)
    raise ValueError(
        f"loss_scale must be 'auto', None, a number, or a LossScale; "
        f"got {spec!r}")


def wire_dtype_of(compressor: str) -> Optional[str]:
    """The float dtype a quantizing compressor puts on the wire, or None
    when the wire is full-precision / scale-normalized.  The quantized
    ring compressors (Int8Compressor, Fp8Compressor) normalize by the
    per-chunk amax before quantizing, so a large loss scale cannot
    saturate their grids — overflow there is caught by the
    post-quantization saturation counters inside the ring legs and the
    guard's finiteness bits, not by this pre-flight rule."""
    if compressor in ("HorovodCompressor", "HorovodCompressorEF"):
        return "bfloat16"
    return None


def _finfo_max(dtype: str) -> float:
    if dtype == "bfloat16":
        try:  # ml_dtypes registers bfloat16 with numpy under jax
            import ml_dtypes
            return float(np.finfo(ml_dtypes.bfloat16).max)
        except Exception:  # pragma: no cover - ml_dtypes always ships w/ jax
            return 3.3895e38
    return float(np.finfo(np.dtype(dtype)).max)


def scale_saturates_wire(scale: Optional[LossScale],
                         compressor: str) -> Optional[str]:
    """Why this (loss scale, compressor) combination can saturate the
    compressor's wire dtype, or None when it cannot — the pure rule
    behind the ``numerics/loss-scale-saturates-wire`` ERROR, shared by
    the analyzer and the runtime build-time check.

    The test is conservative: the largest scale the schedule can reach
    (``max_scale`` for dynamic, ``init`` for static) times a
    :data:`WIRE_HEADROOM` gradient-magnitude allowance must stay below
    the wire dtype's finite max.  A saturated wire value dequantizes to
    a FINITE (clamped/inf-collapsed) number, so the post-dequantize
    guard cannot see the overflow — which is why this is an ERROR, not a
    WARN."""
    if scale is None:
        return None
    wire = wire_dtype_of(compressor)
    if wire is None:
        return None
    peak = scale.max_scale if scale.dynamic else scale.init
    wire_max = _finfo_max(wire)
    if peak * WIRE_HEADROOM > wire_max:
        return (f"loss scale can reach {peak:.3g}; gradients scaled that "
                f"far saturate the {compressor} {wire} wire "
                f"(finite max {wire_max:.3g}, headroom {WIRE_HEADROOM:.0e})")
    return None


# -- step-state transition (traced) ------------------------------------------

def init_state(scale: Optional[LossScale]):
    """The numerics step state: loss scale + health counters, all scalar
    leaves (replicated across the mesh).  Carried in the step like
    optimizer state and checkpointed with the sync state."""
    import jax.numpy as jnp

    init = float(scale.init) if scale is not None else 1.0
    return {
        "scale": jnp.float32(init),
        "good_steps": jnp.int32(0),
        "bad_steps": jnp.int32(0),      # consecutive non-finite steps
        "skipped": jnp.int32(0),        # cumulative skipped updates
        "step": jnp.int32(0),           # device-side step counter
    }


def update_state(state, all_finite, scale: Optional[LossScale]):
    """One transition of the numerics state given this step's health.
    Pure/traced: clean step → good_steps+1 (growth at the interval);
    non-finite step → backoff + skip counters.  With ``scale`` None the
    scale stays exactly 1 and only the counters move."""
    import jax.numpy as jnp

    ok = all_finite
    good = jnp.where(ok, state["good_steps"] + 1, 0)
    bad = jnp.where(ok, 0, state["bad_steps"] + 1)
    skipped = state["skipped"] + jnp.where(ok, 0, 1).astype(jnp.int32)
    s = state["scale"]
    if scale is not None and scale.dynamic:
        grown = jnp.where(good >= scale.growth_interval,
                          s * scale.growth_factor, s)
        good = jnp.where(good >= scale.growth_interval, 0, good)
        s = jnp.where(ok, grown, s * scale.backoff_factor)
        s = jnp.clip(s, scale.min_scale, scale.max_scale)
    return {
        "scale": s.astype(jnp.float32),
        "good_steps": good.astype(jnp.int32),
        "bad_steps": bad.astype(jnp.int32),
        "skipped": skipped,
        "step": (state["step"] + 1).astype(jnp.int32),
    }
