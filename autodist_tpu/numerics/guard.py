"""The fused gradient-health guard.

Detection has to live *inside* the sync path, not after it: EQuARX-style
quantized collectives (arXiv:2506.17615) can saturate on the wire while
the post-dequantize values look finite, and a second full pass over the
gradients would double the sync path's HBM traffic.  So the guard is
computed as a **byproduct of the existing bucketed pack/reduce**
(``kernel/synchronization/explicit_sync.py``):

* the per-bucket *finiteness bit* is an elementwise ``isfinite``
  reduction of the already-packed bucket vector (pipelined buckets use
  the reduced accumulator instead — their reduction is linear, so a NaN
  survives it);
* the per-bucket *squared-norm partial* comes from the already-reduced
  value — for ZeRO-1 buckets that is the reduce-scattered SHARD, whose
  shard sq-norms psum to exactly the full bucket norm (the shards
  partition the vector);
* compressors with a float wire additionally report pre-quantization
  *saturation* (a finite value that casts to Inf on the wire);
* quantized-wire buckets (int8/fp8, ``quant_ring``) report
  POST-quantization saturation counts from inside the ring legs —
  elements clipped to ±127 / overflowed on the fp8 grid per quantize
  event — so wire saturation is observed where it happens, not
  estimated before the collective;
* everything rolls into ONE small psum piggybacked on the bucket chain
  (a ``[3 × n_keys]`` f32 vector over every mesh axis, each contribution
  divided by its replication factor so nothing is double counted).

The result is a :class:`GradHealth` struct returned with the step
metrics, and the scalar inputs for exact global-norm clipping and the
skip/backoff update gate.  Everything here is traced inside the step;
the pure decision rules live in :mod:`~autodist_tpu.numerics.loss_scale`
and :mod:`~autodist_tpu.numerics.policy`.
"""
from __future__ import annotations

from typing import Any, Dict, List, NamedTuple, Optional, Sequence, Tuple

#: reserved sync-state key for the numerics step state (loss scale +
#: health counters).  The ``~`` prefix cannot appear in a variable path
#: or a bucket key, so it never collides.
NUMERICS_KEY = "~numerics"


class GradHealth(NamedTuple):
    """Per-step gradient health, returned in ``metrics["grad_health"]``.

    ``per_bucket`` maps bucket key (or variable name for the
    per-variable tier) → ``{"finite": bool, "sq_norm": f32[,
    "saturated": bool]}``.  ``sq_norm`` values and ``global_norm`` are
    UNSCALED (the loss scale is divided out).  ``skipped_steps`` is the
    cumulative count of skipped (zero-update) steps this run."""

    all_finite: Any
    global_norm: Any
    loss_scale: Any
    skipped_steps: Any
    per_bucket: Dict[str, Dict[str, Any]]


class HealthAccumulator:
    """Collects per-key health contributions inside the step, then
    finalizes them with one psum (or locally, on the GSPMD path where
    values are already global)."""

    def __init__(self, total_devices: int = 1, *, fused: bool = False,
                 interpret: Optional[bool] = None):
        self._n = max(int(total_devices), 1)
        #: fused detection (docs/kernels.md): per-key statistics come
        #: from ONE Pallas pass producing the non-finite count and the
        #: squared-norm partial together, instead of two separate
        #: full-vector reductions.  The finite BIT (count > 0) and
        #: therefore the skip decision are bit-identical to the unfused
        #: arithmetic; the sq partial matches to f32 summation order.
        self._fused = bool(fused)
        self._interpret = interpret
        #: key -> (sq_partial, nonfinite_count, sat_value, sat_kind)
        #: sat_kind: None | "flag" (pre-quantization 0/1) | "count"
        #: (post-quantization clipped/overflowed element count)
        self._rows: List[Tuple[str, Any, Any, Any, Any]] = []

    def add(self, key: str, value, *, shard_axes_size: int = 0,
            finite_src=None, saturation=None, sat_count=None) -> None:
        """Record one synced value's contribution.

        ``value`` is the REDUCED tensor this key's optimizer update will
        consume (the mean gradient, or its local shard for ZeRO-1 /
        partitioned vars).  ``shard_axes_size`` is the product of mesh
        axis sizes the value is SHARDED over (0 or 1 = fully replicated);
        the contribution is divided by its replication factor so the
        all-axis psum counts every element exactly once.  ``finite_src``
        optionally supplies a different tensor for the finiteness bit
        (the pre-reduce packed vector — the pack-time byproduct);
        ``saturation`` is an optional extra 0/1 scalar (PRE-quantization
        wire saturation from a float-wire compressor); ``sat_count`` is
        an optional POST-quantization saturation element count observed
        inside the quantized ring legs (clipped-to-±127 / fp8-overflow),
        pre-normalized by the caller so the all-axis psum returns the
        global count.  Either saturation input trips the step's
        ``all_finite`` gate when non-zero."""
        import jax.numpy as jnp

        repl = self._n / max(int(shard_axes_size) or 1, 1)
        if self._fused:
            from autodist_tpu.ops.fused_kernels import fused_detect_stats
            from autodist_tpu.telemetry.timeline import sync_span

            # One kernel pass per tensor yields BOTH statistics; when
            # the finite bit comes from a different tensor than the
            # norm (the pre-pack vector vs the reduced shard) each
            # tensor still pays exactly one pass.
            with sync_span(f"fused_pack_detect/{key}"):
                nf_value, sq_raw = fused_detect_stats(
                    value, interpret=self._interpret)
                if finite_src is None:
                    nf = nf_value
                else:
                    nf, _ = fused_detect_stats(
                        finite_src, interpret=self._interpret)
            sq = sq_raw / repl
            nonfinite = (nf > 0).astype(jnp.float32) / self._n
        else:
            v32 = value.astype(jnp.float32)
            sq = jnp.sum(v32 * v32) / repl
            fin_t = value if finite_src is None else finite_src
            nonfinite = (1.0 - jnp.all(jnp.isfinite(fin_t)).astype(
                jnp.float32)) / self._n
        if sat_count is not None:
            sat, kind = sat_count.astype(jnp.float32), "count"
        elif saturation is not None:
            sat, kind = saturation.astype(jnp.float32) / self._n, "flag"
        else:
            sat, kind = jnp.float32(0.0), None
        self._rows.append((key, sq, nonfinite, sat, kind))

    def finalize(self, axis_names: Sequence[str], loss,
                 inv_scale) -> Tuple[Any, Any, Dict[str, Dict[str, Any]]]:
        """One psum over ``axis_names`` (empty = already-global values)
        combining every contribution; returns ``(all_finite,
        global_norm, per_bucket)`` with the loss scale divided out of the
        norms.  A non-finite LOSS also trips ``all_finite`` (a NaN loss
        with finite gradients still means the step must not count as
        clean)."""
        import jax.numpy as jnp
        from jax import lax

        keys = [k for k, _, _, _, _ in self._rows]
        if self._rows:
            stacked = jnp.stack(
                [jnp.stack([sq, nf, sat])
                 for _, sq, nf, sat, _ in self._rows])    # [n_keys, 3]
        else:
            stacked = jnp.zeros((0, 3), jnp.float32)
        loss_nf = (1.0 - jnp.all(jnp.isfinite(loss)).astype(jnp.float32)) \
            / self._n
        packed = jnp.concatenate([stacked.ravel(), loss_nf[None]])
        if axis_names:
            packed = lax.psum(packed, tuple(axis_names))
        totals = packed[:-1].reshape((-1, 3)) if keys \
            else jnp.zeros((0, 3), jnp.float32)
        loss_bad = packed[-1]

        inv2 = inv_scale * inv_scale
        per_bucket: Dict[str, Dict[str, Any]] = {}
        bad_count = loss_bad
        total_sq = jnp.float32(0.0)
        for i, key in enumerate(keys):
            sq = totals[i, 0] * inv2
            nf, sat = totals[i, 1], totals[i, 2]
            entry = {"finite": nf == 0, "sq_norm": sq}
            kind = self._rows[i][4]
            if kind is not None:
                entry["saturated"] = sat > 0
                if kind == "count":
                    # post-quantization saturation: the global number of
                    # elements the ring legs clipped to the wire rail.
                    entry["sat_count"] = sat
            per_bucket[key] = entry
            bad_count = bad_count + nf + sat
            total_sq = total_sq + sq
        global_norm = jnp.sqrt(total_sq)
        all_finite = (bad_count == 0) & jnp.isfinite(global_norm)
        return all_finite, global_norm, per_bucket


def wire_saturation(vec, wire_dtype: Optional[str]):
    """0/1 scalar: does casting finite ``vec`` entries to the wire dtype
    produce a non-finite value (pre-quantization saturation)?  None when
    the compressor has no float wire."""
    import jax.numpy as jnp

    if wire_dtype is None:
        return None
    wired = vec.astype(jnp.dtype(wire_dtype))
    sat = jnp.any(jnp.isfinite(vec) & ~jnp.isfinite(wired))
    return sat


def clip_multiplier(global_norm, clip_norm: Optional[float]):
    """The global-norm clip factor — ``optax.clip_by_global_norm``'s
    exact formula (``clip / max(norm, clip)``), so the sharded clip
    matches the unsharded optax chain to float round-off.  Returns None
    when clipping is off."""
    import jax.numpy as jnp

    if clip_norm is None:
        return None
    c = jnp.float32(clip_norm)
    return c / jnp.maximum(global_norm, c)


def tree_select(pred, on_true, on_false):
    """``jnp.where(pred, a, b)`` over a pytree — the skip gate: with
    ``pred`` False every leaf (params AND optimizer state) keeps its old
    value bit-identically."""
    import jax
    import jax.numpy as jnp

    return jax.tree_util.tree_map(
        lambda a, b: jnp.where(pred, a, b), on_true, on_false)


# -- chaos gradient injection (trace-time) -----------------------------------

def resolve_injections(buckets: Sequence, known_names: Sequence[str],
                       ) -> Dict[str, List[Tuple[int, float]]]:
    """Map the ``nan_grad``/``inf_grad`` chaos events (AUTODIST_CHAOS)
    onto gradient-tree leaf names: ``bucket=<key>`` poisons the first
    member of that bucket, ``var=<name>`` the named variable, neither —
    the first known variable.  Resolved at trace time (the same
    deterministic step/proc/attempt filtering as every other chaos
    event); returns ``{var_name: [(step, value), ...]}``."""
    from autodist_tpu.resilience import chaos as chaos_mod
    from autodist_tpu.utils import logging

    out: Dict[str, List[Tuple[int, float]]] = {}
    by_key = {b.key: b for b in buckets}
    for ev in chaos_mod.grad_injections():
        value = float("nan") if ev.action == "nan_grad" else float("inf")
        name: Optional[str] = None
        if "bucket" in ev.args:
            b = by_key.get(ev.args["bucket"])
            if b is None:
                logging.warning(
                    "CHAOS: %s names bucket %r but this program plans %s; "
                    "ignoring the event", ev.action, ev.args["bucket"],
                    sorted(by_key) or "no buckets")
                continue
            name = b.names[0]
        elif "var" in ev.args:
            name = ev.args["var"]
            if name not in known_names:
                logging.warning(
                    "CHAOS: %s names unknown variable %r; ignoring the "
                    "event", ev.action, name)
                continue
        elif known_names:
            name = list(known_names)[0]
        if name is None:
            continue
        step = ev.step if ev.step is not None else 0
        out.setdefault(name, []).append((int(step), value))
        logging.warning(
            "CHAOS: will inject %s into grad of %s at step %d "
            "(trace-time, fires on the device step counter)",
            ev.action, name, step)
    return out


def _poison_leaf(g, cur_step, step: int, value: float):
    import jax.numpy as jnp

    if not jnp.issubdtype(g.dtype, jnp.floating):
        return g
    hit = cur_step == step
    bad = jnp.asarray(value, g.dtype)
    if g.ndim == 0:
        return jnp.where(hit, bad, g)
    flat = g.reshape(-1)
    flat = flat.at[0].set(jnp.where(hit, bad, flat[0]))
    return flat.reshape(g.shape)


def wrap_injections(vg_fn,
                    injections: Dict[str, List[Tuple[int, float]]],
                    cur_step):
    """Wrap a value-and-grad so the chaos-named gradient leaves are
    poisoned when the device step counter matches — the single injection
    point every sync tier (per-variable, bucketed, ZeRO-1, pipelined)
    flows through, so one chaos spec exercises all of them."""
    import jax

    from autodist_tpu.graph_item import path_name

    if not injections:
        return vg_fn

    def wrapped(params, batch):
        out, grads = vg_fn(params, batch)
        flat, treedef = jax.tree_util.tree_flatten_with_path(grads)
        poisoned = []
        for path, g in flat:
            for step, value in injections.get(path_name(path), ()):
                g = _poison_leaf(g, cur_step, step, value)
            poisoned.append(g)
        return out, jax.tree_util.tree_unflatten(treedef, poisoned)

    return wrapped
