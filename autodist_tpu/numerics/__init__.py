"""autodist_tpu.numerics — numerical-failure detection and recovery.

PR 4 (``autodist_tpu.resilience``) made *process* failure a recoverable
event; this package does the same for *numerical* failure — the NaN/Inf
gradient, the compressed-bucket overflow, the loss spike after a bad
batch — which otherwise poisons the parameters silently and burns the
whole attempt.  Four pieces (docs/numerics.md):

* :mod:`~autodist_tpu.numerics.guard` — the fused gradient-health guard:
  per-bucket finiteness bits and squared-norm partials computed as a
  byproduct of the bucketed pack/reduce in the explicit sync path (one
  extra small psum piggybacked on the bucket chain — no second pass over
  the gradients), rolled into a :class:`GradHealth` struct returned with
  every step's metrics;
* :mod:`~autodist_tpu.numerics.loss_scale` — dynamic loss scaling
  (:class:`LossScale`: init/growth/backoff), state carried in the step
  like optimizer state and checkpointed, auto-enabled when parameters or
  gradient buckets are low-precision;
* global-norm clipping that is **exact under ZeRO-1 and pipelined
  overlap**: norm partials come from the reduce-scattered shards (a psum
  of shard squared-norms, replication divided out), and the clip factor
  is applied before the local 1/N optimizer update;
* :mod:`~autodist_tpu.numerics.policy` — the step policy
  (``on_nonfinite="skip"|"raise"|"rollback"``): skip applies a
  zero-update (with loss-scale backoff) and counts it; rollback restores
  the last *verified-good* checkpoint
  (:meth:`~autodist_tpu.checkpoint.saver.Saver.restore_last_good`) after
  K consecutive bad steps or a loss-spike z-score, and emits a failure
  marker the PR 4 :class:`~autodist_tpu.resilience.Supervisor`
  understands.

Enable with ``AutoDist.capture(..., numerics=True)`` (or a
:class:`NumericsConfig`); everything is OFF by default so existing
programs are byte-identical.  Imports are lazy (PEP 562) so the
analysis CLI can consult the pure rules without dragging jax in.
"""
from __future__ import annotations

_EXPORTS = {
    "GradHealth": "autodist_tpu.numerics.guard",
    "NUMERICS_KEY": "autodist_tpu.numerics.guard",
    "LossScale": "autodist_tpu.numerics.loss_scale",
    "resolve_loss_scale": "autodist_tpu.numerics.loss_scale",
    "scale_saturates_wire": "autodist_tpu.numerics.loss_scale",
    "wire_dtype_of": "autodist_tpu.numerics.loss_scale",
    "NumericsConfig": "autodist_tpu.numerics.policy",
    "NonFiniteError": "autodist_tpu.numerics.policy",
    "StepHealthMonitor": "autodist_tpu.numerics.policy",
    "ON_NONFINITE": "autodist_tpu.numerics.policy",
}

__all__ = sorted(_EXPORTS)


def __getattr__(name):
    module = _EXPORTS.get(name)
    if module is None:
        raise AttributeError(
            f"module 'autodist_tpu.numerics' has no attribute {name!r}")
    import importlib

    return getattr(importlib.import_module(module), name)
