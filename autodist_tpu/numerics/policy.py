"""The numerics step policy: configuration, the host-side health
monitor, and rollback plumbing.

``on_nonfinite`` decides what a bad step costs:

* ``"skip"`` (default) — the step applies a ZERO update (params and
  optimizer state bit-identical, loss scale backs off) and counts it;
  purely device-side, no host sync per step.
* ``"raise"`` — ``fit`` fetches the health scalar every step and raises
  :class:`NonFiniteError` on the first bad one (a debugging mode; the
  per-step host sync serializes dispatch).
* ``"rollback"`` — after ``rollback_after`` CONSECUTIVE bad steps, or a
  loss spike beyond ``spike_zscore`` standard deviations of the recent
  window, ``fit`` restores the last *verified-good* checkpoint
  (:meth:`Saver.restore_last_good`), optionally re-seeds the data order
  so the offending batch sequence is not replayed verbatim, emits a
  failure marker the PR 4 Supervisor understands, and resumes.  Also
  per-step host sync.

The config rides :meth:`AutoDist.capture(numerics=...)`; ``fit`` can
override the host policy with ``fit(on_nonfinite=...)``.
"""
from __future__ import annotations

from collections import deque
from dataclasses import dataclass
from typing import Any, List, Optional

from autodist_tpu.utils import logging

ON_NONFINITE = ("skip", "raise", "rollback")

#: failure-marker code for a numerics rollback (distinct from worker
#: exits; the Supervisor records it for attribution like any marker).
NUMERICS_MARKER_CODE = 74


class NonFiniteError(RuntimeError):
    """Raised by ``fit(on_nonfinite="raise")`` on a non-finite step, and
    by rollback when no recovery is possible (no checkpoint_dir, no
    verified-good step, or the rollback budget is exhausted)."""


@dataclass(frozen=True)
class NumericsConfig:
    """Everything the numerics guard needs, resolved at capture time.

    ``loss_scale``: ``"auto"`` (dynamic scaling iff params or gradient
    buckets are low-precision — fp16/bf16), ``None`` (off), a number
    (static scale), or a :class:`~autodist_tpu.numerics.LossScale`.
    ``clip_norm``: global-norm clip threshold (optax formula; exact
    under ZeRO-1 and pipelined overlap).  ``spike_zscore``: enable the
    loss-spike detector at this z-score over the last ``spike_window``
    finite losses (None = off).  ``rollback_after``: consecutive bad
    steps before a rollback triggers.  ``max_rollbacks`` bounds how many
    times one ``fit`` call may roll back before giving up with
    :class:`NonFiniteError`."""

    guard: bool = True
    clip_norm: Optional[float] = None
    loss_scale: Any = "auto"
    on_nonfinite: str = "skip"
    rollback_after: int = 3
    spike_zscore: Optional[float] = None
    spike_window: int = 32
    max_rollbacks: int = 2
    reseed_on_rollback: bool = True

    def __post_init__(self):
        if self.on_nonfinite not in ON_NONFINITE:
            raise ValueError(
                f"on_nonfinite must be one of {ON_NONFINITE}, "
                f"got {self.on_nonfinite!r}")
        if self.clip_norm is not None and self.clip_norm <= 0:
            raise ValueError("clip_norm must be > 0 (or None)")
        if self.rollback_after < 1:
            raise ValueError("rollback_after must be >= 1")
        if self.spike_window < 4:
            raise ValueError("spike_window must be >= 4")

    @staticmethod
    def coerce(value) -> Optional["NumericsConfig"]:
        """Normalize the ``capture(numerics=...)`` argument: None/False
        (off), True (defaults), one of :data:`ON_NONFINITE` (defaults
        with that policy), a dict of fields, or a config instance."""
        if value is None or value is False:
            return None
        if value is True:
            return NumericsConfig()
        if isinstance(value, str):
            return NumericsConfig(on_nonfinite=value)
        if isinstance(value, dict):
            return NumericsConfig(**value)
        if isinstance(value, NumericsConfig):
            return value
        raise ValueError(
            "numerics must be None/bool, an on_nonfinite string, a dict "
            f"of NumericsConfig fields, or a NumericsConfig; got {value!r}")


@dataclass
class RollbackRequest(Exception):
    """Internal signal from the step loop to ``fit``'s rollback handler
    (an Exception so it unwinds the epoch loop cleanly)."""

    step: int
    reason: str

    def __str__(self):
        return f"rollback requested at step {self.step}: {self.reason}"


class StepHealthMonitor:
    """Host-side per-step health tracking for ``raise``/``rollback``
    policies and the loss-spike detector.

    ``observe`` returns None (healthy), ``"raise"``, or ``"rollback"``.
    Chaos ``loss_spike`` events (AUTODIST_CHAOS) multiply the OBSERVED
    loss once at their step — a synthetic detector drill that leaves the
    real trajectory untouched, so a rollback test can still match an
    uninterrupted oracle exactly."""

    #: minimum finite-loss samples before the z-score test is trusted.
    MIN_SAMPLES = 8

    def __init__(self, config: NumericsConfig,
                 policy: Optional[str] = None):
        from autodist_tpu.resilience import chaos as chaos_mod

        self.config = config
        self.policy = policy or config.on_nonfinite
        self._bad = 0
        self._losses: deque = deque(maxlen=config.spike_window)
        self._spikes: List = list(chaos_mod.loss_spike_events())

    @property
    def bad_streak(self) -> int:
        """Current run of consecutive unhealthy steps."""
        return self._bad

    def reset(self) -> None:
        """After a rollback restore: the bad-step streak clears.  The
        loss window is KEPT — it describes the healthy trajectory the
        restore rejoined, so the spike detector stays armed through the
        replayed steps instead of needing MIN_SAMPLES fresh ones."""
        self._bad = 0

    def _chaos_factor(self, step: int) -> float:
        """At most ONE loss_spike event fires per observation (each event
        fires once) — N queued events spike N successive observations
        that reach their step, which is how the budget-exhaustion drill
        spikes every post-rollback replay."""
        for ev in self._spikes:
            if not ev.fired and (ev.step is None or step >= ev.step):
                ev.fired = True
                factor = float(ev.args.get("factor", 1e6))
                logging.warning(
                    "CHAOS: loss_spike observed at step %d (factor %g)",
                    step, factor)
                return factor
        return 1.0

    def observe(self, step: int, loss: float,
                all_finite: bool) -> Optional[str]:
        import math

        loss = loss * self._chaos_factor(step)
        spiked = False
        if all_finite and math.isfinite(loss):
            if (self.config.spike_zscore is not None
                    and len(self._losses) >= self.MIN_SAMPLES):
                n = len(self._losses)
                mean = sum(self._losses) / n
                var = sum((x - mean) ** 2 for x in self._losses) / n
                std = math.sqrt(var)
                if std > 0 and (loss - mean) / std > self.config.spike_zscore:
                    spiked = True
                    logging.warning(
                        "numerics: loss spike at step %d (%.4g vs window "
                        "mean %.4g, z > %.1f)", step, loss, mean,
                        self.config.spike_zscore)
            if not spiked:
                self._losses.append(loss)
                self._bad = 0
                return None
        self._bad += 1
        if not all_finite and self.policy == "raise":
            return "raise"
        if self.policy == "rollback" and (
                spiked or self._bad >= self.config.rollback_after):
            return "rollback"
        return None


def emit_failure_marker(reason: str) -> Optional[str]:
    """Write a numerics failure marker into the supervisor's marker dir
    (AUTODIST_SUPERVISOR_DIR) when one is configured — the same file
    format the PR 4 :class:`Supervisor` reads for failure attribution,
    with the numerics reason attached."""
    import socket

    from autodist_tpu.const import ENV
    from autodist_tpu.resilience.supervisor import write_failure_marker

    marker_dir = ENV.AUTODIST_SUPERVISOR_DIR.val
    if not marker_dir:
        return None
    path = write_failure_marker(marker_dir, socket.gethostname(),
                                NUMERICS_MARKER_CODE, reason=reason)
    logging.warning("numerics: failure marker written to %s (%s)",
                    path, reason)
    return path
