"""Shared Pallas TPU kernel plumbing (interpret mode, tiling, blocks).

Every Pallas kernel in the repo re-derived the same three decisions —
when to run the interpreter (off-TPU CPU tests), how to pad a dimension
to an MXU-tileable length, and how to pick a block edge that divides the
(padded) extent — first in ``ops/flash_attention.py``, then again in
``ops/quant.py``.  The fused-kernel suite (``ops/fused_kernels.py``)
would have made a third copy; this module is the single definition all
of them import, so a tiling-policy fix lands everywhere at once.

The policies themselves are unchanged from the flash-attention
originals (measured defaults documented there):

* :func:`use_interpret` — Pallas interpret mode is selected
  automatically whenever the first device is not a TPU, so the CPU test
  mesh exercises the exact kernel bodies the TPU compiles;
* :func:`pad_len` — compiled Pallas wants (8, 128)-aligned tiles:
  lengths ≤ 128 round up to a multiple of 8 (the whole extent is one
  block), longer ones to a multiple of :data:`TILE`; interpret mode has
  no constraint and pads nothing;
* :func:`pick_block` — largest block ≤ target dividing the extent,
  preferring multiples of the MXU tile;
* :func:`pad_to` — plain round-up, the unit everything else composes.
"""
from __future__ import annotations

from typing import Optional

#: MXU lane quantum: pad unit and block alignment for every TPU kernel.
TILE = 128

#: f32 sublane quantum (min tile is (8, 128) for float32).
SUBLANE = 8


def use_interpret() -> bool:
    """Run Pallas in interpret mode?  Resolved from the backend — off-TPU
    (the CPU test mesh) interprets, on TPU the kernel compiles."""
    import jax

    return jax.devices()[0].platform != "tpu"


def resolve_interpret(interpret: Optional[bool]) -> bool:
    """``interpret`` if explicitly given, else :func:`use_interpret` —
    the per-op knob every public kernel entry point exposes."""
    return use_interpret() if interpret is None else bool(interpret)


def pad_to(n: int, m: int) -> int:
    """``n`` rounded up to the next multiple of ``m``."""
    return -(-int(n) // int(m)) * int(m)


def pad_len(t: int, interpret: bool) -> int:
    """Sequence/vector length after padding to an MXU-tileable length.
    Compiled Pallas requires (8, 128)-aligned tiles; interpret mode has
    no such constraint.  ≤128 → next multiple of 8 (the whole extent is
    one block); >128 → next multiple of 128 (a 128-multiple block always
    divides)."""
    if interpret:
        return t
    if t <= TILE:
        return pad_to(t, SUBLANE)
    return pad_to(t, TILE)


def pick_block(t: int, target: int) -> int:
    """Largest block ≤ ``target`` dividing ``t``, preferring multiples
    of the MXU tile (``pad_len`` guarantees a 128-multiple divisor
    exists on the compiled path; tiny interpret-mode extents fall back
    to any divisor)."""
    b = min(t, target)
    for cand in range(b - b % TILE, 0, -TILE):
        if t % cand == 0:
            return cand
    while t % b:
        b -= 1
    return b
