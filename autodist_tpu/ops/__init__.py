"""TPU hot-op kernels (Pallas).

The compute path of the framework is JAX/XLA; ops that XLA's automatic
fusion cannot produce (blockwise attention with online softmax) live here as
Pallas kernels.  Everything degrades gracefully off-TPU via interpret mode so
the CPU test mesh exercises the same code path.
"""
from autodist_tpu.ops.chunked_xent import (  # noqa: F401
    chunked_softmax_cross_entropy,
)
from autodist_tpu.ops.sampled_xent import (  # noqa: F401
    sampled_softmax_cross_entropy,
)
from autodist_tpu.ops.flash_attention import (  # noqa: F401
    flash_attention,
    make_flash_attention,
)
