"""The one symmetric-quantization scale rule (shared, drift-proof).

Two quantizers grew up independently: the weight-only serving kernel
(``ops/quant.py``, per-output-channel scales) and the quantized ring
collectives (``kernel/synchronization/quant_ring.py``, per-chunk scale
grid).  Both compute ``scale = amax / qmax`` with a zero-amax guard and
``q = clip(round(x / scale), ±qmax)`` — but each spelled it locally, so
the fused hop kernel (``ops/fused_kernels.py``) would have been a THIRD
spelling of the same arithmetic, free to drift from the compressors it
must match bit-for-bit.  This module is the single definition all three
call; it is jax-lazy (imports ``jax.numpy`` inside each function) so the
pure planning modules that import ``quant_ring`` stay jax-free, and the
helpers work unchanged INSIDE a Pallas kernel body (jnp ops on loaded
blocks lower fine there).

Two zero-amax conventions exist on purpose and are kept distinct:

* :func:`chunk_scale` (collectives): floor the scale away from zero
  (``max(amax/qmax, 1e-30)``) — an all-zero gradient chunk quantizes
  exactly to zeros and dequantizes exactly back, and the scale stays a
  well-defined positive number the wire can carry;
* :func:`channel_scale` (stored weights): an all-zero weight column
  keeps ``scale = 1.0`` — the stored scale array is long-lived model
  state and an identity scale is the honest "nothing here" marker.
"""
from __future__ import annotations

#: positive floor keeping all-zero-block scales finite and exact.
SCALE_FLOOR = 1e-30


def chunk_scale(amax, qmax: float):
    """Per-chunk collective-wire scale: ``max(amax / qmax,
    SCALE_FLOOR)``.  ``amax`` is the chunk's FINITE absolute max (the
    caller masks non-finite entries — they land in the saturation
    counter instead of flattening the grid)."""
    import jax.numpy as jnp

    return jnp.maximum(amax / qmax, SCALE_FLOOR)


def channel_scale(amax, qmax: float):
    """Per-output-channel stored-weight scale: ``amax / qmax`` with
    all-zero channels pinned at the identity scale 1.0."""
    import jax.numpy as jnp

    return jnp.where(amax > 0, amax / qmax, 1.0)


def quantize_values(y, qmax: float, wire_dtype, *, rounded: bool):
    """Clip ``y`` (already divided by its scale) to the wire rail and
    cast.  ``rounded=True`` is the integer grid (round-to-nearest before
    the clip, the int8 rule); ``rounded=False`` lets the float wire
    (fp8) do its own rounding in the cast."""
    import jax.numpy as jnp

    if rounded:
        y = jnp.round(y)
    return jnp.clip(y, -qmax, qmax).astype(wire_dtype)


def saturation_count(y, finite, qmax: float, *, rounded: bool):
    """Elements this quantize event clips to the rail or received
    non-finite — the post-quantization saturation counter the numerics
    guard rolls up.  ``y`` is the scaled (pre-clip) value, ``finite``
    the per-element finiteness mask of the source."""
    import jax.numpy as jnp

    mag = jnp.abs(jnp.round(y)) if rounded else jnp.abs(y)
    return jnp.sum((~finite) | (finite & (mag > qmax)))
