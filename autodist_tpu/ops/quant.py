"""Int8 weight-only quantization + Pallas matmul kernel (TPU serving).

Autoregressive decode is bandwidth-bound: every tick re-reads every
weight matrix from HBM while doing almost no FLOPs (see the decode-tick
anatomy in BASELINE.md).  Weight-only int8 halves that traffic — the
classic serving lever.  The kernel keeps weights **int8 in HBM** and
dequantizes per-tile in VMEM; a naive ``x @ (q * scale)`` in XLA would
materialize the dequantized f32/bf16 matrix in HBM once, after which
every tick re-reads FULL-WIDTH weights and the quantization saves
nothing.

Scheme: symmetric per-output-channel.  For ``w [K, N]``:
``scale[n] = max_k |w[k, n]| / 127``, ``q = round(w / scale)``.  Because
the scale is per OUTPUT column it factors out of the contraction —
``x @ (q * scale) == (x @ q) * scale`` — so the kernel runs one integer
valued matmul per tile and scales the result columns, never
materializing a dequantized weight block.

No counterpart exists in the reference (training-only framework).
Layout/padding conventions follow ``ops/flash_attention.py``; interpret
mode (CPU tests) is selected automatically off-TPU.
"""
from __future__ import annotations

import functools
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from autodist_tpu.ops import pallas_utils, quant_scale

_TILE = pallas_utils.TILE          # MXU lane quantum
_DEFAULT_BLOCK_N = 512


class Quantized(NamedTuple):
    """Weight-only int8 tensor: ``q`` int8 ``[K, N]``, ``scale`` f32
    ``[1, N]`` (per-output-channel symmetric)."""
    q: jax.Array
    scale: jax.Array

    @property
    def shape(self):
        return self.q.shape

    @property
    def nbytes(self) -> int:
        return self.q.size + self.scale.size * 4


def quantize_weight(w: jax.Array) -> Quantized:
    """Symmetric per-output-channel int8 quantization of a 2-D weight.

    ``w``: [K, N] (contraction dim first — transpose embedding tables to
    [D, V] so the per-channel scale lands on the vocab axis)."""
    if w.ndim != 2:
        raise ValueError(f"quantize_weight expects a 2-D matrix, got "
                         f"shape {w.shape}")
    w = w.astype(jnp.float32)
    amax = jnp.max(jnp.abs(w), axis=0, keepdims=True)       # [1, N]
    # Shared scale rule (ops/quant_scale.py): per-channel amax/127 with
    # all-zero columns pinned at the identity scale.
    scale = quant_scale.channel_scale(amax, 127.0)
    q = quant_scale.quantize_values(w / scale, 127.0, jnp.int8,
                                    rounded=True)
    return Quantized(q=q, scale=scale)


_use_interpret = pallas_utils.use_interpret


def _kernel(x_ref, q_ref, s_ref, o_ref):
    """One N-block program: dequant-free int8 matmul + column scaling.

    Refs: x [M, K]; q [K, bn] int8; s [1, bn] f32; o [M, bn].
    ``q.astype(x.dtype)`` is exact (|q| <= 127 fits bf16's 8-bit
    mantissa); the f32 accumulator keeps the integer dot exact too.
    """
    x = x_ref[...]
    w = q_ref[...].astype(x.dtype)
    acc = jnp.dot(x, w, preferred_element_type=jnp.float32)   # [M, bn]
    o_ref[...] = (acc * s_ref[...]).astype(o_ref.dtype)


_pad_to = pallas_utils.pad_to


@functools.partial(jax.jit, static_argnames=("block_n", "interpret"))
def _int8_matmul_2d(x, q, scale, block_n: int, interpret: bool):
    m, k = x.shape
    kq, n = q.shape
    bn = min(block_n, _pad_to(n, _TILE))
    mp = m if interpret else _pad_to(max(m, 8), 8)
    kp = k if interpret else _pad_to(k, _TILE)
    np_ = _pad_to(n, bn)
    xp = jnp.pad(x, ((0, mp - m), (0, kp - k)))
    qp = jnp.pad(q, ((0, kp - k), (0, np_ - n)))
    sp = jnp.pad(scale, ((0, 0), (0, np_ - n)))
    out = pl.pallas_call(
        _kernel,
        grid=(np_ // bn,),
        in_specs=[
            pl.BlockSpec((mp, kp), lambda j: (0, 0)),
            pl.BlockSpec((kp, bn), lambda j: (0, j)),
            pl.BlockSpec((1, bn), lambda j: (0, j)),
        ],
        out_specs=pl.BlockSpec((mp, bn), lambda j: (0, j)),
        out_shape=jax.ShapeDtypeStruct((mp, np_), x.dtype),
        interpret=interpret,
    )(xp, qp, sp)
    return out[:m, :n]


def int8_matmul(x: jax.Array, w: Quantized, *,
                block_n: int = _DEFAULT_BLOCK_N,
                interpret: Optional[bool] = None) -> jax.Array:
    """``x @ dequant(w)`` with int8 weights resident in HBM.

    ``x``: [..., K] (leading dims flattened for the kernel); returns
    ``[..., N]`` in ``x.dtype``.
    """
    if interpret is None:
        interpret = _use_interpret()
    k = x.shape[-1]
    if w.q.shape[0] != k:
        raise ValueError(f"contraction mismatch: x[..., {k}] @ "
                         f"q{tuple(w.q.shape)}")
    lead = x.shape[:-1]
    x2 = x.reshape((-1, k))
    out = _int8_matmul_2d(x2, w.q, w.scale, int(block_n), bool(interpret))
    return out.reshape(lead + (w.q.shape[1],))
