"""Pallas TPU flash attention (forward + backward kernels).

The hot op of every transformer in the model zoo.  Dense attention
(``models/transformer.py:dense_attention``) materializes the [B, H, T, T]
score matrix in HBM; this kernel keeps scores in VMEM tiles and streams K/V
blocks through the MXU with an online softmax, so HBM traffic is linear in
sequence length (Dao et al. 2022, "FlashAttention"; TPU formulation per the
Pallas guide's blockwise/online-softmax pattern).

No counterpart exists in the reference — it has no attention kernels at all
(its BERT example leans on stock TF ops, ``examples/benchmark/bert.py``).
This is TPU-native new scope that the long-context machinery
(``autodist_tpu/parallel/ring_attention.py``) composes with: ring attention
shards the sequence *across* chips; this kernel is the fast *within-chip*
block computation.

Layout convention matches the pluggable ``attn_fn`` protocol: q/k/v are
``[batch, seq, heads, head_dim]``; internally the kernel runs per (batch,
head) on ``[seq, head_dim]`` tiles.

Interpret mode (CPU tests) is selected automatically off-TPU.
"""
from __future__ import annotations

import functools
from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.sharding import Mesh, PartitionSpec as P

from autodist_tpu.const import MESH_AXIS_DATA, MESH_AXIS_MODEL
from autodist_tpu.ops import pallas_utils
from autodist_tpu.utils import compat

_NEG_INF = -1e30  # finite -inf: keeps exp()/max() NaN-free (masked rows)
# Tiling policy lives in ops/pallas_utils.py (shared by every Pallas
# kernel in the repo); these aliases keep this module's historical
# private names importable (tests pin the padding policy through them).
_TILE = pallas_utils.TILE
_pick_block = pallas_utils.pick_block
_pad_len = pallas_utils.pad_len
_use_interpret = pallas_utils.use_interpret
# Default q/k block edge.  Measured on TPU v5e (B=2,H=8,D=64, causal,
# fwd+bwd, vs XLA dense attention): 512 gives ~1.0x at T=2048, ~1.8x at
# T=4096, ~3.2x at T=8192; 128 loses to dense.  _pick_block degrades
# gracefully for sequences 512 doesn't divide.
_DEFAULT_BLOCK = 512


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------
def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, causal: bool,
                block_k: int, scale: float, kv_len: int):
    """One (batch, head, q-block) program: stream K/V blocks, online softmax.

    Refs: q [1,1,bq,D]; k/v [1,1,T,D]; o [1,1,bq,D]; lse [1,1,bq,1]
    (the trailing singleton keeps the block's last-two dims TPU-tileable).
    ``kv_len`` < T means the tail is alignment padding — masked out.
    """
    q = q_ref[0, 0].astype(jnp.float32) * scale            # [bq, D]
    bq, d = q.shape
    t_k = k_ref.shape[2]
    padded = kv_len < t_k
    num_kb = t_k // block_k
    qi = pl.program_id(2)
    q_pos = qi * bq + lax.broadcasted_iota(jnp.int32, (bq, block_k), 0)

    def body(kb, carry):
        o, l, m = carry
        k0 = kb * block_k
        k = k_ref[0, 0, pl.ds(k0, block_k), :].astype(jnp.float32)
        v = v_ref[0, 0, pl.ds(k0, block_k), :].astype(jnp.float32)
        s = lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32)  # [bq, bk]
        if causal or padded:
            k_pos = k0 + lax.broadcasted_iota(jnp.int32, (bq, block_k), 1)
            mask = k_pos <= q_pos if causal else k_pos >= 0
            if padded:
                mask &= k_pos < kv_len
            s = jnp.where(mask, s, _NEG_INF)
        m_new = jnp.maximum(m, s.max(axis=-1, keepdims=True))   # [bq,1]
        p = jnp.exp(s - m_new)                                  # [bq,bk]
        corr = jnp.exp(m - m_new)                               # [bq,1]
        l_new = l * corr + p.sum(axis=-1, keepdims=True)
        o_new = o * corr + lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return o_new, l_new, m_new

    o0 = jnp.zeros((bq, d), jnp.float32)
    l0 = jnp.zeros((bq, 1), jnp.float32)
    m0 = jnp.full((bq, 1), _NEG_INF, jnp.float32)
    if causal:
        # Only K blocks at or before this q block's last row contribute.
        upper = lax.div(qi * bq + bq + block_k - 1, block_k)
        upper = jnp.minimum(upper, num_kb)
    else:
        upper = num_kb
    o, l, m = lax.fori_loop(0, upper, body, (o0, l0, m0))
    l = jnp.maximum(l, 1e-30)
    o_ref[0, 0] = (o / l).astype(o_ref.dtype)
    lse_ref[0, 0] = m + jnp.log(l)


def _fwd(q, k, v, causal, block_q, block_k, interpret, kv_len):
    """q/k/v: [B, H, T, D] → (o [B,H,T,D], lse [B,H,T])."""
    b, h, t, d = q.shape
    bq = _pick_block(t, block_q)
    bk = _pick_block(t, block_k)
    scale = 1.0 / (d ** 0.5)
    grid = (b, h, t // bq)
    kernel = functools.partial(_fwd_kernel, causal=causal, block_k=bk,
                               scale=scale, kv_len=kv_len)
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, bq, d), lambda bi, hi, qi: (bi, hi, qi, 0)),
            pl.BlockSpec((1, 1, t, d), lambda bi, hi, qi: (bi, hi, 0, 0)),
            pl.BlockSpec((1, 1, t, d), lambda bi, hi, qi: (bi, hi, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, bq, d), lambda bi, hi, qi: (bi, hi, qi, 0)),
            pl.BlockSpec((1, 1, bq, 1), lambda bi, hi, qi: (bi, hi, qi, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, h, t, d), q.dtype),
            jax.ShapeDtypeStruct((b, h, t, 1), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v)


# ---------------------------------------------------------------------------
# backward
# ---------------------------------------------------------------------------
def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref, *,
               causal: bool, block_k: int, scale: float, kv_len: int):
    """dQ for one q block: dS = P∘(dPᵀV − Δ); dQ = scale · dS·K."""
    q = q_ref[0, 0].astype(jnp.float32) * scale
    do = do_ref[0, 0].astype(jnp.float32)
    lse = lse_ref[0, 0]                                     # [bq,1]
    delta = delta_ref[0, 0]                                 # [bq,1]
    bq, d = q.shape
    t_k = k_ref.shape[2]
    padded = kv_len < t_k
    num_kb = t_k // block_k
    qi = pl.program_id(2)
    q_pos = qi * bq + lax.broadcasted_iota(jnp.int32, (bq, block_k), 0)

    def body(kb, dq):
        k0 = kb * block_k
        k = k_ref[0, 0, pl.ds(k0, block_k), :].astype(jnp.float32)
        v = v_ref[0, 0, pl.ds(k0, block_k), :].astype(jnp.float32)
        s = lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32)
        if causal or padded:
            k_pos = k0 + lax.broadcasted_iota(jnp.int32, (bq, block_k), 1)
            mask = k_pos <= q_pos if causal else k_pos >= 0
            if padded:
                mask &= k_pos < kv_len
            s = jnp.where(mask, s, _NEG_INF)
        p = jnp.exp(s - lse)                                # recomputed probs
        dp = lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
        ds = p * (dp - delta)
        return dq + lax.dot_general(ds, k, (((1,), (0,)), ((), ())),
                                    preferred_element_type=jnp.float32)

    if causal:
        upper = jnp.minimum(lax.div(qi * bq + bq + block_k - 1, block_k),
                            num_kb)
    else:
        upper = num_kb
    dq = lax.fori_loop(0, upper, body, jnp.zeros((bq, d), jnp.float32))
    dq_ref[0, 0] = (dq * scale).astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                dk_ref, dv_ref, *, causal: bool, block_q: int, scale: float,
                kv_len: int):
    """dK/dV for one k block: dV = PᵀdO; dK = scale · dSᵀQ."""
    k = k_ref[0, 0].astype(jnp.float32)
    v = v_ref[0, 0].astype(jnp.float32)
    bk, d = k.shape
    t_q = q_ref.shape[2]
    padded = kv_len < t_q
    num_qb = t_q // block_q
    ki = pl.program_id(2)
    k_pos = ki * bk + lax.broadcasted_iota(jnp.int32, (block_q, bk), 1)

    def body(qb, carry):
        dk, dv = carry
        q0 = qb * block_q
        q = q_ref[0, 0, pl.ds(q0, block_q), :].astype(jnp.float32) * scale
        do = do_ref[0, 0, pl.ds(q0, block_q), :].astype(jnp.float32)
        lse = lse_ref[0, 0, pl.ds(q0, block_q), :]          # [bq,1]
        delta = delta_ref[0, 0, pl.ds(q0, block_q), :]      # [bq,1]
        s = lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32)  # [bq,bk]
        if causal or padded:
            q_pos = q0 + lax.broadcasted_iota(jnp.int32, (block_q, bk), 0)
            mask = k_pos <= q_pos if causal else k_pos >= 0
            if padded:
                mask &= k_pos < kv_len
            s = jnp.where(mask, s, _NEG_INF)
        p = jnp.exp(s - lse)
        dv = dv + lax.dot_general(p, do, (((0,), (0,)), ((), ())),
                                  preferred_element_type=jnp.float32)
        dp = lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
        ds = p * (dp - delta)
        dk = dk + lax.dot_general(ds, q, (((0,), (0,)), ((), ())),
                                  preferred_element_type=jnp.float32)
        return dk, dv

    if causal:
        # q rows before this k block's first column are fully masked.
        lower = lax.div(ki * bk, block_q)
    else:
        lower = 0
    dk0 = jnp.zeros((bk, d), jnp.float32)
    dv0 = jnp.zeros((bk, d), jnp.float32)
    dk, dv = lax.fori_loop(lower, num_qb, body, (dk0, dv0))
    # q blocks were pre-scaled, so dSᵀQ already carries the 1/√d factor.
    dk_ref[0, 0] = dk.astype(dk_ref.dtype)
    dv_ref[0, 0] = dv.astype(dv_ref.dtype)


def _bwd(q, k, v, o, lse, do, causal, block_q, block_k, interpret, kv_len,
         dlse=None):
    b, h, t, d = q.shape
    bq = _pick_block(t, block_q)
    bk = _pick_block(t, block_k)
    scale = 1.0 / (d ** 0.5)
    # Δ_i = Σ_d dO_id · O_id — the softmax-normalization gradient term;
    # a cheap elementwise reduce, left to XLA fusion.  [B,H,T,1] like lse.
    # An lse cotangent folds in here: dS_ij = P_ij (dP_ij − Δ_i + dlse_i),
    # so passing Δ' = Δ − dlse reuses the kernels unchanged.
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1,
                    keepdims=True)
    if dlse is not None:
        delta = delta - dlse.astype(jnp.float32)

    qb_spec = pl.BlockSpec((1, 1, bq, d), lambda bi, hi, i: (bi, hi, i, 0))
    kb_spec = pl.BlockSpec((1, 1, bk, d), lambda bi, hi, i: (bi, hi, i, 0))
    full_spec = pl.BlockSpec((1, 1, t, d), lambda bi, hi, i: (bi, hi, 0, 0))
    rowq_spec = pl.BlockSpec((1, 1, bq, 1), lambda bi, hi, i: (bi, hi, i, 0))
    rowf_spec = pl.BlockSpec((1, 1, t, 1), lambda bi, hi, i: (bi, hi, 0, 0))

    dq = pl.pallas_call(
        functools.partial(_dq_kernel, causal=causal, block_k=bk, scale=scale,
                          kv_len=kv_len),
        grid=(b, h, t // bq),
        in_specs=[qb_spec, full_spec, full_spec, qb_spec, rowq_spec,
                  rowq_spec],
        out_specs=qb_spec,
        out_shape=jax.ShapeDtypeStruct((b, h, t, d), q.dtype),
        interpret=interpret,
    )(q, k, v, do, lse, delta)

    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, causal=causal, block_q=bq,
                          scale=scale, kv_len=kv_len),
        grid=(b, h, t // bk),
        in_specs=[full_spec, kb_spec, kb_spec, full_spec, rowf_spec,
                  rowf_spec],
        out_specs=[kb_spec, kb_spec],
        out_shape=[jax.ShapeDtypeStruct((b, h, t, d), k.dtype),
                   jax.ShapeDtypeStruct((b, h, t, d), v.dtype)],
        interpret=interpret,
    )(q, k, v, do, lse, delta)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# public op ([B, T, H, D] layout, custom VJP)
# ---------------------------------------------------------------------------
@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash(q, k, v, causal, block_q, block_k, interpret, kv_len):
    return _fwd(q, k, v, causal, block_q, block_k, interpret, kv_len)


def _flash_fwd(q, k, v, causal, block_q, block_k, interpret, kv_len):
    o, lse = _fwd(q, k, v, causal, block_q, block_k, interpret, kv_len)
    return (o, lse), (q, k, v, o, lse)


def _flash_bwd(causal, block_q, block_k, interpret, kv_len, res, cts):
    q, k, v, o, lse = res
    do, dlse = cts
    return _bwd(q, k, v, o, lse, do, causal, block_q, block_k, interpret,
                kv_len, dlse=dlse)


_flash.defvjp(_flash_fwd, _flash_bwd)


def _pad_and_run(q, k, v, causal, block_q, block_k, interpret):
    """[B,T,H,D] public layout → padded [B,H,T,D] kernel run → sliced
    (o [B,T,H,D], lse [B,H,T])."""
    t = q.shape[1]
    tp = _pad_len(t, interpret)
    qt, kt, vt = (x.transpose(0, 2, 1, 3) for x in (q, k, v))  # → [B,H,T,D]
    if tp != t:
        pad = [(0, 0), (0, 0), (0, tp - t), (0, 0)]
        qt, kt, vt = (jnp.pad(x, pad) for x in (qt, kt, vt))
    o, lse = _flash(qt, kt, vt, causal, block_q, block_k, interpret, t)
    if tp != t:
        o = o[:, :, :t, :]
        lse = lse[:, :, :t, :]
    return o.transpose(0, 2, 1, 3), lse[..., 0]


def flash_attention(q, k, v, causal: bool = False, *,
                    block_q: int = _DEFAULT_BLOCK,
                    block_k: int = _DEFAULT_BLOCK,
                    interpret: Optional[bool] = None) -> jax.Array:
    """Drop-in ``attn_fn(q, k, v, causal)`` on ``[B, T, H, D]`` tensors.

    Sequences whose length is not MXU-tileable are zero-padded to the next
    tileable length (masked inside the kernels; the pad is sliced off), so
    any length compiles on real TPU."""
    if interpret is None:
        interpret = _use_interpret()
    return _pad_and_run(q, k, v, causal, block_q, block_k, interpret)[0]


def flash_attention_with_lse(q, k, v, causal: bool = False, *,
                             block_q: int = _DEFAULT_BLOCK,
                             block_k: int = _DEFAULT_BLOCK,
                             interpret: Optional[bool] = None):
    """Like :func:`flash_attention` but also returns the log-sum-exp of the
    attention logits, ``lse [B, H, T]`` (f32) — the quantity blockwise/ring
    compositions merge partial attention outputs with (Liu et al. 2023).
    Fully differentiable in both outputs: the backward folds the lse
    cotangent into the softmax-normalization term (``Δ − dlse``), reusing
    the same Pallas kernels."""
    if interpret is None:
        interpret = _use_interpret()
    return _pad_and_run(q, k, v, causal, block_q, block_k, interpret)


def make_flash_attention(mesh: Optional[Mesh] = None, *,
                         block_q: int = _DEFAULT_BLOCK,
                         block_k: int = _DEFAULT_BLOCK,
                         interpret: Optional[bool] = None) -> Callable:
    """Factory returning an ``attn_fn``.

    With a mesh, the kernel runs inside ``shard_map`` manual over the
    ``data`` (batch dim) and ``model`` (heads dim) axes — a ``pallas_call``
    is a compiler black box GSPMD would otherwise all-gather around.  The
    ``seq`` axis is not handled here: compose with ring attention
    (``parallel/ring_attention.py``) for sequence parallelism.

    The interpret-mode decision is resolved HERE, at construction — not at
    trace time — so the product behaves identically under AOT lowering and
    multi-backend use.
    """
    if interpret is None:
        interpret = _use_interpret()
    kw = dict(block_q=block_q, block_k=block_k, interpret=interpret)

    @functools.lru_cache(maxsize=None)
    def _sharded(causal: bool, axes_key: frozenset):
        spec = P(MESH_AXIS_DATA if MESH_AXIS_DATA in axes_key else None,
                 None,
                 MESH_AXIS_MODEL if MESH_AXIS_MODEL in axes_key else None,
                 None)
        fn = functools.partial(flash_attention, causal=causal, **kw)
        # check_vma off: pallas_call's out_shape carries no varying-axis
        # metadata, and the kernel is trivially per-shard (no collectives).
        # jit: eager shard_map with partial axis_names trips JAX's internal
        # unmatch path; under jit (inlined when already tracing) it is sound.
        return jax.jit(compat.shard_map(
            fn, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
            axis_names=set(axes_key), check_vma=False))

    def attn_fn(q, k, v, causal: bool):
        manual_axes = set()
        if mesh is not None:
            # Axes an enclosing shard_map (the explicit-sync path) already
            # manualized are local here — re-sharding them would double-split.
            already_manual = set(
                jax.sharding.get_abstract_mesh().manual_axes)
            # Shard only over axes that evenly divide the local dim — e.g.
            # model.init traces with a tiny batch that the data axis may not
            # divide; that trace just runs the kernel unsharded.
            for ax, dim in ((MESH_AXIS_DATA, q.shape[0]),
                            (MESH_AXIS_MODEL, q.shape[2])):
                size = mesh.shape.get(ax, 1)
                if size > 1 and dim % size == 0 and ax not in already_manual:
                    manual_axes.add(ax)
        if not manual_axes:
            return flash_attention(q, k, v, causal, **kw)
        return _sharded(causal, frozenset(manual_axes))(q, k, v)

    return attn_fn
