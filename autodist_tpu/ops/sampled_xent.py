"""Sampled softmax cross entropy (the reference lm1b's training loss).

The reference's lm1b trained its 793k-word softmax with TF's
``sampled_softmax_loss`` (``examples/lm1b/language_model.py``) — a biased
but cheap estimator that scores each token against its true class plus
``k`` sampled negatives.  This framework's default for huge vocabularies
is the EXACT chunked loss (``ops/chunked_xent.py``); this module provides
the sampled estimator for reference-parity and for the regime where even
streaming the vocabulary is too slow (k ≪ V matmuls instead of V).

Estimator: uniform negative sampling with importance correction on the
sampled logits only (offset ``−log(E[count]) = −log(k/V)``), making this
an importance-weighted estimator of the FULL cross entropy — it tracks
the exact loss as ``k → V`` (tested).  Note this deliberately differs
from TF's ``sampled_softmax_loss``, which corrects BOTH true and sampled
logits (a wash under a uniform sampler, reducing to an uncorrected
``(k+1)``-way softmax whose value is not comparable to the full CE);
loss curves here are comparable to the exact loss, not to TF's.

Gradients flow to the true-class and sampled rows of ``softmax_w`` only
(a sparse, scatter-shaped update — the property that made the reference
pair this loss with sharded-PS embeddings).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp


def sampled_softmax_cross_entropy(features: jax.Array,
                                  softmax_w: jax.Array,
                                  labels: jax.Array,
                                  rng: jax.Array, *,
                                  num_sampled: int = 1024) -> jax.Array:
    """Mean sampled-softmax loss of ``features @ softmax_w.T``.

    Args:
      features: ``[..., E]`` activations (leading shape flattened).
      softmax_w: ``[V, E]`` output-embedding table.
      labels: integer array matching ``features``'s leading shape.
      rng: PRNG key for drawing the shared negative sample set.
      num_sampled: negatives per step (shared across the batch, the
        standard trick — one ``[k, E]`` gather and one ``[N, k]`` matmul).

    A biased estimator of the full cross entropy: use for throughput, use
    :func:`~autodist_tpu.ops.chunked_xent.chunked_softmax_cross_entropy`
    when the exact loss matters.
    """
    v, e = softmax_w.shape
    k = min(num_sampled, v)
    h = features.reshape(-1, e).astype(jnp.float32)
    y = labels.reshape(-1).astype(jnp.int32)

    neg = jax.random.randint(rng, (k,), 0, v)
    w_true = jnp.take(softmax_w, y, axis=0).astype(jnp.float32)   # [N, E]
    w_neg = jnp.take(softmax_w, neg, axis=0).astype(jnp.float32)  # [k, E]

    logit_true = jnp.sum(h * w_true, axis=1, keepdims=True)       # [N, 1]
    logit_neg = h @ w_neg.T                                       # [N, k]
    # importance correction for the uniform proposal (E[count] = k/V);
    # the true class is always present (expected count 1).
    logit_neg = logit_neg - jnp.log(k / v)
    # accidental hits: a sampled negative equal to the row's label would
    # double-count the true class — mask it out (TF's remove_accidental_hits).
    hit = neg[None, :] == y[:, None]
    logit_neg = jnp.where(hit, -1e30, logit_neg)

    logits = jnp.concatenate([logit_true, logit_neg], axis=1)     # [N, 1+k]
    return jnp.mean(jax.nn.logsumexp(logits, axis=1) - logits[:, 0])
