"""Reduced-precision optimizer state (the bf16-moments MFU lever).

Adam-family optimizers carry two param-shaped moment tensors: at trainer
scale that is 2/3 of the optimizer-step HBM traffic and 8 bytes per
parameter of resident state when f32.  Storing the moments in bfloat16
halves both; the update itself still computes in f32 (moments are upcast
on entry, downcast on exit — round-to-nearest-even each step).

Note optax creates moments with ``zeros_like(params)`` — they INHERIT
the parameter dtype.  So a bf16-params model already trains with bf16
moments, and this wrapper matters in two directions:

* f32 master params + ``cast_opt_state(adamw)``: the classic "f32
  params, bf16 optimizer state" recipe — halve state bytes without
  touching the weights.
* bf16 params + ``cast_opt_state(adamw, jnp.float32)``: force WIDE
  moments where the default would be narrow (precision-sensitive
  finetuning, or as the control arm when measuring the narrow-state
  lever).

The bias-corrected Adam moments tolerate bf16's 8 mantissa bits well
(the update divides two same-scale quantities).

Usage::

    optimizer = cast_opt_state(optax.adamw(3e-4))       # bf16 moments
    ad.capture(params=params, optimizer=optimizer, loss_fn=...)

Composes with every strategy builder (the state tree shape is unchanged
— only leaf dtypes differ, so sharding specs, checkpoints, and the
frozen-variable masking all apply as-is).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import optax


def _cast_state(tree, to_dtype):
    """Cast every NON-SCALAR floating leaf (the param-shaped moments) to
    ``to_dtype``; ints (step counts) and scalar floats (schedule state,
    where narrow storage could perturb hyperparameters) pass through."""
    def cast(leaf):
        if (hasattr(leaf, "dtype") and getattr(leaf, "ndim", 0) > 0
                and jnp.issubdtype(leaf.dtype, jnp.floating)):
            return leaf.astype(to_dtype)
        return leaf

    return jax.tree_util.tree_map(cast, tree)


def cast_opt_state(inner: optax.GradientTransformation,
                   state_dtype=jnp.bfloat16) -> optax.GradientTransformation:
    """Store ``inner``'s param-shaped floating state leaves in
    ``state_dtype``; the update computes in f32 regardless."""
    state_dtype = jnp.dtype(state_dtype)

    def init(params):
        return _cast_state(inner.init(params), state_dtype)

    def update(updates, state, params=None):
        wide = _cast_state(state, jnp.float32)
        new_updates, new_state = inner.update(updates, wide, params)
        return new_updates, _cast_state(new_state, state_dtype)

    return optax.GradientTransformation(init, update)
