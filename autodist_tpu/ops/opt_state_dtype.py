"""Reduced-precision optimizer state (the bf16-moments MFU lever).

Adam-family optimizers carry two param-shaped moment tensors: at trainer
scale that is 2/3 of the optimizer-step HBM traffic and 8 bytes per
parameter of resident state when f32.  Storing the moments in bfloat16
halves both; the update itself still computes in f32 (moments are upcast
on entry, downcast on exit — round-to-nearest-even each step).

Note optax creates moments with ``zeros_like(params)`` — they INHERIT
the parameter dtype.  So a bf16-params model already trains with bf16
moments, and this wrapper matters in two directions:

* f32 master params + ``cast_opt_state(adamw)``: the classic "f32
  params, bf16 optimizer state" recipe — halve state bytes without
  touching the weights.
* bf16 params + ``cast_opt_state(adamw, jnp.float32)``: force WIDE
  moments where the default would be narrow (precision-sensitive
  finetuning, or as the control arm when measuring the narrow-state
  lever).

**Shard-aware**: the casting rule is deliberately SHAPE-AGNOSTIC — it
keys on "non-scalar floating leaf", not on matching the parameter tree.
Under ZeRO-1 weight-update sharding (``Zero1`` / ``sync=
"reduce_scatter"``) the explicit sync path carries the moments as flat
bucket-major shards (one 1/N slice of each gradient bucket per device,
``kernel/synchronization/bucketing.py``), and the update runs on those
shards only; the same wrapper casts them identically, so the two levers
MULTIPLY: state bytes/device = full · (1/N) · (1/2).  Elementwise
casting commutes with the flatten-concat-shard transform, so the
sharded bf16 update equals the replicated bf16 update exactly.  Scalar
floating leaves (schedule state, where narrow storage could perturb
hyperparameters) and integer leaves (step counts — including the
bucket optimizer's own count) always pass through.

The bias-corrected Adam moments tolerate bf16's 8 mantissa bits well
(the update divides two same-scale quantities).

Usage::

    optimizer = cast_opt_state(optax.adamw(3e-4))       # bf16 moments
    ad.capture(params=params, optimizer=optimizer, loss_fn=...)

Composes with every strategy builder (the state tree shape is unchanged
— only leaf dtypes differ, so sharding specs, checkpoints, and the
frozen-variable masking all apply as-is), including ``Zero1``.
"""
from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp
import optax


def default_cast_rule(leaf) -> bool:
    """Cast this leaf?  True for every NON-SCALAR floating leaf — the
    param-shaped moments of the tree layout AND the flat bucket shards
    of the ZeRO-1 layout; ints (step counts) and scalar floats
    (schedule state) pass through."""
    return (hasattr(leaf, "dtype") and getattr(leaf, "ndim", 0) > 0
            and jnp.issubdtype(leaf.dtype, jnp.floating))


def _cast_state(tree, to_dtype, rule: Callable = default_cast_rule):
    def cast(leaf):
        if rule(leaf):
            return leaf.astype(to_dtype)
        return leaf

    return jax.tree_util.tree_map(cast, tree)


def cast_opt_state(inner: optax.GradientTransformation,
                   state_dtype=jnp.bfloat16, *,
                   cast_rule: Optional[Callable] = None
                   ) -> optax.GradientTransformation:
    """Store ``inner``'s floating state leaves in ``state_dtype``; the
    update computes in f32 regardless.

    ``cast_rule`` (optional) overrides which leaves are narrowed —
    ``cast_rule(leaf) -> bool`` on each state leaf; the default is
    :func:`default_cast_rule` (every non-scalar floating leaf,
    tree-shaped or bucket-sharded alike)."""
    state_dtype = jnp.dtype(state_dtype)
    rule = cast_rule or default_cast_rule

    def init(params):
        return _cast_state(inner.init(params), state_dtype, rule)

    def update(updates, state, params=None):
        wide = _cast_state(state, jnp.float32, rule)
        new_updates, new_state = inner.update(updates, wide, params)
        return new_updates, _cast_state(new_state, state_dtype, rule)

    return optax.GradientTransformation(init, update)
