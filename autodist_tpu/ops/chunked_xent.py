"""Chunked-vocabulary softmax cross entropy: O(N·chunk) logits memory.

Motivation (measured, BASELINE.md): lm1b's 793k-word softmax makes the
``[tokens, vocab]`` logits tensor the training bound — 16 GB at batch
256 — and the reference hit the same wall (its lm1b used a *sampled*
softmax, ``examples/lm1b/language_model.py``, trading accuracy for
memory).  TPU-natively the exact loss is computable without ever
materializing full logits: stream the vocabulary in chunks through the
MXU, carrying running ``(max, sumexp, target_logit)`` — the same
streaming-softmax algebra as flash attention, applied to the output
projection.

* forward: one ``lax.scan`` over vocab chunks; per chunk an ``[N, C]``
  matmul in fp32, folded into the running stats and discarded.
* backward (custom VJP): a second scan recomputes each chunk's softmax
  probabilities from the saved row stats and accumulates ``dh`` and the
  (unavoidable, gradient-sized) ``dW``.

Peak extra memory: ``N·chunk`` fp32 instead of ``N·V`` logits.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax


# Padded table rows (vocab not a multiple of chunk) are masked to this
# finite floor: exp(floor − m) underflows to exactly 0, and unlike −inf it
# cannot produce NaNs in max/sub arithmetic.
_MASKED = -1e30


def _stats_scan(h, w, chunk, valid_v):
    """Running (max, sumexp) stats over vocab chunks.  ``w`` is already
    padded to a chunk multiple; columns ≥ ``valid_v`` are masked out.
    Returns (m, s): per-row max [N] and sum-exp [N] with logits in fp32."""
    n = h.shape[0]
    nc = w.shape[0] // chunk
    wc = w.reshape(nc, chunk, w.shape[1])

    def step(carry, args):
        c_idx, w_c = args
        m, s = carry
        logits = jnp.dot(h, w_c.T, preferred_element_type=jnp.float32)
        col = c_idx * chunk + jnp.arange(chunk)
        logits = jnp.where(col[None, :] < valid_v, logits, _MASKED)
        m_c = jnp.max(logits, axis=1)
        m_new = jnp.maximum(m, m_c)
        s = s * jnp.exp(m - m_new) + jnp.sum(
            jnp.exp(logits - m_new[:, None]), axis=1)
        return (m_new, s), None

    init = (jnp.full((n,), _MASKED, jnp.float32),
            jnp.zeros((n,), jnp.float32))
    (m, s), _ = lax.scan(step, init, (jnp.arange(nc), wc))
    return m, s


def _target_logits(h, w, labels):
    """Per-row logit of the label class: a gather of W rows, no big matmul."""
    w_y = jnp.take(w, labels, axis=0)                      # [N, E]
    return jnp.sum(h.astype(jnp.float32) * w_y.astype(jnp.float32), axis=1)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _xent_rows(h, w, labels, chunk, valid_v):
    return _xent_rows_fwd(h, w, labels, chunk, valid_v)[0]


def _xent_rows_fwd(h, w, labels, chunk, valid_v):
    m, s = _stats_scan(h, w, chunk, valid_v)
    losses = (jnp.log(s) + m) - _target_logits(h, w, labels)
    return losses, (h, w, labels, m, s)


def _xent_rows_bwd(chunk, valid_v, res, g):
    """d loss_i / d logits_ic = softmax_ic − 1[c == labels_i]; recompute
    softmax per chunk from the saved row stats (logZ = m + log s)."""
    h, w, labels, m, s = res
    nc = w.shape[0] // chunk
    wc = w.reshape(nc, chunk, w.shape[1])
    logz = m + jnp.log(s)                                   # [N]
    gh32 = (g.astype(jnp.float32))[:, None]                 # [N, 1]
    h32 = h.astype(jnp.float32)

    def step(dh, args):
        c_idx, w_c = args
        logits = jnp.dot(h, w_c.T, preferred_element_type=jnp.float32)
        col = c_idx * chunk + jnp.arange(chunk)
        logits = jnp.where(col[None, :] < valid_v, logits, _MASKED)
        p = jnp.exp(logits - logz[:, None])                 # [N, C]; pad→0
        local = labels - c_idx * chunk
        onehot = (local[:, None] ==
                  jnp.arange(chunk)[None, :]).astype(jnp.float32)
        d = (p - onehot) * gh32                             # [N, C]
        dh = dh + jnp.dot(d, w_c.astype(jnp.float32))
        dw_c = jnp.dot(d.T, h32)                            # [C, E]
        return dh, dw_c

    dh, dwc = lax.scan(step, jnp.zeros_like(h, jnp.float32),
                       (jnp.arange(nc), wc))
    dw = dwc.reshape(w.shape)
    return dh.astype(h.dtype), dw.astype(w.dtype), None


_xent_rows.defvjp(_xent_rows_fwd, _xent_rows_bwd)


def chunked_softmax_cross_entropy(features: jax.Array, softmax_w: jax.Array,
                                  labels: jax.Array, *,
                                  chunk: int = 8192) -> jax.Array:
    """Mean softmax cross entropy of ``features @ softmax_w.T`` against
    integer ``labels`` without materializing the logits.

    Args:
      features: ``[..., E]`` activations (any leading shape; flattened).
      softmax_w: ``[V, E]`` output-embedding table; any ``V`` — tables
        that don't divide into chunks are zero-padded and the pad columns
        masked out (their probabilities are exactly 0, their ``dW`` rows
        exactly 0, sliced away on return).
      labels: integer array matching ``features``'s leading shape.
      chunk: vocab rows per streamed block (``[N, chunk]`` fp32 is the
        peak logits footprint; keep it MXU-friendly — a multiple of 128).

    Exact (fp32 logit accumulation), unlike the reference's sampled
    softmax.  Matches ``cross_entropy_loss`` to fp32 tolerance.
    """
    e = features.shape[-1]
    h = features.reshape(-1, e)
    y = labels.reshape(-1).astype(jnp.int32)
    v = softmax_w.shape[0]
    chunk = min(chunk, v)
    vp = -(-v // chunk) * chunk
    w = softmax_w if vp == v else jnp.pad(softmax_w,
                                          ((0, vp - v), (0, 0)))
    return jnp.mean(_xent_rows(h, w, y, chunk, v))
