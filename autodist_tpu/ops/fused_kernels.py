"""Fused Pallas TPU kernels for the measured sync/serving hot paths.

PR 10's leg profiler finally attributed where the step time goes
(BENCH_profiler.json): the 5-7% numerics-guard overhead of
BENCH_guard.json is fused-DETECTION arithmetic (the rollup psum itself
is ~5 µs), quantize/dequantize work sits at every ring-hop boundary
(EQuARX, arXiv:2506.17615, fuses exactly this into the collective), the
ZeRO-1 shard update is the classic fusion target of weight-update
sharding (arXiv:2004.13336), and serving's paged decode still gathers
the whole KV window per layer per tick.  Four kernels delete that
arithmetic by fusion:

1. **Fused bucket pack + finiteness detect** (:func:`fused_pack_detect`
   / :func:`fused_detect_stats`): ONE pass over the packed bucket
   producing both guard statistics — the non-finite element count and
   the squared-norm partial — that ``numerics/guard.py`` otherwise
   computes as two separate full-vector reductions inside
   ``explicit_sync.py``.  The guard becomes a byproduct of the pack.
2. **Fused unscale/clip/update** (:func:`fused_adam_update`): the
   loss-scale unscale, the global-norm clip factor (one multiplier,
   computed from the guard psum), and the Adam moment + parameter math
   of the ZeRO-1 flat bucket-major shard in one elementwise kernel —
   one HBM read and write of (p, g, m, v) instead of the optax chain's
   per-transform passes.  Exact vs the unsharded optax chain at 1e-6
   (the PR 5 contract); requires the program's optimizer to be
   :func:`fusable_adam` so the hyperparameters are known statically.
3. **Fused quantize hop** (:func:`fused_quantize` /
   :func:`fused_hop_accumulate` / :func:`fused_dequant_add`): each
   quantized ring hop's dequantize → accumulate-f32 → requantize
   (``quant_ring.py``) as one kernel over the per-chunk scale grid —
   the f32 partial lives only in VMEM between the wire formats, and the
   scale/clip arithmetic is the SAME shared rule
   (``ops/quant_scale.py``) the unfused compressors apply, so the two
   paths agree to float round-off.
4. **Paged attention** (:func:`paged_attention`): decode attention
   reading K/V directly through the block table (scalar-prefetch index
   maps — the block that is DMA'd is the block the table names) with
   the flash-attention online-softmax structure, replacing
   ``serving/paged_kv.py``'s gather-per-layer materialization of every
   slot's whole logical window.

Selection is an explicit opt-in: ``AUTODIST_FUSED_KERNELS`` names the
kernels (``all`` or a comma list of ``guard,update,quant_hop,
paged_attention``).  Off-TPU, or on configs a kernel does not support,
the runtime falls back to the unfused lowering with a shared
drop-reason WARN (:func:`fused_drop_reason` — the
``bucket_drop_reason`` pattern: runtime and analysis surface the same
string).  ``AUTODIST_FUSED_INTERPRET=1`` forces Pallas interpret mode
off-TPU — the test/bench escape hatch that lets the CPU mesh execute
the exact fused step (slower than XLA; never the default).  Enabled
kernels are recorded in the schedule IR (``fused_detect`` /
``fused_update`` / ``fused_hop`` legs, ``docs/schedule-ir.md``) and
priced per kind by ``estimate_ir_cost`` through
``telemetry/calibration.py``'s fused calibration kinds.

Tiling policy (interpret auto-selection, 128-lane padding) comes from
``ops/pallas_utils.py``; layout conventions follow
``ops/flash_attention.py``.
"""
from __future__ import annotations

import functools
from typing import Callable, List, NamedTuple, Optional, Tuple

from autodist_tpu.ops import pallas_utils, quant_scale

#: kernel names — the ``AUTODIST_FUSED_KERNELS`` vocabulary.
KERNEL_GUARD = "guard"
KERNEL_UPDATE = "update"
KERNEL_QUANT_HOP = "quant_hop"
KERNEL_PAGED_ATTENTION = "paged_attention"
ALL_KERNELS = (KERNEL_GUARD, KERNEL_UPDATE, KERNEL_QUANT_HOP,
               KERNEL_PAGED_ATTENTION)

#: elementwise-kernel block: 64 sublanes x 128 lanes of f32 per program.
_BLOCK_ROWS = 64
_BLOCK_ELEMS = _BLOCK_ROWS * pallas_utils.TILE

#: rows of the per-chunk scale grid one hop-kernel program covers; 32
#: sublanes keeps the int8 wire block (32, 256) at the int8 min tile.
_QROWS = 32

_NEG_INF = -1e30


# ---------------------------------------------------------------------------
# selection knobs + the shared drop-reason rule
# ---------------------------------------------------------------------------

def requested_kernels() -> frozenset:
    """The kernels ``AUTODIST_FUSED_KERNELS`` opts into (``all`` or a
    comma list); empty when the knob is unset — fusion is never
    ambient."""
    from autodist_tpu.const import ENV

    raw = (ENV.AUTODIST_FUSED_KERNELS.val or "").strip()
    if not raw:
        return frozenset()
    if raw.lower() == "all":
        return frozenset(ALL_KERNELS)
    return frozenset(p.strip() for p in raw.split(",") if p.strip())


def interpret_forced() -> bool:
    """Is the off-TPU interpret-mode escape hatch on
    (``AUTODIST_FUSED_INTERPRET=1``)?  Test/bench only — interpret mode
    executes the exact kernel bodies but slower than XLA."""
    from autodist_tpu.const import ENV

    return bool(ENV.AUTODIST_FUSED_INTERPRET.val)


def fused_drop_reason(kernel: str, *, on_tpu: bool,
                      interpret_ok: bool = False,
                      optimizer_fusable: bool = True,
                      adam_state_shaped: bool = True,
                      f32_buckets: bool = True) -> Optional[str]:
    """Why a REQUESTED fused kernel cannot lower on this program, or
    None when it can.  Pure — the single rule shared by the runtime
    fallback WARN, the ``schedule/fused-fallback`` analysis WARN, and
    the bench, so the lint can never drift from the lowering (the
    ``bucket_drop_reason`` pattern)."""
    if kernel not in ALL_KERNELS:
        return (f"unknown fused kernel {kernel!r}; expected one of "
                f"{ALL_KERNELS}")
    if not on_tpu and not interpret_ok:
        return ("Pallas fused kernels need a TPU backend; this process "
                "is off-TPU (set AUTODIST_FUSED_INTERPRET=1 to force "
                "interpret mode — test/bench only, slower than XLA)")
    if kernel == KERNEL_UPDATE:
        if not optimizer_fusable:
            return ("the fused unscale/clip/update kernel needs the Adam "
                    "hyperparameters statically: build the optimizer with "
                    "ops.fused_kernels.fusable_adam(...) (any other optax "
                    "chain keeps the unfused shard update)")
        if not adam_state_shaped:
            return ("optimizer state is not the optax.adam shape "
                    "(ScaleByAdamState with count/mu/nu); the fused shard "
                    "update cannot address its moments")
        if not f32_buckets:
            return ("a ZeRO-1 bucket is not float32: the fused update "
                    "kernel runs the f32 moment math only (optax keeps "
                    "low-precision moments in the bucket dtype, which "
                    "the kernel would not match bit-for-bit)")
    return None


def _platform_tpu() -> bool:
    import jax

    return jax.devices()[0].platform == "tpu"


def kernels_runnable() -> Tuple[bool, bool]:
    """(on_tpu, interpret_ok) — the platform half of the drop rule."""
    return _platform_tpu(), interpret_forced()


def resolve_fused(*, guard: bool, has_rs: bool, has_quant_ring: bool,
                  optimizer_fusable: bool = False,
                  adam_state_shaped: bool = True,
                  f32_buckets: bool = True
                  ) -> Tuple[Tuple[str, ...],
                             List[Tuple[str, str]]]:
    """Resolve the training-step fused-kernel set for one program.

    Returns ``(active, drops)``: kernels that lower fused, and
    ``(kernel, reason)`` pairs for requested kernels this program must
    drop.  A requested kernel whose hot path does not exist in the
    program at all (no guard, no ZeRO-1 buckets, no quantized-ring
    buckets) is silently inapplicable, not a drop — the WARN is
    reserved for fusion that was plausibly on the table.  Pure given
    the platform pair, which is resolved here once (the same rule
    analysis applies through :func:`fused_drop_reason`)."""
    requested = requested_kernels()
    on_tpu, interp = kernels_runnable()
    active: List[str] = []
    drops: List[Tuple[str, str]] = []
    applicable = {
        KERNEL_GUARD: guard,
        KERNEL_UPDATE: has_rs,
        KERNEL_QUANT_HOP: has_quant_ring,
    }
    for kernel in (KERNEL_GUARD, KERNEL_UPDATE, KERNEL_QUANT_HOP):
        if kernel not in requested or not applicable[kernel]:
            continue
        why = fused_drop_reason(
            kernel, on_tpu=on_tpu, interpret_ok=interp,
            optimizer_fusable=optimizer_fusable,
            adam_state_shaped=adam_state_shaped,
            f32_buckets=f32_buckets)
        if why is None:
            active.append(kernel)
        else:
            drops.append((kernel, why))
    return tuple(active), drops


def paged_attention_status() -> Tuple[bool, Optional[str]]:
    """(active, drop_reason) for the serving paged-attention kernel —
    resolved at trace time by ``serving/paged_kv.py``.  ``(False,
    None)`` when simply not requested."""
    if KERNEL_PAGED_ATTENTION not in requested_kernels():
        return False, None
    on_tpu, interp = kernels_runnable()
    why = fused_drop_reason(KERNEL_PAGED_ATTENTION, on_tpu=on_tpu,
                            interpret_ok=interp)
    return why is None, why


def _interpret(interpret: Optional[bool]) -> bool:
    return pallas_utils.resolve_interpret(interpret)


# ---------------------------------------------------------------------------
# kernel 1: fused bucket pack + finiteness/sq-norm detect
# ---------------------------------------------------------------------------

def _stats_kernel(x_ref, nf_ref, sq_ref):
    """One block's guard statistics, accumulated across the sequential
    grid: non-finite element count + squared sum.  A NaN/Inf propagates
    into ``sq`` exactly as in the unfused ``sum(v*v)`` (the finite BIT
    comes from the count, so the skip decision stays bit-identical)."""
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    i = pl.program_id(0)
    x = x_ref[...].astype(jnp.float32)
    nf = jnp.sum(1.0 - jnp.isfinite(x).astype(jnp.float32))
    sq = jnp.sum(x * x)

    @pl.when(i == 0)
    def _init():
        nf_ref[0, 0] = nf
        sq_ref[0, 0] = sq

    @pl.when(i > 0)
    def _acc():
        nf_ref[0, 0] += nf
        sq_ref[0, 0] += sq


def fused_detect_stats(vec, *, interpret: Optional[bool] = None):
    """One Pallas pass over flat ``vec`` → ``(nonfinite_count,
    sq_sum)`` (both f32 scalars) — the two guard statistics
    ``numerics.guard.HealthAccumulator`` needs, produced together
    instead of as two separate full-vector reductions.  Zero-pads to a
    tileable length (pad is finite and adds 0 to the square sum)."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    interpret = _interpret(interpret)
    vec = jnp.ravel(vec)
    n = vec.shape[0]
    if n == 0:
        return jnp.float32(0.0), jnp.float32(0.0)
    padded = pallas_utils.pad_to(n, _BLOCK_ELEMS)
    if padded != n:
        vec = jnp.pad(vec, (0, padded - n))
    x2 = vec.reshape(-1, pallas_utils.TILE)
    grid = x2.shape[0] // _BLOCK_ROWS
    nf, sq = pl.pallas_call(
        _stats_kernel,
        grid=(grid,),
        in_specs=[pl.BlockSpec((_BLOCK_ROWS, pallas_utils.TILE),
                               lambda i: (i, 0))],
        out_specs=[pl.BlockSpec((1, 1), lambda i: (0, 0)),
                   pl.BlockSpec((1, 1), lambda i: (0, 0))],
        out_shape=[jax.ShapeDtypeStruct((1, 1), jnp.float32),
                   jax.ShapeDtypeStruct((1, 1), jnp.float32)],
        interpret=interpret,
    )(x2)
    return nf[0, 0], sq[0, 0]


def fused_pack_detect(bucket, leaves, *, interpret: Optional[bool] = None):
    """Pack one gradient bucket AND detect in the same call: returns
    ``(vec, nonfinite_count, sq_sum)`` where ``vec`` is the padded flat
    bucket (``bucketing.pack_bucket``) and the statistics come from the
    single fused pass over it — the guard as a byproduct of the pack."""
    from autodist_tpu.kernel.synchronization.bucketing import pack_bucket

    vec = pack_bucket(bucket, leaves)
    nf, sq = fused_detect_stats(vec, interpret=interpret)
    return vec, nf, sq


# ---------------------------------------------------------------------------
# kernel 2: fused unscale/clip/Adam shard update (ZeRO-1)
# ---------------------------------------------------------------------------

class AdamSpec(NamedTuple):
    """Statically known Adam hyperparameters — what the fused update
    kernel closes over."""

    lr: float
    b1: float = 0.9
    b2: float = 0.999
    eps: float = 1e-8


class FusedAdam(NamedTuple):
    """An optax-compatible gradient transformation whose ``init`` /
    ``update`` ARE ``optax.adam``'s (the unfused path is literally the
    optax chain) plus the :class:`AdamSpec` the fused ZeRO-1 shard
    update needs.  Built by :func:`fusable_adam`."""

    init: Callable
    update: Callable
    fused_spec: AdamSpec


def fusable_adam(lr: float, b1: float = 0.9, b2: float = 0.999,
                 eps: float = 1e-8) -> FusedAdam:
    """``optax.adam`` with its hyperparameters attached, so the fused
    unscale/clip/update kernel can lower the ZeRO-1 shard update.  Any
    program is free to use it without the fused-kernel knob — it
    behaves exactly like ``optax.adam``."""
    import optax

    base = optax.adam(lr, b1=b1, b2=b2, eps=eps)
    return FusedAdam(init=base.init, update=base.update,
                     fused_spec=AdamSpec(lr=float(lr), b1=float(b1),
                                         b2=float(b2), eps=float(eps)))


def find_adam_state(state):
    """The ``ScaleByAdamState``-shaped component (count/mu/nu) inside
    an optax state tuple, or None — the structural probe behind the
    ``adam_state_shaped`` drop reason and the fused update's state
    addressing.  Top-level components only: ``fusable_adam``'s state is
    ``(ScaleByAdamState, ...)``; a nested chain is exactly the shape
    the kernel refuses."""
    if all(hasattr(state, a) for a in ("count", "mu", "nu")):
        return state
    if isinstance(state, (tuple, list)):
        for part in state:
            if all(hasattr(part, a) for a in ("count", "mu", "nu")):
                return part
    return None


def replace_adam_state(state, new_adam):
    """``state`` with its ScaleByAdamState component swapped for
    ``new_adam`` (see :func:`find_adam_state`)."""
    if all(hasattr(state, a) for a in ("count", "mu", "nu")):
        return new_adam
    parts = []
    replaced = False
    for part in state:
        if not replaced and all(hasattr(part, a)
                                for a in ("count", "mu", "nu")):
            parts.append(new_adam)
            replaced = True
        else:
            parts.append(part)
    if isinstance(state, list):
        return parts
    if hasattr(state, "_fields"):            # NamedTuple
        return type(state)(*parts)
    return tuple(parts)


def _adam_kernel(p_ref, g_ref, m_ref, v_ref, s_ref, po_ref, mo_ref, vo_ref,
                 *, lr: float, b1: float, b2: float, eps: float):
    """One elementwise block of the fused update.  ``s_ref`` carries
    the three traced scalars: row 0 = the unscale*clip multiplier, row
    1 / row 2 = the Adam bias corrections ``1 - b^count`` (computed
    once outside — they are scalars, not per-element work).  The moment
    expressions mirror ``optax.scale_by_adam`` exactly so the fused
    shard update matches the unsharded optax chain to float round-off
    (the PR 5 ZeRO-1 exactness contract)."""
    import jax.numpy as jnp

    g = g_ref[...].astype(jnp.float32) * s_ref[0, 0]
    m = (1.0 - b1) * g + b1 * m_ref[...]
    v = (1.0 - b2) * (g * g) + b2 * v_ref[...]
    m_hat = m / s_ref[1, 0]
    v_hat = v / s_ref[2, 0]
    po_ref[...] = p_ref[...] - lr * (m_hat / (jnp.sqrt(v_hat) + eps))
    mo_ref[...] = m
    vo_ref[...] = v


def fused_adam_update(p, g, mu, nu, count, spec: AdamSpec, *,
                      mult=None, interpret: Optional[bool] = None):
    """Fused unscale/clip/Adam update of one flat f32 shard.

    ``p``/``g``/``mu``/``nu`` are the ZeRO-1 bucket-major shard vectors
    (one per bucket); ``count`` is the optax step counter BEFORE this
    step; ``mult`` the combined loss-scale-unscale × global-norm-clip
    multiplier (None = 1.0).  Returns ``(new_p, new_mu, new_nu)`` —
    exactly ``optax.adam(spec)`` applied to ``mult * g`` (1e-6; the
    counter increments once per step OUTSIDE, it is shared by every
    bucket)."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    interpret = _interpret(interpret)
    n = p.shape[0]
    count_inc = (count + 1).astype(jnp.float32)
    scalars = jnp.stack([
        jnp.float32(1.0) if mult is None else mult.astype(jnp.float32),
        1.0 - jnp.float32(spec.b1) ** count_inc,
        1.0 - jnp.float32(spec.b2) ** count_inc,
    ]).reshape(3, 1)
    padded = pallas_utils.pad_to(max(n, 1), _BLOCK_ELEMS)

    def prep(x):
        x = x.astype(jnp.float32)
        if padded != n:
            x = jnp.pad(x, (0, padded - n))
        return x.reshape(-1, pallas_utils.TILE)

    rows = padded // pallas_utils.TILE
    grid = rows // _BLOCK_ROWS
    blk = pl.BlockSpec((_BLOCK_ROWS, pallas_utils.TILE), lambda i: (i, 0))
    kernel = functools.partial(_adam_kernel, lr=spec.lr, b1=spec.b1,
                               b2=spec.b2, eps=spec.eps)
    new_p, new_m, new_v = pl.pallas_call(
        kernel,
        grid=(grid,),
        in_specs=[blk, blk, blk, blk,
                  pl.BlockSpec((3, 1), lambda i: (0, 0))],
        out_specs=[blk, blk, blk],
        out_shape=[jax.ShapeDtypeStruct((rows, pallas_utils.TILE),
                                        jnp.float32)] * 3,
        interpret=interpret,
    )(prep(p), prep(g), prep(mu), prep(nu), scalars)
    return (new_p.reshape(-1)[:n], new_m.reshape(-1)[:n],
            new_v.reshape(-1)[:n])


# ---------------------------------------------------------------------------
# kernel 3: fused quantize / dequantize at ring-hop boundaries
# ---------------------------------------------------------------------------

def _wire_dtype(fmt):
    import jax.numpy as jnp

    return jnp.int8 if fmt.name == "int8" else jnp.float8_e4m3fn


def _grid_shapes(length: int, block: int):
    """(nb, nb_pad, grid) for a flat vector on the per-chunk grid."""
    from autodist_tpu.kernel.synchronization.quant_ring import scale_count

    nb = scale_count(length, block)
    nb_pad = pallas_utils.pad_to(max(nb, 1), _QROWS)
    return nb, nb_pad, nb_pad // _QROWS


def _pad_grid(x, length: int, nb_pad: int, block: int):
    import jax.numpy as jnp

    pad = nb_pad * block - length
    if pad:
        x = jnp.pad(x, (0, pad))
    return x.reshape(nb_pad, block)


def _quant_body(acc, qo_ref, so_ref, eo_ref, sat_ref, *, qmax, rounded,
                wire_dt):
    """Shared tail of the quantize kernels: per-chunk scale grid over
    the f32 block ``acc`` [R, B] — the SAME scale/clip rule the unfused
    ``quant_ring.quantize_blocks`` applies (``ops/quant_scale.py``)."""
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    i = pl.program_id(0)
    finite = jnp.isfinite(acc)
    amax = jnp.max(jnp.where(finite, jnp.abs(acc), 0.0), axis=1)
    scale = quant_scale.chunk_scale(amax, qmax)
    y = acc / scale[:, None]
    sat = quant_scale.saturation_count(y, finite, qmax,
                                       rounded=rounded).astype(jnp.float32)
    q = quant_scale.quantize_values(y, qmax, wire_dt, rounded=rounded)
    qo_ref[...] = q
    so_ref[...] = scale[:, None]
    eo_ref[...] = acc - q.astype(jnp.float32) * scale[:, None]

    @pl.when(i == 0)
    def _init():
        sat_ref[0, 0] = sat

    @pl.when(i > 0)
    def _acc():
        sat_ref[0, 0] += sat


def _quantize_kernel(x_ref, qo_ref, so_ref, eo_ref, sat_ref, *, qmax,
                     rounded, wire_dt):
    import jax.numpy as jnp

    _quant_body(x_ref[...].astype(jnp.float32), qo_ref, so_ref, eo_ref,
                sat_ref, qmax=qmax, rounded=rounded, wire_dt=wire_dt)


def _hop_kernel(q_ref, s_ref, c_ref, qo_ref, so_ref, eo_ref, sat_ref, *,
                qmax, rounded, wire_dt):
    """dequantize(received) + own chunk + requantize — one hop boundary,
    the f32 partial never leaving VMEM between the wire formats."""
    import jax.numpy as jnp

    acc = q_ref[...].astype(jnp.float32) * s_ref[...] \
        + c_ref[...].astype(jnp.float32)
    _quant_body(acc, qo_ref, so_ref, eo_ref, sat_ref, qmax=qmax,
                rounded=rounded, wire_dt=wire_dt)


def _deq_add_kernel(q_ref, s_ref, c_ref, o_ref):
    import jax.numpy as jnp

    o_ref[...] = q_ref[...].astype(jnp.float32) * s_ref[...] \
        + c_ref[...].astype(jnp.float32)


def _quant_specs(block: int, with_chunk: bool):
    from jax.experimental import pallas as pl

    vec_blk = pl.BlockSpec((_QROWS, block), lambda i: (i, 0))
    scale_blk = pl.BlockSpec((_QROWS, 1), lambda i: (i, 0))
    ins = [vec_blk, scale_blk, vec_blk] if with_chunk else [vec_blk]
    outs = [vec_blk, scale_blk, vec_blk,
            pl.BlockSpec((1, 1), lambda i: (0, 0))]
    return ins, outs


def _run_quant(kernel, args, length: int, nb: int, nb_pad: int, grid: int,
               block: int, fmt, interpret: bool):
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    ins, outs = _quant_specs(block, with_chunk=len(args) == 3)
    q, scales, err, sat = pl.pallas_call(
        kernel,
        grid=(grid,),
        in_specs=ins,
        out_specs=outs,
        out_shape=[
            jax.ShapeDtypeStruct((nb_pad, block), _wire_dtype(fmt)),
            jax.ShapeDtypeStruct((nb_pad, 1), jnp.float32),
            jax.ShapeDtypeStruct((nb_pad, block), jnp.float32),
            jax.ShapeDtypeStruct((1, 1), jnp.float32),
        ],
        interpret=interpret,
    )(*args)
    return (q.reshape(-1)[:length], scales.reshape(-1)[:nb],
            err.reshape(-1)[:length], sat[0, 0])


def fused_quantize(x, fmt, block: int = 256, *,
                   interpret: Optional[bool] = None):
    """Quantize flat f32 ``x`` on the per-chunk scale grid with the
    error and saturation count produced in the SAME pass: ``(q, scales,
    err, sat_count)``.  ``err = x - dequantize(q, scales)`` — the
    stage-1 error-feedback residual the unfused path derives with a
    separate dequantize."""
    interpret = _interpret(interpret)
    length = x.shape[0]
    nb, nb_pad, grid = _grid_shapes(length, block)
    kernel = functools.partial(_quantize_kernel, qmax=fmt.qmax,
                               rounded=fmt.name == "int8",
                               wire_dt=_wire_dtype(fmt))
    return _run_quant(kernel, (_pad_grid(x, length, nb_pad, block),),
                      length, nb, nb_pad, grid, block, fmt, interpret)


def fused_hop_accumulate(q_in, scales_in, chunk, fmt, block: int = 256, *,
                         interpret: Optional[bool] = None):
    """One ring-hop boundary fused: dequantize the received payload,
    add this device's f32 chunk, requantize with fresh per-chunk scales
    — ``(q_out, scales_out, err, sat_count)``.  The f32 partial exists
    only inside the kernel; HBM sees wire dtype in, wire dtype out."""
    import jax.numpy as jnp

    interpret = _interpret(interpret)
    length = chunk.shape[0]
    nb, nb_pad, grid = _grid_shapes(length, block)
    sp = jnp.zeros((nb_pad, 1), jnp.float32).at[:nb, 0].set(scales_in)
    kernel = functools.partial(_hop_kernel, qmax=fmt.qmax,
                               rounded=fmt.name == "int8",
                               wire_dt=_wire_dtype(fmt))
    return _run_quant(
        kernel,
        (_pad_grid(q_in, length, nb_pad, block), sp,
         _pad_grid(chunk, length, nb_pad, block)),
        length, nb, nb_pad, grid, block, fmt, interpret)


def fused_dequant_add(q_in, scales_in, chunk, fmt, block: int = 256, *,
                      interpret: Optional[bool] = None):
    """The final hop's receive side: dequantize + accumulate only (the
    owned shard stays f32, never requantized) — flat f32 result."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    interpret = _interpret(interpret)
    length = chunk.shape[0]
    nb, nb_pad, grid = _grid_shapes(length, block)
    sp = jnp.zeros((nb_pad, 1), jnp.float32).at[:nb, 0].set(scales_in)
    vec_blk = pl.BlockSpec((_QROWS, block), lambda i: (i, 0))
    scale_blk = pl.BlockSpec((_QROWS, 1), lambda i: (i, 0))
    out = pl.pallas_call(
        _deq_add_kernel,
        grid=(grid,),
        in_specs=[vec_blk, scale_blk, vec_blk],
        out_specs=vec_blk,
        out_shape=jax.ShapeDtypeStruct((nb_pad, block), jnp.float32),
        interpret=interpret,
    )(_pad_grid(q_in, length, nb_pad, block), sp,
      _pad_grid(chunk, length, nb_pad, block))
    return out.reshape(-1)[:length]


# ---------------------------------------------------------------------------
# kernel 4: paged attention (decode, block tables as scalar prefetch)
# ---------------------------------------------------------------------------

def _paged_attn_kernel(bt_ref, rel_ref, q_ref, k_ref, v_ref, o_ref,
                       m_ref, l_ref, acc_ref, *, bs: int, scale: float):
    """One (slot, logical-block) program: the named block arrives via
    the scalar-prefetch index map (no gather — the DMA reads exactly
    the physical block the table points at), and an online softmax
    accumulates across the slot's logical blocks.

    Refs: q [1,H,Dh]; k/v [1,BS,H,Dh] (the table-selected block);
    o [1,H,Dh]; scratch m/l [H,1], acc [H,Dh] (f32, persistent across
    the sequential block grid)."""
    import jax.numpy as jnp
    from jax import lax
    from jax.experimental import pallas as pl

    bi = pl.program_id(0)
    j = pl.program_id(1)
    nb = pl.num_programs(1)

    @pl.when(j == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    q = q_ref[0].astype(jnp.float32)                     # [H, Dh]
    k = k_ref[0].astype(jnp.float32)                     # [BS, H, Dh]
    v = v_ref[0].astype(jnp.float32)
    h, _ = q.shape
    # s[h, p] = q[h, :] . k[p, h, :]  (head is a batch dim)
    s = lax.dot_general(q, k, (((1,), (2,)), ((0,), (1,))),
                        preferred_element_type=jnp.float32) * scale
    pos = j * bs + lax.broadcasted_iota(jnp.int32, (h, bs), 1)
    s = jnp.where(pos <= rel_ref[bi], s, _NEG_INF)
    m_prev, l_prev = m_ref[...], l_ref[...]
    m_new = jnp.maximum(m_prev, s.max(axis=-1, keepdims=True))
    p = jnp.exp(s - m_new)
    corr = jnp.exp(m_prev - m_new)
    m_ref[...] = m_new
    l_ref[...] = l_prev * corr + p.sum(axis=-1, keepdims=True)
    acc_ref[...] = acc_ref[...] * corr + lax.dot_general(
        p, v, (((1,), (0,)), ((0,), (1,))),
        preferred_element_type=jnp.float32)

    @pl.when(j == nb - 1)
    def _write():
        l = jnp.maximum(l_ref[...], 1e-30)
        o_ref[0] = (acc_ref[...] / l).astype(o_ref.dtype)


def paged_attention(q, kc, vc, bt, rel, *,
                    interpret: Optional[bool] = None):
    """Decode attention over the paged KV pool, block tables read as
    scalar prefetch.

    ``q`` [B, H, Dh] (this tick's query per slot); ``kc``/``vc``
    [NB, BS, H, Dh] (ONE layer's pool); ``bt`` [B, MAXB] int32 block
    table; ``rel`` [B] int32 logical position (positions ``0..rel``
    attend).  Returns [B, H, Dh] in ``q``'s dtype — numerically the
    gather-per-layer reference of ``serving/paged_kv.py`` (masked
    positions get exactly-zero weight; the online softmax matches the
    dense softmax to f32 round-off, the flash-attention argument)."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    interpret = _interpret(interpret)
    b, h, dh = q.shape
    _, bs, _, _ = kc.shape
    maxb = bt.shape[1]
    scale = 1.0 / (dh ** 0.5)
    kernel = functools.partial(_paged_attn_kernel, bs=bs, scale=scale)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, maxb),
        in_specs=[
            pl.BlockSpec((1, h, dh), lambda bi, j, bt_r, rel_r: (bi, 0, 0)),
            pl.BlockSpec((1, bs, h, dh),
                         lambda bi, j, bt_r, rel_r: (bt_r[bi, j], 0, 0, 0)),
            pl.BlockSpec((1, bs, h, dh),
                         lambda bi, j, bt_r, rel_r: (bt_r[bi, j], 0, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, h, dh),
                               lambda bi, j, bt_r, rel_r: (bi, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((h, 1), jnp.float32),
            pltpu.VMEM((h, 1), jnp.float32),
            pltpu.VMEM((h, dh), jnp.float32),
        ],
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, h, dh), q.dtype),
        interpret=interpret,
    )(bt.astype(jnp.int32), rel.astype(jnp.int32), q, kc, vc)
