"""Pipeline parallelism: microbatched ring schedules over the ``pipe`` axis.

Absent from the reference (SURVEY §2.8: pipeline parallelism NO); new
first-class scope for the TPU build.

Design (the SPMD "pipelining on a mesh" formulation, cf. the scaling-book
collective-matmul recipe rather than torch-style per-rank stage processes):

* Stage parameters are *stacked*: every stage-local parameter carries a
  leading ``[num_stages]`` axis, sharded over ``pipe`` — so the strategy
  layer sees ordinary variables whose PartitionSpec leads with ``pipe``.
* The whole pipeline runs inside ``shard_map`` manual over ``pipe``: one
  ``lax.scan`` over the schedule's ticks; each tick every device applies
  its current stage to its current activation, then the activations rotate
  one hop along the ring via ``ppermute`` (nearest neighbor on ICI).
  Stage 0 injects fresh microbatches; the last stage banks results.
* Backward is ``jax.grad`` through the scan — XLA reverses the ppermute
  ring automatically.

Schedules (both fall out of ONE tick formula, see ``_chunk_at``):

* **GPipe** (``num_virtual_stages=1``): M microbatches through S stages in
  ``M + S - 1`` ticks → bubble fraction ``(S-1)/(M+S-1)``.  The default
  ``num_microbatches ≈ 4·S`` keeps that under ~20%.
* **Interleaved / circular** (``num_virtual_stages=V``, the Megatron-LM
  interleaved schedule, arxiv 2104.04473): each device holds V *chunks* of
  ``depth/(S·V)`` layers; global stage ``v·S + d`` lives on device ``d``.
  Activations circulate the ring V times; ticks = ``M·V + S - 1`` of
  ``1/V``-size stage work each → bubble ``(S-1)/(M·V + S-1)``, a V× cut
  for the same microbatch count.
"""
from __future__ import annotations

import functools
from typing import Any, Callable, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from autodist_tpu.const import MESH_AXIS_PIPE
from autodist_tpu.utils import compat


def interleaved_stage_order(num_stages: int, num_virtual_stages: int
                            ) -> Tuple[int, ...]:
    """Device-major permutation of pipeline-order stage indices.

    For V>1 ``pipeline_apply`` expects the stage axis laid out device-major
    — entry ``d·V + v`` is global stage ``v·S + d`` — so the compiler's
    contiguous sharding of the leading axis over ``pipe`` puts each device's
    V chunks on it with NO per-step resharding.  Apply this permutation to a
    pipeline-ordered stage list before ``stack_stage_params``."""
    s, v = num_stages, num_virtual_stages
    return tuple(vv * s + d for d in range(s) for vv in range(v))


def schedule_ticks(num_stages: int, num_microbatches: int,
                   num_virtual_stages: int = 1) -> int:
    """Total ring ticks the schedule takes.

    The last microbatch (index M-1) is injected at tick
    ``((M-1)//S)·S·V + (M-1)%S`` (device 0 accepts a fresh microbatch only
    when an empty ring slot arrives) and exits ``S·V`` ticks later."""
    s, m, v = num_stages, num_microbatches, num_virtual_stages
    return ((m - 1) // s) * s * v + ((m - 1) % s) + s * v


def bubble_fraction(num_stages: int, num_microbatches: int,
                    num_virtual_stages: int = 1) -> float:
    """Idle fraction of the schedule: 1 − ideal_ticks / actual_ticks, where
    ideal = M·V ticks of chunk-sized work."""
    t = schedule_ticks(num_stages, num_microbatches, num_virtual_stages)
    return 1.0 - (num_microbatches * num_virtual_stages) / t


def default_num_microbatches(num_stages: int, batch: int) -> int:
    """Largest feasible microbatch count ≤ 4·S — the GPipe bubble at 4·S is
    (S-1)/(5S-1) < 20% (vs ~50% at the pipe-filling minimum M=S)."""
    m = min(4 * num_stages, batch)
    while batch % m:
        m -= 1
    return m


def pipeline_apply(stage_fn: Callable, stage_params: Any, x: jax.Array,
                   mesh: Mesh, *, num_microbatches: Optional[int] = None,
                   num_virtual_stages: int = 1, remat: bool = False,
                   axis_name: str = MESH_AXIS_PIPE) -> jax.Array:
    """Apply a pipeline of stacked stages to a batch.

    Args:
      stage_fn: ``(params_one_stage, x_microbatch) -> y_microbatch`` with
        ``y`` shaped like ``x`` (inter-stage activations must be homogeneous
        — true of transformer stacks).  Must be a *stable* callable: the
        compiled schedule is cached keyed on its identity, so passing a
        fresh closure/partial per call recompiles (and grows the cache)
        every time.
      stage_params: pytree whose leaves lead with a ``[S·V]`` stage axis —
        pipeline order for V=1; **device-major** for V>1 (entry ``d·V + v``
        = global stage ``v·S + d``; see :func:`interleaved_stage_order`), so
        contiguous ``pipe`` sharding of the axis lands each device's chunks
        on it without any per-step resharding.
      x: global batch ``[B, ...]``; must divide into ``num_microbatches``.
      num_microbatches: defaults to the largest feasible count ≤ ``4·S``.
      num_virtual_stages: chunks per device (interleaved schedule); the
        stage axis must equal ``S · num_virtual_stages``.
      remat: rematerialize each stage application in the backward pass.
        Differentiating the tick-scan stashes every tick's stage-internal
        activations for the whole schedule — the GPipe memory profile; with
        ``remat`` only the tick BOUNDARY activations are stashed and stage
        internals recompute during backward, trading ~1 extra forward of
        FLOPs for an O(depth/S) cut in stashed bytes per device (the
        scan-boundary memory shape 1F1B targets, achieved here within
        whole-program autodiff instead of a hand-scheduled backward).

    Returns ``[B, ...]`` after all stages.
    """
    s = mesh.shape.get(axis_name, 1)
    v = num_virtual_stages
    if s <= 1:
        # No pipe axis: sequential scan over the stage dimension.  With
        # S=1 the device-major layout coincides with pipeline order, so no
        # reordering is needed.
        fn = jax.checkpoint(stage_fn) if remat else stage_fn

        def body(h, p):
            return fn(p, h), None
        out, _ = lax.scan(body, x, stage_params)
        return out

    b = x.shape[0]
    m = num_microbatches or default_num_microbatches(s, b)
    if b % m:
        raise ValueError(f"batch {b} not divisible into {m} microbatches")
    for leaf in jax.tree_util.tree_leaves(stage_params):
        if leaf.shape[0] != s * v:
            raise ValueError(
                f"stage_params leading dim {leaf.shape[0]} != pipe axis "
                f"size {s} x {v} virtual stages")

    # Device-major [S·V] → [S, V]: row d = device d's V chunks.  A plain
    # reshape, and contiguous 'pipe' sharding of the stored axis is exactly
    # the sharding of dim 0 here — no data movement.
    chunk_params = jax.tree_util.tree_map(
        lambda p: p.reshape((s, v) + p.shape[1:]), stage_params)
    return _jitted_pipeline(stage_fn, mesh, m, v, remat,
                            axis_name)(chunk_params, x)


@functools.lru_cache(maxsize=None)
def _jitted_pipeline(stage_fn: Callable, mesh: Mesh, num_microbatches: int,
                     num_virtual: int, remat: bool,
                     axis_name: str) -> Callable:
    # Cache note: keyed on stage_fn identity — callers must pass a stable
    # callable (the bundled models create stage_fn once per ModelSpec).
    if remat:
        stage_fn = jax.checkpoint(stage_fn)
    local = functools.partial(_pipeline_local, stage_fn, axis_name=axis_name,
                              num_microbatches=num_microbatches,
                              num_virtual=num_virtual)
    # Partial-manual: only the pipe axis is manualized; data/model sharding
    # of the batch and stage params stays with GSPMD.  jit (inlined when the
    # caller already traces) because eager shard_map with partial axis_names
    # trips JAX's internal unmatch path — same workaround as
    # ops/flash_attention.make_flash_attention.
    return jax.jit(compat.shard_map(
        local, mesh=mesh,
        in_specs=(P(axis_name), P()), out_specs=P(),
        axis_names={axis_name}, check_vma=False,
    ))


def _pipeline_local(stage_fn: Callable, chunk_params: Any, x: jax.Array, *,
                    axis_name: str, num_microbatches: int,
                    num_virtual: int) -> jax.Array:
    """Per-device schedule loop (inside shard_map over ``axis_name``).

    One tick formula covers GPipe and interleaved: the activation at device
    ``d`` on tick ``t`` is on chunk ``v(d,t) = ((t-d) mod S·V) // S``.
    Device 0 injects a fresh microbatch whenever the arriving ring slot is
    empty (``v=0``); the last device banks whenever it finishes ``v=V-1``.
    """
    s = compat.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    m = num_microbatches
    nv = num_virtual
    period = s * nv
    # chunk_params local shape [1, V, ...]: squeeze the device dim.
    params = jax.tree_util.tree_map(lambda p: jnp.squeeze(p, 0), chunk_params)

    mb = x.reshape((m, x.shape[0] // m) + x.shape[1:])  # [M, mb, ...]
    zero = jnp.zeros_like(mb[0])
    # Rotate forward: stage i sends to stage i+1 (ring; the wraparound
    # advances the activation to the device's next chunk).
    perm = [(i, (i + 1) % s) for i in range(s)]

    def tick(carry, t):
        acc, a_in = carry
        v = jnp.mod(t - idx, period) // s           # this device's chunk now
        # Device 0 injects microbatch j when an empty slot arrives (v == 0).
        j = (t // period) * s + jnp.mod(t, period)
        inject = jnp.logical_and(idx == 0, jnp.mod(t, period) < s)
        feed = lax.dynamic_index_in_dim(mb, jnp.clip(j, 0, m - 1), 0,
                                        keepdims=False)
        a = jnp.where(inject, feed, a_in)
        p_v = jax.tree_util.tree_map(
            lambda p: lax.dynamic_index_in_dim(p, v, 0, keepdims=False),
            params)
        y = stage_fn(p_v, a)
        # Last device banks microbatch je once its final chunk completes
        # (injection tick te = t - (S·V - 1); je < m guards schedule padding
        # when M is not a multiple of S).
        te = t - (period - 1)
        je = (te // period) * s + jnp.mod(te, period)
        bank = jnp.logical_and(idx == s - 1, v == nv - 1)
        bank = jnp.logical_and(bank, jnp.logical_and(te >= 0, je < m))
        slot = jnp.clip(je, 0, m - 1)
        cur = lax.dynamic_index_in_dim(acc, slot, 0, keepdims=False)
        acc = lax.dynamic_update_index_in_dim(
            acc, jnp.where(bank, y, cur), slot, 0)
        a_next = lax.ppermute(y, axis_name, perm)
        return (acc, a_next), None

    vary = lambda v_: compat.pcast(v_, axis_name, to="varying")  # noqa: E731
    acc0 = vary(jnp.zeros_like(mb))
    ticks = schedule_ticks(int(s), m, nv)
    (acc, _), _ = lax.scan(tick, (acc0, vary(zero)), jnp.arange(ticks))
    # Only the last stage holds real outputs; zero elsewhere — a psum
    # replicates them across pipe (out_specs=P()).
    acc = lax.psum(jnp.where(idx == s - 1, acc, jnp.zeros_like(acc)),
                   axis_name)
    return acc.reshape(x.shape)


def stack_stage_params(per_stage_params) -> Any:
    """Stack a list of per-stage pytrees into one pytree with a leading
    ``[S]`` (or ``[S·V]``) axis in pipeline order (helper for hand-built
    pipelines)."""
    return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs),
                                  *per_stage_params)
