"""Pipeline parallelism: GPipe-style microbatching over the ``pipe`` mesh axis.

Absent from the reference (SURVEY §2.8: pipeline parallelism NO); new
first-class scope for the TPU build.

Design (the SPMD "pipelining on a mesh" formulation, cf. the scaling-book
collective-matmul recipe rather than torch-style per-rank stage processes):

* Stage parameters are *stacked*: every stage-local parameter carries a
  leading ``[num_stages]`` axis, sharded over ``pipe`` — so the strategy
  layer sees ordinary variables whose PartitionSpec leads with ``pipe``.
* The whole pipeline runs inside ``shard_map`` manual over ``pipe``: one
  ``lax.scan`` over ``num_microbatches + num_stages - 1`` ticks; each tick
  every device applies its stage to its current activation, then the
  activations rotate one hop along the ring via ``ppermute`` (nearest
  neighbor on ICI).  Stage 0 injects a fresh microbatch each tick; the last
  stage banks its result.
* Backward is ``jax.grad`` through the scan — XLA reverses the ppermute
  ring automatically, so no hand-written 1F1B schedule is needed; the
  bubble is the GPipe bubble (S-1 ticks out of M+S-1).

All other mesh axes stay auto (GSPMD) — data/model sharding of activations
inside a stage composes transparently.
"""
from __future__ import annotations

import functools
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from autodist_tpu.const import MESH_AXIS_PIPE


def _stage_slice(stacked: Any, keepdim: bool = False) -> Any:
    """Inside shard_map the stage axis is length-1 per device; drop it."""
    if keepdim:
        return stacked
    return jax.tree_util.tree_map(lambda x: jnp.squeeze(x, 0), stacked)


def pipeline_apply(stage_fn: Callable, stage_params: Any, x: jax.Array,
                   mesh: Mesh, *, num_microbatches: Optional[int] = None,
                   axis_name: str = MESH_AXIS_PIPE) -> jax.Array:
    """Apply a pipeline of ``S`` identical-signature stages to a batch.

    Args:
      stage_fn: ``(params_one_stage, x_microbatch) -> y_microbatch`` with
        ``y`` shaped like ``x`` (inter-stage activations must be homogeneous
        — true of transformer stacks).
      stage_params: pytree whose leaves lead with a ``[S]`` stage axis
        (shard it over ``pipe`` via ``PartitionSpec(axis_name, ...)``).
      x: global batch ``[B, ...]``; must divide into ``num_microbatches``.
      num_microbatches: defaults to ``S`` (minimum that fills the pipe).

    Returns ``[B, ...]`` after all stages.
    """
    s = mesh.shape.get(axis_name, 1)
    if s <= 1:
        # No pipe axis: sequential scan over the stage dimension.
        def body(h, p):
            return stage_fn(p, h), None
        out, _ = lax.scan(body, x, stage_params)
        return out

    m = num_microbatches or s
    b = x.shape[0]
    if b % m:
        raise ValueError(f"batch {b} not divisible into {m} microbatches")
    for leaf in jax.tree_util.tree_leaves(stage_params):
        if leaf.shape[0] != s:
            raise ValueError(
                f"stage_params leading dim {leaf.shape[0]} != pipe axis "
                f"size {s}")

    return _jitted_pipeline(stage_fn, mesh, m, axis_name)(stage_params, x)


@functools.lru_cache(maxsize=None)
def _jitted_pipeline(stage_fn: Callable, mesh: Mesh, num_microbatches: int,
                     axis_name: str) -> Callable:
    local = functools.partial(_pipeline_local, stage_fn, axis_name=axis_name,
                              num_microbatches=num_microbatches)
    # Partial-manual: only the pipe axis is manualized; data/model sharding
    # of the batch and stage params stays with GSPMD.  jit (inlined when the
    # caller already traces) because eager shard_map with partial axis_names
    # trips JAX's internal unmatch path — same workaround as
    # ops/flash_attention.make_flash_attention.
    return jax.jit(jax.shard_map(
        local, mesh=mesh,
        in_specs=(P(axis_name), P()), out_specs=P(),
        axis_names={axis_name}, check_vma=False,
    ))


def _pipeline_local(stage_fn: Callable, stage_params: Any, x: jax.Array, *,
                    axis_name: str, num_microbatches: int) -> jax.Array:
    """Per-device pipeline loop (inside shard_map over ``axis_name``)."""
    s = lax.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    m = num_microbatches
    params = _stage_slice(stage_params)

    mb = x.reshape((m, x.shape[0] // m) + x.shape[1:])  # [M, mb, ...]
    zero = jnp.zeros_like(mb[0])
    # Rotate forward: stage i sends to stage i+1 (ring; the wraparound
    # carries garbage that stage 0 ignores).
    perm = [(i, (i + 1) % s) for i in range(s)]

    def tick(carry, t):
        acc, a_in = carry
        # Stage 0 picks up microbatch t (while available), others use the
        # activation received from the previous stage.
        feed = lax.dynamic_index_in_dim(mb, jnp.minimum(t, m - 1), 0,
                                        keepdims=False)
        a = jnp.where(idx == 0, feed, a_in)
        y = stage_fn(params, a)
        # Last stage banks microbatch t-(S-1) once it emerges.
        out_slot = t - (s - 1)
        bank = jnp.logical_and(idx == s - 1, out_slot >= 0)
        slot = jnp.maximum(out_slot, 0)
        cur = lax.dynamic_index_in_dim(acc, slot, 0, keepdims=False)
        acc = lax.dynamic_update_index_in_dim(
            acc, jnp.where(bank, y, cur), slot, 0)
        a_next = lax.ppermute(y, axis_name, perm)
        return (acc, a_next), None

    vary = lambda v: lax.pcast(v, axis_name, to="varying")  # noqa: E731
    acc0 = vary(jnp.zeros_like(mb))
    (acc, _), _ = lax.scan(tick, (acc0, vary(zero)),
                           jnp.arange(m + s - 1))
    # Only the last stage holds real outputs; zero elsewhere — a psum
    # replicates them across pipe (out_specs=P()).
    acc = lax.psum(jnp.where(idx == s - 1, acc, jnp.zeros_like(acc)),
                   axis_name)
    return acc.reshape(x.shape)


def stack_stage_params(per_stage_params) -> Any:
    """Stack a list of per-stage pytrees into one pytree with a leading
    ``[S]`` axis (helper for hand-built pipelines)."""
    return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs),
                                  *per_stage_params)
