"""Activation/gradient transport plane between MPMD stage programs.

Stages are SEPARATE processes (separate jax.distributed worlds), so
boundary activations and cotangents move over DCN, not over a mesh
axis.  The wire is the PR 12 retry-transport idiom the peer checkpoint
tier established (``checkpoint/tiers.py`` ``PeerMirror``): atomic
tmp+``os.replace`` publishes, a digest header so a torn or corrupt blob
is SKIPPED and re-polled rather than half-read, and
:meth:`~autodist_tpu.cluster.Cluster.remote_copy` /
:meth:`~autodist_tpu.cluster.Cluster.remote_fetch` (each with the
cluster's retry schedule) when the peer stage lives on another host.

Two paths, one API:

* **in-memory fast path** — stages in one process (tests, bench, the
  thread-backed runners) rendezvous through a process-local registry
  under a condition variable: no filesystem, no polling.
* **directory path** — stages in separate processes share
  ``AUTODIST_MPMD_DIR`` (tmpfs in production); ``recv`` polls with a
  deadline (``AUTODIST_MPMD_TIMEOUT_S``) so a dead upstream stage
  surfaces as :class:`TransportTimeout`, which the supervisor turns
  into a stage restart (docs/pipeline.md).

Buffer names are the schedule IR's ``act:`` buffer spellings
(``act:pipe/f0@3``) — the same strings the verifier's
``schedule/act-transport`` rule pairs and the liveness watermark
tracks, so a wedged transport names an IR buffer, not a private path.
"""
from __future__ import annotations

import hashlib
import io
import os
import tempfile
import threading
import time
from typing import Any, Dict, Optional, Tuple

import numpy as np

from autodist_tpu.const import ENV
from autodist_tpu.utils import logging

#: default recv deadline when neither the constructor nor
#: ``AUTODIST_MPMD_TIMEOUT_S`` says otherwise.
DEFAULT_TIMEOUT_S = 120.0

_MAGIC = b"ADTPUACT1"


class TransportTimeout(TimeoutError):
    """No valid blob for the buffer arrived before the deadline."""


# -- in-process rendezvous registry (the fast path) ---------------------------

_LOCK = threading.Condition()
_REGISTRY: Dict[Tuple[str, str], bytes] = {}


def _registry_put(scope: str, buf: str, blob: bytes) -> None:
    with _LOCK:
        _REGISTRY[(scope, buf)] = blob
        _LOCK.notify_all()


def _registry_take(scope: str, buf: str, deadline: float
                   ) -> Optional[bytes]:
    with _LOCK:
        while (scope, buf) not in _REGISTRY:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return None
            _LOCK.wait(min(remaining, 0.25))
        return _REGISTRY.pop((scope, buf))


def reset_registry() -> None:
    """Test hook: drop every in-flight in-memory buffer."""
    with _LOCK:
        _REGISTRY.clear()
        _LOCK.notify_all()


def _encode(value: Any) -> bytes:
    bio = io.BytesIO()
    np.save(bio, np.asarray(value), allow_pickle=False)
    payload = bio.getvalue()
    digest = hashlib.sha256(payload).hexdigest().encode()
    return _MAGIC + b" " + digest + b"\n" + payload


def _decode(blob: bytes) -> Optional[np.ndarray]:
    """Payload array, or None when the blob is torn/corrupt (header
    missing or digest mismatch) — the caller re-polls."""
    head, sep, payload = blob.partition(b"\n")
    if not sep or not head.startswith(_MAGIC + b" "):
        return None
    digest = head[len(_MAGIC) + 1:]
    if hashlib.sha256(payload).hexdigest().encode() != digest:
        return None
    try:
        return np.load(io.BytesIO(payload), allow_pickle=False)
    except Exception:
        return None


def _safe(buf: str) -> str:
    return "".join(c if c.isalnum() or c in "._-" else "_" for c in buf)


class ActivationTransport:
    """One stage process's window onto the DCN activation plane.

    Args:
      directory: shared directory for cross-process blobs (default:
        ``AUTODIST_MPMD_DIR``; empty = in-memory only, which reaches
        only stages in THIS process).
      channel: disambiguates replicas of the same pipeline — data-
        parallel rank r of every stage passes ``channel="dp<r>"`` so
        the per-replica transport grids never collide while all
        replicas keep the same IR buffer names (SPMD within a stage).
      cluster / peers: optional :class:`~autodist_tpu.cluster.Cluster`
        plus ``{stage_name: address}`` for cross-host pipelines — sends
        push the published blob to the consuming stage's host with the
        cluster's retry schedule (the ``PeerMirror`` push path).
      timeout_s: recv deadline (default ``AUTODIST_MPMD_TIMEOUT_S`` or
        :data:`DEFAULT_TIMEOUT_S`).
    """

    def __init__(self, directory: Optional[str] = None, *,
                 channel: str = "", cluster: Any = None,
                 peers: Optional[Dict[str, str]] = None,
                 timeout_s: Optional[float] = None,
                 poll_s: float = 0.002):
        if directory is None:
            directory = ENV.AUTODIST_MPMD_DIR.val or ""
        self.directory = directory
        self.channel = channel or ""
        self._cluster = cluster
        self._peers = dict(peers or {})
        env_t = ENV.AUTODIST_MPMD_TIMEOUT_S.val
        self.timeout_s = float(timeout_s if timeout_s is not None
                               else (env_t or DEFAULT_TIMEOUT_S))
        self.poll_s = float(poll_s)
        self._scope = f"{self.directory}|{self.channel}"
        if self.directory:
            os.makedirs(self._dir(), exist_ok=True)

    def _dir(self) -> str:
        return os.path.join(self.directory, self.channel) \
            if self.channel else self.directory

    def _path(self, buf: str) -> str:
        return os.path.join(self._dir(), _safe(buf) + ".act")

    # -- send -----------------------------------------------------------------

    def send(self, buf: str, value: Any, *, to_stage: str = "") -> None:
        """Publish ``value`` under the IR buffer name ``buf``.

        Always lands in the in-process registry (the fast path); when a
        directory is configured the blob is ALSO published atomically
        there (tmp + ``os.replace``, the torn-write-proof idiom), and —
        when ``to_stage`` maps to a remote peer — pushed to that host.
        """
        blob = _encode(value)
        _registry_put(self._scope, buf, blob)
        if not self.directory:
            return
        final = self._path(buf)
        fd, tmp = tempfile.mkstemp(dir=self._dir(), suffix=".tmp")
        try:
            with os.fdopen(fd, "wb") as f:
                f.write(blob)
            os.replace(tmp, final)   # atomic publish
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
        addr = self._peers.get(to_stage) if to_stage else None
        if addr and self._cluster is not None:
            self._cluster.remote_copy(final, final, addr)

    # -- recv -----------------------------------------------------------------

    def recv(self, buf: str, *, from_stage: str = "",
             timeout_s: Optional[float] = None) -> np.ndarray:
        """Block until a VALID blob for ``buf`` exists; consume it.

        The in-process registry is checked first (and woken by sends);
        the directory is polled otherwise.  A corrupt or torn blob is
        skipped and re-polled — upstream retransmits land under the
        same name via atomic replace.  Directory blobs are NOT deleted
        on consume: they persist until the producer's per-step
        :meth:`gc`, so a chaos-killed stage restarted mid-step re-reads
        the step's published activations instead of deadlocking its
        peers (the recovery drill in tests/integration/mpmd_train.py).
        Raises :class:`TransportTimeout` past the deadline (naming the
        IR buffer, so the supervisor's hang report and the transport
        error point at the same leg).
        """
        deadline = time.monotonic() + float(
            timeout_s if timeout_s is not None else self.timeout_s)
        if not self.directory:
            blob = _registry_take(self._scope, buf, deadline)
            if blob is None:
                raise TransportTimeout(
                    f"transport recv timed out waiting for {buf!r} "
                    f"(in-memory, {self.timeout_s:g}s)")
            val = _decode(blob)
            if val is None:
                raise TransportTimeout(
                    f"transport blob for {buf!r} is corrupt (in-memory)")
            return val
        path = self._path(buf)
        addr = self._peers.get(from_stage) if from_stage else None
        warned = False
        while True:
            with _LOCK:
                blob = _REGISTRY.pop((self._scope, buf), None)
            if blob is None and os.path.exists(path):
                try:
                    with open(path, "rb") as f:
                        blob = f.read()
                except OSError:
                    blob = None
            if blob is not None:
                val = _decode(blob)
                if val is not None:
                    return val
                if not warned:
                    logging.warning(
                        "transport: skipping corrupt blob for %s "
                        "(digest mismatch); re-polling", buf)
                    warned = True
            if time.monotonic() >= deadline:
                raise TransportTimeout(
                    f"transport recv timed out waiting for {buf!r} "
                    f"under {self._dir()}")
            if addr and self._cluster is not None:
                try:      # remote pull (retry schedule inside the cluster)
                    self._cluster.remote_fetch(path, path, addr)
                except Exception:
                    pass  # not there yet; keep polling
            time.sleep(self.poll_s)

    # -- housekeeping ----------------------------------------------------------

    def gc(self, prefix: str) -> int:
        """Drop every published buffer whose name starts with ``prefix``
        (e.g. a completed step's namespace); returns the count."""
        n = 0
        with _LOCK:
            for key in [k for k in _REGISTRY
                        if k[0] == self._scope and k[1].startswith(prefix)]:
                del _REGISTRY[key]
                n += 1
        if self.directory and os.path.isdir(self._dir()):
            tag = _safe(prefix)
            for name in os.listdir(self._dir()):
                if name.startswith(tag) and name.endswith(".act"):
                    try:
                        os.unlink(os.path.join(self._dir(), name))
                        n += 1
                    except OSError:
                        pass
        return n
