"""Stage partitioner for the MPMD pipeline runtime (jax-free).

Splits a layer catalog into per-stage programs — contiguous balanced
layer runs, one disjoint slice process group per stage — and builds THE
schedule-IR program both sides share: :func:`build_pipeline_ir` is the
single constructor the live :class:`~autodist_tpu.parallel.mpmd.runner.
StageRunner`, the static analyzer, the ``--simulate`` sweep, and the
bench modes all call, so the runtime's executed fingerprint and the
planner's predicted fingerprint are equal by construction (the
acceptance assertion in ``tests/test_mpmd.py``).

Naming is the :func:`~autodist_tpu.kernel.synchronization.schedule_ir.
stage_name` spelling — ``stage_of(stage_name(i) + "/" + name)`` recovers
the assignment, so hand-laid ``stage0/`` param groups, the chaos
``stage=`` filter, and auto-partitioned stages all lint identically.

Elastic resume across a stage-count change rides
:func:`preflight_stage_resize` — the pipeline analog of
:func:`~autodist_tpu.resilience.elastic.preflight_elastic`: layer
membership is a pure function of the catalog (never of the stage
count), so re-prefixing moves every parameter losslessly, and the new
program is verified before any process restarts (docs/pipeline.md).
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Mapping, Optional, Sequence, Tuple

import numpy as np

from autodist_tpu.const import MESH_AXIS_DATA
from autodist_tpu.kernel.synchronization import schedule_ir as sir
from autodist_tpu.resilience.elastic import ElasticResumeError

#: the sweep/partitioner prune rule for an inexpressible pipeline shape
#: — canonical home is the (jax-free, parallel-package-free) schedule
#: IR so ``--simulate`` can prune without importing this package;
#: re-exported here because the partitioner is the rule's raiser.
RULE_STAGE_MISMATCH = sir.RULE_STAGE_MISMATCH
stage_mismatch_reason = sir.stage_mismatch_reason

#: one catalog entry: (layer-local param name, shape, dtype).
CatalogEntry = Tuple[str, Tuple[int, ...], str]
#: per-layer parameter catalog: ``catalog[j]`` lists layer j's params.
Catalog = Tuple[Tuple[CatalogEntry, ...], ...]


def assign_layers(num_layers: int, num_stages: int
                  ) -> Tuple[Tuple[int, ...], ...]:
    """Contiguous balanced layer→stage assignment: ``L // S`` layers per
    stage, the first ``L % S`` stages carrying one extra (front-loading
    matches the 1F1B memory profile — early stages hold more in-flight
    activations, so giving them the spare layer rather than the spare
    bubble keeps the steady state dense)."""
    ln, s = int(num_layers), int(num_stages)
    if s < 1 or s > ln:
        raise ValueError(stage_mismatch_reason(s, s, ln)
                         or f"bad partition {ln} layers / {s} stages")
    base, extra = divmod(ln, s)
    out, start = [], 0
    for i in range(s):
        size = base + (1 if i < extra else 0)
        out.append(tuple(range(start, start + size)))
        start += size
    return tuple(out)


def strip_stage(name: str) -> str:
    """Remove a leading ``stage<i>/`` prefix (identity when absent) —
    the catalog-relative name that survives a stage-count change."""
    head, _, rest = (name or "").partition("/")
    return rest if rest and sir.stage_of(head) == head else name


def catalog_from_layers(layer_params: Sequence[Mapping[str, Any]]
                        ) -> Catalog:
    """Project per-layer param dicts to the mesh-free catalog the IR
    builder and the resize preflight consume."""
    out = []
    for layer in layer_params:
        out.append(tuple(
            (str(k), tuple(int(x) for x in np.shape(v)),
             str(np.asarray(v).dtype) if not hasattr(v, "dtype")
             else str(v.dtype))
            for k, v in sorted(layer.items())))
    return tuple(out)


@dataclass(frozen=True)
class StagePartition:
    """One resolved layer→stage assignment over a catalog."""

    num_stages: int
    layers: Tuple[Tuple[int, ...], ...]      # per stage, layer indices
    catalog: Catalog

    @property
    def num_layers(self) -> int:
        return len(self.catalog)

    def stage_of_layer(self, layer: int) -> int:
        for i, run in enumerate(self.layers):
            if layer in run:
                return i
        raise KeyError(f"layer {layer} outside the partition")

    def param_names(self, stage: int) -> Tuple[str, ...]:
        """This stage's fully-qualified (``stage<i>/l<j>/<name>``)
        parameter names, catalog order."""
        pre = sir.stage_name(stage)
        return tuple(f"{pre}/l{j}/{name}"
                     for j in self.layers[stage]
                     for name, _, _ in self.catalog[j])

    def to_meta(self) -> dict:
        """Serializable form for checkpoint/snapshot metadata."""
        return {"num_stages": int(self.num_stages),
                "layers": [list(run) for run in self.layers],
                "catalog": [[[n, list(sh), dt] for n, sh, dt in layer]
                            for layer in self.catalog]}

    @classmethod
    def from_meta(cls, meta: Mapping[str, Any]) -> "StagePartition":
        catalog = tuple(
            tuple((str(n), tuple(int(x) for x in sh), str(dt))
                  for n, sh, dt in layer)
            for layer in meta["catalog"])
        return cls(num_stages=int(meta["num_stages"]),
                   layers=tuple(tuple(int(j) for j in run)
                                for run in meta["layers"]),
                   catalog=catalog)


def partition_catalog(catalog: Catalog, num_stages: int) -> StagePartition:
    return StagePartition(num_stages=int(num_stages),
                          layers=assign_layers(len(catalog), num_stages),
                          catalog=tuple(catalog))


def partition_params(layer_params: Sequence[Mapping[str, Any]],
                     num_stages: int
                     ) -> Tuple[StagePartition, List[Dict[str, Any]]]:
    """Split per-layer param dicts into per-stage flat dicts keyed by
    the fully-qualified ``stage<i>/l<j>/<name>`` spelling (what the IR's
    :class:`~autodist_tpu.kernel.synchronization.schedule_ir.PlanFact`
    names and the ZeRO-1 bucket members carry)."""
    part = partition_catalog(catalog_from_layers(layer_params), num_stages)
    stages: List[Dict[str, Any]] = []
    for i, run in enumerate(part.layers):
        pre = sir.stage_name(i)
        stages.append({f"{pre}/l{j}/{k}": v
                       for j in run
                       for k, v in sorted(layer_params[j].items())})
    return part, stages


def restage_params(stage_params: Sequence[Mapping[str, Any]],
                   new_num_stages: int) -> List[Dict[str, Any]]:
    """Re-prefix saved per-stage params for a different stage count.

    Lossless and exact: the catalog-relative names (``l<j>/<name>``)
    are stage-independent, so the move is a pure rename + regroup.
    Raises :class:`ElasticResumeError` when two stages disagree about a
    layer (a torn snapshot) or the new count cannot split the layers.
    """
    by_layer: Dict[int, Dict[str, Any]] = {}
    for sp in stage_params:
        for name, v in sp.items():
            rel = strip_stage(name)
            head, _, pname = rel.partition("/")
            if not head.startswith("l") or not head[1:].isdigit():
                raise ElasticResumeError(
                    f"param {name!r} has no layer tag; cannot restage")
            j = int(head[1:])
            layer = by_layer.setdefault(j, {})
            if pname in layer:
                raise ElasticResumeError(
                    f"layer {j} param {pname!r} appears in two stage "
                    "snapshots; torn save")
            layer[pname] = v
    if sorted(by_layer) != list(range(len(by_layer))):
        raise ElasticResumeError(
            f"stage snapshots cover layers {sorted(by_layer)}; expected "
            f"a dense 0..{len(by_layer) - 1} catalog")
    ordered = [by_layer[j] for j in range(len(by_layer))]
    _, out = partition_params(ordered, new_num_stages)
    return out


# -- THE shared IR constructor ------------------------------------------------

@dataclass(frozen=True)
class PipelineProgram:
    """One pipeline's verified schedule program: the IR instance the
    runtime executes AND the facts that rebuilt it — carrying both lets
    any consumer re-derive the fingerprint from either side and assert
    they agree (``ir_from_facts``/``build_schedule_ir`` emit
    identically; ``facts_fingerprint`` hashes the input)."""

    ir: sir.ScheduleIR
    facts: Tuple[sir.PlanFact, ...]
    pipeline: Tuple[sir.PipelineFact, ...]
    partition: StagePartition
    axes: Dict[str, int] = field(default_factory=dict)
    guard: bool = False

    def fingerprint(self) -> str:
        """The STATIC side: hash of the fact inputs (the search's
        dedupe key) — must equal what a fresh ``ir_from_facts`` build
        from the same facts executes."""
        return sir.facts_fingerprint(
            list(self.facts), axes=dict(self.axes),
            accum_steps=int(self.ir.accum_steps), guard=self.guard,
            pipeline=list(self.pipeline))


def build_pipeline_ir(*, layer_params: Optional[Sequence[Mapping[str, Any]]]
                      = None, catalog: Optional[Catalog] = None,
                      num_stages: int, num_microbatches: int,
                      act_nbytes: int, data_axis: int = 1,
                      num_virtual: int = 1, key: str = "pipe",
                      act_dtype: str = "float32",
                      compressor: Optional[str] = None,
                      zero1: bool = False, bucket_bytes: int = 0,
                      guard: bool = False) -> PipelineProgram:
    """Build the ONE schedule program an MPMD pipeline runs.

    Per-stage parameters become :class:`PlanFact`\\ s with ``group`` =
    stage index (buckets never merge across stages — each stage's
    gradient sync is its own process group) and ``sync_mode`` =
    ``reduce_scatter`` when ``zero1`` (the bucketed ZeRO-1 data-parallel
    sync the StageRunner composes within each stage).  The transport
    grid is one :class:`PipelineFact` (wire knob:
    :func:`~autodist_tpu.kernel.synchronization.schedule_ir.
    pipeline_wire_compressor_default`).  ``accum_steps`` is pinned to
    ``num_microbatches`` so the cost model's slot-hiding rule exposes
    only the steady-state bubble's last-slot legs.
    """
    if catalog is None:
        if layer_params is None:
            raise ValueError("build_pipeline_ir needs layer_params or "
                             "catalog")
        catalog = catalog_from_layers(layer_params)
    reason = stage_mismatch_reason(num_stages, num_microbatches,
                                   len(catalog))
    if reason is not None:
        raise ValueError(reason)
    part = partition_catalog(catalog, num_stages)
    facts: List[sir.PlanFact] = []
    for i, run in enumerate(part.layers):
        pre = sir.stage_name(i)
        for j in run:
            for name, shape, dtype in catalog[j]:
                facts.append(sir.PlanFact(
                    name=f"{pre}/l{j}/{name}", shape=tuple(shape),
                    dtype=str(dtype), sync_kind="AllReduce",
                    group=i,
                    sync_mode="reduce_scatter" if zero1 else "all_reduce",
                    bucket_bytes=int(bucket_bytes)))
    pipe: Tuple[sir.PipelineFact, ...] = ()
    if int(num_stages) > 1:
        pipe = (sir.PipelineFact(
            key=str(key), num_stages=int(num_stages),
            num_microbatches=int(num_microbatches),
            act_nbytes=int(act_nbytes), num_virtual=int(num_virtual),
            dtype=str(act_dtype),
            compressor=compressor
            or sir.pipeline_wire_compressor_default()),)
    axes = {MESH_AXIS_DATA: max(int(data_axis), 1)}
    ir = sir.ir_from_facts(facts, axes=axes,
                           accum_steps=int(num_microbatches),
                           guard=guard, pipeline=list(pipe))
    return PipelineProgram(ir=ir, facts=tuple(facts), pipeline=pipe,
                           partition=part, axes=axes, guard=guard)


# -- elastic resume across a stage-count change -------------------------------

def preflight_stage_resize(meta: Mapping[str, Any], *, num_stages: int,
                           num_microbatches: Optional[int] = None,
                           data_axis: int = 1,
                           zero1: Optional[bool] = None
                           ) -> PipelineProgram:
    """Validate a stage-count change BEFORE any process restarts — the
    pipeline analog of :func:`~autodist_tpu.resilience.elastic.
    preflight_elastic` (docs/resilience.md "Elastic resume").

    ``meta`` is what :meth:`~autodist_tpu.parallel.mpmd.runner.
    StageRunner.meta` records next to snapshots: the partition
    (:meth:`StagePartition.to_meta`), ``num_microbatches``,
    ``act_nbytes``, and optionally ``zero1``.  Raises
    :class:`ElasticResumeError` when the new shape is inexpressible;
    returns the VERIFIED new program otherwise (its fingerprint is what
    the restarted runners must execute)."""
    part = StagePartition.from_meta(meta["partition"]
                                    if "partition" in meta else meta)
    m = int(num_microbatches if num_microbatches is not None
            else meta["num_microbatches"])
    reason = stage_mismatch_reason(num_stages, m, part.num_layers)
    if reason is not None:
        raise ElasticResumeError(reason)
    z = bool(meta.get("zero1", False)) if zero1 is None else bool(zero1)
    prog = build_pipeline_ir(
        catalog=part.catalog, num_stages=int(num_stages),
        num_microbatches=m, act_nbytes=int(meta.get("act_nbytes", 0)),
        data_axis=data_axis, key=str(meta.get("key", "pipe")),
        act_dtype=str(meta.get("act_dtype", "float32")), zero1=z,
        bucket_bytes=int(meta.get("bucket_bytes", 0)))
    errs = sir.errors(sir.verify(prog.ir))
    if errs:
        raise ElasticResumeError(
            f"restaged schedule fails verification: {errs[0].rule}: "
            f"{errs[0].message}")
    return prog
