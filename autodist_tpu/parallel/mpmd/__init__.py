"""MPMD pipeline runtime: per-stage programs on separate slices with
DCN activation transport (docs/pipeline.md).

Three pieces, one verified program:

* :mod:`.partition` — the stage partitioner and
  :func:`~autodist_tpu.parallel.mpmd.partition.build_pipeline_ir`, THE
  shared schedule-IR constructor (runtime, analyzer, ``--simulate``,
  bench all call it, so static and runtime fingerprints agree by
  construction);
* :mod:`.transport` — the DCN activation/gradient plane (atomic
  digest-checked blobs with an in-memory fast path, on the PR 12 retry
  transport);
* :mod:`.runner` — the per-stage 1F1B jit loop with flight-recorder
  cursors on every transport leg and ZeRO-1 bucketed sync within the
  stage.
"""
from autodist_tpu.parallel.mpmd.partition import (
    RULE_STAGE_MISMATCH,
    PipelineProgram,
    StagePartition,
    assign_layers,
    build_pipeline_ir,
    catalog_from_layers,
    partition_catalog,
    partition_params,
    preflight_stage_resize,
    restage_params,
    stage_mismatch_reason,
    strip_stage,
)
from autodist_tpu.parallel.mpmd.transport import (
    ActivationTransport,
    TransportTimeout,
)


def __getattr__(name):
    # The runner is the only jax-importing piece; load it lazily so the
    # mesh-free consumers (--simulate sweeps, the analyzer, the
    # verifier goldens) can use the partitioner without paying — or
    # even having — a jax import.
    if name in ("StageRunner", "make_zero1_update"):
        from autodist_tpu.parallel.mpmd import runner
        return getattr(runner, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")

__all__ = [
    "ActivationTransport",
    "PipelineProgram",
    "RULE_STAGE_MISMATCH",
    "StagePartition",
    "StageRunner",
    "TransportTimeout",
    "assign_layers",
    "build_pipeline_ir",
    "catalog_from_layers",
    "make_zero1_update",
    "partition_catalog",
    "partition_params",
    "preflight_stage_resize",
    "restage_params",
    "stage_mismatch_reason",
    "strip_stage",
]
