"""StageRunner: one MPMD stage's jit loop under the 1F1B schedule.

Each pipeline stage is its OWN program — its own process group (its
own ``jax.distributed`` world on its own slice), its own params, its
own data-parallel gradient sync — and the ONLY cross-stage coupling is
the DCN activation plane (:mod:`.transport`).  The runner executes the
interleaved 1F1B tick loop whose transport grid
:func:`~autodist_tpu.kernel.synchronization.schedule_ir.
_emit_pipeline_legs` emitted: per tick it forwards microbatch
``t - s`` and backwards microbatch ``t - 2(S-1) + s``, so only the
schedule's steady-state bubble is exposed — never an extra
serialization the IR didn't price.

The runner executes the SAME :class:`~autodist_tpu.parallel.mpmd.
partition.PipelineProgram` instance the static side verifies and
prices: ``assert_verified`` gates construction, every transport
recv/send stamps a flight-recorder cursor with the IR leg id (so
``localize_hang`` names the wedged stage and frontier ``recv_act``
leg), and the executed ``ir.fingerprint()`` is exported for the
static-vs-runtime equality assertion.

Data parallelism within a stage composes two ways, mirroring the IR's
two lowerings: per-leaf ``pmean`` (the psum-tree legs) or bucketed
ZeRO-1 — flat-packed buckets reduce-scattered over the stage's data
axis, the 1/d owner shard SGD-updated, and all-gathered back (the
``reduce_scatter`` bucket legs; :func:`make_zero1_update` is the
jitted collective, unit-testable against its d=1 degenerate form).
"""
from __future__ import annotations

import os
import tempfile
from typing import Any, Callable, Dict, List, Mapping, Optional, Sequence

import numpy as np

from autodist_tpu.const import MESH_AXIS_DATA
from autodist_tpu.kernel.synchronization import schedule_ir as sir
from autodist_tpu.parallel.mpmd.partition import PipelineProgram
from autodist_tpu.parallel.mpmd.transport import ActivationTransport
from autodist_tpu.telemetry import flightrec
from autodist_tpu.utils import logging


def _step_ns(step: int) -> str:
    """Transport namespace for one step: buffers are reused every step,
    so the step tag keeps step k+1's sends from colliding with step k's
    unconsumed blobs (and keeps step k's blobs re-readable for the
    chaos-restart path until :meth:`StageRunner._gc` retires them)."""
    return f"s{int(step)}/"


def make_zero1_update(mesh, lr: float, num_shards: int) -> Callable:
    """The jitted ZeRO-1 bucket update: ``(grad_stack [d, P] sharded
    over data, params_flat [P] replicated) -> new params_flat``.

    reduce-scatter the summed gradient (mean over the d data shards),
    SGD-update only this rank's 1/d owner shard, all-gather the
    updated vector — the collective sequence of the IR's
    ``reduce_scatter`` bucket legs."""
    import jax
    from jax import lax
    from jax.sharding import PartitionSpec as P

    from autodist_tpu.utils import compat

    d = max(int(num_shards), 1)

    def zstep(gstack, pflat):
        g = gstack[0]                               # my rank's full grad
        gsh = lax.psum_scatter(g, MESH_AXIS_DATA,
                               scatter_dimension=0, tiled=True) / d
        i = lax.axis_index(MESH_AXIS_DATA)
        shard = pflat.shape[0] // d
        psh = lax.dynamic_slice(pflat, (i * shard,), (shard,))
        nsh = (psh - lr * gsh).astype(pflat.dtype)
        return lax.all_gather(nsh, MESH_AXIS_DATA, tiled=True)

    return jax.jit(compat.shard_map(
        zstep, mesh=mesh, in_specs=(P(MESH_AXIS_DATA), P()),
        out_specs=P(), axis_names={MESH_AXIS_DATA}, check_vma=False))


class StageRunner:
    """Drive one stage's 1F1B loop over a verified pipeline program.

    Args:
      program: the :func:`~autodist_tpu.parallel.mpmd.partition.
        build_pipeline_ir` output — the runner executes ``program.ir``
        as-is and refuses an unverifiable one.
      stage: this process group's stage index.
      stage_fn: ``(params_dict, x_mb) -> y_mb`` for THIS stage's params.
      params: the stage's flat param dict (``stage<i>/l<j>/<name>``
        keys, the :func:`partition_params` layout).
      transport: the stage's :class:`ActivationTransport` (channel
        already set to this data-parallel rank).
      loss_fn: ``(y_mb, target_mb) -> scalar`` — last stage only; the
        step loss is the MEAN over microbatches (the ``one_f_one_b``
        oracle contract).
      mesh: jax mesh with a ``data`` axis when the stage group is
        data-parallel (d > 1 requires ``jax.process_count() > 1`` — one
        DP rank per process); None for d = 1.
      zero1: bucketed ZeRO-1 sync/update instead of per-leaf pmean.
      state_dir: where per-step snapshots land (enables the bit-exact
        chaos-restart path); None disables snapshotting.
      chaos: a :class:`~autodist_tpu.resilience.chaos.ChaosMonkey`
        (default: from ``AUTODIST_CHAOS``) fired at step boundaries —
        its ``stage=`` filter matches this runner via the
        ``AUTODIST_STAGE`` stamp.
    """

    def __init__(self, program: PipelineProgram, stage: int, *,
                 stage_fn: Callable, params: Mapping[str, Any],
                 transport: ActivationTransport, lr: float = 0.1,
                 loss_fn: Optional[Callable] = None, mesh: Any = None,
                 zero1: bool = False, state_dir: Optional[str] = None,
                 chaos: Any = None, step: int = 0):
        self.program = program
        self.stage = int(stage)
        self.num_stages = int(program.partition.num_stages)
        if not 0 <= self.stage < self.num_stages:
            raise ValueError(f"stage {stage} outside 0.."
                             f"{self.num_stages - 1}")
        pf = program.pipeline[0] if program.pipeline else None
        self.key = pf.key if pf else "pipe"
        self.num_microbatches = int(pf.num_microbatches if pf
                                    else program.ir.accum_steps)
        self.stage_fn = stage_fn
        self.params: Dict[str, Any] = dict(params)
        self.transport = transport
        self.lr = float(lr)
        self.loss_fn = loss_fn
        self.mesh = mesh
        self.zero1 = bool(zero1)
        self.state_dir = state_dir
        self.step = int(step)
        self.d = int(program.ir.axes.get(MESH_AXIS_DATA, 1))
        if self.stage == self.num_stages - 1 and loss_fn is None:
            raise ValueError("last stage needs loss_fn")
        # The runtime executes EXACTLY the verified instance: gate on
        # the same verifier the analyzer runs, then export the executed
        # fingerprint for the static-vs-runtime equality assertion.
        sir.assert_verified(program.ir,
                            context=f"mpmd:{sir.stage_name(self.stage)}")
        self.fingerprint = program.ir.fingerprint()
        flightrec.set_fingerprint(self.fingerprint)
        # Stamp the stage identity: the chaos `stage=` filter, the
        # telemetry journal, and subprocesses all read this.
        os.environ["AUTODIST_STAGE"] = sir.stage_name(self.stage)
        if chaos is None:
            from autodist_tpu.resilience.chaos import ChaosMonkey

            chaos = ChaosMonkey.from_env()
        self._chaos = chaos
        self._zupdate = None
        if state_dir:
            os.makedirs(state_dir, exist_ok=True)
            self.maybe_restore()

    # -- the 1F1B tick loop ----------------------------------------------------

    def run_step(self, x_mbs: Optional[Sequence[Any]] = None,
                 tgt_mbs: Optional[Sequence[Any]] = None) -> float:
        """One training step: M microbatches through the interleaved
        1F1B schedule, gradient sync + SGD update, snapshot, chaos
        hook.  Returns the step's mean loss (0.0 off the last stage)."""
        import jax
        import jax.numpy as jnp

        s, s_n = self.stage, self.num_stages
        m_n = self.num_microbatches
        first, last = s == 0, s == s_n - 1
        if first and (x_mbs is None or len(x_mbs) != m_n):
            raise ValueError(f"stage 0 needs {m_n} input microbatches")
        if last and (tgt_mbs is None or len(tgt_mbs) != m_n):
            raise ValueError(f"last stage needs {m_n} target microbatches")
        ns = _step_ns(self.step)
        pid = f"pipe/{self.key}"
        drain = 2 * (s_n - 1)
        stash: Dict[int, Any] = {}     # mb -> (y, pullback)
        grads = None
        loss_acc = 0.0
        for t in range(sir.schedule_ticks_1f1b(s_n, m_n, 1)):
            jf = t - s
            jb = t - drain + s
            if 0 <= jf < m_n:
                if first:
                    x_in = jnp.asarray(x_mbs[jf])
                else:
                    x_in = jnp.asarray(self._recv(
                        ns, f"act:{self.key}/f{s - 1}@{jf}",
                        f"{pid}/f{s - 1}@{jf}/recv", sir.LEG_RECV_ACT, jf,
                        from_stage=sir.stage_name(s - 1)))
                y, pull = jax.vjp(
                    lambda p, xx: self.stage_fn(p, xx), self.params, x_in)
                stash[jf] = (y, pull)
                if not last:
                    self._send(ns, f"act:{self.key}/f{s}@{jf}",
                               f"{pid}/f{s}@{jf}/send", sir.LEG_SEND_ACT,
                               jf, y, to_stage=sir.stage_name(s + 1))
            if 0 <= jb < m_n:
                y, pull = stash.pop(jb)
                if last:
                    loss_j, lpull = jax.vjp(
                        lambda yy: self.loss_fn(yy, tgt_mbs[jb]), y)
                    (ct,) = lpull(jnp.ones_like(loss_j) / m_n)
                    loss_acc += float(loss_j) / m_n
                else:
                    ct = jnp.asarray(self._recv(
                        ns, f"act:{self.key}/b{s}@{jb}",
                        f"{pid}/b{s}@{jb}/recv", sir.LEG_RECV_ACT, jb,
                        from_stage=sir.stage_name(s + 1)), y.dtype)
                dp, dx = pull(ct)
                grads = dp if grads is None else jax.tree_util.tree_map(
                    lambda a, b: a + b, grads, dp)
                if not first:
                    self._send(ns, f"act:{self.key}/b{s - 1}@{jb}",
                               f"{pid}/b{s - 1}@{jb}/send",
                               sir.LEG_SEND_ACT, jb, dx,
                               to_stage=sir.stage_name(s - 1))
        loss = self._sync_and_update(grads, loss_acc)
        self.step += 1
        if self.state_dir:
            self.save_state()
        self._chaos.on_step(self.step - 1)
        self._gc()
        return loss

    def _recv(self, ns: str, buf: str, leg: str, leg_kind: str,
              slot: int, *, from_stage: str) -> np.ndarray:
        flightrec.record_cursor(leg, kind="leg", leg_kind=leg_kind,
                                slot=slot, event="enter", step=self.step)
        try:
            return self.transport.recv(ns + buf, from_stage=from_stage)
        finally:
            flightrec.record_cursor(leg, kind="leg", leg_kind=leg_kind,
                                    slot=slot, event="exit",
                                    step=self.step)

    def _send(self, ns: str, buf: str, leg: str, leg_kind: str,
              slot: int, value: Any, *, to_stage: str) -> None:
        flightrec.record_cursor(leg, kind="leg", leg_kind=leg_kind,
                                slot=slot, event="enter", step=self.step)
        self.transport.send(ns + buf, np.asarray(value), to_stage=to_stage)
        flightrec.record_cursor(leg, kind="leg", leg_kind=leg_kind,
                                slot=slot, event="exit", step=self.step)

    def _gc(self) -> None:
        """Retire the PREVIOUS step's transport blobs: the just-
        finished step's stay published so a chaos-restarted peer can
        replay it (transport.recv's non-consuming contract)."""
        if self.step >= 2:
            self.transport.gc(_step_ns(self.step - 2))

    # -- gradient sync + update ------------------------------------------------

    def _sync_and_update(self, grads, loss_local: float) -> float:
        import jax
        import jax.numpy as jnp

        names = sorted(self.params)
        if self.d <= 1:
            for n in names:
                p = np.asarray(self.params[n])
                g = np.asarray(grads[n], np.float32)
                self.params[n] = jnp.asarray(
                    (p.astype(np.float32) - self.lr * g).astype(p.dtype))
            return loss_local
        if jax.process_count() <= 1:
            raise RuntimeError(
                "StageRunner data parallelism maps one DP rank per "
                "process; build the stage group with jax.distributed "
                "(d=%d, process_count=1)" % self.d)
        from jax.sharding import NamedSharding, PartitionSpec as P

        shard = NamedSharding(self.mesh, P(MESH_AXIS_DATA))
        rep = NamedSharding(self.mesh, P())
        # step loss: mean over the stage group's DP ranks
        lstack = jax.make_array_from_process_local_data(
            shard, np.asarray([loss_local], np.float32))
        loss = float(jax.jit(lambda a: jnp.mean(a),
                             out_shardings=rep)(lstack))
        if self.zero1 and self.program.ir.buckets:
            self._zero1_update(grads)
        else:
            # per-leaf pmean — the per-variable psum-tree lowering
            mean = jax.jit(lambda a: jnp.mean(a, axis=0),
                           out_shardings=rep)
            for n in names:
                g = np.asarray(grads[n], np.float32)
                gstack = jax.make_array_from_process_local_data(
                    shard, g[None])
                gm = np.asarray(mean(gstack))
                p = np.asarray(self.params[n])
                self.params[n] = jnp.asarray(
                    (p - self.lr * gm).astype(p.dtype))
        return loss

    def _zero1_update(self, grads) -> None:
        """Bucketed ZeRO-1: pack this stage's grads/params into the
        IR's planned flat buckets, run the reduce-scatter → shard
        update → all-gather collective, unpack."""
        import jax
        import jax.numpy as jnp

        if self._zupdate is None:
            self._zupdate = make_zero1_update(self.mesh, self.lr, self.d)
        from jax.sharding import NamedSharding, PartitionSpec as P

        shard = NamedSharding(self.mesh, P(MESH_AXIS_DATA))
        mine = set(self.params)
        for node in self.program.ir.buckets:
            members = [v for v in node["vars"] if v["name"] in mine]
            if not members:
                continue   # another stage's bucket
            pt = int(node["padded_total"])
            gflat = np.zeros((pt,), np.float32)
            pflat = np.zeros((pt,), np.float32)
            off = 0
            spans = []
            for v in members:
                arr = np.asarray(grads[v["name"]], np.float32).ravel()
                par = np.asarray(self.params[v["name"]],
                                 np.float32).ravel()
                gflat[off:off + arr.size] = arr
                pflat[off:off + par.size] = par
                spans.append((v["name"], off, arr.size))
                off += arr.size
            gstack = jax.make_array_from_process_local_data(
                shard, gflat[None])
            pnew = np.asarray(self._zupdate(gstack, jnp.asarray(pflat)))
            for name, start, size in spans:
                p = np.asarray(self.params[name])
                self.params[name] = jnp.asarray(
                    pnew[start:start + size].reshape(p.shape)
                    .astype(p.dtype))

    # -- snapshots (the chaos-restart path) ------------------------------------

    def _state_path(self) -> str:
        return os.path.join(self.state_dir,
                            f"{sir.stage_name(self.stage)}"
                            f"_{self.transport.channel or 'dp0'}.npz")

    def meta(self) -> dict:
        """What :func:`~autodist_tpu.parallel.mpmd.partition.
        preflight_stage_resize` needs to validate a stage-count change
        against this run."""
        pf = self.program.pipeline[0] if self.program.pipeline else None
        return {"partition": self.program.partition.to_meta(),
                "num_microbatches": int(self.num_microbatches),
                "act_nbytes": int(pf.act_nbytes) if pf else 0,
                "act_dtype": pf.dtype if pf else "float32",
                "key": self.key, "zero1": self.zero1,
                "schedule_fingerprint": self.fingerprint}

    def save_state(self) -> str:
        path = self._state_path()
        fd, tmp = tempfile.mkstemp(dir=self.state_dir, suffix=".tmp.npz")
        os.close(fd)
        arrays = {f"param:{n}": np.asarray(v)
                  for n, v in self.params.items()}
        np.savez(tmp, step=np.int64(self.step), **arrays)
        os.replace(tmp, path)   # atomic publish, the transport idiom
        return path

    def maybe_restore(self) -> bool:
        """Load the newest snapshot if one exists (the supervisor
        restart path); bit-exact — params land with their saved bytes."""
        import jax.numpy as jnp

        path = self._state_path()
        if not os.path.exists(path):
            return False
        try:
            with np.load(path, allow_pickle=False) as z:
                step = int(z["step"])
                params = {k[len("param:"):]: np.array(z[k])
                          for k in z.files if k.startswith("param:")}
        except Exception as e:
            logging.warning("mpmd: snapshot %s unreadable (%s); "
                            "starting fresh", path, e)
            return False
        if sorted(params) != sorted(self.params):
            logging.warning("mpmd: snapshot %s param catalog mismatch; "
                            "starting fresh", path)
            return False
        self.params = {n: jnp.asarray(v) for n, v in params.items()}
        self.step = step
        logging.info("mpmd: %s restored step %d from %s",
                     sir.stage_name(self.stage), step, path)
        return True
