"""One-forward-one-backward (1F1B) pipeline schedule with a hand-built
backward pass.

``pipeline.py`` differentiates the GPipe tick-scan with whole-program
autodiff: correct, but every microbatch's boundary activations stay
stashed until the scan's backward runs — O(M) live activations (remat
trims the per-tick internals, not the count).  The 1F1B schedule
(PipeDream-flush / Megatron-LM) interleaves each microbatch's backward
as soon as its forward clears the last stage, so a device holds at most
``2·(S−1)`` in-flight boundary activations — O(S), independent of M.

Schedule algebra (unit fwd+bwd per tick), including the **interleaved /
circular** variant (``num_virtual_stages=V``): device ``d`` holds the V
chunks at global stages ``v·S + d`` (the device-major layout shared with
``pipeline.py``), activations circulate the forward ring V times and
cotangents circulate the reverse ring V times:

* microbatch ``j`` is injected at device 0 at tick
  ``tj = (j//S)·S·V + j%S`` (S injections per ``S·V``-tick period — the
  circular-GPipe injection cadence, which keeps every device's forward
  slot dense);
* its forward runs global stage ``g = v·S + d`` at tick ``tj + g``;
* the last global stage (device S−1, chunk V−1) computes the
  per-microbatch loss AND its cotangent at the same tick its forward
  completes (``tj + SV − 1``);
* its backward runs global stage ``g`` at tick ``tj + 2(SV−1) − g`` —
  cotangents hop ``d → d−1`` on the reverse ring (the ``g ≡ 0 (mod S)``
  wraparound hop 0 → S−1 is exactly the ring's wraparound);
* every tick a device does (at most) one chunk-forward AND one
  chunk-backward: the eponymous 1F1B steady state.  Total ticks
  ``(M−1)//S·SV + (M−1)%S + 2(SV−1) + 1`` (= ``M + 2(S−1)`` at V=1).

Bubble accounting (``bubble_fraction_1f1b``): warmup+drain idle is
``SV + S − 2`` ticks of 1/V-size chunk work — in stage-work units
``S + (S−2)/V``, vs ``2(S−1)`` for V=1, so interleaving cuts the 1F1B
bubble toward its ``S``-stage-unit floor (S=4: 6 → 5 → 4.5 stage units
at V=1→2→4).  The Megatron-interleaved ``(S−1)/V`` bubble is NOT
reachable in this SPMD formulation: it needs per-device-divergent
forward/backward slots, but ``ppermute`` is a uniform collective — every
device must run the same tick body, so the floor is the ``2(SV−1)``-hop
ring latency of the last microbatch.  What interleaving buys here is the
warmup/drain HALF-idle ticks shrinking by V in work units, plus the same
O(S·V) (M-independent) activation stash.

Each device keeps a circular buffer of its saved chunk INPUTS (capacity
``2·S·V``, static; the maximum forward→backward span is ``2(SV−1)``
ticks); backward recomputes the chunk forward under ``jax.vjp`` from the
saved input — the recompute-based 1F1B every large-scale implementation
uses.

The public entry returns ``(mean_loss, d_stage_params, d_x)`` directly —
a manual value-and-grad over the pipeline — and is verified bit-close
against autodiff through ``pipeline_apply`` in ``tests/test_pipeline_1f1b.py``.
"""
from __future__ import annotations

import functools
from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from autodist_tpu.const import MESH_AXIS_DATA, MESH_AXIS_PIPE
# The tick/bubble algebra is pure and shared with the mesh-free side
# (schedule IR pricing, the --simulate sweep, the MPMD StageRunner), so
# it lives jax-free in schedule_ir; re-exported here for compatibility.
from autodist_tpu.kernel.synchronization.schedule_ir import (  # noqa: F401
    bubble_fraction_1f1b,
    schedule_ticks_1f1b,
)
from autodist_tpu.utils import compat


def one_f_one_b(stage_fn: Callable, loss_fn: Callable, stage_params: Any,
                x: jax.Array, targets: Any, mesh: Mesh, *,
                num_microbatches: int, loss_params: Any = None,
                num_virtual_stages: int = 1,
                axis_name: str = MESH_AXIS_PIPE):
    """Pipelined value-and-grad under the 1F1B schedule.

    Args:
      stage_fn: ``(params_one_stage, x_mb) -> y_mb``, activation-shape
        homogeneous across stages (the ``pipeline_apply`` contract).
      loss_fn: ``(y_mb, target_mb) -> scalar`` per-microbatch loss — or,
        with ``loss_params``, ``(loss_params, y_mb, target_mb) -> scalar``
        (the head/norm/logits that live AFTER the pipeline; their
        gradients accumulate on the last stage).  The total loss is the
        MEAN over microbatches.
      stage_params: pytree with a leading ``[S·V]`` stage axis — pipeline
        order for V=1, **device-major** for V>1 (entry ``d·V + v`` =
        global stage ``v·S + d``, the ``pipeline_apply`` /
        :func:`~autodist_tpu.parallel.pipeline.interleaved_stage_order`
        contract), sharded over ``axis_name``.
      x: global batch ``[B, ...]``; ``B % num_microbatches == 0``.  When
        the mesh carries a ``data`` axis the batch is data-sharded and
        the schedule composes with data parallelism: each shard runs its
        own 1F1B over its rows (``num_microbatches`` applies PER SHARD)
        and gradients/loss pmean over ``data``.
      targets: pytree of arrays with leading dim ``B`` (what ``loss_fn``
        consumes per microbatch).
      loss_params: optional pytree consumed by ``loss_fn``; replicated.
      num_virtual_stages: chunks per device (interleaved schedule — the
        module docstring's circular 1F1B); the stage axis must equal
        ``S · num_virtual_stages``.

    Returns ``(loss, d_stage_params, d_x)`` — or, with ``loss_params``,
    ``(loss, d_stage_params, d_loss_params, d_x)`` — gradients for the
    stacked stage params (same ``[S·V]``-leading layout), the loss-side
    params, and the batch input (so upstream layers, e.g. embeddings,
    keep training).
    """
    s = mesh.shape.get(axis_name, 1)
    v = num_virtual_stages
    m = num_microbatches
    b = x.shape[0]
    if v < 1:
        raise ValueError(f"num_virtual_stages must be >= 1, got {v}")
    if b % m:
        raise ValueError(f"batch {b} not divisible into {m} microbatches")
    for leaf in jax.tree_util.tree_leaves(targets):
        if leaf.shape[0] != b:
            raise ValueError(
                f"targets leading dim {leaf.shape[0]} != batch {b}")
    if m < s:
        raise ValueError(f"1F1B needs num_microbatches ({m}) >= stages ({s})")
    if s > 1:
        for leaf in jax.tree_util.tree_leaves(stage_params):
            if leaf.shape[0] != s * v:
                raise ValueError(
                    f"stage_params leading dim {leaf.shape[0]} != pipe axis "
                    f"{s} x {v} virtual stages")

    if s <= 1:
        # No pipe axis: plain scan + autodiff (nothing to schedule).
        def whole(sp, lp, x):
            def body(h, p):
                return stage_fn(p, h), None
            out, _ = lax.scan(body, x, sp)
            fn = loss_fn if loss_params is None \
                else functools.partial(loss_fn, lp)
            return jnp.mean(_loss_over_microbatches(fn, out, targets, m))
        loss, (dsp, dlp, dx) = jax.value_and_grad(whole, argnums=(0, 1, 2))(
            stage_params, loss_params, x)
        if loss_params is None:
            return loss, dsp, dx
        return loss, dsp, dlp, dx

    dp_axis = MESH_AXIS_DATA if (axis_name != MESH_AXIS_DATA and
                                 mesh.shape.get(MESH_AXIS_DATA, 1) > 1) \
        else None
    if dp_axis is not None:
        dsize = mesh.shape[MESH_AXIS_DATA]
        if b % (dsize * m):
            raise ValueError(
                f"batch {b} not divisible into {dsize} data shards x {m} "
                "microbatches")
    lp = {} if loss_params is None else loss_params
    # Device-major [S·V] → [S, V]: row d = device d's V chunks (a plain
    # reshape; contiguous 'pipe' sharding of the stored axis IS the
    # sharding of dim 0 here — no data movement).
    chunked = jax.tree_util.tree_map(
        lambda p: p.reshape((s, v) + p.shape[1:]), stage_params)
    out = _jitted_1f1b(stage_fn, loss_fn, mesh, m, v,
                       loss_params is not None, dp_axis, axis_name)(
        chunked, lp, x, targets)
    loss, dsp, dlp, dx = out
    # [S, V, ...] gradients back to the caller's [S·V, ...] layout.
    dsp = jax.tree_util.tree_map(
        lambda g, p: g.reshape(p.shape), dsp, stage_params)
    if loss_params is None:
        return loss, dsp, dx
    return loss, dsp, dlp, dx


def _loss_over_microbatches(loss_fn, out, targets, m):
    mb = out.reshape((m, out.shape[0] // m) + out.shape[1:])
    tb = jax.tree_util.tree_map(
        lambda t: t.reshape((m, t.shape[0] // m) + t.shape[1:]), targets)
    return jax.vmap(loss_fn)(mb, tb)


@functools.lru_cache(maxsize=None)
def _jitted_1f1b(stage_fn: Callable, loss_fn: Callable, mesh: Mesh,
                 num_microbatches: int, num_virtual: int,
                 has_loss_params: bool,
                 dp_axis, axis_name: str) -> Callable:
    # Cache keyed on (stage_fn, loss_fn) identity — pass stable callables
    # (same contract as pipeline._jitted_pipeline).  Partial-manual over
    # {pipe, data}: the batch additionally splits over ``dp_axis`` (each
    # data shard runs its own 1F1B over its rows; grads pmean over data),
    # while model/seq axes stay with GSPMD inside stage_fn.
    local = functools.partial(_local_1f1b, stage_fn, loss_fn,
                              axis_name=axis_name, m=num_microbatches,
                              nv=num_virtual,
                              has_lp=has_loss_params, dp_axis=dp_axis)
    bspec = P(dp_axis) if dp_axis else P()
    manual = {axis_name} | ({dp_axis} if dp_axis else set())
    return jax.jit(compat.shard_map(
        local, mesh=mesh,
        in_specs=(P(axis_name), P(), bspec, bspec),
        out_specs=(P(), P(axis_name), P(), bspec),
        axis_names=manual, check_vma=False,
    ))


def _local_1f1b(stage_fn: Callable, loss_fn: Callable, chunk_params: Any,
                loss_params: Any, x: jax.Array, targets: Any, *,
                axis_name: str, m: int, nv: int, has_lp: bool, dp_axis=None):
    """Per-device 1F1B loop (inside full-manual shard_map): ``x`` and
    ``targets`` arrive as this data shard's rows (replicated over the
    pipe axis); the schedule runs over the LOCAL rows, and gradients /
    loss pmean over ``dp_axis`` at the end.

    Schedule index algebra (module docstring): microbatch ``j`` is
    injected at ``tj = (j//S)·SV + j%S``; its forward at global stage
    ``g = v·S + d`` runs at tick ``tj + g`` and its backward at tick
    ``tj + 2(SV−1) − g``.  Inverting for (tick, device) gives exactly one
    forward chunk ``vf`` and one backward chunk ``vb`` per device per
    tick — both streams ride one uniform ppermute pair."""
    s = compat.axis_size(axis_name)
    d = lax.axis_index(axis_name)
    period = s * nv
    # chunk_params local shape [1, V, ...]: squeeze the device dim.
    params = jax.tree_util.tree_map(lambda p: jnp.squeeze(p, 0), chunk_params)

    mb = x.reshape((m, x.shape[0] // m) + x.shape[1:])       # [M, mb, ...]
    tgt = jax.tree_util.tree_map(
        lambda t: t.reshape((m, t.shape[0] // m) + t.shape[1:]), targets)
    zero_a = jnp.zeros_like(mb[0])
    k = 2 * s * nv                                            # stash slots
    stash0 = jnp.zeros((k,) + mb[0].shape, mb.dtype)
    dparams0 = jax.tree_util.tree_map(
        lambda p: jnp.zeros(p.shape, jnp.float32), params)    # [V, ...]
    dx0 = jnp.zeros_like(mb, jnp.float32)                     # [M, mb, ...]
    dlp0 = jax.tree_util.tree_map(
        lambda p: jnp.zeros(jnp.shape(p), jnp.float32), loss_params)

    fwd_perm = [(i, (i + 1) % s) for i in range(s)]
    bwd_perm = [(i, (i - 1) % s) for i in range(s)]
    vary = lambda v: compat.pcast(v, axis_name, to="varying")  # noqa: E731
    ticks = schedule_ticks_1f1b(int(s), m, nv)

    def chunk_at(v):
        return jax.tree_util.tree_map(
            lambda p: lax.dynamic_index_in_dim(p, v, 0, keepdims=False),
            params)

    def stage_vjp(p, xin, ct):
        y, pullback = jax.vjp(lambda pp, xx: stage_fn(pp, xx), p, xin)
        dp, dxin = pullback(ct.astype(y.dtype))
        return dp, dxin

    def tick(carry, t):
        a_in, g_in, stash, dparams, dlp, dx_bank, loss_acc = carry

        # ---- forward phase ------------------------------------------------
        # Chunk this device forwards now, the mb it belongs to, and its
        # injection tick (mod-arithmetic inversion; garbage when inactive).
        vf = jnp.mod(t - d, period) // s
        gf = vf * s + d                              # global stage
        tjf = t - gf                                 # injection tick
        jf = (tjf // period) * s + jnp.mod(tjf, s)   # mb this device fwd's
        active_f = jnp.logical_and(tjf >= 0, jf < m)
        feed = lax.dynamic_index_in_dim(mb, jnp.clip(jf, 0, m - 1), 0,
                                        keepdims=False)
        x_in = jnp.where(jnp.logical_and(d == 0, vf == 0), feed, a_in)
        y = stage_fn(chunk_at(vf), x_in)
        # save this tick's chunk INPUT for the backward recompute
        slot_f = jnp.mod(t, k)
        cur = lax.dynamic_index_in_dim(stash, slot_f, 0, keepdims=False)
        stash = lax.dynamic_update_index_in_dim(
            stash, jnp.where(active_f, x_in, cur), slot_f, 0)

        # last global stage (device S-1, chunk V-1): per-microbatch loss +
        # its cotangent, entering the backward stream THIS tick (bwd of mb
        # jf at stage SV-1 is tick tjf + 2(SV-1) - (SV-1) = tjf + SV-1 = t).
        tgt_j = jax.tree_util.tree_map(
            lambda tt: lax.dynamic_index_in_dim(
                tt, jnp.clip(jf, 0, m - 1), 0, keepdims=False), tgt)
        is_last = jnp.logical_and(d == s - 1, vf == nv - 1)
        if has_lp:
            loss_j, loss_pull = jax.vjp(
                lambda lp, yy: loss_fn(lp, yy, tgt_j), loss_params, y)
            dlp_j, dy_loss = loss_pull(jnp.float32(1.0 / m))
            # loss-side param grads accumulate on the LAST stage only, at
            # the microbatch's loss tick (where-mask: see below).
            last_active = jnp.logical_and(is_last, active_f)
            dlp = jax.tree_util.tree_map(
                lambda a, g: a + jnp.where(last_active,
                                           g.astype(jnp.float32), 0.0),
                dlp, dlp_j)
        else:
            loss_j, loss_pull = jax.vjp(lambda yy: loss_fn(yy, tgt_j), y)
            (dy_loss,) = loss_pull(jnp.float32(1.0 / m))
        loss_acc = loss_acc + jnp.where(
            jnp.logical_and(is_last, active_f), loss_j / m, 0.0)

        # ---- backward phase ----------------------------------------------
        # Invert tb = tj + 2(SV-1) - g for (t, d): vb is the unique chunk
        # with (t + d - 2(SV-1) + vb·S) an injection tick (mod period < S).
        u = t + d - 2 * (s * nv - 1)
        vb = jnp.mod(-(jnp.mod(u, period) // s), nv)
        gb = vb * s + d
        tjb = u + vb * s
        jb = (tjb // period) * s + jnp.mod(tjb, s)   # mb this device bwd's
        active_b = jnp.logical_and(tjb >= 0, jb < m)
        # cotangent: locally generated at the last global stage, ring-
        # arriving everywhere else
        fresh_ct = jnp.logical_and(d == s - 1, vb == nv - 1)
        ct = jnp.where(fresh_ct, dy_loss.astype(jnp.float32),
                       g_in.astype(jnp.float32))
        # retrieve the saved chunk input of mb jb (saved at tick tjb + gb)
        slot_b = jnp.mod(tjb + gb, k)
        x_saved = lax.dynamic_index_in_dim(stash, slot_b, 0, keepdims=False)
        dp, dxin = stage_vjp(chunk_at(vb), x_saved, ct)
        # where-mask, not multiply: inactive ticks can compute on garbage
        # (NaN-capable) values, and 0 * NaN = NaN would poison the sums.
        dparams = jax.tree_util.tree_map(
            lambda a, g: a.at[vb].add(
                jnp.where(active_b, g.astype(jnp.float32), 0.0)),
            dparams, dp)
        # device 0 chunk 0's dxin is the gradient w.r.t. the injected mb
        bank = jnp.logical_and(jnp.logical_and(d == 0, vb == 0), active_b)
        slot_x = jnp.clip(jb, 0, m - 1)
        cur_dx = lax.dynamic_index_in_dim(dx_bank, slot_x, 0, keepdims=False)
        dx_bank = lax.dynamic_update_index_in_dim(
            dx_bank, jnp.where(bank, dxin.astype(jnp.float32), cur_dx),
            slot_x, 0)

        a_next = lax.ppermute(y, axis_name, fwd_perm)
        g_next = lax.ppermute(dxin.astype(jnp.float32), axis_name, bwd_perm)
        return (a_next, g_next, stash, dparams, dlp, dx_bank, loss_acc), None

    carry0 = (vary(zero_a), vary(jnp.zeros_like(zero_a, jnp.float32)),
              vary(stash0), vary(dparams0), vary(dlp0), vary(dx0),
              vary(jnp.float32(0)))
    (a, g, stash, dparams, dlp, dx_bank, loss_acc), _ = lax.scan(
        tick, carry0, jnp.arange(ticks))

    # loss lives on the last device; dx on device 0 — replicate via psum.
    loss = lax.psum(jnp.where(d == s - 1, loss_acc, 0.0), axis_name)
    dx = lax.psum(jnp.where(d == 0, dx_bank, jnp.zeros_like(dx_bank)),
                  axis_name)
    dx = dx.reshape((dx.shape[0] * dx.shape[1],) + dx.shape[2:])
    # loss-side grads live on the last device; replicate over pipe.
    dlp = jax.tree_util.tree_map(
        lambda g: lax.psum(jnp.where(d == s - 1, g, jnp.zeros_like(g)),
                           axis_name), dlp)
    if dp_axis is not None:
        # Each data shard computed d(mean over ITS rows); the global loss
        # is the mean over shards, so everything averages over data —
        # except dx, whose rows are shard-local: scale by 1/D.
        dsize = compat.axis_size(dp_axis)
        loss = lax.pmean(loss, dp_axis)
        dparams = jax.tree_util.tree_map(
            lambda g: lax.pmean(g, dp_axis), dparams)
        dlp = jax.tree_util.tree_map(lambda g: lax.pmean(g, dp_axis), dlp)
        dx = dx / dsize
    # Accumulation ran in f32; return grads in the primal dtypes (what
    # autodiff — and the s==1 fallback — would produce).
    dx = dx.astype(x.dtype)
    # dparams stays device-local: out_specs P(axis_name) restacks the [S]
    # axis exactly like the incoming stage_params layout.
    dparams = jax.tree_util.tree_map(
        lambda g, p: g[None].astype(p.dtype), dparams, params)
    dlp = jax.tree_util.tree_map(
        lambda g, p: g.astype(jnp.result_type(p)), dlp, loss_params)
    return loss, dparams, dlp, dx
