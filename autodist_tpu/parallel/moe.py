"""Expert parallelism: mixture-of-experts FFN over the ``expert`` mesh axis.

Absent from the reference (SURVEY §2.8: EP/MoE NO); new first-class scope.

Formulation: GShard/Switch-style capacity-based routing (Lepikhin et al.
2020, arxiv 2006.16668) expressed as dense einsums over one-hot dispatch/
combine tensors — the TPU-idiomatic MoE: static shapes (capacity bounds the
per-expert token count), MXU-friendly batched expert matmuls, and GSPMD
inserts the expert all-to-alls from the sharding constraints alone
(expert-major tensors lead with the ``expert`` axis; no hand-written
``lax.all_to_all`` needed, though the layout is exactly the all-to-all
dispatch of DeepSpeed-MoE/Tutel-style implementations).

Router runs in fp32 (bf16 softmax over experts is noisy enough to flip
top-k decisions).  The auxiliary load-balancing loss is returned to the
caller — models fold it into the training loss.
"""
from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from autodist_tpu.const import MESH_AXIS_DATA, MESH_AXIS_EXPERT


def init_moe_params(rng, d_model: int, d_ff: int, num_experts: int,
                    dtype=jnp.float32) -> dict:
    """Router + stacked expert FFN weights (leading ``[E]`` axis — flag these
    via ``expert_vars`` so the compiler shards it over ``expert``)."""
    r_router, r_wi, r_wo = jax.random.split(rng, 3)
    scale_in = 1.0 / (d_model ** 0.5)
    scale_out = 1.0 / (d_ff ** 0.5)
    return {
        "router": (jax.random.normal(r_router, (d_model, num_experts),
                                     jnp.float32) * scale_in),
        "wi": (jax.random.normal(r_wi, (num_experts, d_model, d_ff),
                                 dtype) * scale_in),
        "wo": (jax.random.normal(r_wo, (num_experts, d_ff, d_model),
                                 dtype) * scale_out),
    }


def _top2_dispatch(probs: jax.Array, capacity: int
                   ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """probs [G, S, E] → (dispatch [G,S,E,C] bool, combine [G,S,E,C], aux).

    G = groups (batch), S = tokens per group, E = experts, C = capacity.
    Tokens overflowing an expert's capacity within their group are dropped
    (their combine weight is zero — the residual connection carries them).
    """
    g, s, e = probs.shape

    idx1 = jnp.argmax(probs, axis=-1)                       # [G,S]
    mask1 = jax.nn.one_hot(idx1, e, dtype=probs.dtype)      # [G,S,E]
    probs_wo1 = probs * (1.0 - mask1)
    idx2 = jnp.argmax(probs_wo1, axis=-1)
    mask2 = jax.nn.one_hot(idx2, e, dtype=probs.dtype)
    if e == 1:
        # Single expert: argmax over all-zero probs_wo1 re-selects expert 0,
        # which would double-book two capacity slots per token.
        mask2 = jnp.zeros_like(mask2)

    # Positions within each expert's buffer, first-come-first-served along
    # the token axis; second choices queue after all first choices.
    pos1 = jnp.cumsum(mask1, axis=1) - mask1                # [G,S,E]
    pos2 = jnp.cumsum(mask2, axis=1) - mask2 \
        + jnp.sum(mask1, axis=1, keepdims=True)
    keep1 = mask1 * (pos1 < capacity)
    keep2 = mask2 * (pos2 < capacity)

    w1 = jnp.sum(probs * keep1, axis=-1)                    # [G,S]
    w2 = jnp.sum(probs * keep2, axis=-1)
    denom = jnp.maximum(w1 + w2, 1e-9)
    w1, w2 = w1 / denom, w2 / denom

    oh1 = jax.nn.one_hot(jnp.sum(pos1 * keep1, axis=-1).astype(jnp.int32),
                         capacity, dtype=probs.dtype)       # [G,S,C]
    oh2 = jax.nn.one_hot(jnp.sum(pos2 * keep2, axis=-1).astype(jnp.int32),
                         capacity, dtype=probs.dtype)
    combine = (w1[..., None, None] * keep1[..., None] * oh1[:, :, None]
               + w2[..., None, None] * keep2[..., None] * oh2[:, :, None])
    dispatch = combine > 0.0                                # [G,S,E,C]

    # Load-balancing aux loss (GShard eq. 4): fraction of tokens routed to
    # each expert × mean router probability, summed over experts, scaled E.
    frac = jnp.mean(mask1, axis=1)                          # [G,E]
    prob_mean = jnp.mean(probs, axis=1)                     # [G,E]
    aux = jnp.mean(jnp.sum(frac * prob_mean, axis=-1)) * e
    return dispatch, combine, aux


def moe_ffn(params: dict, x: jax.Array, *,
            capacity_factor: float = 2.0,
            mesh: Optional[Mesh] = None,
            activation=jax.nn.gelu) -> Tuple[jax.Array, jax.Array]:
    """Top-2 routed expert FFN.

    Args:
      params: dict from :func:`init_moe_params`.
      x: ``[batch, seq, d_model]``.
      capacity_factor: expert buffer size = ``cf · S / E`` per group.
      mesh: optional — adds sharding constraints so expert-major
        intermediates shard over ``expert`` (and groups over ``data``),
        making GSPMD lower the dispatch/combine einsums to all-to-alls.

    Returns ``(y [batch, seq, d_model], aux_loss scalar)``.
    """
    g, s, m = x.shape
    e = params["router"].shape[-1]
    capacity = max(1, int(capacity_factor * s / e))

    logits = jnp.einsum("gsm,me->gse", x.astype(jnp.float32),
                        params["router"])
    probs = jax.nn.softmax(logits, axis=-1)
    dispatch, combine, aux = _top2_dispatch(probs, capacity)
    dispatch = dispatch.astype(x.dtype)
    combine = combine.astype(x.dtype)

    ep_sharding = None
    if mesh is not None and mesh.shape.get(MESH_AXIS_EXPERT, 1) > 1:
        # Inside a partial-manual shard_map (e.g. the 1F1B schedule,
        # manual over pipe/data) a constraint may only name AUTO axes —
        # drop any axis the current trace has manualized (it is already
        # device-local there).
        try:
            manual = set(jax.sharding.get_abstract_mesh().manual_axes)
        except Exception:  # pragma: no cover - API drift
            manual = set()
        if MESH_AXIS_EXPERT in manual:
            ep_sharding = None
        else:
            data_ok = (mesh.shape.get(MESH_AXIS_DATA, 1) > 1
                       and MESH_AXIS_DATA not in manual
                       and g % mesh.shape[MESH_AXIS_DATA] == 0)
            ep_sharding = NamedSharding(mesh, P(
                MESH_AXIS_EXPERT, MESH_AXIS_DATA if data_ok else None))

    expert_in = jnp.einsum("gsec,gsm->egcm", dispatch, x)   # [E,G,C,M]
    if ep_sharding is not None:
        expert_in = jax.lax.with_sharding_constraint(expert_in, ep_sharding)
    h = activation(jnp.einsum("egcm,emf->egcf", expert_in, params["wi"]))
    expert_out = jnp.einsum("egcf,efm->egcm", h, params["wo"])
    if ep_sharding is not None:
        expert_out = jax.lax.with_sharding_constraint(expert_out, ep_sharding)
    y = jnp.einsum("gsec,egcm->gsm", combine, expert_out)
    return y, aux
