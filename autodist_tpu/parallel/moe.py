"""Expert parallelism: mixture-of-experts FFN over the ``expert`` mesh axis.

Absent from the reference (SURVEY §2.8: EP/MoE NO); new first-class scope.

Formulation: GShard/Switch-style capacity-based routing (Lepikhin et al.
2020, arxiv 2006.16668) expressed as dense einsums over one-hot dispatch/
combine tensors — the TPU-idiomatic MoE: static shapes (capacity bounds the
per-expert token count), MXU-friendly batched expert matmuls, and GSPMD
inserts the expert all-to-alls from the sharding constraints alone
(expert-major tensors lead with the ``expert`` axis; no hand-written
``lax.all_to_all`` needed, though the layout is exactly the all-to-all
dispatch of DeepSpeed-MoE/Tutel-style implementations).

Router runs in fp32 (bf16 softmax over experts is noisy enough to flip
top-k decisions).  The auxiliary load-balancing loss is returned to the
caller — models fold it into the training loss.
"""
from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from autodist_tpu.const import MESH_AXIS_DATA, MESH_AXIS_EXPERT
from autodist_tpu.utils import logging

#: capacity configs already warned about (one line per distinct config,
#: not one per trace).
_warned_capacity: set = set()


def moe_wire_format(wire: Optional[str] = None):
    """Resolve the expert-a2a wire format: the explicit ``wire`` arg
    ("int8" / a compressor name) wins, else the shared
    ``AUTODIST_MOE_WIRE`` knob — the SAME default the schedule IR's
    :func:`~autodist_tpu.kernel.synchronization.schedule_ir.
    moe_wire_compressor_default` reads, so the legs' priced wire bytes
    and the runtime payload cannot disagree.  Returns a
    ``quant_ring.WireFormat`` or None (full-precision wire)."""
    from autodist_tpu.kernel.synchronization import quant_ring, schedule_ir

    name = wire if wire is not None \
        else schedule_ir.moe_wire_compressor_default()
    if not name or name == "NoneCompressor":
        return None
    if name == "int8":
        name = "Int8Compressor"
    fmt = quant_ring.wire_format_of(name)
    if fmt is None:
        raise ValueError(f"moe wire {name!r} has no quantized wire format")
    return fmt


def init_moe_params(rng, d_model: int, d_ff: int, num_experts: int,
                    dtype=jnp.float32) -> dict:
    """Router + stacked expert FFN weights (leading ``[E]`` axis — flag these
    via ``expert_vars`` so the compiler shards it over ``expert``)."""
    r_router, r_wi, r_wo = jax.random.split(rng, 3)
    scale_in = 1.0 / (d_model ** 0.5)
    scale_out = 1.0 / (d_ff ** 0.5)
    return {
        "router": (jax.random.normal(r_router, (d_model, num_experts),
                                     jnp.float32) * scale_in),
        "wi": (jax.random.normal(r_wi, (num_experts, d_model, d_ff),
                                 dtype) * scale_in),
        "wo": (jax.random.normal(r_wo, (num_experts, d_ff, d_model),
                                 dtype) * scale_out),
    }


def _top2_dispatch(probs: jax.Array, capacity: int
                   ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """probs [G, S, E] → (dispatch [G,S,E,C] bool, combine [G,S,E,C], aux).

    G = groups (batch), S = tokens per group, E = experts, C = capacity.
    Tokens overflowing an expert's capacity within their group are dropped
    (their combine weight is zero — the residual connection carries them).
    """
    g, s, e = probs.shape

    idx1 = jnp.argmax(probs, axis=-1)                       # [G,S]
    mask1 = jax.nn.one_hot(idx1, e, dtype=probs.dtype)      # [G,S,E]
    probs_wo1 = probs * (1.0 - mask1)
    idx2 = jnp.argmax(probs_wo1, axis=-1)
    mask2 = jax.nn.one_hot(idx2, e, dtype=probs.dtype)
    if e == 1:
        # Single expert: argmax over all-zero probs_wo1 re-selects expert 0,
        # which would double-book two capacity slots per token.
        mask2 = jnp.zeros_like(mask2)

    # Positions within each expert's buffer, first-come-first-served along
    # the token axis; second choices queue after all first choices.
    pos1 = jnp.cumsum(mask1, axis=1) - mask1                # [G,S,E]
    pos2 = jnp.cumsum(mask2, axis=1) - mask2 \
        + jnp.sum(mask1, axis=1, keepdims=True)
    keep1 = mask1 * (pos1 < capacity)
    keep2 = mask2 * (pos2 < capacity)

    w1 = jnp.sum(probs * keep1, axis=-1)                    # [G,S]
    w2 = jnp.sum(probs * keep2, axis=-1)
    denom = jnp.maximum(w1 + w2, 1e-9)
    w1, w2 = w1 / denom, w2 / denom

    oh1 = jax.nn.one_hot(jnp.sum(pos1 * keep1, axis=-1).astype(jnp.int32),
                         capacity, dtype=probs.dtype)       # [G,S,C]
    oh2 = jax.nn.one_hot(jnp.sum(pos2 * keep2, axis=-1).astype(jnp.int32),
                         capacity, dtype=probs.dtype)
    combine = (w1[..., None, None] * keep1[..., None] * oh1[:, :, None]
               + w2[..., None, None] * keep2[..., None] * oh2[:, :, None])
    dispatch = combine > 0.0                                # [G,S,E,C]

    # Load-balancing aux loss (GShard eq. 4): fraction of tokens routed to
    # each expert × mean router probability, summed over experts, scaled E.
    frac = jnp.mean(mask1, axis=1)                          # [G,E]
    prob_mean = jnp.mean(probs, axis=1)                     # [G,E]
    aux = jnp.mean(jnp.sum(frac * prob_mean, axis=-1)) * e
    return dispatch, combine, aux


def moe_ffn(params: dict, x: jax.Array, *,
            capacity_factor: float = 2.0,
            mesh: Optional[Mesh] = None,
            activation=jax.nn.gelu,
            wire: Optional[str] = None) -> Tuple[jax.Array, jax.Array]:
    """Top-2 routed expert FFN.

    Args:
      params: dict from :func:`init_moe_params`.
      x: ``[batch, seq, d_model]``.
      capacity_factor: expert buffer size = ``cf · S / E`` per group.
      mesh: optional — adds sharding constraints so expert-major
        intermediates shard over ``expert`` (and groups over ``data``),
        making GSPMD lower the dispatch/combine einsums to all-to-alls.
      wire: expert-a2a wire format ("int8"); None reads the shared
        ``AUTODIST_MOE_WIRE`` knob.  A quantized wire crosses the a2a
        boundary as int8 payload + per-block f32 scales on the
        ``quant_ring`` scale grid and dequantizes on arrival — grid-
        exact inputs round-trip bit-exactly.

    Returns ``(y [batch, seq, d_model], aux_loss scalar)``.
    """
    g, s, m = x.shape
    e = params["router"].shape[-1]
    capacity = max(1, int(capacity_factor * s / e))

    # The runtime half of the moe/capacity-overflow lint: the SAME pure
    # rule the schedule verifier applies to the IR's MoE facts.
    from autodist_tpu.kernel.synchronization.schedule_ir import (
        RULE_CAPACITY_OVERFLOW,
        moe_capacity_drop_fraction,
    )
    drop = moe_capacity_drop_fraction(capacity_factor, s, e)
    cfg = (float(capacity_factor), int(s), int(e))
    if drop > 0 and cfg not in _warned_capacity:
        _warned_capacity.add(cfg)
        logging.warning(
            "%s: capacity_factor=%g keeps %d slots/expert for balanced "
            "top-2 demand of %.0f over %d experts — ~%.0f%% of routed "
            "tokens will be dropped to the residual path",
            RULE_CAPACITY_OVERFLOW, capacity_factor, capacity,
            2.0 * s / e, e, drop * 100.0)
    fmt = moe_wire_format(wire)

    logits = jnp.einsum("gsm,me->gse", x.astype(jnp.float32),
                        params["router"])
    probs = jax.nn.softmax(logits, axis=-1)
    dispatch, combine, aux = _top2_dispatch(probs, capacity)
    dispatch = dispatch.astype(x.dtype)
    combine = combine.astype(x.dtype)

    ep_sharding = None
    if mesh is not None and mesh.shape.get(MESH_AXIS_EXPERT, 1) > 1:
        # Inside a partial-manual shard_map (e.g. the 1F1B schedule,
        # manual over pipe/data) a constraint may only name AUTO axes —
        # drop any axis the current trace has manualized (it is already
        # device-local there).
        try:
            manual = set(jax.sharding.get_abstract_mesh().manual_axes)
        except Exception:  # pragma: no cover - API drift
            manual = set()
        if MESH_AXIS_EXPERT in manual:
            ep_sharding = None
        else:
            data_ok = (mesh.shape.get(MESH_AXIS_DATA, 1) > 1
                       and MESH_AXIS_DATA not in manual
                       and g % mesh.shape[MESH_AXIS_DATA] == 0)
            ep_sharding = NamedSharding(mesh, P(
                MESH_AXIS_EXPERT, MESH_AXIS_DATA if data_ok else None))

    def a2a(t: jax.Array) -> jax.Array:
        """Cross the expert a2a boundary: quantize-at-the-wire when a
        wire format is active (the sharding constraint lands on the
        int8 payload, so GSPMD's all-to-all ships 1/4 the bytes plus
        the per-block scale grid), plain constraint otherwise."""
        if ep_sharding is None:
            return t
        if fmt is None:
            return jax.lax.with_sharding_constraint(t, ep_sharding)
        from autodist_tpu.kernel.synchronization import quant_ring

        q, scales, _ = quant_ring.quantize_blocks(
            t.astype(jnp.float32).reshape(-1), fmt)
        q = jax.lax.with_sharding_constraint(
            q.reshape(t.shape), ep_sharding)
        deq = quant_ring.dequantize_blocks(q.reshape(-1), scales)
        return deq.reshape(t.shape).astype(t.dtype)

    expert_in = a2a(jnp.einsum("gsec,gsm->egcm", dispatch, x))  # [E,G,C,M]
    h = activation(jnp.einsum("egcm,emf->egcf", expert_in, params["wi"]))
    expert_out = a2a(jnp.einsum("egcf,efm->egcm", h, params["wo"]))
    y = jnp.einsum("gsec,egcm->gsm", combine, expert_out)
    return y, aux
