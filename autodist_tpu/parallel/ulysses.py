"""Ulysses (DeepSpeed-style) sequence parallelism via all-to-all.

Alternative to ring attention: instead of rotating K/V blocks, a single
``all_to_all`` re-shards activations from sequence-sharded to head-sharded,
dense attention runs on full sequences for a subset of heads, and a second
``all_to_all`` restores sequence sharding.  Two collectives per attention
call, no per-block loop — typically faster than a ring when
``num_heads >= seq_axis_size`` and sequence fits per-device memory after the
head split.

Absent from the reference (SURVEY §5.7); new first-class scope.
"""
from __future__ import annotations

import functools
from typing import Callable, Optional

import jax
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from autodist_tpu.const import MESH_AXIS_SEQ
from autodist_tpu.utils import compat


def _ulysses_local(q, k, v, *, axis_name: str, causal: bool,
                   inner_attn: Callable):
    """Inside shard_map: q/k/v are [B, T_local, H, D]."""
    # seq-sharded -> head-sharded: [B, T_global, H/n, D]
    def to_heads(x):
        return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                              tiled=True)

    def to_seq(x):
        return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                              tiled=True)

    out = inner_attn(to_heads(q), to_heads(k), to_heads(v), causal)
    return to_seq(out)


def make_ulysses_attention(mesh: Mesh, axis_name: str = MESH_AXIS_SEQ,
                           inner: str = "auto", block_q: int = 512,
                           block_k: int = 512,
                           interpret: Optional[bool] = None) -> Callable:
    """Returns an ``attn_fn(q, k, v, causal)`` drop-in for dense_attention,
    sequence-parallel via all-to-all.  Requires num_heads divisible by the
    seq axis size.

    ``inner`` selects the full-sequence attention run per head subset
    between the two all-to-alls: ``"dense"``, ``"flash"`` (the Pallas
    kernel — the global sequence is what each device sees here, so the
    O(T²) HBM saving applies to the FULL length), or ``"auto"`` (flash on
    TPU, dense elsewhere; decided at construction)."""
    if inner == "auto":
        inner = "flash" if jax.devices()[0].platform == "tpu" else "dense"
    if inner not in ("dense", "flash"):
        raise ValueError(f"inner must be dense|flash|auto, got {inner!r}")
    from autodist_tpu.models.transformer import dense_attention

    if inner == "flash":
        from autodist_tpu.ops.flash_attention import (
            _use_interpret,
            flash_attention,
        )
        if interpret is None:
            interpret = _use_interpret()
        inner_fn = functools.partial(flash_attention, block_q=block_q,
                                     block_k=block_k, interpret=interpret)
    else:
        inner_fn = dense_attention
    spec = P(None, axis_name, None, None)

    @functools.lru_cache(maxsize=None)
    def _mapped(causal: bool):
        local = functools.partial(_ulysses_local, axis_name=axis_name,
                                  causal=causal, inner_attn=inner_fn)
        # jit + check_vma=False on the flash path (pallas out_shape carries
        # no vma; partial-axes eager shard_map needs the jit wrapper —
        # same workarounds as ring_attention.py).
        if inner == "flash":
            return jax.jit(compat.shard_map(
                local, mesh=mesh, in_specs=(spec, spec, spec),
                out_specs=spec, axis_names={axis_name}, check_vma=False))
        return compat.shard_map(
            local, mesh=mesh, in_specs=(spec, spec, spec),
            out_specs=spec, axis_names={axis_name})

    def attn_fn(q, k, v, causal: bool):
        n = mesh.shape.get(axis_name, 1)
        if n <= 1:
            return dense_attention(q, k, v, causal)
        if q.shape[2] % n != 0:
            raise ValueError(
                f"Ulysses needs num_heads ({q.shape[2]}) divisible by the "
                f"'{axis_name}' axis size ({n}); use ring attention instead")
        # Legacy shard_map hard-aborts XLA on the all-to-all lowering —
        # fail cleanly instead of crashing.
        compat.require_native("shard_map", "Ulysses attention")
        return _mapped(bool(causal))(q, k, v)

    return attn_fn
