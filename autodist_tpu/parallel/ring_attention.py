"""Ring attention: sequence-parallel exact attention over the ``seq`` axis.

Blockwise ring attention (Liu et al. 2023 "Ring Attention with Blockwise
Transformers"): each device holds a chunk of the sequence; K/V blocks rotate
around the ring via ``ppermute`` while a numerically stable online softmax
(flash-attention style running max/sum) accumulates the output.  Compute on
the current block overlaps (courtesy of XLA's latency-hiding scheduler) with
the ICI transfer of the next block, so sequence length scales linearly with
the number of chips at constant memory per chip.

Absent from the reference (no sequence-scaling machinery at all — SURVEY
§5.7); this is new first-class scope for the TPU build.

Layout convention: q/k/v are ``[batch, seq, heads, head_dim]``; inside the
ring step the local shard is ``[B, T_local, H, D]``.
"""
from __future__ import annotations

import functools
from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from autodist_tpu.const import MESH_AXIS_SEQ
from autodist_tpu.utils import compat

_NEG_INF = -1e30  # finite "minus infinity": keeps exp()/max() NaN-free


def _ring_attention_local(q, k, v, *, axis_name: str, causal: bool):
    """Runs on one device inside shard_map: q/k/v are local seq shards."""
    axis_size = compat.axis_size(axis_name)
    axis_index = lax.axis_index(axis_name)
    b, t_q, h, d = q.shape
    t_k = k.shape[1]
    scale = 1.0 / jnp.sqrt(d).astype(jnp.float32)
    q32 = q.astype(jnp.float32)

    q_pos = axis_index * t_q + jnp.arange(t_q)  # global positions of queries
    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]

    def accumulate(step, o, l, m, k_blk, v_blk):
        """Online-softmax update with the K/V block originally owned by
        chunk (axis_index - step) mod axis_size."""
        j = (axis_index - step) % axis_size
        logits = jnp.einsum("bqhd,bkhd->bhqk", q32,
                            k_blk.astype(jnp.float32)) * scale
        if causal:
            k_pos = j * t_k + jnp.arange(t_k)
            allowed = k_pos[None, :] <= q_pos[:, None]  # [t_q, t_k]
            logits = jnp.where(allowed[None, None], logits, _NEG_INF)
        m_new = jnp.maximum(m, logits.max(axis=-1))          # [B,H,Tq]
        p = jnp.exp(logits - m_new[..., None])               # [B,H,Tq,Tk]
        corr = jnp.exp(m - m_new)                            # [B,H,Tq]
        l_new = l * corr + p.sum(axis=-1)
        o_new = (o * corr[..., None]
                 + jnp.einsum("bhqk,bkhd->bhqd", p,
                              v_blk.astype(jnp.float32)))
        return o_new, l_new, m_new

    def body(step, carry):
        o, l, m, k_blk, v_blk = carry
        o, l, m = accumulate(step, o, l, m, k_blk, v_blk)
        k_next = lax.ppermute(k_blk, axis_name, perm)
        v_next = lax.ppermute(v_blk, axis_name, perm)
        return o, l, m, k_next, v_next

    # pcast-to-varying: the accumulators are per-shard values (varying over
    # the manual seq axis) even though their initial contents are constants.
    vary = lambda x: compat.pcast(x, axis_name, to="varying")  # noqa: E731
    o0 = vary(jnp.zeros((b, h, t_q, d), jnp.float32))
    l0 = vary(jnp.zeros((b, h, t_q), jnp.float32))
    m0 = vary(jnp.full((b, h, t_q), _NEG_INF, jnp.float32))
    # The last block computes outside the loop so no wasted final ppermute
    # rotates K/V that nothing consumes (a collective in the loop body can't
    # be dead-code-eliminated by XLA).
    o, l, m, k_last, v_last = lax.fori_loop(
        0, axis_size - 1, body, (o0, l0, m0, k, v))
    o, l, m = accumulate(axis_size - 1, o, l, m, k_last, v_last)
    out = o / jnp.maximum(l, 1e-30)[..., None]               # [B,H,Tq,D]
    return out.transpose(0, 2, 1, 3).astype(q.dtype)         # [B,Tq,H,D]


def _ring_flash_local(q, k, v, *, axis_name: str, causal: bool,
                      block_q: int, block_k: int, interpret: bool):
    """Ring step with the Pallas flash kernel as the within-chip block
    computation (ring-flash: Liu et al. 2023 composition).  The kernel
    returns (o, lse); partial outputs merge in log-space:

        lse' = logaddexp(lse_a, lse_b)
        o'   = o_a·exp(lse_a − lse') + o_b·exp(lse_b − lse')

    For causal attention, K/V blocks from FUTURE chunks contribute nothing:
    their lse is masked to −inf so the merge is an exact no-op (the block
    still computes — the ring must stay uniform across devices — matching
    the dense ring's cost model)."""
    from autodist_tpu.ops.flash_attention import flash_attention_with_lse

    axis_size = compat.axis_size(axis_name)
    axis_index = lax.axis_index(axis_name)
    flash = functools.partial(flash_attention_with_lse, block_q=block_q,
                              block_k=block_k, interpret=interpret)

    # Step 0 — the diagonal block (my own K/V): within-chunk causal mask.
    o0, lse0 = flash(q, k, v, causal)
    acc = o0.astype(jnp.float32)                       # [B,Tq,H,D]
    lse_acc = lse0.transpose(0, 2, 1)                  # [B,Tq,H]

    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]

    def body(step, carry):
        acc, lse_acc, k_blk, v_blk = carry
        k_blk = lax.ppermute(k_blk, axis_name, perm)
        v_blk = lax.ppermute(v_blk, axis_name, perm)
        j = (axis_index - step) % axis_size            # block owner
        o_b, lse_b = flash(q, k_blk, v_blk, False)     # full cross-block
        lse_b = lse_b.transpose(0, 2, 1)               # [B,Tq,H]
        if causal:
            # Future chunks (j > me) are fully masked out of the merge.
            lse_b = jnp.where(j <= axis_index, lse_b, _NEG_INF)
        lse_new = jnp.logaddexp(lse_acc, lse_b)
        w_acc = jnp.exp(lse_acc - lse_new)[..., None]
        w_b = jnp.exp(lse_b - lse_new)[..., None]
        acc = acc * w_acc + o_b.astype(jnp.float32) * w_b
        return acc, lse_new, k_blk, v_blk

    acc, lse_acc, _, _ = lax.fori_loop(
        1, axis_size, body, (acc, lse_acc, k, v))
    return acc.astype(q.dtype)


def make_ring_attention(mesh: Mesh, axis_name: str = MESH_AXIS_SEQ,
                        inner: str = "auto", block_q: int = 512,
                        block_k: int = 512,
                        interpret: Optional[bool] = None) -> Callable:
    """Returns an ``attn_fn(q, k, v, causal)`` drop-in for
    :func:`autodist_tpu.models.transformer.dense_attention`, sequence-parallel
    over ``axis_name``.  Call it on GLOBAL [B, T, H, D] tensors inside jit —
    the partial-manual shard_map manualizes only the seq axis, leaving
    data/model axes to GSPMD.

    ``inner`` selects the within-chip block computation: ``"dense"`` (the
    blockwise softmax in this module), ``"flash"`` (the Pallas kernel with
    log-space merging — HBM traffic linear in the LOCAL length too), or
    ``"auto"`` (flash on TPU, dense elsewhere; decided at construction)."""
    if inner == "auto":
        import jax as _jax
        inner = "flash" if _jax.devices()[0].platform == "tpu" else "dense"
    if inner not in ("dense", "flash"):
        raise ValueError(f"inner must be dense|flash|auto, got {inner!r}")
    if interpret is None and inner == "flash":
        from autodist_tpu.ops.flash_attention import _use_interpret
        interpret = _use_interpret()
    spec = P(None, axis_name, None, None)

    @functools.lru_cache(maxsize=None)
    def _flash_ring(causal: bool):
        # check_vma off: pallas_call's out_shape carries no varying-axis
        # metadata (vma tracking rejects it), and this ring needs no
        # auto-collectives — ppermute is explicit and the merge is purely
        # local.  jit (inlined when the caller already traces): eager
        # shard_map with partial axis_names trips JAX's internal unmatch
        # path (same workaround as ops/flash_attention.py); cached per
        # causal flag so eager callers keep a stable jit identity.
        local = functools.partial(
            _ring_flash_local, axis_name=axis_name, causal=causal,
            block_q=block_q, block_k=block_k, interpret=interpret)
        return jax.jit(compat.shard_map(
            local, mesh=mesh,
            in_specs=(spec, spec, spec), out_specs=spec,
            axis_names={axis_name}, check_vma=False))

    def attn_fn(q, k, v, causal: bool):
        if mesh.shape.get(axis_name, 1) <= 1:
            from autodist_tpu.models.transformer import dense_attention
            return dense_attention(q, k, v, causal)
        # Legacy shard_map hard-aborts XLA on this ring's
        # collective_permute — fail cleanly instead of crashing.
        compat.require_native("shard_map", "ring attention")
        if inner == "flash":
            return _flash_ring(bool(causal))(q, k, v)
        local = functools.partial(_ring_attention_local,
                                  axis_name=axis_name, causal=causal)
        return compat.shard_map(
            local, mesh=mesh,
            in_specs=(spec, spec, spec), out_specs=spec,
            axis_names={axis_name})(q, k, v)

    return attn_fn
