"""Multi-dimensional parallelism beyond the reference's DP+PS scope.

Sequence/context parallelism — ring attention (ring_attention.py) and
Ulysses all-to-all (ulysses.py); pipeline parallelism (pipeline.py);
expert parallelism / MoE (moe.py)."""
from autodist_tpu.parallel.moe import init_moe_params, moe_ffn  # noqa: F401
from autodist_tpu.parallel.pipeline_1f1b import (  # noqa: F401
    bubble_fraction_1f1b,
    one_f_one_b,
    schedule_ticks_1f1b,
)
from autodist_tpu.parallel.pipeline import (  # noqa: F401
    pipeline_apply,
    stack_stage_params,
)
from autodist_tpu.parallel.ring_attention import make_ring_attention  # noqa: F401
from autodist_tpu.parallel.ulysses import make_ulysses_attention  # noqa: F401


def sequence_parallel_attention(kind: str, mesh, axis_name: str = "seq"):
    """Factory: 'ring' | 'ulysses' | 'dense' → attn_fn(q, k, v, causal)."""
    if kind == "ring":
        return make_ring_attention(mesh, axis_name)
    if kind == "ulysses":
        return make_ulysses_attention(mesh, axis_name)
    if kind == "dense":
        from autodist_tpu.models.transformer import dense_attention
        return dense_attention
    raise ValueError(f"unknown sequence-parallel attention kind {kind!r}")
