"""GraphTransformer: compile the captured program into a distributed step.

Parity target: reference ``autodist/kernel/graph_transformer.py:55-92`` which
orchestrates partition → replicate → in-graph sync → between-graph sync by
rewriting the TF graph.  TPU-natively all four phases collapse into *choosing
shardings and jitting once*:

* partitioning   → per-variable ``PartitionSpec`` (compiler VarPlan)
* replication    → the ``data`` mesh axis + batch sharding
* in-graph sync  → GSPMD-inserted ``psum`` over ``data`` when params are
                   replicated and the batch is sharded
* between-graph  → the same collectives ride DCN axes on multi-slice meshes;
  sync              weight-update sharding turns PS reduction into
                   reduce-scatter + sharded update + all-gather

The transformer emits a :class:`DistributedStep`: a jitted
``(params, opt_state, sync_state, batch) ->
(params, opt_state, sync_state, metrics)`` function with input/output
shardings bound and buffers donated (``sync_state`` carries per-device
synchronizer state such as compressor residuals; empty on the GSPMD path).
"""
from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Any, Callable, Dict, Optional

import jax
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

from autodist_tpu.graph_item import GraphItem
from autodist_tpu.kernel import sharding_utils as su
from autodist_tpu.strategy.compiler import CompiledStrategy
from autodist_tpu.utils import logging


@dataclass
class DistributedStep:
    """The compiled training step plus everything needed to run it.

    ``step_fn(params, opt_state, sync_state, batch)`` →
    ``(params, opt_state, sync_state, metrics)``.  ``sync_state`` carries
    per-device synchronizer state (compressor residuals etc.); it is an empty
    dict on the GSPMD path.

    Pad-to-divisible sharding: when any variable carries a
    ``VarPlan.pad_axis``, the step's state is PHYSICAL (padded) and
    ``pad_info``/``opt_pad_info`` describe the boundary; ``place_params``
    pads logical → physical, ``export_*``/``unpad_host`` recover the
    logical view (so checkpoints keep the single-device interchange
    invariant).  ``pad_info is None`` ⇒ all of these are identities."""

    step_fn: Callable
    init_fn: Callable            # jitted physical params -> opt_state (sharded)
    init_sync_state: Callable    # (params?) -> sync-state pytree
    param_shardings: Any         # pytree of NamedSharding (physical layout)
    opt_shardings: Any
    mesh: Any
    compiled_strategy: CompiledStrategy
    eval_fn: Optional[Callable] = None  # (params, batch) -> metrics; no update
    pad_info: Any = None             # params-shaped info tree, or None
    opt_pad_info: Any = None         # opt-state-shaped info tree, or None
    logical_param_shardings: Any = None  # pad axis dropped; None = physical
    logical_opt_shardings: Any = None
    # ZeRO-1 flat-bucket plan (explicit reduce-scatter path only; empty
    # elsewhere): checkpoints record it so elastic resume can reslice the
    # flat optimizer shards at a different data-axis size.
    zero1_buckets: Any = ()
    # The verified sync-schedule IR this step lowered (docs/schedule-ir.md)
    # — both paths build one; its fingerprint rides telemetry StepRecords
    # and checkpoint meta so planned-vs-executed drift is detectable.
    schedule_ir: Any = None
    _placer: Optional[Callable] = None
    _param_exporter: Optional[Callable] = None
    _opt_exporter: Optional[Callable] = None
    _opt_importer: Optional[Callable] = None

    def place_params(self, params):
        # A jitted pad+identity (not device_put): device_put may alias the
        # caller's buffers when layouts already match, and the step's
        # donation would then delete the user's original arrays.  Cached so
        # repeated placement (set_params/restore) compiles once.
        if self._placer is None:
            info = self.pad_info
            fn = (lambda p: su.pad_tree(p, info)) if info is not None \
                else (lambda p: p)
            self._placer = jax.jit(fn, out_shardings=self.param_shardings)
        return self._placer(params)

    # -- logical/physical boundary ----------------------------------------
    def export_params(self, phys_params):
        """Physical (padded) params → logical sharded arrays (pad axis
        gathered); identity when nothing is padded."""
        if self.pad_info is None:
            return phys_params
        if self._param_exporter is None:
            info = self.pad_info
            self._param_exporter = jax.jit(
                lambda p: su.unpad_tree(p, info),
                out_shardings=self.logical_param_shardings)
        return self._param_exporter(phys_params)

    def export_opt_state(self, opt_state):
        if self.pad_info is None:
            return opt_state
        if self._opt_exporter is None:
            info = self.opt_pad_info
            self._opt_exporter = jax.jit(
                lambda s: su.unpad_tree(s, info),
                out_shardings=self.logical_opt_shardings)
        return self._opt_exporter(opt_state)

    def import_opt_state(self, logical_opt_state):
        if self.pad_info is None:
            return logical_opt_state
        if self._opt_importer is None:
            info = self.opt_pad_info
            self._opt_importer = jax.jit(
                lambda s: su.pad_tree(s, info),
                out_shardings=self.opt_shardings)
        return self._opt_importer(logical_opt_state)

    def unpad_host(self, host_params):
        """Logical view of a host-gathered params tree (numpy in/out)."""
        if self.pad_info is None:
            return host_params
        return su.unpad_host_tree(host_params, self.pad_info)

    def place_batch(self, batch):
        def put(x, sh):
            import numpy as np
            if isinstance(x, np.ndarray) and not x.flags.owndata:
                # Non-owning views (e.g. the native DataLoader's ring-buffer
                # batches) must be copied on EVERY backend: the CPU backend
                # zero-copy aliases them, and on TPU device_put's host→HBM
                # DMA is ASYNC — the loader may recycle and rewrite the slot
                # while the transfer is still in flight (prefetch() exists
                # precisely to overlap those transfers with compute).
                # Reclaiming this copy requires synchronizing the loader's
                # slot release with transfer completion, not skipping it.
                x = np.array(x, copy=True)
            return jax.device_put(x, sh)

        return jax.tree_util.tree_map(
            put, batch, self.compiled_strategy.batch_shardings(batch))

    def place_local_batch(self, local_batch):
        """Assemble a GLOBAL batch from this process's LOCAL shard.

        ``place_batch`` requires every process to hold the identical global
        batch (the reference's feed model — the same feed_dict re-split by
        the Remapper, remapper.py:81-123).  Multi-host input pipelines
        instead read disjoint shards per host; this is the
        ``jax.make_array_from_process_local_data`` path: each process
        passes its local rows and the result is one global array whose
        leading dim is the concatenation over the data axis.  Scalars and
        already-placed leaves pass through."""
        import numpy as np

        # Sharding decisions (data-axis divisibility, seq-dim detection)
        # must see the GLOBAL shapes: leading dims are per-process here,
        # so scale them by process_count before consulting the strategy.
        pcount = jax.process_count()

        def global_like(x):
            shape = np.shape(x)
            if isinstance(x, jax.Array) or len(shape) == 0:
                return x
            return jax.ShapeDtypeStruct((shape[0] * pcount,) + shape[1:],
                                        np.asarray(x).dtype)

        shardings = self.compiled_strategy.batch_shardings(
            jax.tree_util.tree_map(global_like, local_batch))

        def put(x, sh):
            if isinstance(x, jax.Array):
                return x                      # already placed
            x = np.asarray(x)
            if x.ndim == 0:
                return jax.device_put(x, sh)  # scalars replicate
            if pcount > 1 and sh.spec == jax.sharding.PartitionSpec():
                # A replicated layout would stamp each process's DIFFERENT
                # local rows as "the same" global array — silent
                # cross-process divergence.  Replicated feeds must go
                # through place_batch with identical global data.
                raise ValueError(
                    "place_local_batch: this leaf lowers to a replicated "
                    f"layout (global shape {(x.shape[0] * pcount,) + x.shape[1:]} "
                    "does not shard on the data axis); feed it identically "
                    "on every process via place_batch instead")
            if not x.flags.owndata:
                x = np.array(x, copy=True)  # same DMA-lifetime rule as above
            return jax.make_array_from_process_local_data(sh, x)

        return jax.tree_util.tree_map(put, local_batch, shardings)


class GraphTransformer:
    """Builds a :class:`DistributedStep` from strategy + program."""

    def __init__(self, compiled_strategy: CompiledStrategy,
                 graph_item: GraphItem):
        self.compiled = compiled_strategy
        self.graph_item = graph_item

    # -- sharding trees ----------------------------------------------------
    def _param_specs(self) -> Dict[str, P]:
        return {name: plan.param_spec
                for name, plan in self.compiled.var_plans.items()}

    def _opt_specs(self) -> Dict[str, P]:
        return {name: plan.opt_spec
                for name, plan in self.compiled.var_plans.items()}

    def transform(self, extra_metrics_fn: Optional[Callable] = None
                  ) -> DistributedStep:
        gi = self.graph_item
        if gi.optimizer is None or gi.loss_fn is None:
            raise ValueError(
                "GraphItem must carry an optimizer and loss_fn to transform "
                "(capture them via AutoDist.capture)")
        mesh = self.compiled.mesh
        params = gi.params

        from autodist_tpu.const import MESH_AXIS_DATA
        from autodist_tpu.kernel.synchronization import explicit_sync
        if explicit_sync.uses_explicit_path(self.compiled):
            if gi.grad_fn is not None:
                raise ValueError(
                    "capture(grad_fn=...) cannot combine with gradient "
                    "compressors / fused groups (the explicit shard_map "
                    "path owns the gradient computation); drop the "
                    "compressor or the manual grad_fn")
            if mesh.shape.get(MESH_AXIS_DATA, 1) > 1:
                from autodist_tpu.kernel.synchronization.stale_sync import \
                    uses_stale_path
                if uses_stale_path(self.compiled):
                    logging.warning(
                        "strategy requests bounded staleness / proxy "
                        "variables AND gradient compression; the explicit "
                        "compressor path runs fully synchronous — "
                        "staleness/proxy settings are ignored")
                return self._transform_explicit(extra_metrics_fn)
            # No data axis ⇒ no gradient traffic to compress; the GSPMD path
            # is equivalent and supports arbitrary meshes.
            logging.info("compressors requested but mesh has no data axis; "
                         "using the GSPMD path (nothing to compress)")

        # Pad-to-divisible sharding: vars whose partitioned dim doesn't
        # divide the mesh axis are stored physically padded; the loss sees
        # the logical view through an unpad slice (autodiff then scatters
        # exactly-zero gradients into the pad rows).
        pad_map = {name: (axis, self.graph_item.info.by_name(name).shape[axis],
                          padded)
                   for name, (axis, padded) in self.compiled.pad_plans().items()}
        pad_info = su.pad_info_tree(params, pad_map) if pad_map else None
        if pad_info is not None:
            phys_params = jax.eval_shape(
                lambda p: su.pad_tree(p, pad_info), params)
            gi_loss = gi.loss_fn

            def loss_fn(p, batch):
                return gi_loss(su.unpad_tree(p, pad_info), batch)
            if extra_metrics_fn is not None:
                # metrics_fn, like the loss, sees the LOGICAL param view.
                user_metrics = extra_metrics_fn

                def extra_metrics_fn(p, batch):  # noqa: F811
                    return user_metrics(su.unpad_tree(p, pad_info), batch)
        else:
            phys_params = params
            loss_fn = gi.loss_fn

        param_spec_tree = su.spec_tree_for_params(params, self._param_specs())
        grad_spec_tree = su.spec_tree_for_params(params, self._opt_specs())
        param_sh = su.sharding_tree(mesh, param_spec_tree)
        # NamedSharding trees for in-step constraints: a bare PartitionSpec
        # needs an ambient mesh at trace time, which jit tracing doesn't have.
        grad_sh = su.sharding_tree(mesh, grad_spec_tree)

        # Freeze untrainable variables for real (zero updates, no
        # optimizer state) — see GraphItem.frozen_aware_optimizer.
        optimizer = gi.frozen_aware_optimizer(phys_params)

        # Optimizer-state layout: param-shaped blocks follow the per-variable
        # opt_spec (weight-update sharding for PS vars); scalars replicate.
        # Shapes are PHYSICAL (the state the step carries is padded).
        opt_shape = jax.eval_shape(optimizer.init, phys_params)
        opt_spec_tree = su.opt_spec_tree(opt_shape, phys_params, grad_spec_tree)
        opt_sh = su.sharding_tree(mesh, opt_spec_tree)

        if gi.grad_fn is not None:
            # Manual value-and-grad (e.g. the 1F1B pipeline backward):
            # the contract is LOGICAL params in, LOGICAL grads out — under
            # pad-to-divisible sharding unpad on entry and zero-pad the
            # returned grads (pad rows stay untrained, matching the masked
            # update).
            user_grad = gi.grad_fn
            if pad_info is not None:
                def vg(p, batch):
                    loss, g = user_grad(su.unpad_tree(p, pad_info), batch)
                    return loss, su.pad_tree(g, pad_info)
            else:
                vg = user_grad
        else:
            vg = jax.value_and_grad(loss_fn, has_aux=gi.has_aux)
        has_aux = gi.has_aux
        if gi.accum_steps > 1 and extra_metrics_fn is not None:
            logging.warning(
                "accum_steps=%d with metrics_fn: metrics run one "
                "FULL-batch forward in the same step, so peak "
                "activation memory stays O(batch) — the accumulation "
                "memory win applies to the gradient pass only",
                gi.accum_steps)

        # Bounded staleness / proxy mirrors ride in sync_state (see
        # stale_sync module; the SSP translation of the reference's token
        # queues, ps_synchronizer.py:385-455).
        from autodist_tpu.kernel.synchronization.stale_sync import (
            StaleSync, uses_stale_path)
        stale = StaleSync(gi, self.compiled) \
            if uses_stale_path(self.compiled) else None

        # Numerics guard on the GSPMD path (docs/numerics.md): grads are
        # already-global arrays here, so health is a fused local
        # reduction over the gradient tree (no extra collective — XLA
        # folds it into the update program).
        num_cfg = getattr(gi, "numerics", None)
        num_active = bool(num_cfg is not None and num_cfg.guard)
        num_ls = None
        injections: Dict[str, Any] = {}
        guard_mod = ls_mod = None
        if num_active and stale is not None:
            logging.warning(
                "numerics guard disabled: bounded-staleness/proxy sync "
                "state owns the sync_state slot on this path; drop "
                "staleness or route through the explicit bucketed path")
            num_active = False
        if num_active:
            import numpy as _np

            from autodist_tpu.numerics import guard as guard_mod
            from autodist_tpu.numerics import loss_scale as ls_mod

            dtypes = [str(_np.asarray(v).dtype)
                      for v in gi.name_to_leaf().values()]
            num_ls = ls_mod.resolve_loss_scale(num_cfg.loss_scale, dtypes)
            if num_ls is not None and gi.grad_fn is not None:
                logging.warning(
                    "numerics: loss scaling disabled — capture(grad_fn=...)"
                    " owns the backward pass, so the scale cannot be "
                    "threaded through it (guard/clip/skip stay active)")
                num_ls = None
            injections = guard_mod.resolve_injections(
                (), list(gi.name_to_leaf()))
            logging.info(
                "numerics guard: ON (GSPMD path, loss_scale=%s, "
                "clip_norm=%s, on_nonfinite=%s)",
                "off" if num_ls is None else "%g" % num_ls.init,
                num_cfg.clip_norm, num_cfg.on_nonfinite)
        else:
            from autodist_tpu.kernel.synchronization.explicit_sync import \
                chaos_grad_events_probe
            if list(chaos_grad_events_probe()):
                logging.warning(
                    "AUTODIST_CHAOS requests a gradient injection but the "
                    "numerics guard is off — nan_grad/inf_grad need "
                    "capture(numerics=...); ignoring the event")
        if num_active and num_ls is not None:
            def _scaled_loss(p, batch, scale):
                if has_aux:
                    loss_, aux_ = loss_fn(p, batch)
                    return loss_ * scale, aux_
                return loss_fn(p, batch) * scale
            vg_scaled = jax.value_and_grad(_scaled_loss, has_aux=has_aux)
        else:
            vg_scaled = None
        if gi.accum_steps > 1 and not num_active:
            vg = _accumulate_grads(vg, gi.accum_steps, has_aux)
        frozen_names = {v.name for v in gi.info.untrainable_variables}

        # Schedule IR (docs/schedule-ir.md): the GSPMD lowering of the
        # sync program — per-variable psum-tree collectives plus the
        # guard roll-up — built from the SAME plan facts the explicit
        # path buckets from, verified before tracing, and carried on the
        # step for telemetry/checkpoint fingerprints.
        from autodist_tpu.kernel.synchronization import schedule_ir as sir
        facts = []
        for name, plan in self.compiled.var_plans.items():
            vi = gi.info.by_name(name)
            if vi is None or name in frozen_names:
                continue
            facts.append(sir.fact_from_varplan(plan, vi))
        mesh_axes = {str(k): int(v) for k, v in dict(mesh.shape).items()}
        sched = sir.ir_from_facts(
            facts, axes=mesh_axes,
            accum_steps=gi.accum_steps, guard=num_active,
            moe=sir.moe_facts_from_vars(gi.info.variables, axes=mesh_axes))
        sir.assert_verified(sched, "gspmd build")

        def step(params, opt_state, sync_state, batch):
            import jax.numpy as jnp

            params_in, opt_in = params, opt_state
            grad_params = params if stale is None \
                else stale.before_grads(params, sync_state)
            if num_active:
                from autodist_tpu.numerics.guard import NUMERICS_KEY
                ns = sync_state[NUMERICS_KEY]
                scale = ns["scale"] if num_ls is not None else None
                if scale is None:
                    vg_local = vg
                else:
                    vg_local = lambda p, b: vg_scaled(p, b, scale)  # noqa: E731
                if injections:
                    vg_local = guard_mod.wrap_injections(
                        vg_local, injections, ns["step"])
                if gi.accum_steps > 1:
                    vg_local = _accumulate_grads(vg_local, gi.accum_steps,
                                                 has_aux)
            else:
                scale = None
                vg_local = vg
            if has_aux:
                (loss, aux), grads = vg_local(grad_params, batch)
            else:
                loss, grads = vg_local(grad_params, batch)
                aux = None
            # Force the gradient layout the synchronizers chose: for PS/WUS
            # variables this lowers the data-axis reduction to
            # reduce-scatter; for sharded embeddings the scatter-add lands
            # on the owning shard.
            grads = su.constrain(grads, grad_sh)
            if stale is not None:
                grads, sync_state = stale.exchange(grads, sync_state)
            all_finite = gnorm = per_bucket = None
            if num_active:
                # Health over the (already-global) gradient tree — the
                # per-variable analog of the bucketed guard; frozen vars
                # are excluded (their updates are masked to zero anyway).
                from autodist_tpu.graph_item import path_name as _pn
                health = guard_mod.HealthAccumulator(1)
                for path, g in \
                        jax.tree_util.tree_flatten_with_path(grads)[0]:
                    if _pn(path) not in frozen_names:
                        health.add(_pn(path), g)
                inv_scale = jnp.float32(1.0) if scale is None \
                    else jnp.float32(1.0) / scale
                all_finite, gnorm, per_bucket = health.finalize(
                    (), loss, inv_scale)
                mult = inv_scale
                clip = guard_mod.clip_multiplier(gnorm, num_cfg.clip_norm)
                if clip is not None:
                    mult = mult * clip
                if clip is not None or scale is not None:
                    grads = jax.tree_util.tree_map_with_path(
                        lambda p, g: g if _pn(p) in frozen_names
                        else (g.astype(jnp.float32) * mult).astype(g.dtype),
                        grads)
            updates, opt_state = optimizer.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            if pad_info is not None:
                # Keep pad rows exactly zero even for optimizers whose
                # update is not zero-preserving (noise, non-zero decay).
                params = su.mask_pad_tree(params, pad_info)
            # Fresh params return to their compute layout (all-gather for
            # WUS variables — "broadcast from the PS").
            params = su.constrain(params, param_sh)
            if stale is not None:
                sync_state = stale.after_update(params, sync_state)
            metrics = {"loss": loss}
            if num_active:
                from autodist_tpu.numerics import loss_scale as _lsm
                params = guard_mod.tree_select(all_finite, params, params_in)
                opt_state = guard_mod.tree_select(all_finite, opt_state,
                                                  opt_in)
                new_ns = _lsm.update_state(ns, all_finite, num_ls)
                sync_state = dict(sync_state)
                sync_state[NUMERICS_KEY] = new_ns
                if scale is not None:
                    metrics["loss"] = loss * inv_scale
                metrics["grad_health"] = guard_mod.GradHealth(
                    all_finite=all_finite, global_norm=gnorm,
                    loss_scale=ns["scale"],
                    skipped_steps=new_ns["skipped"],
                    per_bucket=per_bucket)
            if aux is not None:
                metrics["aux"] = aux
            if extra_metrics_fn is not None:
                metrics = _merge_metrics(metrics, extra_metrics_fn(params,
                                                                   batch))
            return params, opt_state, sync_state, metrics

        # Batch shardings are per-leaf (data on dim 0, seq on dim 1 where it
        # applies) — leave them unspecified and let placed arguments carry
        # their own layout.
        sync_sh = None if stale is None \
            else stale.state_shardings(mesh, phys_params)
        jit_kwargs = {}
        combiner = self._combiner_bytes()
        from autodist_tpu.const import ENV
        flag = ENV.AUTODIST_COMBINER_FLAG.val
        if combiner and flag and mesh.devices.flat[0].platform == "tpu":
            # Strategy `group`/chunk_size lowered as XLA's all-reduce
            # combiner threshold: the compiler merges the grouped psums into
            # fused collectives — the TPU-native form of the reference's
            # scoped-allocator chunk merge (all_reduce_strategy.py:21-90).
            # Env-gated: accepted option names vary by compile service (the
            # remote-TPU AOT path rejects xla_tpu_*); XLA's DEFAULT combiner
            # already merges same-program psums (verified in HLO), so the
            # flag only tunes the threshold.  Set e.g.
            # AUTODIST_COMBINER_FLAG=xla_gpu_all_reduce_combine_threshold_bytes.
            jit_kwargs["compiler_options"] = {flag: combiner}
        step_fn = jax.jit(
            step,
            in_shardings=(param_sh, opt_sh, sync_sh, None),
            out_shardings=(param_sh, opt_sh, sync_sh, None),
            # Numerics state, like stale-sync state, is rewritten every
            # step — donation-safe.
            donate_argnums=(0, 1) if stale is None and not num_active
            else (0, 1, 2),
            **jit_kwargs,
        )

        # Same loss_fn as training (the pad-aware wrapper), so padded rows
        # contribute nothing to evaluation.
        eval_fn = jax.jit(
            _make_eval_step(loss_fn, has_aux, extra_metrics_fn),
            in_shardings=(param_sh, None))
        init_fn = jax.jit(optimizer.init, out_shardings=opt_sh)
        if stale is None and num_active:
            def init_sync_state(current_params=None):
                from autodist_tpu.numerics import loss_scale as _lsm
                from autodist_tpu.numerics.guard import NUMERICS_KEY
                return {NUMERICS_KEY: _lsm.init_state(num_ls)}
        elif stale is None:
            def init_sync_state(current_params=None):
                return {}
        else:
            # Takes the CURRENT params (a set_params/checkpoint restore must
            # seed proxy caches from the restored values, not the capture-time
            # ones); jitted with out_shardings so the delay queue's zeros are
            # built shard-by-shard in place, never dense on one device.
            jit_init = jax.jit(stale.init_state, out_shardings=sync_sh)

            def init_sync_state(current_params=None):
                if current_params is None:
                    # The rare explicit-None path takes LOGICAL params.
                    current_params = params if pad_info is None \
                        else su.pad_tree(params, pad_info)
                return jit_init(current_params)

        # Logical-layout sharding trees (pad axis gathered) for the
        # checkpoint/export boundary — identical to physical when unpadded.
        opt_pad_info = logical_param_sh = logical_opt_sh = None
        if pad_info is not None:
            opt_pad_info = su.opt_spec_tree(opt_shape, phys_params, pad_info,
                                            default="")
            logical_param_specs = self._logical_specs(self._param_specs())
            logical_grad_specs = self._logical_specs(self._opt_specs())
            logical_param_sh = su.sharding_tree(
                mesh, su.spec_tree_for_params(params, logical_param_specs))
            opt_shape_logical = jax.eval_shape(optimizer.init, params)
            logical_opt_sh = su.sharding_tree(mesh, su.opt_spec_tree(
                opt_shape_logical, params,
                su.spec_tree_for_params(params, logical_grad_specs)))

        logging.info(
            "GraphTransformer: compiled step over mesh %s (%d vars: %s)",
            dict(mesh.shape), len(self.compiled.var_plans),
            _plan_summary(self.compiled))
        return DistributedStep(
            step_fn=step_fn, init_fn=init_fn,
            init_sync_state=init_sync_state,
            param_shardings=param_sh, opt_shardings=opt_sh,
            mesh=mesh, compiled_strategy=self.compiled,
            eval_fn=eval_fn,
            pad_info=pad_info, opt_pad_info=opt_pad_info,
            logical_param_shardings=logical_param_sh,
            logical_opt_shardings=logical_opt_sh,
            schedule_ir=sched)

    def _combiner_bytes(self) -> int:
        """Largest collective-group byte sum — the all-reduce combiner
        threshold that lets XLA merge each strategy group into one fused
        collective.  0 when no group has ≥2 members (grouping inert)."""
        best = 0
        for names in self.compiled.fusable_groups().values():
            total = sum(self.graph_item.info.by_name(n).byte_size
                        for n in names)
            best = max(best, total)
        return best

    def _logical_specs(self, specs: Dict[str, P]) -> Dict[str, P]:
        """Per-variable specs with the pad axis entry dropped (the logical
        view cannot be sharded along a dim that doesn't tile evenly)."""
        from autodist_tpu.strategy.compiler import spec_from_entries

        out: Dict[str, P] = {}
        for name, spec in specs.items():
            plan = self.compiled.var_plans[name]
            if plan.pad_axis is None:
                out[name] = spec
                continue
            entries = list(spec)
            if plan.pad_axis < len(entries):
                entries[plan.pad_axis] = None
            out[name] = spec_from_entries(entries)
        return out

    def _transform_explicit(self, extra_metrics_fn: Optional[Callable] = None
                            ) -> DistributedStep:
        """Compressor-carrying programs run the whole step inside shard_map
        with manual collectives (see explicit_sync module docstring)."""
        from autodist_tpu.kernel.synchronization import explicit_sync

        gi = self.graph_item
        mesh = self.compiled.mesh
        # extra metrics run OUTSIDE shard_map, on the updated params and the
        # GLOBAL batch — identical semantics to the GSPMD path (inside the
        # mapped step they would see only the local data shard and get
        # pmean-averaged, silently changing non-mean metrics).
        (step_fn, init_fn, init_sync, param_sh, opt_sh, rs_buckets,
         sched) = explicit_sync.make_explicit_step(gi, self.compiled)
        if extra_metrics_fn is not None:
            inner_step = step_fn

            def wrapped(params, opt_state, sync_state, batch):
                params, opt_state, sync_state, metrics = inner_step(
                    params, opt_state, sync_state, batch)
                metrics = _merge_metrics(metrics,
                                         extra_metrics_fn(params, batch))
                return params, opt_state, sync_state, metrics

            # Donation must live on the OUTER jit (the inner jit inlines
            # under tracing and its donate_argnums are ignored).
            step_fn = jax.jit(wrapped, donate_argnums=(0, 1, 2))
        eval_fn = jax.jit(
            _make_eval_step(gi.loss_fn, gi.has_aux, extra_metrics_fn))
        logging.info(
            "GraphTransformer: compiled EXPLICIT step over mesh %s (%d vars)",
            dict(mesh.shape), len(self.compiled.var_plans))
        return DistributedStep(
            step_fn=step_fn, init_fn=init_fn, init_sync_state=init_sync,
            param_shardings=param_sh, opt_shardings=opt_sh,
            mesh=mesh, compiled_strategy=self.compiled, eval_fn=eval_fn,
            zero1_buckets=tuple(rs_buckets), schedule_ir=sched)


def _make_eval_step(loss_fn: Callable, has_aux: bool,
                    metrics_fn: Optional[Callable] = None) -> Callable:
    """Fetch-only metrics step (the reference's ``sess.run(loss)``): loss
    (+ captured ``metrics_fn`` extras) on the current params, no state
    change."""
    def eval_step(params, batch):
        if has_aux:
            loss, aux = loss_fn(params, batch)
            out = {"loss": loss, "aux": aux}
        else:
            out = {"loss": loss_fn(params, batch)}
        if metrics_fn is not None:
            out = _merge_metrics(out, metrics_fn(params, batch))
        return out

    return eval_step


def _accumulate_grads(vg: Callable, accum: int, has_aux: bool) -> Callable:
    """Wrap a value-and-grad so one step averages gradients over ``accum``
    microbatches (leading-dim split) under a ``lax.scan`` — effective
    batch B at the live activation memory of B/accum.  Exact for row-mean
    losses (every bundled model): the mean of per-microbatch means equals
    the full-batch mean, and likewise for their gradients.  With
    ``has_aux`` the returned aux is STACKED along a leading [accum] axis.

    A leading dim that does not divide ``accum`` runs UNEVEN tail
    microbatches: the first ``dim % accum`` microbatches carry one extra
    row, the loop unrolls (shapes differ per microbatch, so no scan),
    and every contribution is weighted by its row count — still exactly
    the full-batch mean for row-mean losses.

    On the explicit compressor path this wrapper runs INSIDE shard_map,
    so the leading dim it splits is the device's LOCAL batch slice
    (global batch / data-axis size) — that is what must divide (or at
    least reach) accum.
    """
    from jax import lax

    def vg_accum(params, batch):
        leaves = jax.tree_util.tree_leaves(batch)
        dims = {leaf.shape[0] for leaf in leaves}
        if len(dims) != 1:
            raise ValueError(
                f"batch leaves disagree on the leading dim: {sorted(dims)}")
        (length,) = dims
        if length % accum:
            return _uneven_accumulate(vg, accum, has_aux, params, batch,
                                      length)
        mbs = jax.tree_util.tree_map(
            lambda x: x.reshape((accum, x.shape[0] // accum) + x.shape[1:]),
            batch)

        def body(carry, mb):
            loss_acc, g_acc = carry
            if has_aux:
                (loss, aux), g = vg(params, mb)
            else:
                loss, g = vg(params, mb)
                aux = None
            g_acc = jax.tree_util.tree_map(
                lambda a, b: a + b.astype(a.dtype), g_acc, g)
            return (loss_acc + loss.astype(jax.numpy.float32), g_acc), aux

        # f32 accumulators: microbatch grads may be bf16; summing accum of
        # them in bf16 loses low bits the single-pass computation keeps.
        # The final average casts back to the grad dtypes autodiff made.
        g_shapes = jax.eval_shape(lambda p, b: vg(p, b)[1], params,
                                  jax.tree_util.tree_map(
                                      lambda x: x[0], mbs))
        g0 = jax.tree_util.tree_map(
            lambda s: jax.numpy.zeros(s.shape, jax.numpy.float32), g_shapes)
        (loss_sum, g_sum), auxs = lax.scan(
            body, (jax.numpy.float32(0.0), g0), mbs)
        grads = jax.tree_util.tree_map(
            lambda g, s: (g / accum).astype(s.dtype), g_sum, g_shapes)
        loss = loss_sum / accum
        if has_aux:
            return (loss, auxs), grads
        return loss, grads

    return vg_accum


def _uneven_accumulate(vg: Callable, accum: int, has_aux: bool,
                       params, batch, length: int):
    """Row-weighted accumulation over uneven microbatches (the tail of
    ``_accumulate_grads``): unrolled because microbatch shapes differ.
    ``sum_k (rows_k / length) · mean_k`` equals the full-batch mean for
    row-mean losses, so the trajectory matches the divisible case."""
    from jax import lax

    from autodist_tpu.kernel.synchronization.overlap import microbatch_slices

    slices = microbatch_slices(length, accum)
    loss_acc = jax.numpy.float32(0.0)
    g_acc = None
    auxs = []
    for off, rows in slices:
        mb = jax.tree_util.tree_map(
            lambda x: lax.dynamic_slice_in_dim(x, off, rows, 0), batch)
        if has_aux:
            (loss, aux), g = vg(params, mb)
            auxs.append(aux)
        else:
            loss, g = vg(params, mb)
        w = rows / length
        loss_acc = loss_acc + w * loss.astype(jax.numpy.float32)
        if g_acc is None:
            g_acc = jax.tree_util.tree_map(
                lambda x: w * x.astype(jax.numpy.float32), g)
        else:
            g_acc = jax.tree_util.tree_map(
                lambda a, x: a + w * x.astype(jax.numpy.float32), g_acc, g)
    grads = jax.tree_util.tree_map(
        lambda a, x: a.astype(x.dtype), g_acc, g)
    if has_aux:
        aux = jax.tree_util.tree_map(lambda *xs: jax.numpy.stack(xs), *auxs)
        return (loss_acc, aux), grads
    return loss_acc, grads


def _merge_metrics(metrics: Dict, extra: Dict) -> Dict:
    """Merge user metrics, refusing to clobber the framework's keys."""
    overlap = set(metrics) & set(extra)
    if overlap:
        raise ValueError(
            f"metrics_fn returned reserved metric key(s) {sorted(overlap)}; "
            "rename them — 'loss' and 'aux' are produced by the step itself")
    out = dict(metrics)
    out.update(extra)
    return out


def _plan_summary(compiled: CompiledStrategy) -> Dict[str, int]:
    out: Dict[str, int] = {}
    for plan in compiled.var_plans.values():
        key = plan.sync_kind + ("/part" if plan.param_spec != P() else "")
        out[key] = out.get(key, 0) + 1
    return out
