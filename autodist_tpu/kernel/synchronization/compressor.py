"""Gradient compressors wrapping the data-axis all-reduce.

Parity: reference ``autodist/kernel/synchronization/compressor.py`` —
``NoneCompressor`` (:36-96, identity), ``HorovodCompressor`` (:146-176,
dtype-cast compression), ``HorovodCompressorEF`` (:208-284, error feedback),
``PowerSGDCompressor`` (commented out in the reference; implemented here as
a rank-r low-rank compressor since TPU matmuls make it cheap).

TPU-native formulation: a compressor is a pure function around
``lax.pmean``/``psum`` inside a ``shard_map`` over the ``data`` axis.  Any
per-worker persistent state (error-feedback residuals, PowerSGD factors) is
carried explicitly as a *sync state* pytree, sharded so each data shard owns
its own slice — functional replacement for the reference's stateful mirror
variables.
"""
from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from autodist_tpu.kernel.synchronization import quant_ring
from autodist_tpu.utils import compat


class Compressor:
    """Base: compress → all-reduce → decompress, with optional state.

    ``bucketable`` marks compressors whose wire format composes with the
    FLAT gradient buckets of the explicit path (``bucketing.py``): the
    compression must be elementwise (or flat-vector) so quantizing one
    concatenated bucket equals quantizing its members — the EQuARX
    per-collective scale grid.  Bucketable compressors also implement
    :meth:`reduce_scatter`, the ZeRO-1 leg: reduce the bucket but return
    only this shard's ``1/axis_size`` slice of the mean, so the weight
    update can run on the local optimizer-state shard.

    Quantized-wire compressors (int8/fp8, ``quant_ring.WIRE_FORMATS``)
    additionally implement the bucket-level :meth:`bucket_reduce` /
    :meth:`bucket_reduce_scatter` entry points the explicit path lowers
    through: they take the schedule IR's resolved algorithm (per-hop
    requantizing ring vs one-shot collective) and return the
    post-quantization saturation count alongside the reduced value and
    the new error-feedback state.
    """

    name = "Compressor"
    bucketable = True

    def init_state(self, var_value) -> Any:
        """Per-device sync state for one variable or bucket (local shape —
        the explicit path stacks it along a leading per-shard axis).
        None if stateless."""
        return None

    def reduce(self, grad, state, axis_name: str) -> Tuple[Any, Any]:
        """Return (globally averaged gradient, new state)."""
        raise NotImplementedError

    def reduce_scatter(self, vec, state, axis_name: str) -> Tuple[Any, Any]:
        """Return (this shard's slice of the globally averaged ``vec``,
        new state).  ``vec`` is a flat bucket whose length divides the
        axis size (``bucketing`` pads the tail).  Only defined for
        ``bucketable`` compressors."""
        raise NotImplementedError(
            f"{self.name} does not support reduce-scatter (ZeRO-1) mode")


class NoneCompressor(Compressor):
    """Identity compression: plain pmean (reference compressor.py:36-96)."""

    name = "NoneCompressor"

    def reduce(self, grad, state, axis_name):
        return lax.pmean(grad, axis_name), state

    def reduce_scatter(self, vec, state, axis_name):
        n = compat.axis_size(axis_name)
        shard = lax.psum_scatter(vec, axis_name, scatter_dimension=0,
                                 tiled=True)
        return shard / n, state


class HorovodCompressor(Compressor):
    """Cast-down compression: reduce in lower precision, cast back
    (reference compressor.py:146-176).  On TPU the wire format is bfloat16 —
    same exponent range as fp32, so no overflow handling is needed."""

    name = "HorovodCompressor"

    def __init__(self, wire_dtype=jnp.bfloat16):
        self._wire = wire_dtype

    def reduce(self, grad, state, axis_name):
        orig = grad.dtype
        compressed = grad.astype(self._wire)
        summed = lax.pmean(compressed, axis_name)
        return summed.astype(orig), state

    def reduce_scatter(self, vec, state, axis_name):
        n = compat.axis_size(axis_name)
        shard = lax.psum_scatter(vec.astype(self._wire), axis_name,
                                 scatter_dimension=0, tiled=True)
        return (shard / n).astype(vec.dtype), state


class HorovodCompressorEF(Compressor):
    """Error-feedback cast compression (reference compressor.py:208-284):
    the quantization error of each round is added back before the next
    compression, preserving convergence (Karimireddy et al., 2019)."""

    name = "HorovodCompressorEF"

    def __init__(self, wire_dtype=jnp.bfloat16):
        self._wire = wire_dtype

    def init_state(self, var_value):
        return jnp.zeros_like(var_value)

    def reduce(self, grad, state, axis_name):
        corrected = grad + state
        compressed = corrected.astype(self._wire)
        new_state = corrected - compressed.astype(grad.dtype)  # local residual
        summed = lax.pmean(compressed, axis_name)
        return summed.astype(grad.dtype), new_state

    def reduce_scatter(self, vec, state, axis_name):
        # Residual is computable locally BEFORE the scatter (it depends
        # only on this device's quantization error), so error feedback
        # composes with the ZeRO-1 leg at full-bucket state size.
        n = compat.axis_size(axis_name)
        corrected = vec + state
        compressed = corrected.astype(self._wire)
        new_state = corrected - compressed.astype(vec.dtype)
        shard = lax.psum_scatter(compressed, axis_name,
                                 scatter_dimension=0, tiled=True)
        return (shard / n).astype(vec.dtype), new_state


class PowerSGDCompressor(Compressor):
    """Rank-r PowerSGD (Vogels et al., 2019).  The reference carries a
    commented-out implementation (compressor.py:208-284 vicinity); on TPU the
    two small matmuls ride the MXU so low-rank compression is near-free.

    Only applied to rank-2 gradients; others fall back to pmean.  State is
    ``(Q, residual)``: the power-iteration basis and the error feedback.
    """

    name = "PowerSGDCompressor"
    # Low-rank factors need the 2-D gradient; flattening into a bucket
    # would silently disable the compression (every flat vector falls
    # back to pmean), so PowerSGD vars keep their per-variable collective.
    bucketable = False

    def __init__(self, rank: int = 1):
        self.rank = rank

    def init_state(self, var_value):
        shape = tuple(var_value.shape)
        if len(shape) != 2:
            return None
        n, m = shape
        # Deterministic init: varied, full-rank-ish basis.
        q = jax.random.normal(jax.random.PRNGKey(n * 31 + m), (m, self.rank),
                              dtype=var_value.dtype)
        residual = jnp.zeros(shape, var_value.dtype)
        return {"q": q, "residual": residual}

    def reduce(self, grad, state, axis_name):
        if state is None or grad.ndim != 2:
            return lax.pmean(grad, axis_name), state
        q, residual = state["q"], state["residual"]
        corrected = grad + residual
        # P = M Q ; all-reduce P ; orthonormalize ; Q = Mᵀ P̂ ; all-reduce Q
        p = corrected @ q
        p = lax.pmean(p, axis_name)
        p_hat, _ = jnp.linalg.qr(p)
        new_q = corrected.T @ p_hat
        new_q = lax.pmean(new_q, axis_name)
        approx = p_hat @ new_q.T
        new_residual = corrected - approx
        return approx, {"q": new_q, "residual": new_residual}


class QuantizedRingCompressor(Compressor):
    """Quantized-wire all-reduce with error feedback on the per-chunk
    scale grid (EQuARX-style, arxiv 2506.17615: quantized collectives
    cut ICI/DCN bytes ~4x vs f32 at negligible quality loss when
    error-compensated).

    The collectives are built MANUALLY so the 1-byte wire format is what
    actually crosses the interconnect (a dtype round-trip in front of
    ``psum`` would still move 4 bytes/element).  ALL tiers share one
    quantization rule — ``quant_ring.quantize_blocks``'s per-chunk
    scale grid, scales traveling with the payload: the single-collective
    ``all_to_all`` reduce-scatter + re-quantized ``all_gather`` used
    here and by the GSPMD/per-variable tier, and the per-hop
    requantizing ppermute ring the explicit bucketed path lowers to via
    :meth:`bucket_reduce` when the schedule IR resolves ``alg="ring"``.
    Stage-1 quantization error is carried as local error-feedback state
    (Karimireddy et al., 2019); stage-2 (post-aggregation) error is
    uncompensated, as in EQuARX.  Subclasses pin the wire format
    (int8 or fp8 e4m3 via ml_dtypes).
    """

    name = "QuantizedRingCompressor"
    wire = quant_ring.WIRE_INT8

    def init_state(self, var_value):
        return jnp.zeros_like(var_value)

    def reduce(self, grad, state, axis_name):
        n = compat.axis_size(axis_name)
        flat = (grad + state).astype(jnp.float32).ravel()
        pad = (-flat.size) % n
        if pad:
            flat = jnp.concatenate([flat, jnp.zeros((pad,), jnp.float32)])
        mean, new_state, _ = quant_ring.quant_bucket_reduce(
            flat, jnp.zeros_like(flat), axis_name, n, self.wire,
            mode="all_reduce", alg="fused")
        new_state = new_state[:grad.size].reshape(grad.shape) \
            .astype(grad.dtype)
        return mean[:grad.size].reshape(grad.shape).astype(grad.dtype), \
            new_state

    def reduce_scatter(self, vec, state, axis_name):
        # ZeRO-1 leg = EQuARX stage 1 alone: the quantized reduce-scatter
        # already puts 1-byte payloads on the wire; the stage-2
        # re-quantized all-gather is simply not needed (fresh params are
        # gathered instead).  No stage-2 quantization error either.
        n = compat.axis_size(axis_name)
        shard, new_state, _ = self.bucket_reduce_scatter(
            vec, state, axis_name, n, alg="fused")
        return shard, new_state

    # -- bucket-level entry points (explicit path; docs/overlap.md) -------
    def bucket_reduce(self, vec, state, axis_name, n, alg="fused",
                      hop_fused=False):
        """Full mean of flat ``vec`` through the quantized wire under
        the IR-resolved ``alg``; returns ``(mean, new_state,
        sat_count)`` — the saturation counter feeds GradHealth.
        ``hop_fused`` selects the fused Pallas hop boundary for ring
        chains (the IR bucket node's ``hop_fused`` flag,
        docs/kernels.md)."""
        return quant_ring.quant_bucket_reduce(
            vec, state, axis_name, n, self.wire,
            mode="all_reduce", alg=alg, fused=hop_fused)

    def bucket_reduce_scatter(self, vec, state, axis_name, n, alg="fused",
                              hop_fused=False):
        """This device's 1/n mean shard (ZeRO-1 leg) — the update runs
        on the f32-dequantized shard; returns ``(shard, new_state,
        sat_count)``."""
        return quant_ring.quant_bucket_reduce(
            vec, state, axis_name, n, self.wire,
            mode="reduce_scatter", alg=alg, fused=hop_fused)


class Int8Compressor(QuantizedRingCompressor):
    """Int8 wire (±127 grid), per-chunk scales."""

    name = "Int8Compressor"
    wire = quant_ring.WIRE_INT8


class Fp8Compressor(QuantizedRingCompressor):
    """Fp8 e4m3 wire (``ml_dtypes.float8_e4m3fn``, max finite 448):
    same byte count as int8 with a floating grid — more dynamic range
    per block, coarser steps near the block amax."""

    name = "Fp8Compressor"
    wire = quant_ring.WIRE_FP8_E4M3


_REGISTRY: Dict[str, type] = {
    c.name: c for c in (NoneCompressor, HorovodCompressor, HorovodCompressorEF,
                        PowerSGDCompressor, Int8Compressor, Fp8Compressor)
}


def get_compressor(name: str) -> Compressor:
    if name not in _REGISTRY:
        raise ValueError(f"unknown compressor {name!r}; "
                         f"available: {sorted(_REGISTRY)}")
    return _REGISTRY[name]()
