"""Gradient compressors wrapping the data-axis all-reduce.

Parity: reference ``autodist/kernel/synchronization/compressor.py`` —
``NoneCompressor`` (:36-96, identity), ``HorovodCompressor`` (:146-176,
dtype-cast compression), ``HorovodCompressorEF`` (:208-284, error feedback),
``PowerSGDCompressor`` (commented out in the reference; implemented here as
a rank-r low-rank compressor since TPU matmuls make it cheap).

TPU-native formulation: a compressor is a pure function around
``lax.pmean``/``psum`` inside a ``shard_map`` over the ``data`` axis.  Any
per-worker persistent state (error-feedback residuals, PowerSGD factors) is
carried explicitly as a *sync state* pytree, sharded so each data shard owns
its own slice — functional replacement for the reference's stateful mirror
variables.
"""
from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from autodist_tpu.utils import compat


class Compressor:
    """Base: compress → all-reduce → decompress, with optional state.

    ``bucketable`` marks compressors whose wire format composes with the
    FLAT gradient buckets of the explicit path (``bucketing.py``): the
    compression must be elementwise (or flat-vector) so quantizing one
    concatenated bucket equals quantizing its members — the EQuARX
    per-collective scale grid.  Bucketable compressors also implement
    :meth:`reduce_scatter`, the ZeRO-1 leg: reduce the bucket but return
    only this shard's ``1/axis_size`` slice of the mean, so the weight
    update can run on the local optimizer-state shard.
    """

    name = "Compressor"
    bucketable = True

    def init_state(self, var_value) -> Any:
        """Per-device sync state for one variable or bucket (local shape —
        the explicit path stacks it along a leading per-shard axis).
        None if stateless."""
        return None

    def reduce(self, grad, state, axis_name: str) -> Tuple[Any, Any]:
        """Return (globally averaged gradient, new state)."""
        raise NotImplementedError

    def reduce_scatter(self, vec, state, axis_name: str) -> Tuple[Any, Any]:
        """Return (this shard's slice of the globally averaged ``vec``,
        new state).  ``vec`` is a flat bucket whose length divides the
        axis size (``bucketing`` pads the tail).  Only defined for
        ``bucketable`` compressors."""
        raise NotImplementedError(
            f"{self.name} does not support reduce-scatter (ZeRO-1) mode")


class NoneCompressor(Compressor):
    """Identity compression: plain pmean (reference compressor.py:36-96)."""

    name = "NoneCompressor"

    def reduce(self, grad, state, axis_name):
        return lax.pmean(grad, axis_name), state

    def reduce_scatter(self, vec, state, axis_name):
        n = compat.axis_size(axis_name)
        shard = lax.psum_scatter(vec, axis_name, scatter_dimension=0,
                                 tiled=True)
        return shard / n, state


class HorovodCompressor(Compressor):
    """Cast-down compression: reduce in lower precision, cast back
    (reference compressor.py:146-176).  On TPU the wire format is bfloat16 —
    same exponent range as fp32, so no overflow handling is needed."""

    name = "HorovodCompressor"

    def __init__(self, wire_dtype=jnp.bfloat16):
        self._wire = wire_dtype

    def reduce(self, grad, state, axis_name):
        orig = grad.dtype
        compressed = grad.astype(self._wire)
        summed = lax.pmean(compressed, axis_name)
        return summed.astype(orig), state

    def reduce_scatter(self, vec, state, axis_name):
        n = compat.axis_size(axis_name)
        shard = lax.psum_scatter(vec.astype(self._wire), axis_name,
                                 scatter_dimension=0, tiled=True)
        return (shard / n).astype(vec.dtype), state


class HorovodCompressorEF(Compressor):
    """Error-feedback cast compression (reference compressor.py:208-284):
    the quantization error of each round is added back before the next
    compression, preserving convergence (Karimireddy et al., 2019)."""

    name = "HorovodCompressorEF"

    def __init__(self, wire_dtype=jnp.bfloat16):
        self._wire = wire_dtype

    def init_state(self, var_value):
        return jnp.zeros_like(var_value)

    def reduce(self, grad, state, axis_name):
        corrected = grad + state
        compressed = corrected.astype(self._wire)
        new_state = corrected - compressed.astype(grad.dtype)  # local residual
        summed = lax.pmean(compressed, axis_name)
        return summed.astype(grad.dtype), new_state

    def reduce_scatter(self, vec, state, axis_name):
        # Residual is computable locally BEFORE the scatter (it depends
        # only on this device's quantization error), so error feedback
        # composes with the ZeRO-1 leg at full-bucket state size.
        n = compat.axis_size(axis_name)
        corrected = vec + state
        compressed = corrected.astype(self._wire)
        new_state = corrected - compressed.astype(vec.dtype)
        shard = lax.psum_scatter(compressed, axis_name,
                                 scatter_dimension=0, tiled=True)
        return (shard / n).astype(vec.dtype), new_state


class PowerSGDCompressor(Compressor):
    """Rank-r PowerSGD (Vogels et al., 2019).  The reference carries a
    commented-out implementation (compressor.py:208-284 vicinity); on TPU the
    two small matmuls ride the MXU so low-rank compression is near-free.

    Only applied to rank-2 gradients; others fall back to pmean.  State is
    ``(Q, residual)``: the power-iteration basis and the error feedback.
    """

    name = "PowerSGDCompressor"
    # Low-rank factors need the 2-D gradient; flattening into a bucket
    # would silently disable the compression (every flat vector falls
    # back to pmean), so PowerSGD vars keep their per-variable collective.
    bucketable = False

    def __init__(self, rank: int = 1):
        self.rank = rank

    def init_state(self, var_value):
        shape = tuple(var_value.shape)
        if len(shape) != 2:
            return None
        n, m = shape
        # Deterministic init: varied, full-rank-ish basis.
        q = jax.random.normal(jax.random.PRNGKey(n * 31 + m), (m, self.rank),
                              dtype=var_value.dtype)
        residual = jnp.zeros(shape, var_value.dtype)
        return {"q": q, "residual": residual}

    def reduce(self, grad, state, axis_name):
        if state is None or grad.ndim != 2:
            return lax.pmean(grad, axis_name), state
        q, residual = state["q"], state["residual"]
        corrected = grad + residual
        # P = M Q ; all-reduce P ; orthonormalize ; Q = Mᵀ P̂ ; all-reduce Q
        p = corrected @ q
        p = lax.pmean(p, axis_name)
        p_hat, _ = jnp.linalg.qr(p)
        new_q = corrected.T @ p_hat
        new_q = lax.pmean(new_q, axis_name)
        approx = p_hat @ new_q.T
        new_residual = corrected - approx
        return approx, {"q": new_q, "residual": new_residual}


class Int8Compressor(Compressor):
    """Tensor-scaled int8 quantized all-reduce with error feedback
    (EQuARX-style, arxiv 2506.17615: quantized collectives cut ICI/DCN
    bytes ~4x vs f32 at negligible quality loss when error-compensated).

    The all-reduce is built MANUALLY so int8 is what actually crosses the
    wire (a dtype round-trip in front of ``psum`` would still move 4
    bytes/element): quantized reduce-scatter via ``all_to_all``, local
    dequantize-and-sum in f32, then a re-quantized ``all_gather`` — the
    EQuARX double-quantization scheme.  Scales are shared via scalar
    ``pmax`` so every shard uses one grid.  Stage-1 quantization error is
    carried as local error-feedback state (Karimireddy et al., 2019);
    stage-2 (post-aggregation) error is uncompensated, as in EQuARX.
    """

    name = "Int8Compressor"

    def init_state(self, var_value):
        return jnp.zeros_like(var_value)

    @staticmethod
    def _quantize(x, axis_name):
        amax = lax.pmax(jnp.max(jnp.abs(x)), axis_name)
        scale = jnp.maximum(amax / 127.0, 1e-30)
        q = jnp.clip(jnp.round(x / scale), -127, 127).astype(jnp.int8)
        return q, scale

    def reduce(self, grad, state, axis_name):
        n = compat.axis_size(axis_name)
        corrected = (grad + state).astype(jnp.float32)
        flat = corrected.ravel()
        pad = (-flat.size) % n
        if pad:
            flat = jnp.concatenate([flat, jnp.zeros((pad,), jnp.float32)])

        q, scale = self._quantize(flat, axis_name)
        err = flat - q.astype(jnp.float32) * scale            # stage-1 error
        new_state = err[:grad.size].reshape(grad.shape).astype(grad.dtype)

        # Quantized reduce-scatter: chunk j of every shard lands on shard j
        # (int8 wire), then dequantize + sum in f32 locally.
        recv = lax.all_to_all(q.reshape(n, -1), axis_name,
                              split_axis=0, concat_axis=0)
        owned_sum = jnp.sum(recv.astype(jnp.float32), axis=0) * scale

        # Re-quantized all-gather of the aggregated chunk (int8 wire again).
        q2, scale2 = self._quantize(owned_sum, axis_name)
        gathered = lax.all_gather(q2, axis_name, axis=0).reshape(-1)
        mean = gathered.astype(jnp.float32) * (scale2 / n)
        return mean[:grad.size].reshape(grad.shape).astype(grad.dtype), \
            new_state

    def reduce_scatter(self, vec, state, axis_name):
        # ZeRO-1 leg = EQuARX stage 1 alone: the quantized all_to_all
        # already IS a reduce-scatter with an int8 wire; the stage-2
        # re-quantized all-gather is simply not needed (fresh params are
        # gathered instead).  No stage-2 quantization error either.
        n = compat.axis_size(axis_name)
        corrected = (vec + state).astype(jnp.float32)
        q, scale = self._quantize(corrected, axis_name)
        err = corrected - q.astype(jnp.float32) * scale
        new_state = err.astype(vec.dtype)
        recv = lax.all_to_all(q.reshape(n, -1), axis_name,
                              split_axis=0, concat_axis=0)
        owned_mean = jnp.sum(recv.astype(jnp.float32), axis=0) * (scale / n)
        return owned_mean.astype(vec.dtype), new_state


_REGISTRY: Dict[str, type] = {
    c.name: c for c in (NoneCompressor, HorovodCompressor, HorovodCompressorEF,
                        PowerSGDCompressor, Int8Compressor)
}


def get_compressor(name: str) -> Compressor:
    if name not in _REGISTRY:
        raise ValueError(f"unknown compressor {name!r}; "
                         f"available: {sorted(_REGISTRY)}")
    return _REGISTRY[name]()
