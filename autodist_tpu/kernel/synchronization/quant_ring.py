"""EQuARX-style quantized ring collectives: int8/fp8 wire, per-chunk scales.

The PR 3 ring machinery (``overlap.ring_reduce_scatter``) made large-bucket
collectives schedulable ppermute legs; this module makes the QUANTIZED
collective a first-class instance of the same shape (EQuARX,
arXiv:2506.17615: quantized TPU collectives cut ICI/DCN bytes ~4x at
negligible quality loss when error-compensated).  Three design rules:

1. **Per-chunk scale grid, computed in the legs.**  A flat vector is
   quantized in :data:`QUANT_BLOCK_ELEMS`-element blocks, each with its
   own f32 scale (``amax / qmax``); the scales travel WITH the payload
   (ppermute'd alongside it, or ``all_to_all``'d in the single-collective
   lowering) — no extra ``pmax`` collective, no tensor-wide grid that one
   outlier flattens.  One quantization rule for every tier: the ring
   hops, the single-collective ``all_to_all`` reduce-scatter, and the
   GSPMD/per-variable path all call the same :func:`quantize_blocks`.
2. **Dequantize → accumulate in f32 → requantize per hop.**  A ring hop
   receives the quantized partial, dequantizes it, adds its own f32
   chunk, and requantizes with fresh per-chunk scales for the next hop —
   the partial sum never travels wider than 1 byte/element + scales.
   Stage-1 quantization error (every requantize before the partial
   reaches its owner) is returned vector-shaped so the caller can carry
   it as error feedback in sync_state; stage-2 error (the re-quantized
   all-gather of the aggregated value) is uncompensated, as in EQuARX.
3. **Saturation observed where it happens.**  Each quantize event counts
   the elements it clipped to the wire rail (|q| > ±127 pre-clip for
   int8, an fp8-overflow for e4m3) or received non-finite — with amax
   scaling these counters are zero on healthy gradients, so a non-zero
   count is a wire-saturation alarm raised INSIDE the leg that saw it,
   not estimated before the collective.  Counts roll into the numerics
   guard's one-psum health rollup (``GradHealth.per_bucket``).

Everything that *decides* here (which compressors ring-quantize, scale
byte accounting) is pure and jax-free at module import, so the schedule
IR builder, the static verifier, and the cost model share the exact
rules the runtime lowers (the ``bucket_drop_reason`` pattern).  The
traced collectives import jax lazily, like ``overlap.py``.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Tuple

#: per-chunk scale-grid granularity: one f32 scale per this many
#: elements (4 bytes of scale per 256 payload bytes ≈ 1.6% overhead on
#: the int8 wire).  Small enough that one outlier only flattens its own
#: block's grid; large enough that scales stay a rounding error in the
#: wire-byte budget.
QUANT_BLOCK_ELEMS = 256


@dataclass(frozen=True)
class WireFormat:
    """One quantized wire format (pure metadata, shared with the IR)."""

    name: str       # numpy/ml_dtypes dtype name on the wire
    qmax: float     # largest finite wire magnitude the grid targets
    itemsize: int = 1


WIRE_INT8 = WireFormat(name="int8", qmax=127.0)
#: fp8 e4m3 (ml_dtypes float8_e4m3fn): max finite 448, no inf encoding.
WIRE_FP8_E4M3 = WireFormat(name="float8_e4m3fn", qmax=448.0)

#: compressors whose bucket collectives may lower to quantized ring legs
#: (per-hop scale grids) and pipeline one quantized collective per
#: microbatch slot — the relaxed ``schedule/quantized-pipelined`` shape.
WIRE_FORMATS = {
    "Int8Compressor": WIRE_INT8,
    "Fp8Compressor": WIRE_FP8_E4M3,
}


def wire_format_of(compressor: str) -> Optional[WireFormat]:
    """The quantized wire format ``compressor`` puts on the ring, or
    None for full-precision / cast-based compressors."""
    return WIRE_FORMATS.get(compressor or "")


def is_quant_ring_compressor(compressor: str) -> bool:
    """Does this compressor own a per-hop scale-grid ring lowering (and
    therefore the per-microbatch-slot pipelining contract)?"""
    return (compressor or "") in WIRE_FORMATS


def ring_applies(mode: str, nbytes: int, d: int, threshold: int) -> bool:
    """Does a quantized bucket ring-decompose?  Pure rule shared by the
    IR builder and the lowering: only under an EXPLICIT ring request
    (``overlap="ring"``/``"full"``) — per-hop requantization changes the
    wire numerics vs the one-shot quantized collective, and ``auto``
    never changes numerics — and only when the bucket clears the same
    byte threshold linear buckets use."""
    from autodist_tpu.kernel.synchronization import overlap as ov
    return (mode in (ov.OVERLAP_RING, ov.OVERLAP_FULL) and d > 1
            and int(nbytes) >= int(threshold))


def scale_count(length: int, block: int = QUANT_BLOCK_ELEMS) -> int:
    """Number of per-chunk scales covering ``length`` elements."""
    return -(-int(length) // int(block)) if length else 0


def scale_nbytes(length: int, block: int = QUANT_BLOCK_ELEMS) -> int:
    """Bytes of f32 scales accompanying ``length`` quantized elements."""
    return 4 * scale_count(length, block)


def wire_nbytes(length: int, fmt: WireFormat,
                block: int = QUANT_BLOCK_ELEMS) -> int:
    """Honest wire bytes of one quantized transfer of ``length``
    elements: 1-byte/elem payload (fp8 likewise) + per-chunk scales."""
    return int(length) * fmt.itemsize + scale_nbytes(length, block)


# -- traced quantize/dequantize (the one quantization rule) ------------------

def _wire_dtype(fmt: WireFormat):
    import jax.numpy as jnp

    return jnp.int8 if fmt.name == "int8" else jnp.float8_e4m3fn


def quantize_blocks(x, fmt: WireFormat, block: int = QUANT_BLOCK_ELEMS
                    ) -> Tuple:
    """Quantize flat f32 ``x`` on the per-chunk scale grid.

    Returns ``(q, scales, sat_count)``: the wire payload (``fmt``'s
    dtype, same length as ``x``), one f32 scale per
    :data:`QUANT_BLOCK_ELEMS` block (``amax / qmax``, floored away from
    zero so all-zero blocks stay exact), and the scalar count of
    elements this quantize event clipped to the rail or received
    non-finite — the post-quantization saturation counter the numerics
    guard rolls up."""
    import jax.numpy as jnp

    from autodist_tpu.ops import quant_scale

    length = x.shape[0]
    nb = scale_count(length, block)
    pad = nb * block - length
    xp = jnp.pad(x, (0, pad)) if pad else x
    xb = xp.reshape(nb, block)
    finite = jnp.isfinite(xb)
    # The grid is set by the block's FINITE amax: a stray Inf/NaN lands
    # in the saturation counter instead of flattening its neighbors'
    # scale to zero resolution.  Scale + clip arithmetic is the shared
    # rule in ops/quant_scale.py — the fused hop kernel
    # (ops/fused_kernels.py) calls the same helpers, so the two wire
    # formats cannot drift.
    amax = jnp.max(jnp.where(finite, jnp.abs(xb), 0.0), axis=1)
    scales = quant_scale.chunk_scale(amax, fmt.qmax)
    y = xb / scales[:, None]
    rounded = fmt.name == "int8"
    sat = quant_scale.saturation_count(y, finite, fmt.qmax,
                                       rounded=rounded)
    q = quant_scale.quantize_values(y, fmt.qmax, _wire_dtype(fmt),
                                    rounded=rounded)
    if pad:
        # padded tail is zero: quantizes exactly, never counts.
        q = q.reshape(-1)[:length]
    else:
        q = q.reshape(-1)
    return q, scales, sat.astype(jnp.float32)


def dequantize_blocks(q, scales, block: int = QUANT_BLOCK_ELEMS):
    """Inverse of :func:`quantize_blocks`: f32 values, same length."""
    import jax.numpy as jnp

    length = q.shape[0]
    nb = scales.shape[0]
    pad = nb * block - length
    qf = q.astype(jnp.float32)
    if pad:
        qf = jnp.pad(qf, (0, pad))
    out = (qf.reshape(nb, block) * scales[:, None]).reshape(-1)
    return out[:length] if pad else out


# -- quantized ring collectives (trace-time, inside shard_map) ---------------

def quantized_ring_reduce_scatter(vec, axis_name: str, n: int,
                                  fmt: WireFormat,
                                  block: int = QUANT_BLOCK_ELEMS,
                                  fused: bool = False):
    """Sum-reduce-scatter of flat ``vec`` (length divisible by ``n``) as
    n−1 quantized ppermute ring hops.

    Each hop quantizes the f32 partial with fresh per-chunk scales,
    sends payload + scales, dequantizes on arrival, and adds the
    receiver's own chunk in f32 — device ``r`` ends with the f32
    ``sum_d chunks_d[r]``.  Returns ``(shard_sum, err, sat_count)``:
    ``err`` is THIS device's injected stage-1 quantization error,
    vector-shaped with each hop's error at the chunk position it was
    quantizing (the error-feedback contract: feed it back into the next
    round's input and the bias cancels, Karimireddy et al., 2019).

    ``fused=True`` lowers each hop BOUNDARY through the fused Pallas
    kernels (``ops/fused_kernels.py``, docs/kernels.md): dequantize the
    received payload, add the local chunk, and requantize for the next
    send in one kernel — the f32 partial stays in VMEM between wire
    formats instead of round-tripping HBM, and the error + saturation
    count come out of the same pass.  Same scale rule
    (``ops/quant_scale.py``), same hop order, same wire bytes — the
    fused and unfused paths agree to float round-off (the wire payloads
    bit-equal)."""
    import jax.numpy as jnp
    from jax import lax

    from autodist_tpu.telemetry.timeline import sync_span

    if n <= 1:
        return vec, jnp.zeros_like(vec), jnp.float32(0.0)
    chunks = jnp.reshape(vec, (n, -1))
    idx = lax.axis_index(axis_name)
    perm = [(i, (i + 1) % n) for i in range(n)]
    if fused:
        from autodist_tpu.ops import fused_kernels as fk

        # Hop s's receive side (dequantize + accumulate) and hop s+1's
        # send side (requantize) are one fused boundary; the first send
        # and the final owned-shard receive are the half-boundaries.
        acc0 = jnp.take(chunks, (idx - 1) % n, axis=0)
        err = jnp.zeros_like(chunks)
        with sync_span("quant_ring_fused/leg1"):
            q, scales, err_h, sat = fk.fused_quantize(acc0, fmt, block)
            err = err.at[(idx - 1) % n].set(err_h)
        for s in range(1, n):
            with sync_span(f"quant_ring_fused/leg{s}"):
                q = lax.ppermute(q, axis_name, perm)
                scales = lax.ppermute(scales, axis_name, perm)
                chunk = jnp.take(chunks, (idx - 1 - s) % n, axis=0)
                if s < n - 1:
                    q, scales, err_h, s_cnt = fk.fused_hop_accumulate(
                        q, scales, chunk, fmt, block)
                    err = err.at[(idx - 1 - s) % n].set(err_h)
                    sat = sat + s_cnt
                else:
                    acc = fk.fused_dequant_add(q, scales, chunk, fmt,
                                               block)
        return acc, jnp.reshape(err, vec.shape), sat
    acc = jnp.take(chunks, (idx - 1) % n, axis=0)
    err = jnp.zeros_like(chunks)
    sat = jnp.float32(0.0)
    for s in range(1, n):
        with sync_span(f"quant_ring_reduce_scatter/leg{s}"):
            q, scales, s_cnt = quantize_blocks(acc, fmt, block)
            # before hop s this device's partial is destined for chunk
            # (idx − s): record the requantization error there.
            err = err.at[(idx - s) % n].set(
                acc - dequantize_blocks(q, scales, block))
            sat = sat + s_cnt
            q = lax.ppermute(q, axis_name, perm)
            scales = lax.ppermute(scales, axis_name, perm)
            acc = dequantize_blocks(q, scales, block) \
                + jnp.take(chunks, (idx - 1 - s) % n, axis=0)
    return acc, jnp.reshape(err, vec.shape), sat


def quantized_ring_all_gather(shard, axis_name: str, n: int,
                              fmt: WireFormat,
                              block: int = QUANT_BLOCK_ELEMS):
    """All-gather of per-device f32 ``shard``s over a quantized ring.

    The shard is quantized ONCE (stage 2 of the EQuARX double
    quantization — uncompensated) and the payload + scales circulate
    n−1 hops; every device materializes the DEQUANTIZED value for all
    shards including its own, so replicated consumers stay bit-identical
    across the mesh.  Returns ``(gathered, sat_count)``."""
    import jax.numpy as jnp
    from jax import lax

    from autodist_tpu.telemetry.timeline import sync_span

    if n <= 1:
        return shard, jnp.float32(0.0)
    idx = lax.axis_index(axis_name)
    perm = [(i, (i + 1) % n) for i in range(n)]
    q, scales, sat = quantize_blocks(shard, fmt, block)
    out = jnp.zeros((n,) + shard.shape, jnp.float32)
    out = out.at[idx].set(dequantize_blocks(q, scales, block))
    for s in range(1, n):
        with sync_span(f"quant_ring_all_gather/leg{s}"):
            q = lax.ppermute(q, axis_name, perm)
            scales = lax.ppermute(scales, axis_name, perm)
            out = out.at[(idx - s) % n].set(
                dequantize_blocks(q, scales, block))
    return jnp.reshape(out, (n * shard.shape[0],) + shard.shape[1:]), sat


# -- single-collective (non-ring) lowerings ----------------------------------

def quantized_all_to_all_reduce_scatter(vec, axis_name: str, n: int,
                                        fmt: WireFormat,
                                        block: int = QUANT_BLOCK_ELEMS):
    """One-shot quantized reduce-scatter: quantize the whole vector with
    the per-chunk grid (each of the ``n`` ring chunks carries its own
    scale blocks), ``all_to_all`` payload + scales, dequantize each
    sender's contribution with that sender's scales, and sum in f32.
    The GSPMD/per-variable tier and small buckets use this — one launch
    instead of n−1 hops, same quantization rule.  Returns
    ``(shard_sum, err, sat_count)`` like the ring variant (the error
    here is the single quantize event's, whole-vector)."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    from autodist_tpu.telemetry.timeline import sync_span

    if n <= 1:
        return vec, jnp.zeros_like(vec), jnp.float32(0.0)
    chunks = jnp.reshape(vec, (n, -1))
    with sync_span("quant_all_to_all_reduce_scatter"):
        q, scales, sat = jax.vmap(
            lambda c: quantize_blocks(c, fmt, block))(chunks)
        err = (chunks - jax.vmap(
            lambda qq, ss: dequantize_blocks(qq, ss, block))(q, scales)
        ).reshape(vec.shape)
        recv_q = lax.all_to_all(q, axis_name, split_axis=0, concat_axis=0)
        recv_s = lax.all_to_all(scales, axis_name, split_axis=0,
                                concat_axis=0)
        owned = jnp.sum(jax.vmap(
            lambda qq, ss: dequantize_blocks(qq, ss, block)
        )(recv_q, recv_s), axis=0)
    return owned, err, jnp.sum(sat)


def quantized_all_gather(shard, axis_name: str, n: int, fmt: WireFormat,
                         block: int = QUANT_BLOCK_ELEMS):
    """One-shot quantized all-gather (stage 2): quantize the owned
    shard, ``all_gather`` payload + scales, dequantize every shard —
    including the local one, so all devices agree bit-identically."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    from autodist_tpu.telemetry.timeline import sync_span

    if n <= 1:
        return shard, jnp.float32(0.0)
    with sync_span("quant_all_gather"):
        q, scales, sat = quantize_blocks(shard, fmt, block)
        gq = lax.all_gather(q, axis_name, axis=0)
        gs = lax.all_gather(scales, axis_name, axis=0)
        full = jax.vmap(
            lambda qq, ss: dequantize_blocks(qq, ss, block))(gq, gs)
    return jnp.reshape(full, (n * shard.shape[0],) + shard.shape[1:]), sat


# -- bucket-level entry point (what explicit_sync lowers) --------------------

def quant_bucket_reduce(vec, state, axis_name: str, n: int,
                        fmt: WireFormat, *, mode: str, alg: str,
                        block: int = QUANT_BLOCK_ELEMS,
                        fused: bool = False):
    """Reduce one flat bucket through the quantized wire.

    ``mode`` is the bucket sync mode (``all_reduce`` returns the full
    mean vector, ``reduce_scatter`` this device's 1/n mean shard —
    ZeRO-1 updates from the f32-dequantized shard); ``alg`` is the
    schedule IR's resolved lowering (``ring`` = per-hop requantizing
    ppermute chain, anything else = the one-shot ``all_to_all``
    collective).  Error feedback: ``state`` (vector-shaped stage-1
    residual) is added before quantization and the new residual is
    returned; stage-2 (the ``all_reduce`` gather leg) is uncompensated.
    ``fused`` lowers ring hop boundaries through the fused Pallas
    kernels (docs/kernels.md; ring algorithm only — the one-shot and
    gather lowerings have no per-hop boundary to fuse).
    Returns ``(reduced, new_state, sat_count)``."""
    import jax.numpy as jnp

    from autodist_tpu.kernel.synchronization.bucketing import (
        MODE_REDUCE_SCATTER,
    )

    orig_dtype = vec.dtype
    corrected = vec.astype(jnp.float32)
    if state is not None:
        corrected = corrected + state.astype(jnp.float32)
    if n <= 1:
        out = corrected
        new_state = jnp.zeros_like(vec) if state is not None else None
        return out.astype(orig_dtype), new_state, jnp.float32(0.0)
    if alg == "ring":
        shard_sum, err, sat = quantized_ring_reduce_scatter(
            corrected, axis_name, n, fmt, block, fused=fused)
    else:
        shard_sum, err, sat = quantized_all_to_all_reduce_scatter(
            corrected, axis_name, n, fmt, block)
    new_state = err.astype(orig_dtype) if state is not None else None
    mean_shard = shard_sum / n
    if mode == MODE_REDUCE_SCATTER:
        return mean_shard.astype(orig_dtype), new_state, sat
    if alg == "ring":
        full, sat2 = quantized_ring_all_gather(mean_shard, axis_name, n,
                                               fmt, block)
    else:
        full, sat2 = quantized_all_gather(mean_shard, axis_name, n, fmt,
                                          block)
    return full.astype(orig_dtype), new_state, sat + sat2
