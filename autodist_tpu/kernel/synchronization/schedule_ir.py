"""Sync-schedule IR: the one program both sync lowerings execute.

Every sync feature since the bucketed rebuild exists twice — once on the
explicit shard_map path (``explicit_sync.py``) and once as a GSPMD
"tree-level analog" (``graph_transformer.py``) — and the static analyzer
linted a lossy ``PlanLite`` summary rather than what the runtime would
actually run.  This module extracts the schedule itself as a small,
**pure, JSON-serializable IR** (the Automap argument, arXiv:2112.02958:
make the partition/schedule decision a first-class analyzable artifact):

* a :class:`ScheduleIR` is a program of **bucket nodes** (the planner's
  :class:`~autodist_tpu.kernel.synchronization.bucketing.Bucket`s plus
  the resolved per-bucket lowering decisions) and **legs** — one
  :class:`Leg` per schedulable unit of sync work (reduce_scatter /
  all_gather / all_reduce / ppermute ring hop / guard psum / update),
  each carrying dtype, wire bytes, mesh axis, microbatch slot,
  compressor tag, participant stage, and explicit dep edges;
* :func:`build_schedule_ir` constructs it from the SAME pure inputs the
  runtime resolves (``bucketing.assign_buckets`` output + an
  :class:`~autodist_tpu.kernel.synchronization.overlap.OverlapPlan`),
  so the explicit and GSPMD paths become two *lowerings* of one IR
  instance: ``explicit_sync.make_explicit_step`` derives its pipeline
  membership, ring/one-shot/fused reduce lowering, and ZeRO-1 gather
  issue order from the IR's bucket nodes, and the GSPMD transform
  builds the per-variable (psum-tree) instance of the same schema;
* :func:`verify` is the **static schedule verifier** — an exact model
  check over the leg partial order, replacing the old heuristic
  plan-tuple comparisons.  Rules (see docs/schedule-ir.md):

  - ``schedule/unknown-dep`` (ERROR) — a dep edge names a missing leg
    (or two legs share an id): the partial order is not well formed.
  - ``schedule/dep-cycle`` (ERROR) — the dep graph has a cycle: no
    execution order exists, every rank blocks.
  - ``schedule/ring-degenerate`` (ERROR) — ppermute ring hops on an
    axis of size <= 1: there is no ring to permute over.
  - ``schedule/ring-hop-order`` (ERROR) — a ring hop chain is not the
    consecutive, dep-ordered sequence 1..n-1 (swapped, duplicated,
    missing, or back-edged hops): ranks disagree on which chunk is in
    flight and the ppermute deadlocks.
  - ``schedule/quantized-pipelined`` (ERROR) — a quantized bucket's
    collectives violate the pipelining contract.  The ADMITTED shapes
    are exactly: one quantized collective per bucket at end-of-step, OR
    — for quantized-ring compressors (int8/fp8,
    ``quant_ring.WIRE_FORMATS``) under an explicit pipeline request —
    exactly one quantized collective per microbatch slot ``0..accum-1``
    (error feedback threaded across slots).  Rejected: two quantized
    collectives in one slot/step, partial slot coverage, a mix of
    slotted and end-of-step quantized collectives, a slotted collective
    for a compressor without the per-slot contract
    (``HorovodCompressor*``), and a quantized ppermute ring chain for a
    compressor with no per-hop requantize lowering.
  - ``schedule/read-after-donate`` (ERROR) — a donated buffer (ANY
    namespace: ``sync:``/``param:``/``opt:``) has a pure read
    reachable after a write in the dep graph, by a leg OUTSIDE the
    buffer's own read-modify-write chain (a reader whose
    (bucket, slot) group also writes the buffer is threading carried
    state — the quantized-ring error-feedback contract — and reads
    the new value): the donated buffer's old handle is deleted by
    then (the PR 3 donation audit, now a checked invariant over
    every donated namespace).
  - ``schedule/race-unordered-write`` (ERROR) — two legs write the
    same buffer with no happens-before path between them (the
    transitive dep closure, ``analysis/dataflow.py``): the lowerings
    may commit the writes in either order.
  - ``schedule/race-read-write`` (ERROR) — a read and a write of one
    buffer with no happens-before path: the reader may observe either
    value depending on issue timing.
  - ``schedule/buffer-leak`` (WARN) — a transient buffer written but
    never read nor donated: the sync work producing it is dead
    (``param:``/``opt:`` step outputs are exempt).
  - ``schedule/collective-mismatch`` (ERROR) — two participant stages
    issue different ordered collective sequences for the same
    microbatch slot (the classic MPMD/manual-schedule hang; consumed
    by the ``collectives`` analysis pass under its established rule
    id ``collectives/stage-collective-mismatch``).
  - ``schedule/reduction-order-divergence`` (WARN) — a low-precision
    or compressed bucket whose reduce ring-decomposes: the explicit
    ring order and the GSPMD psum-tree order round differently, so the
    two lowerings of this IR are not bit-identical for it.
  - ``schedule/fused-inconsistent`` (ERROR) — a fused-kernel leg
    (``fused_detect``/``fused_update``/``fused_hop``, docs/kernels.md)
    in a program whose ``fused_kernels`` record does not claim that
    kernel, a ``hop_fused`` bucket node without the ``quant_hop``
    record, or a fused hop for a compressor with no per-hop requantize
    lowering: the fused and unfused halves of the lowering disagree
    about what runs.
  - ``schedule/hier-tier-order`` (ERROR) — the two-tier hierarchy's
    ordering contract: a slice-local ``hier_reduce_scatter`` with no
    cross-slice DCN leg after it (slices silently diverge), a DCN leg
    not ordered between its slice-local RS and AG, more than one DCN
    exchange per bucket/slot, a ZeRO-1 shard exchange without the
    DCN-then-ICI param gather pair, a tier tag that contradicts the
    leg kind, or hier legs on a program whose ``num_slices`` does not
    factor the data axis.
  - ``schedule/act-transport`` (ERROR) — the MPMD pipeline transport
    pairing contract: every ``act:`` boundary buffer owes exactly one
    ``send_act`` and one ``recv_act`` joining two different named
    stages, the recv dep-ordered after its send on the same microbatch
    slot, tier ``dcn``, send slots monotone per boundary chain
    (orphaned/mis-ordered halves are the cross-slice wedge the MPMD
    runtime would block on — docs/pipeline.md).

Everything here is mesh-free and jax-free at module import (numpy
only), so the analyzer's sub-second verdict survives, and the verifier
is cheap enough (< 1 s on the largest fixtures, asserted in
tests/test_schedule_ir.py) to run as a pre-trace gate on every explicit
build and every bench mode.
"""
from __future__ import annotations

import hashlib
import json
import re
from dataclasses import asdict, dataclass, field
from typing import (
    Any,
    Dict,
    FrozenSet,
    Iterable,
    List,
    Optional,
    Sequence,
    Tuple,
)

import numpy as np

from autodist_tpu.const import MESH_AXIS_DATA, MESH_AXIS_EXPERT
from autodist_tpu.kernel.synchronization import overlap as overlap_mod
from autodist_tpu.kernel.synchronization import quant_ring
from autodist_tpu.kernel.synchronization.bucketing import (
    Bucket,
    MODE_REDUCE_SCATTER,
)

IR_VERSION = 1

#: leg kinds — the collective vocabulary of the schedule.
LEG_REDUCE_SCATTER = "reduce_scatter"
LEG_ALL_GATHER = "all_gather"
LEG_ALL_REDUCE = "all_reduce"
LEG_PPERMUTE_HOP = "ppermute_hop"
LEG_PSUM_GUARD = "psum_guard"
LEG_PS_EXCHANGE = "ps_exchange"
LEG_UPDATE = "update"
#: fused-kernel leg kinds (docs/kernels.md): the Pallas lowerings the
#: ``AUTODIST_FUSED_KERNELS`` knob selects.  ``fused_hop`` is a
#: ppermute ring hop whose dequantize→accumulate→requantize boundary
#: runs as one kernel (same wire, same hop-order rules);
#: ``fused_detect`` is the single-pass guard statistics pass over a
#: bucket; ``fused_update`` the one-kernel unscale/clip/Adam ZeRO-1
#: shard update.  Distinct kinds so ``fit_leg_constants`` prices
#: fused-vs-unfused as separate calibrated alternatives.
LEG_FUSED_HOP = "fused_hop"
LEG_FUSED_DETECT = "fused_detect"
LEG_FUSED_UPDATE = "fused_update"
#: MoE expert all-to-all (docs/schedule-ir.md): the dispatch/combine
#: pair of capacity-based expert routing (``parallel/moe.py``).  Both
#: roles share one kind (one wire shape, one calibration constant);
#: the leg ``sig`` distinguishes dispatch from combine so the cross-
#: stage sequence check catches a swapped pair.
LEG_ALL_TO_ALL = "all_to_all"
#: hierarchical two-tier collectives (docs/schedule-ir.md): the pod
#: recipe — reduce-scatter within a slice over ICI, exchange the
#: slice-partial shards over the (much slower) DCN, all-gather the
#: reduced result back over ICI.  ``hier_reduce_scatter`` /
#: ``hier_all_gather`` are the slice-local halves; ``dcn_all_reduce``
#: is the cross-slice shard reduction of plain data parallelism and
#: ``dcn_exchange`` the ZeRO-1 variant (a cross-slice reduce-scatter:
#: each device keeps only its owner sub-shard, so the weight update
#: stays 1/d).  Each carries an explicit ``tier`` tag so the cost
#: model prices the two networks with distinct calibrated constants.
LEG_HIER_REDUCE_SCATTER = "hier_reduce_scatter"
LEG_DCN_ALL_REDUCE = "dcn_all_reduce"
LEG_DCN_EXCHANGE = "dcn_exchange"
LEG_HIER_ALL_GATHER = "hier_all_gather"
#: MPMD pipeline activation transport (docs/pipeline.md): the
#: point-to-point DCN legs carrying one microbatch's boundary
#: activation (``send_act``, forward) or its cotangent (same pair of
#: kinds, ``sig`` role ``bwd``) between per-stage programs on separate
#: slices.  Always tier ``dcn``, always an ``act:`` buffer, always
#: emitted in 1F1B tick order so the per-stage dep chains ARE the
#: runtime issue order (``parallel/mpmd``).
LEG_SEND_ACT = "send_act"
LEG_RECV_ACT = "recv_act"
LEG_KINDS = (LEG_REDUCE_SCATTER, LEG_ALL_GATHER, LEG_ALL_REDUCE,
             LEG_PPERMUTE_HOP, LEG_PSUM_GUARD, LEG_PS_EXCHANGE, LEG_UPDATE,
             LEG_FUSED_HOP, LEG_FUSED_DETECT, LEG_FUSED_UPDATE,
             LEG_ALL_TO_ALL, LEG_HIER_REDUCE_SCATTER, LEG_DCN_ALL_REDUCE,
             LEG_DCN_EXCHANGE, LEG_HIER_ALL_GATHER,
             LEG_SEND_ACT, LEG_RECV_ACT)
#: kinds that issue wire traffic (every rank must agree on these).
COLLECTIVE_KINDS = (LEG_REDUCE_SCATTER, LEG_ALL_GATHER, LEG_ALL_REDUCE,
                    LEG_PPERMUTE_HOP, LEG_PSUM_GUARD, LEG_PS_EXCHANGE,
                    LEG_FUSED_HOP, LEG_ALL_TO_ALL,
                    LEG_HIER_REDUCE_SCATTER, LEG_DCN_ALL_REDUCE,
                    LEG_DCN_EXCHANGE, LEG_HIER_ALL_GATHER,
                    LEG_SEND_ACT, LEG_RECV_ACT)
#: the point-to-point pipeline transport subset: excluded from the
#: cross-stage sequence comparison (adjacent stages legitimately issue
#: conjugate, not identical, send/recv sequences — the pairwise
#: ``schedule/act-transport`` rule owns their deadlock check instead).
TRANSPORT_KINDS = (LEG_SEND_ACT, LEG_RECV_ACT)
#: the two network tiers a leg can ride; ``""`` = the (single-tier)
#: default, serialized away so pre-hier programs keep their recorded
#: fingerprints.
TIER_ICI = "ici"
TIER_DCN = "dcn"
#: the hierarchical leg vocabulary and its cross-slice (DCN) subset.
HIER_KINDS = (LEG_HIER_REDUCE_SCATTER, LEG_DCN_ALL_REDUCE,
              LEG_DCN_EXCHANGE, LEG_HIER_ALL_GATHER)
DCN_KINDS = (LEG_DCN_ALL_REDUCE, LEG_DCN_EXCHANGE)
#: ppermute ring-hop kinds — one chain grammar, fused or not.
RING_HOP_KINDS = (LEG_PPERMUTE_HOP, LEG_FUSED_HOP)
#: leg kind each fused kernel name lowers to (the consistency contract
#: schedule/fused-inconsistent checks).
FUSED_KERNEL_KINDS = {
    "guard": LEG_FUSED_DETECT,
    "update": LEG_FUSED_UPDATE,
    "quant_hop": LEG_FUSED_HOP,
}

#: reduce-lowering algorithms a bucket node resolves to.
ALG_RING = "ring"            # explicit ppermute hop chain (overlap.py)
ALG_ONE_SHOT = "one_shot"    # latency-optimal gather + local reduce
ALG_FUSED = "fused"          # XLA's fused collective (psum_scatter/pmean)
ALG_PSUM_TREE = "psum_tree"  # GSPMD-inserted psum (tree reduction order)

#: microbatch slot value for end-of-step (non-pipelined) legs.
END_OF_STEP = -1

#: participant-stage naming for hand-laid per-stage parameter groups —
#: shared with the ``collectives`` analysis pass.
STAGE_RE = re.compile(r"(?:^|/)(stage|expert)[_-]?(\d+)(?=/|$)")


def stage_of(name: str) -> str:
    """The participant stage a variable name implies (``"stage0"``,
    ``"expert3"``) or ``""`` for all-rank (SPMD-uniform) work."""
    m = STAGE_RE.search(name or "")
    return f"{m.group(1)}{int(m.group(2))}" if m else ""


def stage_name(index: int, kind: str = "stage") -> str:
    """THE stage spelling: what the MPMD partitioner prefixes parameter
    names with, what :class:`PipelineFact` legs carry in ``Leg.stage``,
    and exactly what :func:`stage_of` recovers — one helper so
    hand-laid ``stage0/`` param groups and auto-partitioned stages lint
    identically (``assert stage_of(stage_name(i) + "/w") ==
    stage_name(i)``)."""
    return f"{kind}{int(index)}"


def stage_index(stage: str) -> Optional[int]:
    """Inverse of :func:`stage_name`: the numeric index of a
    ``stage<i>``/``expert<i>`` participant tag, or None for all-rank."""
    m = re.match(r"([a-z]+)(\d+)$", stage or "")
    return int(m.group(2)) if m else None


# -- 1F1B schedule algebra (pure; re-exported by parallel.pipeline_1f1b) -----

#: the prune rule for an inexpressible pipeline shape — one rule string
#: shared by the MPMD partitioner (raise), the ``--simulate`` sweep
#: (prune), and ``preflight_stage_resize`` (ElasticResumeError), like
#: ``legality/slice-mismatch``.
RULE_STAGE_MISMATCH = "pipeline/stage-mismatch"


def stage_mismatch_reason(num_stages: int, num_microbatches: int,
                          num_layers: Optional[int] = None
                          ) -> Optional[str]:
    """Why this (stages, microbatches, layers) shape cannot run 1F1B,
    or None when it can."""
    s, m = int(num_stages), int(num_microbatches)
    if s < 1:
        return f"{RULE_STAGE_MISMATCH}: num_stages {s} < 1"
    if num_layers is not None and s > int(num_layers):
        return (f"{RULE_STAGE_MISMATCH}: {s} stages cannot split "
                f"{int(num_layers)} layer(s) contiguously")
    if m < s:
        return (f"{RULE_STAGE_MISMATCH}: 1F1B needs num_microbatches "
                f"({m}) >= stages ({s})")
    return None


def schedule_ticks_1f1b(num_stages: int, num_microbatches: int,
                        num_virtual_stages: int = 1) -> int:
    """Total ticks of the interleaved 1F1B schedule: microbatch ``j``
    injects at tick ``(j // S) * S * V + j % S`` and its last backward
    completes ``2 * (S * V - 1)`` ticks after injection."""
    s = max(int(num_stages), 1)
    v = max(int(num_virtual_stages), 1)
    m = max(int(num_microbatches), 1)
    t_last = ((m - 1) // s) * s * v + (m - 1) % s
    return t_last + 2 * (s * v - 1) + 1


def bubble_fraction_1f1b(num_stages: int, num_microbatches: int,
                         num_virtual_stages: int = 1) -> float:
    """Fraction of pipeline ticks spent idle (warm-up + drain): each
    microbatch occupies one forward+backward tick pair per device, so
    ``M * V`` of the schedule's ticks are useful work."""
    s = max(int(num_stages), 1)
    v = max(int(num_virtual_stages), 1)
    m = max(int(num_microbatches), 1)
    ticks = schedule_ticks_1f1b(s, m, v)
    return max(0.0, 1.0 - (m * v) / ticks)


def is_quantizing(compressor: str) -> bool:
    """Does this compressor change the wire format (and therefore owe
    the one-quantized-collective-per-bucket-per-step contract)?"""
    return not overlap_mod.is_linear_compressor(compressor)


_STATEFUL_CACHE: Dict[str, bool] = {
    # Statically known; others are probed (lazily, cached) below.
    "": False, "NoneCompressor": False, "HorovodCompressor": False,
}


def compressor_stateful(name: str) -> bool:
    """Does ``name``'s compressor carry per-device sync state (error
    feedback residuals, factors)?  Probed abstractly through the
    compressor's own ``init_state`` (the gate and the construction
    cannot diverge); unknown names conservatively report stateful."""
    key = name or "NoneCompressor"
    if key in _STATEFUL_CACHE:
        return _STATEFUL_CACHE[key]
    try:
        import jax

        from autodist_tpu.kernel.synchronization.compressor import (
            get_compressor,
        )
        probe = jax.eval_shape(get_compressor(key).init_state,
                               jax.ShapeDtypeStruct((8,), np.float32))
        out = probe is not None
    except Exception:
        out = True
    _STATEFUL_CACHE[key] = out
    return out


# -- the IR ------------------------------------------------------------------

@dataclass(frozen=True)
class Leg:
    """One schedulable unit of sync work.

    ``deps`` are leg ids that must complete first (the partial order a
    rank's issue stream must respect).  ``reads``/``writes`` name the
    logical buffers the leg touches (``grad:<key>``, ``red:<key>``,
    ``sync:<key>``, ``param:<key>``, ``opt:<key>``) — the substrate of
    the donation-race rule.  ``slot`` is the microbatch pipeline slot
    (:data:`END_OF_STEP` outside the accumulation pipeline), ``chain``
    groups the hops of one ring decomposition, ``stage`` the
    participant group (``""`` = every rank), and ``sig`` an optional
    opaque signature used for cross-stage sequence comparison."""

    id: str
    kind: str
    bucket: str = ""
    dtype: str = "float32"
    nbytes: int = 0
    axis: str = ""
    slot: int = END_OF_STEP
    compressor: str = "NoneCompressor"
    alg: str = ALG_FUSED
    hop: int = 0
    chain: str = ""
    stage: str = ""
    sig: str = ""
    #: network tier (:data:`TIER_ICI`/:data:`TIER_DCN`) for hierarchical
    #: legs; ``""`` (single-tier) is stripped from the serialized form
    #: so every pre-hier program keeps its recorded fingerprint.
    tier: str = ""
    deps: Tuple[str, ...] = ()
    reads: Tuple[str, ...] = ()
    writes: Tuple[str, ...] = ()


@dataclass
class ScheduleIR:
    """A sync-schedule program (see module docstring).

    ``buckets`` carries one dict per planned bucket — the planner facts
    plus the resolved lowering decisions (``alg``, ``pipelined``,
    ``gather_alg``) the runtime lowerings consume; ``legs`` is the
    verification substrate.  ``donated`` lists the sync-state buffers
    the runtime donates (``sync:<key>`` names)."""

    axes: Dict[str, int] = field(default_factory=dict)
    accum_steps: int = 1
    overlap_mode: str = overlap_mod.OVERLAP_AUTO
    guard: bool = False
    prefetch: bool = False
    buckets: List[dict] = field(default_factory=list)
    legs: List[Leg] = field(default_factory=list)
    gather_order: List[Tuple[str, str]] = field(default_factory=list)
    donated: Tuple[str, ...] = ()
    #: fused Pallas kernels this program lowers through (docs/kernels.md)
    #: — already drop-filtered by the builder's caller, so the record is
    #: what actually runs, not what was requested.
    fused_kernels: Tuple[str, ...] = ()
    #: MoE expert-routing facts behind the a2a legs (empty for non-MoE
    #: programs) — carried so the verifier's capacity rule and the
    #: watermark see the routing config, not just the lowered legs.
    moe: Tuple["MoEFact", ...] = ()
    #: second network tier: how many ICI slices the data axis spans
    #: (DCN legs reduce over ``num_slices`` participants, ICI legs over
    #: ``data/num_slices``).  1 = single-slice, serialized away so
    #: pre-hier programs keep their fingerprints.
    num_slices: int = 1
    #: MPMD pipeline facts behind the send_act/recv_act legs (empty for
    #: single-program schedules) — carried so the cost model prices the
    #: bubble fraction from the routing config, not just the legs.
    pipeline: Tuple["PipelineFact", ...] = ()
    version: int = IR_VERSION

    # -- decision surface (what the lowerings consume) --------------------
    def bucket_node(self, key: str) -> Optional[dict]:
        for b in self.buckets:
            if b["key"] == key:
                return b
        return None

    def pipelined_keys(self) -> FrozenSet[str]:
        """Buckets whose reduce joins the accumulation pipeline."""
        return frozenset(b["key"] for b in self.buckets if b["pipelined"])

    def reduce_alg(self, key: str) -> str:
        node = self.bucket_node(key)
        return node["alg"] if node else ALG_FUSED

    def gather_plan(self) -> List[Tuple[str, str]]:
        """ZeRO-1 param all-gather issue order: ``[(bucket_key, alg)]``."""
        return [tuple(kv) for kv in self.gather_order]

    # -- serialization -----------------------------------------------------
    @staticmethod
    def _leg_dict(l: Leg) -> dict:
        d = asdict(l)
        if not d.get("tier"):
            d.pop("tier", None)     # single-tier legs serialize as before
        return d

    def to_dict(self) -> dict:
        return {
            "version": self.version,
            "axes": {str(k): int(v) for k, v in self.axes.items()},
            "accum_steps": int(self.accum_steps),
            "overlap_mode": self.overlap_mode,
            "guard": bool(self.guard),
            "prefetch": bool(self.prefetch),
            "buckets": [dict(b) for b in self.buckets],
            "legs": [self._leg_dict(l) for l in self.legs],
            "gather_order": [list(kv) for kv in self.gather_order],
            "donated": list(self.donated),
            # Omitted when empty so every pre-fusion program keeps its
            # recorded fingerprint (checkpoints, BENCH_leg_samples.jsonl,
            # calibration.json all key on it).
            **({"fused_kernels": list(self.fused_kernels)}
               if self.fused_kernels else {}),
            # Same omit-when-empty contract: every non-MoE program's
            # fingerprint is untouched by the MoE extension.
            **({"moe": [asdict(m) for m in self.moe]} if self.moe else {}),
            # Omit-when-1: single-slice programs keep their fingerprints.
            **({"num_slices": int(self.num_slices)}
               if int(self.num_slices) > 1 else {}),
            # Same omit-when-empty contract: every non-pipeline
            # program's fingerprint is untouched by the MPMD extension.
            **({"pipeline": [asdict(p) for p in self.pipeline]}
               if self.pipeline else {}),
        }

    @classmethod
    def from_dict(cls, d: dict) -> "ScheduleIR":
        legs = []
        known = set(Leg.__dataclass_fields__)
        for ld in d.get("legs", ()):
            kw = {k: v for k, v in ld.items() if k in known}
            for tup in ("deps", "reads", "writes"):
                kw[tup] = tuple(kw.get(tup, ()) or ())
            legs.append(Leg(**kw))
        return cls(
            axes={str(k): int(v) for k, v in (d.get("axes") or {}).items()},
            accum_steps=int(d.get("accum_steps", 1)),
            overlap_mode=d.get("overlap_mode", overlap_mod.OVERLAP_AUTO),
            guard=bool(d.get("guard", False)),
            prefetch=bool(d.get("prefetch", False)),
            buckets=[dict(b) for b in d.get("buckets", ())],
            legs=legs,
            gather_order=[tuple(kv) for kv in d.get("gather_order", ())],
            donated=tuple(d.get("donated", ())),
            fused_kernels=tuple(d.get("fused_kernels", ())),
            moe=tuple(MoEFact(**{
                k: v for k, v in md.items()
                if k in MoEFact.__dataclass_fields__})
                for md in d.get("moe", ())),
            num_slices=int(d.get("num_slices", 1)),
            pipeline=tuple(PipelineFact(**{
                k: v for k, v in pd.items()
                if k in PipelineFact.__dataclass_fields__})
                for pd in d.get("pipeline", ())),
            version=int(d.get("version", IR_VERSION)))

    def to_json(self, indent: Optional[int] = None) -> str:
        return json.dumps(self.to_dict(), indent=indent, sort_keys=True)

    @classmethod
    def from_json(cls, s: str) -> "ScheduleIR":
        return cls.from_dict(json.loads(s))

    def fingerprint(self) -> str:
        """Short stable hash of the canonical IR — stamped into
        telemetry StepRecords and checkpoint meta so planned-vs-executed
        schedule drift is detectable across resume/elastic resize."""
        blob = json.dumps(self.to_dict(), sort_keys=True,
                          separators=(",", ":")).encode()
        return hashlib.sha256(blob).hexdigest()[:12]

    def to_dot(self) -> str:
        """Graphviz view of the leg dep graph (CLI ``--dump-ir dot``)."""
        shape = {LEG_PPERMUTE_HOP: "cds", LEG_UPDATE: "box",
                 LEG_PSUM_GUARD: "diamond"}
        out = ["digraph schedule {", "  rankdir=LR;",
               "  node [fontsize=9, shape=ellipse];"]
        for l in self.legs:
            label = l.kind if not l.bucket else f"{l.kind}\\n{l.bucket}"
            if l.slot != END_OF_STEP:
                label += f"\\nslot {l.slot}"
            if l.kind == LEG_PPERMUTE_HOP:
                label += f" hop{l.hop}"
            if is_quantizing(l.compressor):
                label += f"\\n[{l.compressor}]"
            out.append(f'  "{l.id}" [label="{label}", '
                       f'shape={shape.get(l.kind, "ellipse")}];')
        for l in self.legs:
            for dep in l.deps:
                out.append(f'  "{dep}" -> "{l.id}";')
        out.append("}")
        return "\n".join(out)


# -- plan facts (mesh-free input shared by analysis and GSPMD) ---------------

@dataclass(frozen=True)
class PlanFact:
    """One variable's mesh-free sync facts — the projection both
    :class:`~autodist_tpu.analysis.analyzer.PlanLite` and the
    compiler's ``VarPlan`` reduce to, so :func:`ir_from_facts` builds
    identical IRs from either side."""

    name: str
    shape: Tuple[int, ...]
    dtype: str
    sync_kind: str                       # "AllReduce" | "PS"
    compressor: str = "NoneCompressor"
    group: int = 0
    fused: bool = False
    sync_mode: str = "all_reduce"
    bucket_bytes: int = 0
    overlap: str = overlap_mod.OVERLAP_AUTO
    staleness: int = 0
    partitioned: bool = False
    padded: bool = False
    #: two-tier hierarchical sync requested (takes effect only when the
    #: program's ``num_slices`` makes :func:`hier_applies` true AND the
    #: variable's bucket is linear-compressor — quantized gradient wires
    #: keep the flat lowering, the DCN leg owns its own wire knob).
    hier: bool = False

    @property
    def nbytes(self) -> int:
        size = int(np.prod(tuple(self.shape) or (1,)))
        return size * np.dtype(self.dtype).itemsize

    def sig(self) -> str:
        """Cross-stage comparison signature: the wire-visible identity
        of this variable's collective (name and byte size deliberately
        excluded — heterogeneous stage shapes with matching configs are
        legal)."""
        return "|".join(str(x) for x in (
            self.sync_kind, self.compressor or "NoneCompressor",
            bool(self.fused), int(self.group), self.sync_mode,
            int(self.staleness), bool(self.partitioned))
            + (("hier",) if self.hier else ()))


def plan_route(fact: PlanFact) -> Tuple[bool, bool]:
    """``(bucketable, explicit_hint)`` for one plan — THE shared
    projection of the runtime's routing rules (``bucket_drop_reason`` +
    ``overlap.explicit_hint``), consumed by :func:`ir_from_facts`, the
    ``sync`` coverage pass, and the ``collectives`` pass so none of
    them reconstructs it independently."""
    from autodist_tpu.kernel.synchronization.bucketing import (
        bucket_drop_reason,
    )
    bucketable = (fact.sync_kind == "AllReduce"
                  and bucket_drop_reason(
                      [(0, "x")] if fact.partitioned else [],
                      fact.padded, fact.compressor) is None)
    explicit = overlap_mod.explicit_hint(
        fact.compressor, fact.sync_mode, fact.bucket_bytes,
        fused=fact.fused, overlap=fact.overlap, hier=fact.hier)
    return bucketable, explicit


def fact_from_planlite(name: str, plan: Any) -> PlanFact:
    """Project an analyzer :class:`PlanLite` to :class:`PlanFact`."""
    return PlanFact(
        name=name, shape=tuple(plan.var.shape), dtype=str(plan.var.dtype),
        sync_kind=plan.sync_kind or "AllReduce",
        compressor=plan.compressor or "NoneCompressor",
        group=int(plan.group), fused=bool(plan.fused),
        sync_mode=getattr(plan, "sync_mode", "all_reduce") or "all_reduce",
        bucket_bytes=int(getattr(plan, "bucket_bytes", 0) or 0),
        overlap=getattr(plan, "overlap", overlap_mod.OVERLAP_AUTO) or
        overlap_mod.OVERLAP_AUTO,
        staleness=int(getattr(plan, "staleness", 0) or 0),
        partitioned=bool(plan.placement), padded=plan.pad is not None,
        hier=bool(getattr(plan, "hier", False)))


def fact_from_varplan(plan: Any, var_info: Any) -> PlanFact:
    """Project a compiler ``VarPlan`` (+ its ``VarInfo``)."""
    from jax.sharding import PartitionSpec as P
    return PlanFact(
        name=plan.var_name, shape=tuple(var_info.shape),
        dtype=str(var_info.dtype), sync_kind=plan.sync_kind,
        compressor=plan.compressor or "NoneCompressor",
        group=int(plan.group), fused=bool(plan.fused),
        sync_mode=getattr(plan, "sync_mode", "all_reduce") or "all_reduce",
        bucket_bytes=int(getattr(plan, "bucket_bytes", 0) or 0),
        overlap=getattr(plan, "overlap", overlap_mod.OVERLAP_AUTO) or
        overlap_mod.OVERLAP_AUTO,
        staleness=int(getattr(plan, "staleness", 0) or 0),
        partitioned=plan.param_spec != P(),
        padded=getattr(plan, "pad_axis", None) is not None,
        hier=bool(getattr(plan, "hier", False)))


# -- MoE expert-routing facts (mesh-free, shared by runtime + analysis) ------

#: Static per-group token-count default when no batch shape is known at
#: build time (the IR is built before the first batch arrives, like the
#: activation estimate in ``analysis/memory.py``).  Override with the
#: ``tokens_per_group=`` argument or ``AUTODIST_MOE_TOKENS`` so the a2a
#: wire bytes reflect the real batch — the runtime and the analyzer
#: read the same knob, so their fingerprints stay identical.
DEFAULT_MOE_TOKENS_PER_GROUP = 1024

MOE_ROLE_DISPATCH = "dispatch"
MOE_ROLE_COMBINE = "combine"


def moe_capacity_drop_fraction(capacity_factor: float, seq: int,
                               num_experts: int) -> float:
    """Predicted fraction of top-2 expert assignments dropped under
    BALANCED routing — the shared pure rule behind the
    ``moe/capacity-overflow`` WARN (analysis) and the runtime fallback
    warning (``parallel/moe.py``).  Every token wants 2 expert slots,
    so balanced per-expert demand is ``2*seq/num_experts`` slots per
    group against a capacity of ``max(1, int(capacity_factor * seq /
    num_experts))`` (the exact ``moe_ffn`` formula, floor included);
    skewed routing only drops more.  Group count cancels in the
    balanced case — the surfaced message scales it back to tokens."""
    e = max(int(num_experts), 1)
    s = max(int(seq), 1)
    cap = max(1, int(float(capacity_factor) * s / e))
    demand = 2.0 * s / e
    if demand <= 0:
        return 0.0
    return max(0.0, 1.0 - cap / demand)


@dataclass(frozen=True)
class MoEFact:
    """One MoE layer's mesh-free expert-routing facts.

    Feeds the a2a leg pair (dispatch + combine) the builder emits: per
    group of ``seq`` tokens, top-2 routing with ``capacity_factor``
    fills a ``[num_experts, groups, capacity, d_model]`` buffer that is
    all-to-all'd over ``axis`` to the expert shards, transformed, and
    all-to-all'd back — the capacity-sized transient between the two
    a2as is the dominant MoE activation cost the watermark tracks via
    the ``expert:<key>`` buffer."""

    key: str                      # e.g. "layers_0/moe" — buffer namespace
    groups: int                   # G: token groups per microbatch
    seq: int                      # S: tokens per group
    d_model: int                  # M: model width dispatched per token
    num_experts: int              # E
    capacity_factor: float = 2.0
    dtype: str = "float32"
    axis: str = MESH_AXIS_EXPERT
    stage: str = ""               # "" = all-rank; "stage0"/"expert0" groups
    compressor: str = "NoneCompressor"   # Int8Compressor = quantized wire

    def capacity(self) -> int:
        """Slots per expert per group — the EXACT ``moe_ffn`` formula."""
        return max(1, int(float(self.capacity_factor) * int(self.seq)
                          / max(int(self.num_experts), 1)))

    def drop_fraction(self) -> float:
        return moe_capacity_drop_fraction(
            self.capacity_factor, self.seq, self.num_experts)

    def payload_elems(self, axis_size: int) -> int:
        """Per-device elements of one a2a payload: the full
        ``[E, G, C, M]`` capacity buffer sharded over the expert axis."""
        total = (int(self.num_experts) * int(self.groups) * self.capacity()
                 * int(self.d_model))
        return max(1, total // max(int(axis_size), 1))

    def leg_nbytes(self, axis_size: int) -> int:
        """Honest per-device wire bytes of one a2a leg: f32 payload, or
        — quantized wire — 1-byte/elem payload plus the per-chunk scale
        grid (``quant_ring.wire_nbytes``)."""
        elems = self.payload_elems(axis_size)
        fmt = quant_ring.wire_format_of(self.compressor or "")
        if fmt is not None:
            return quant_ring.wire_nbytes(elems, fmt)
        return elems * np.dtype(self.dtype).itemsize

    def sig(self, role: str) -> str:
        """Cross-stage comparison signature — the role is IN the
        signature so a swapped dispatch/combine pair compares unequal
        (the classic interleaving wedge)."""
        return "|".join(str(x) for x in (
            "moe", role, self.compressor or "NoneCompressor",
            int(self.num_experts)))


def moe_tokens_per_group_default() -> int:
    """The static token-count hint: ``AUTODIST_MOE_TOKENS`` when set,
    else :data:`DEFAULT_MOE_TOKENS_PER_GROUP`.  Read by every MoE fact
    producer (explicit lowering, GSPMD transform, analysis passes) so
    one env knob keeps all fingerprints in agreement."""
    import os
    raw = os.environ.get("AUTODIST_MOE_TOKENS", "")
    try:
        val = int(raw)
        return val if val > 0 else DEFAULT_MOE_TOKENS_PER_GROUP
    except ValueError:
        return DEFAULT_MOE_TOKENS_PER_GROUP


def moe_capacity_factor_default() -> float:
    """The capacity-factor hint shared by every MoE fact producer:
    ``AUTODIST_MOE_CAPACITY_FACTOR`` when set, else the ``moe_ffn``
    default of 2.0 (zero balanced drops under top-2 routing)."""
    import os
    raw = os.environ.get("AUTODIST_MOE_CAPACITY_FACTOR", "")
    try:
        val = float(raw)
        return val if val > 0 else 2.0
    except ValueError:
        return 2.0


def hier_applies(d: int, num_slices: int) -> bool:
    """Does the two-tier hierarchy actually factor this data axis?  THE
    shared gate (runtime lowering, ``ir_from_facts``, beam search, the
    ``--simulate`` sweep): ``num_slices`` > 1 slices that evenly divide
    the axis, with at least 2 chips per slice (a 1-chip slice has no
    ICI stage — that degenerates to the flat DCN collective)."""
    d = max(int(d), 1)
    s = max(int(num_slices), 1)
    return s > 1 and d % s == 0 and d // s > 1


def dcn_wire_compressor_default() -> str:
    """The DCN wire knob: ``AUTODIST_DCN_WIRE=int8`` puts the
    cross-slice shard exchange on the quantized wire
    (``quant_ring.quantize_blocks`` — a fresh per-chunk scale grid per
    step, stateless, no error feedback; DCN is exactly where the 4x
    compression pays most); anything else is the full-precision wire.
    Read by every hier leg producer (explicit lowering,
    ``ir_from_facts``, bench modes) so one env knob keeps all
    fingerprints in agreement."""
    import os
    wire = os.environ.get("AUTODIST_DCN_WIRE", "").strip().lower()
    return "Int8Compressor" if wire == "int8" else "NoneCompressor"


def moe_wire_compressor_default() -> str:
    """The ``moe`` wire knob: ``AUTODIST_MOE_WIRE=int8`` puts the
    dispatch/combine payloads on the quantized wire
    (``quant_ring.quantize_blocks`` per-chunk scale grid — the leg
    bytes then carry payload + scales); anything else is the f32
    wire."""
    import os
    wire = os.environ.get("AUTODIST_MOE_WIRE", "").strip().lower()
    return "Int8Compressor" if wire == "int8" else "NoneCompressor"


def moe_facts_from_vars(variables: Iterable[Any], *,
                        axes: Optional[Dict[str, int]] = None,
                        tokens_per_group: Optional[int] = None,
                        capacity_factor: Optional[float] = None,
                        compressor: Optional[str] = None,
                        ) -> List[MoEFact]:
    """Derive :class:`MoEFact`s from an expert-flagged variable catalog
    — THE shared projection of ``expert_vars`` (runtime capture and
    analyzer see the same ``VarInfo`` rows, so both sides build
    identical facts and the IR instances agree).

    ``variables`` yields objects with ``.name``/``.shape``/``.expert``
    (and optionally ``.pipeline``).  Expert variables group by parent
    path (``layers_0/moe/wi`` -> key ``layers_0/moe``); the first
    expert variable of a group is wi-shaped ``[experts, d_model, d_ff]``
    (one leading stage dim first when pipeline-stacked), which fixes
    ``num_experts`` and ``d_model``.  Token counts are static hints:
    ``groups`` defaults to the data-axis size (one token group per data
    shard — the ``moe_ffn`` grouping), ``seq`` to
    :func:`moe_tokens_per_group_default`."""
    axes = dict(axes or {})
    groups = max(int(axes.get(MESH_AXIS_DATA, 1)), 1)
    seq = int(tokens_per_group or moe_tokens_per_group_default())
    if capacity_factor is None:
        capacity_factor = moe_capacity_factor_default()
    if compressor is None:
        compressor = moe_wire_compressor_default()
    by_key: Dict[str, Any] = {}
    for v in variables:
        if not getattr(v, "expert", False):
            continue
        name = str(v.name)
        key = name.rsplit("/", 1)[0] if "/" in name else name
        if key in by_key:
            continue                      # first var (wi) fixes the shapes
        shape = tuple(int(x) for x in (v.shape or ()))
        if getattr(v, "pipeline", False):
            shape = shape[1:]             # drop the stage stacking dim
        if len(shape) < 2:
            continue
        by_key[key] = MoEFact(
            key=key, groups=groups, seq=seq, d_model=int(shape[1]),
            num_experts=int(shape[0]),
            capacity_factor=float(capacity_factor),
            dtype="float32", axis=MESH_AXIS_EXPERT, stage=stage_of(key),
            compressor=compressor or "NoneCompressor")
    return [by_key[k] for k in sorted(by_key)]


# -- MPMD pipeline facts (mesh-free, shared by runtime + analysis) -----------

PIPE_ROLE_FWD = "fwd"
PIPE_ROLE_BWD = "bwd"


@dataclass(frozen=True)
class PipelineFact:
    """One MPMD pipeline's mesh-free transport facts.

    Feeds the ``send_act``/``recv_act`` leg grid the builder emits in
    1F1B tick order: per stage boundary ``b`` (stage ``b`` →
    ``b + 1``) and microbatch slot ``m``, one forward activation pair
    (``act:<key>/f<b>@<m>``) and one backward cotangent pair
    (``act:<key>/b<b>@<m>``), all tier ``dcn``.  The per-stage dep
    chains ARE the runtime's issue order (``parallel/mpmd`` executes
    the same IR instance, flight-recorder cursors carry the leg ids),
    so the verifier's pairwise ``schedule/act-transport`` rule and the
    dataflow race/leak rules model exactly what runs."""

    key: str                      # e.g. "pipe" — buffer/leg namespace
    num_stages: int               # S
    num_microbatches: int         # M (== the program's accum_steps)
    act_nbytes: int               # full-precision bytes of one boundary
    num_virtual: int = 1          # V: virtual stages per device
    dtype: str = "float32"
    compressor: str = "NoneCompressor"   # Int8Compressor = quantized wire

    def ticks(self) -> int:
        return schedule_ticks_1f1b(
            self.num_stages, self.num_microbatches, self.num_virtual)

    def bubble_fraction(self) -> float:
        return bubble_fraction_1f1b(
            self.num_stages, self.num_microbatches, self.num_virtual)

    def leg_nbytes(self) -> int:
        """Honest wire bytes of one transport leg: the f32 boundary, or
        — quantized wire — 1-byte/elem payload plus the per-chunk scale
        grid (``quant_ring.wire_nbytes``)."""
        fmt = quant_ring.wire_format_of(self.compressor or "")
        if fmt is not None:
            elems = max(1, int(self.act_nbytes)
                        // np.dtype(self.dtype).itemsize)
            return quant_ring.wire_nbytes(elems, fmt)
        return int(self.act_nbytes)

    def sig(self, role: str) -> str:
        """Transport-leg signature — the role (fwd activation vs bwd
        cotangent) is IN the signature so a swapped pair compares
        unequal."""
        return "|".join(str(x) for x in (
            "pipe", role, self.compressor or "NoneCompressor",
            int(self.num_stages)))


def pipeline_wire_compressor_default() -> str:
    """The activation-transport wire knob: ``AUTODIST_PIPE_WIRE=int8``
    puts the cross-slice boundary activations on the quantized wire
    (stateless per-microbatch scale grid, like the DCN gradient wire);
    anything else is the full-precision wire.  Read by every pipeline
    fact producer (the MPMD runtime, the ``--simulate`` sweep, bench
    modes) so one env knob keeps all fingerprints in agreement."""
    import os
    wire = os.environ.get("AUTODIST_PIPE_WIRE", "").strip().lower()
    return "Int8Compressor" if wire == "int8" else "NoneCompressor"


# -- builder -----------------------------------------------------------------

@dataclass(frozen=True)
class PerVarEntry:
    """A per-variable (non-bucketed) sync leg source: the fallback tier
    of the explicit path, every PS plan, and every variable of the
    GSPMD (psum-tree) lowering."""

    name: str
    dtype: str
    nbytes: int
    sync_kind: str = "AllReduce"
    compressor: str = "NoneCompressor"
    sig: str = ""
    stateful: bool = False


class _Emitter:
    """Leg emission with per-stage collective issue chaining: each
    collective leg depends on the previous collective its participants
    issued, making a rank's issue stream a total order the verifier can
    compare across stages."""

    def __init__(self):
        self.legs: List[Leg] = []
        self._last: Dict[str, str] = {}

    def emit(self, *, chainable: bool = True, **kw) -> Leg:
        deps = list(kw.pop("deps", ()))
        stage = kw.get("stage", "")
        if chainable:
            prev = self._last.get(stage)
            if prev is None and stage:
                prev = self._last.get("")
            if prev is not None:
                deps.append(prev)
        leg = Leg(deps=tuple(dict.fromkeys(deps)), **kw)
        self.legs.append(leg)
        if chainable:
            self._last[stage] = leg.id
        return leg


def _bucket_sig(b: Bucket) -> str:
    return "|".join(str(x) for x in (
        "bucket", b.mode, b.dtype, b.compressor or "NoneCompressor",
        int(b.group)))


def _bucket_stage(b: Bucket) -> str:
    stages = {stage_of(n) for n in b.names}
    return stages.pop() if len(stages) == 1 else ""


def _ring_chain(em: _Emitter, *, chain: str, b: Bucket,
                d: int, axis: str, slot: int, stage: str, deps: Sequence[str],
                reads: Tuple[str, ...], writes: Tuple[str, ...],
                per_hop: Optional[int] = None,
                compressor: Optional[str] = None,
                hop_kind: str = LEG_PPERMUTE_HOP) -> Leg:
    """Emit a d-1 hop ppermute ring chain; returns the final hop (which
    carries ``writes``).  ``per_hop`` overrides the per-hop wire bytes
    (quantized chains: 1-byte/elem payload + per-chunk scale bytes);
    ``compressor`` overrides the wire tag (the ZeRO-1 param gather
    rides full precision regardless of the bucket's gradient wire);
    ``hop_kind`` selects the fused-boundary variant
    (:data:`LEG_FUSED_HOP`) — same chain grammar, distinct calibration
    kind."""
    prev: Optional[Leg] = None
    if per_hop is None:
        per_hop = int(b.nbytes // max(d, 1))
    if compressor is None:
        compressor = b.compressor or "NoneCompressor"
    for h in range(1, d):
        last = h == d - 1
        leg = em.emit(
            id=f"{chain}/hop{h}", kind=hop_kind, bucket=b.key,
            dtype=b.dtype, nbytes=per_hop, axis=axis, slot=slot,
            compressor=compressor, alg=ALG_RING,
            hop=h, chain=chain, stage=stage, sig=_bucket_sig(b),
            deps=tuple(deps) if prev is None else (prev.id,),
            reads=reads if prev is None else (),
            writes=writes if last else ())
        prev = leg
    return prev


def _emit_pipeline_legs(em: _Emitter, pf: PipelineFact) -> None:
    """Emit one pipeline's ``send_act``/``recv_act`` grid in 1F1B tick
    order (V=1 transport grid; virtual stages only shape the bubble).

    The order matters: the `_Emitter` per-stage chaining makes each
    stage's transport legs a total order, and emitting them in tick
    order makes that chain EXACTLY the order the MPMD StageRunner
    executes — forward recv/send for microbatch ``t - s`` first, then
    backward recv/send for ``t - 2(S-1) + s`` — so the verifier's
    partial order, the liveness watermark's buffer intervals, and the
    flight-recorder's cursor sequence all model the real runtime."""
    s_n = max(int(pf.num_stages), 1)
    m_n = max(int(pf.num_microbatches), 1)
    if s_n < 2:
        return
    nb = pf.leg_nbytes()
    comp = pf.compressor or "NoneCompressor"
    drain = 2 * (s_n - 1)
    pid = f"pipe/{pf.key}"
    for t in range(schedule_ticks_1f1b(s_n, m_n, 1)):
        for st in range(s_n):
            stage = stage_name(st)
            jf = t - st
            jb = t - drain + st
            if 0 <= jf < m_n:
                if st > 0:
                    # forward boundary input arrives over DCN
                    em.emit(
                        id=f"{pid}/f{st - 1}@{jf}/recv", kind=LEG_RECV_ACT,
                        bucket=pf.key, dtype=pf.dtype, nbytes=nb,
                        axis="", slot=jf, compressor=comp,
                        alg=ALG_ONE_SHOT, chain=f"{pid}/f{st - 1}",
                        stage=stage, sig=pf.sig(PIPE_ROLE_FWD),
                        tier=TIER_DCN,
                        deps=(f"{pid}/f{st - 1}@{jf}/send",),
                        reads=(f"act:{pf.key}/f{st - 1}@{jf}",))
                if st < s_n - 1:
                    # boundary output ships right after the stage's fwd
                    em.emit(
                        id=f"{pid}/f{st}@{jf}/send", kind=LEG_SEND_ACT,
                        bucket=pf.key, dtype=pf.dtype, nbytes=nb,
                        axis="", slot=jf, compressor=comp,
                        alg=ALG_ONE_SHOT, chain=f"{pid}/f{st}",
                        stage=stage, sig=pf.sig(PIPE_ROLE_FWD),
                        tier=TIER_DCN,
                        deps=(f"{pid}/f{st - 1}@{jf}/recv",)
                        if st > 0 else (),
                        writes=(f"act:{pf.key}/f{st}@{jf}",))
            if 0 <= jb < m_n:
                if st < s_n - 1:
                    # cotangent from downstream arrives before this
                    # stage's backward for microbatch jb
                    em.emit(
                        id=f"{pid}/b{st}@{jb}/recv", kind=LEG_RECV_ACT,
                        bucket=pf.key, dtype=pf.dtype, nbytes=nb,
                        axis="", slot=jb, compressor=comp,
                        alg=ALG_ONE_SHOT, chain=f"{pid}/b{st}",
                        stage=stage, sig=pf.sig(PIPE_ROLE_BWD),
                        tier=TIER_DCN,
                        deps=(f"{pid}/b{st}@{jb}/send",),
                        reads=(f"act:{pf.key}/b{st}@{jb}",))
                if st > 0:
                    # backward needs the incoming cotangent — or, on
                    # the last stage (fwd and bwd share the tick), the
                    # microbatch's forward input
                    dep = f"{pid}/b{st}@{jb}/recv" if st < s_n - 1 \
                        else f"{pid}/f{st - 1}@{jb}/recv"
                    em.emit(
                        id=f"{pid}/b{st - 1}@{jb}/send", kind=LEG_SEND_ACT,
                        bucket=pf.key, dtype=pf.dtype, nbytes=nb,
                        axis="", slot=jb, compressor=comp,
                        alg=ALG_ONE_SHOT, chain=f"{pid}/b{st - 1}",
                        stage=stage, sig=pf.sig(PIPE_ROLE_BWD),
                        tier=TIER_DCN, deps=(dep,),
                        writes=(f"act:{pf.key}/b{st - 1}@{jb}",))


def build_schedule_ir(*, axes: Dict[str, int], accum_steps: int = 1,
                      buckets: Sequence[Bucket] = (),
                      plan: Optional[overlap_mod.OverlapPlan] = None,
                      per_var: Sequence[PerVarEntry] = (),
                      guard: bool = False,
                      donated: Sequence[str] = (),
                      stateful_keys: Iterable[str] = (),
                      per_var_alg: str = ALG_FUSED,
                      fused_kernels: Sequence[str] = (),
                      moe: Sequence[MoEFact] = (),
                      num_slices: int = 1,
                      hier_keys: Iterable[str] = (),
                      pipeline: Sequence[PipelineFact] = ()) -> ScheduleIR:
    """Build the schedule program for one step.

    Pure: consumes exactly the planner's outputs (``buckets`` from
    ``bucketing.assign_buckets``, ``plan`` from
    ``overlap.resolve_overlap``) plus program facts, so the runtime,
    the analyzer, the cost model, and the bench all construct the SAME
    IR and can never drift.  ``stateful_keys`` names buckets whose
    compressor carries sync state (probed by the runtime, mirrored by
    :func:`compressor_stateful` for mesh-free callers); ``donated``
    lists the donated sync-state buffer names (``sync:<key>``);
    ``fused_kernels`` the ACTIVE fused Pallas kernels (already
    drop-filtered — ``ops.fused_kernels.resolve_fused``), which switch
    the affected legs to their fused kinds (docs/kernels.md).
    ``num_slices``/``hier_keys`` select the two-tier hierarchical
    lowering: buckets named in ``hier_keys`` (linear-compressor only —
    the caller gates) reduce slice-locally over ICI, exchange over DCN,
    and gather back, when :func:`hier_applies` holds."""
    axes = {str(k): int(v) for k, v in axes.items()}
    d = max(int(axes.get(MESH_AXIS_DATA, 1)), 1)
    hier_on = hier_applies(d, num_slices)
    s = max(int(num_slices), 1) if hier_on else 1
    d_in = d // s
    hier_set = set(hier_keys) if hier_on else set()
    dcn_comp = dcn_wire_compressor_default()
    accum = max(int(accum_steps), 1)
    buckets = sorted(buckets, key=lambda b: b.order)
    if plan is None:
        plan = overlap_mod.resolve_overlap(
            [], accum_steps=accum, buckets=buckets, d=d,
            has_rs=any(b.mode == MODE_REDUCE_SCATTER for b in buckets))
    stateful = set(stateful_keys)
    fused = tuple(fused_kernels)
    em = _Emitter()
    reduce_final: Dict[str, str] = {}
    detect_bytes: Dict[str, int] = {}   # f32 bytes the guard pass touches
    bucket_nodes: List[dict] = []

    # MPMD pipeline transport grid first: boundary activations and
    # cotangents move DURING the forward/backward compute, before any
    # within-stage gradient reduction issues — and emitting them first
    # seeds each stage's issue chain so a stage's grad collectives
    # order after its pipeline drain.
    pipeline = sorted(pipeline, key=lambda p: p.key)
    for pf in pipeline:
        _emit_pipeline_legs(em, pf)

    # MoE expert all-to-alls first: dispatch/combine happen inside the
    # forward/backward compute, before any gradient reduction issues.
    # Per layer and microbatch slot one PAIR: dispatch reads the routed
    # activations (``act:<key>``) into the capacity buffer
    # (``expert:<key>``), combine reads it back — the expert buffer's
    # [dispatch, combine] interval is exactly the capacity-sized
    # transient the liveness watermark charges.  With expert-axis size
    # <= 1 the partition is trivial and GSPMD inserts no collective, so
    # no legs exist to disagree on.
    moe = sorted(moe, key=lambda m: m.key)
    for mf in moe:
        e_ax = int(axes.get(mf.axis, 1))
        if e_ax <= 1:
            continue
        nb = mf.leg_nbytes(e_ax)
        comp = mf.compressor or "NoneCompressor"
        slots = list(range(accum)) if accum > 1 else [END_OF_STEP]
        for slot in slots:
            tag = mf.key if slot == END_OF_STEP else f"{mf.key}@{slot}"
            disp = em.emit(
                id=f"moe/{tag}/dispatch", kind=LEG_ALL_TO_ALL,
                bucket=mf.key, dtype=mf.dtype, nbytes=nb, axis=mf.axis,
                slot=slot, compressor=comp, alg=ALG_ONE_SHOT,
                stage=mf.stage, sig=mf.sig(MOE_ROLE_DISPATCH),
                reads=(f"act:{mf.key}",), writes=(f"expert:{mf.key}",))
            em.emit(
                id=f"moe/{tag}/combine", kind=LEG_ALL_TO_ALL,
                bucket=mf.key, dtype=mf.dtype, nbytes=nb, axis=mf.axis,
                slot=slot, compressor=comp, alg=ALG_ONE_SHOT,
                stage=mf.stage, sig=mf.sig(MOE_ROLE_COMBINE),
                deps=(disp.id,),
                reads=(f"expert:{mf.key}",), writes=(f"act:{mf.key}",))

    # Per-variable fallback tier first — the explicit path's tier-3 loop
    # (and the whole GSPMD lowering) issues these before bucket chains.
    for e in per_var:
        kind = LEG_PS_EXCHANGE if e.sync_kind == "PS" else LEG_ALL_REDUCE
        state = (f"sync:{e.name}",) if e.stateful else ()
        leg = em.emit(
            id=f"var/{e.name}", kind=kind, bucket=e.name, dtype=e.dtype,
            nbytes=int(e.nbytes), axis=MESH_AXIS_DATA, slot=END_OF_STEP,
            compressor=e.compressor or "NoneCompressor", alg=per_var_alg,
            stage=stage_of(e.name), sig=e.sig,
            reads=(f"grad:{e.name}",) + state,
            writes=(f"red:{e.name}",) + state)
        reduce_final[e.name] = leg.id
        detect_bytes[e.name] = int(e.nbytes)

    for b in buckets:
        rs = b.mode == MODE_REDUCE_SCATTER
        linear = overlap_mod.is_linear_compressor(b.compressor)
        qfmt = quant_ring.wire_format_of(b.compressor or "")
        # Two-tier hierarchical lowering: linear-compressor buckets the
        # caller named.  A quantized gradient wire keeps the flat path
        # (its per-hop error-feedback contract has no two-level form);
        # the DCN leg's own wire knob quantizes the cross-slice shard.
        hier = b.key in hier_set and linear and qfmt is None
        # The reduce lowering — the EXACT rule bucket_reduce_fn (linear)
        # / quant_bucket_reduce (quantized wire) applies.
        if hier:
            alg = ALG_ONE_SHOT
        elif linear and plan.ring and d > 1 \
                and b.nbytes >= plan.ring_threshold:
            alg = ALG_RING
        elif linear and plan.one_shot_small and d > 1 and not rs:
            alg = ALG_ONE_SHOT
        elif qfmt is not None and quant_ring.ring_applies(
                plan.mode, b.nbytes, d, plan.ring_threshold):
            alg = ALG_RING
        else:
            alg = ALG_FUSED if per_var_alg != ALG_PSUM_TREE else ALG_PSUM_TREE
        pipelined = bool(
            plan.pipeline and accum > 1
            and overlap_mod.pipeline_eligible(b, plan.mode, accum))
        if rs:
            gather_alg = ALG_ONE_SHOT if hier else (
                ALG_RING if plan.ring and d > 1
                and b.nbytes >= plan.ring_threshold else ALG_FUSED)
        else:
            gather_alg = ""
        stage = _bucket_stage(b)
        # Quantized wire accounting (docs/schedule-ir.md): a quantized
        # leg's nbytes is the HONEST transfer — 1-byte/elem payload plus
        # the per-chunk f32 scales traveling with it — so the IR cost
        # model prices the compressed wire, not the f32 vector.
        if qfmt is not None:
            leg_nbytes = quant_ring.wire_nbytes(b.padded_total, qfmt)
            hop_nbytes = quant_ring.wire_nbytes(
                b.padded_total // max(d, 1), qfmt)
        else:
            leg_nbytes = int(b.nbytes)
            hop_nbytes = None
        # Stateful resolution: the runtime passes its exact eval_shape
        # probe results; mesh-free callers fall back to the registry probe.
        is_stateful = (b.key in stateful) if stateful else (
            not linear and compressor_stateful(b.compressor))
        state = (f"sync:{b.key}",) if is_stateful else ()
        # Fused hop boundaries (docs/kernels.md): only a quantized ring
        # chain has per-hop dequantize/requantize arithmetic to fuse.
        hop_fused = ("quant_hop" in fused and qfmt is not None
                     and alg == ALG_RING)
        hop_kind = LEG_FUSED_HOP if hop_fused else LEG_PPERMUTE_HOP
        detect_bytes[b.key] = int(b.padded_total) * 4
        bucket_nodes.append({
            "key": b.key, "mode": b.mode, "dtype": b.dtype,
            "compressor": b.compressor or "NoneCompressor",
            "group": int(b.group), "order": int(b.order),
            "total": int(b.total), "padded_total": int(b.padded_total),
            "nbytes": int(b.nbytes), "alg": alg, "pipelined": pipelined,
            "gather_alg": gather_alg, "stage": stage,
            # quantized-leg metadata (empty/zero for full-precision wire)
            "wire_dtype": qfmt.name if qfmt else "",
            "scale_block": quant_ring.QUANT_BLOCK_ELEMS if qfmt else 0,
            "scale_nbytes": quant_ring.scale_nbytes(b.padded_total)
            if qfmt else 0,
            "requantize_per_hop": bool(qfmt is not None and alg == ALG_RING),
            "vars": [{"name": v.name, "shape": list(v.shape)}
                     for v in b.vars],
            # fused-kernel hop boundary (omitted when off so every
            # pre-fusion bucket node — and fingerprint — is unchanged)
            **({"hop_fused": True} if hop_fused else {}),
            # two-tier lowering flag (same omit-when-off contract)
            **({"hier": True} if hier else {}),
        })
        slots = list(range(accum)) if pipelined else [END_OF_STEP]
        for slot in slots:
            reads = (f"grad:{b.key}",) + state
            writes = (f"red:{b.key}",) + state
            if hier:
                # ICI -> DCN (-> ICI) per bucket: slice-local reduce-
                # scatter, cross-slice shard exchange, slice-local
                # gather (plain AR only — ZeRO-1 keeps the 1/d owner
                # sub-shard for the update and gathers after it).
                dcn_fmt = quant_ring.wire_format_of(dcn_comp)
                shard_elems = int(b.padded_total) // d_in
                dcn_nb = quant_ring.wire_nbytes(shard_elems, dcn_fmt) \
                    if dcn_fmt is not None else int(b.nbytes) // d_in
                rs_leg = em.emit(
                    id=f"{b.key}@{slot}/hier_rs",
                    kind=LEG_HIER_REDUCE_SCATTER, bucket=b.key,
                    dtype=b.dtype, nbytes=int(b.nbytes),
                    axis=MESH_AXIS_DATA, slot=slot,
                    compressor=b.compressor or "NoneCompressor",
                    alg=ALG_ONE_SHOT, stage=stage, sig=_bucket_sig(b),
                    tier=TIER_ICI, reads=reads, writes=writes)
                dcn_leg = em.emit(
                    id=f"{b.key}@{slot}/dcn",
                    kind=LEG_DCN_EXCHANGE if rs else LEG_DCN_ALL_REDUCE,
                    bucket=b.key, dtype=b.dtype, nbytes=dcn_nb,
                    axis=MESH_AXIS_DATA, slot=slot, compressor=dcn_comp,
                    alg=ALG_ONE_SHOT, stage=stage, sig=_bucket_sig(b),
                    tier=TIER_DCN, deps=(rs_leg.id,),
                    reads=(f"red:{b.key}",), writes=writes)
                last = dcn_leg
                if not rs:
                    last = em.emit(
                        id=f"{b.key}@{slot}/hier_ag",
                        kind=LEG_HIER_ALL_GATHER, bucket=b.key,
                        dtype=b.dtype, nbytes=int(b.nbytes),
                        axis=MESH_AXIS_DATA, slot=slot,
                        compressor="NoneCompressor", alg=ALG_ONE_SHOT,
                        stage=stage, sig=_bucket_sig(b), tier=TIER_ICI,
                        deps=(dcn_leg.id,),
                        reads=(f"red:{b.key}",), writes=writes)
            elif alg == ALG_RING:
                if rs:
                    last = _ring_chain(
                        em, chain=f"{b.key}@{slot}/rs", b=b, d=d,
                        axis=MESH_AXIS_DATA, slot=slot, stage=stage,
                        deps=(), reads=reads, writes=writes,
                        per_hop=hop_nbytes, hop_kind=hop_kind)
                else:
                    mid = _ring_chain(
                        em, chain=f"{b.key}@{slot}/rs", b=b, d=d,
                        axis=MESH_AXIS_DATA, slot=slot, stage=stage,
                        deps=(), reads=reads, writes=(),
                        per_hop=hop_nbytes, hop_kind=hop_kind)
                    # The gather stage's per-hop work is a plain
                    # dequantize-into-place (EQuARX stage 2) — no
                    # accumulate/requantize boundary to fuse, so its
                    # hops keep the unfused kind.
                    last = _ring_chain(
                        em, chain=f"{b.key}@{slot}/ag", b=b, d=d,
                        axis=MESH_AXIS_DATA, slot=slot, stage=stage,
                        deps=(mid.id,), reads=(), writes=writes,
                        per_hop=hop_nbytes)
            else:
                last = em.emit(
                    id=f"{b.key}@{slot}/reduce",
                    kind=LEG_REDUCE_SCATTER if rs else LEG_ALL_REDUCE,
                    bucket=b.key, dtype=b.dtype, nbytes=leg_nbytes,
                    axis=MESH_AXIS_DATA, slot=slot,
                    compressor=b.compressor or "NoneCompressor", alg=alg,
                    stage=stage, sig=_bucket_sig(b),
                    reads=reads, writes=writes)
            reduce_final[b.key] = last.id

    # Guard roll-up: ONE small all-axis psum over every bucket/var
    # partial (docs/numerics.md) — depends on every reduce final.  With
    # the fused guard kernel the per-key detection arithmetic (the
    # measured 5-7% of BENCH_guard.json — not the psum) becomes an
    # explicit fused_detect leg per key: one Pallas pass producing the
    # finite-count and sq-norm partials together, priced by its own
    # calibration kind.
    guard_id = None
    if guard:
        rollup_deps = list(reduce_final.values())
        if "guard" in fused:
            for key, lid in sorted(reduce_final.items()):
                leg = em.emit(
                    chainable=False, id=f"detect/{key}",
                    kind=LEG_FUSED_DETECT, bucket=key, dtype="float32",
                    nbytes=int(detect_bytes.get(key, 0)),
                    slot=END_OF_STEP, alg=ALG_FUSED, sig="detect",
                    deps=(lid,), reads=(f"red:{key}",))
                rollup_deps.append(leg.id)
        leg = em.emit(
            id="guard/rollup", kind=LEG_PSUM_GUARD, bucket="~numerics",
            dtype="float32",
            nbytes=4 * (len(reduce_final) + 2), axis="", slot=END_OF_STEP,
            alg=ALG_FUSED, sig="guard",
            deps=tuple(rollup_deps),
            reads=tuple(f"red:{k}" for k in reduce_final)
            + ("sync:~numerics",),
            writes=("sync:~numerics",))
        guard_id = leg.id

    # Updates: ZeRO-1 buckets update their local 1/d shard; everything
    # else rides the tree optimizer.  Not collectives — excluded from
    # the issue chain, ordered purely by data deps.
    rs_nodes = [n for n in bucket_nodes if n["mode"] == MODE_REDUCE_SCATTER]
    update_of: Dict[str, str] = {}
    # Fused unscale/clip/update (docs/kernels.md): only the ZeRO-1 flat
    # bucket-major shard update fuses — the tree update stays the optax
    # chain regardless.
    rs_update_kind = LEG_FUSED_UPDATE if "update" in fused else LEG_UPDATE
    for n in rs_nodes:
        key = n["key"]
        deps = [reduce_final[key]] + ([guard_id] if guard_id else [])
        leg = em.emit(
            chainable=False, id=f"update/{key}", kind=rs_update_kind,
            bucket=key, dtype=n["dtype"],
            nbytes=int(n["padded_total"]
                       * np.dtype(n["dtype"]).itemsize // d),
            slot=END_OF_STEP, alg=ALG_FUSED, stage=n["stage"],
            sig="update", deps=tuple(deps),
            reads=(f"red:{key}", f"opt:{key}", f"param:{key}"),
            writes=(f"param:{key}", f"opt:{key}"))
        update_of[key] = leg.id
    tree_srcs = [lid for k, lid in reduce_final.items()
                 if k not in update_of]
    if tree_srcs or not rs_nodes:
        em.emit(
            chainable=False, id="update/~tree", kind=LEG_UPDATE,
            bucket="~tree", slot=END_OF_STEP, alg=ALG_FUSED, sig="update",
            deps=tuple(tree_srcs) + ((guard_id,) if guard_id else ()),
            reads=tuple(f"red:{k}" for k, lid in reduce_final.items()
                        if k not in update_of)
            + ("param:~tree", "opt:~tree"),
            writes=("param:~tree", "opt:~tree"))

    # ZeRO-1 param gathers in the schedule's issue order (reverse bucket
    # order under prefetch — overlap.gather_schedule).
    gather_order: List[Tuple[str, str]] = []
    if rs_nodes:
        by_key = {n["key"]: n for n in rs_nodes}
        rs_buckets = [b for b in buckets
                      if b.mode == MODE_REDUCE_SCATTER]
        for b in overlap_mod.gather_schedule(rs_buckets, plan.prefetch):
            n = by_key[b.key]
            gather_order.append((b.key, n["gather_alg"]))
            if n.get("hier"):
                # Two-tier ZeRO-1 gather, full precision (the update ran
                # on the dequantized owner sub-shard): cross-slice DCN
                # gather reassembles each slice-chunk, then the ICI
                # gather reassembles the full flat parameter vector.
                g1 = em.emit(
                    id=f"{b.key}@gather/dcn", kind=LEG_HIER_ALL_GATHER,
                    bucket=b.key, dtype=b.dtype,
                    nbytes=int(b.nbytes) // d_in,
                    axis=MESH_AXIS_DATA, slot=END_OF_STEP,
                    alg=ALG_ONE_SHOT, stage=n["stage"], sig=_bucket_sig(b),
                    tier=TIER_DCN, deps=(update_of[b.key],),
                    reads=(f"param:{b.key}",), writes=(f"param:{b.key}",))
                em.emit(
                    id=f"{b.key}@gather/ici", kind=LEG_HIER_ALL_GATHER,
                    bucket=b.key, dtype=b.dtype, nbytes=int(b.nbytes),
                    axis=MESH_AXIS_DATA, slot=END_OF_STEP,
                    alg=ALG_ONE_SHOT, stage=n["stage"], sig=_bucket_sig(b),
                    tier=TIER_ICI, deps=(g1.id,),
                    reads=(f"param:{b.key}",), writes=(f"param:{b.key}",))
            elif n["gather_alg"] == ALG_RING:
                # Fresh parameters gather FULL PRECISION whatever the
                # gradient wire was (ZeRO-1 updates from the dequantized
                # shard) — tag the chain accordingly.
                _ring_chain(
                    em, chain=f"{b.key}@gather/ag",
                    b=b, d=d, axis=MESH_AXIS_DATA, slot=END_OF_STEP,
                    stage=n["stage"], deps=(update_of[b.key],),
                    reads=(f"param:{b.key}",), writes=(f"param:{b.key}",),
                    compressor="NoneCompressor")
            else:
                em.emit(
                    id=f"{b.key}@gather", kind=LEG_ALL_GATHER, bucket=b.key,
                    dtype=b.dtype, nbytes=int(b.nbytes),
                    axis=MESH_AXIS_DATA, slot=END_OF_STEP, alg=ALG_FUSED,
                    stage=n["stage"], sig=_bucket_sig(b),
                    deps=(update_of[b.key],),
                    reads=(f"param:{b.key}",), writes=(f"param:{b.key}",))

    return ScheduleIR(
        axes=axes, accum_steps=accum, overlap_mode=plan.mode, guard=guard,
        prefetch=bool(plan.prefetch), buckets=bucket_nodes, legs=em.legs,
        gather_order=gather_order, donated=tuple(donated),
        fused_kernels=fused, moe=tuple(moe), num_slices=s,
        pipeline=tuple(pipeline))


def facts_fingerprint(facts: Sequence[PlanFact], *, axes: Dict[str, int],
                      accum_steps: int = 1, guard: bool = False,
                      fused_kernels: Sequence[str] = (),
                      moe: Sequence[MoEFact] = (),
                      num_slices: int = 1,
                      pipeline: Sequence[PipelineFact] = ()) -> str:
    """Short stable hash of a candidate's full :func:`ir_from_facts`
    input — the strategy search's dedupe key.  Two candidates with
    identical fact sets build byte-identical IRs (the builder is pure),
    so hashing the INPUT lets the search skip constructing and pricing
    the duplicate entirely."""
    blob = json.dumps({
        "axes": {str(k): int(v) for k, v in axes.items()},
        "accum_steps": int(accum_steps),
        "guard": bool(guard),
        "fused_kernels": list(fused_kernels),
        "facts": [asdict(f) for f in facts],
        # Omit-when-empty: non-MoE candidates keep their dedupe keys.
        **({"moe": [asdict(m)
                    for m in sorted(moe, key=lambda m: m.key)]}
           if moe else {}),
        # Omit-when-1: single-slice candidates keep their dedupe keys.
        **({"num_slices": int(num_slices)}
           if int(num_slices) > 1 else {}),
        # Omit-when-empty: non-pipeline candidates keep their keys.
        **({"pipeline": [asdict(p)
                         for p in sorted(pipeline, key=lambda p: p.key)]}
           if pipeline else {}),
    }, sort_keys=True, separators=(",", ":")).encode()
    return hashlib.sha256(blob).hexdigest()[:12]


def ir_from_facts(facts: Sequence[PlanFact], *, axes: Dict[str, int],
                  accum_steps: int = 1, guard: bool = False,
                  fused_kernels: Sequence[str] = (),
                  moe: Sequence[MoEFact] = (),
                  num_slices: int = 1,
                  pipeline: Sequence[PipelineFact] = ()) -> ScheduleIR:
    """Mesh-free IR construction from per-variable plan facts — the
    analyzer's and the GSPMD transform's entry point.  Routing mirrors
    the runtime exactly: when any plan implies the explicit path
    (:func:`plan_route`), bucketable AllReduce vars bucket through the
    SAME ``assign_buckets`` planner the runtime executes; otherwise
    every variable keeps its per-variable (psum-tree) collective."""
    axes = {str(k): int(v) for k, v in axes.items()}
    d = max(int(axes.get(MESH_AXIS_DATA, 1)), 1)
    routes = {f.name: plan_route(f) for f in facts}
    explicit = any(exp for _, exp in routes.values())
    entries, per_var, cap = [], [], 0
    for f in facts:
        bucketable, _ = routes[f.name]
        if explicit and bucketable:
            entries.append((f.name, tuple(f.shape), str(np.dtype(f.dtype)),
                            f.compressor or "NoneCompressor", int(f.group),
                            f.sync_mode))
            cap = max(cap, int(f.bucket_bytes or 0))
        else:
            per_var.append(PerVarEntry(
                name=f.name, dtype=str(np.dtype(f.dtype)), nbytes=f.nbytes,
                sync_kind=f.sync_kind,
                compressor=f.compressor or "NoneCompressor", sig=f.sig(),
                stateful=compressor_stateful(f.compressor)
                if f.sync_kind == "AllReduce" else False))
    buckets: List[Bucket] = []
    if entries:
        from autodist_tpu.kernel.synchronization import bucketing
        buckets = bucketing.assign_buckets(
            entries, bucket_bytes=cap or bucketing.DEFAULT_BUCKET_BYTES,
            shard_divisor=d)
    plan = overlap_mod.resolve_overlap(
        [f.overlap for f in facts], accum_steps=accum_steps,
        buckets=buckets, d=d,
        has_rs=any(b.mode == MODE_REDUCE_SCATTER for b in buckets)) \
        if explicit else overlap_mod.OverlapPlan(
            mode=overlap_mod.OVERLAP_NONE, pipeline=False, ring=False,
            one_shot_small=False, prefetch=False)
    # Donation mirror of explicit_sync's audit: sync state is donated
    # only when every stateful entry is bucket-level (or numerics).
    stateful_buckets = [b.key for b in buckets
                        if compressor_stateful(b.compressor)]
    donated: Tuple[str, ...] = ()
    if explicit and not any(e.stateful for e in per_var):
        donated = tuple(f"sync:{k}" for k in stateful_buckets) \
            + (("sync:~numerics",) if guard else ())
    # Hier bucket selection — the EXACT rule the runtime applies: a
    # bucket lowers two-tier when every member variable requested it.
    hier_by_name = {f.name: bool(f.hier) for f in facts}
    hier_keys = [b.key for b in buckets
                 if b.names and all(hier_by_name.get(n, False)
                                    for n in b.names)] \
        if hier_applies(d, num_slices) else []
    return build_schedule_ir(
        axes=axes, accum_steps=accum_steps, buckets=buckets, plan=plan,
        per_var=per_var, guard=guard, donated=donated,
        stateful_keys=stateful_buckets,
        per_var_alg=ALG_FUSED if explicit else ALG_PSUM_TREE,
        fused_kernels=fused_kernels, moe=moe,
        num_slices=num_slices, hier_keys=hier_keys, pipeline=pipeline)


# -- the static schedule verifier --------------------------------------------

SEV_ERROR = "error"
SEV_WARN = "warn"

RULE_UNKNOWN_DEP = "schedule/unknown-dep"
RULE_DEP_CYCLE = "schedule/dep-cycle"
RULE_RING_DEGENERATE = "schedule/ring-degenerate"
RULE_RING_HOP_ORDER = "schedule/ring-hop-order"
RULE_QUANTIZED_PIPELINED = "schedule/quantized-pipelined"
RULE_READ_AFTER_DONATE = "schedule/read-after-donate"
RULE_COLLECTIVE_MISMATCH = "schedule/collective-mismatch"
RULE_REDUCTION_ORDER = "schedule/reduction-order-divergence"
RULE_FUSED_INCONSISTENT = "schedule/fused-inconsistent"
RULE_RACE_WRITE = "schedule/race-unordered-write"
RULE_RACE_READ_WRITE = "schedule/race-read-write"
RULE_BUFFER_LEAK = "schedule/buffer-leak"
RULE_CAPACITY_OVERFLOW = "moe/capacity-overflow"
RULE_HIER_TIER_ORDER = "schedule/hier-tier-order"
RULE_ACT_TRANSPORT = "schedule/act-transport"


@dataclass(frozen=True)
class Violation:
    rule: str
    severity: str
    message: str
    leg: str = ""
    location: str = ""

    def __str__(self) -> str:  # pragma: no cover - debugging aid
        where = f" [{self.leg or self.location}]" \
            if (self.leg or self.location) else ""
        return f"{self.rule}{where}: {self.message}"


def _topo_order(legs: Sequence[Leg]) -> Optional[List[str]]:
    """Kahn topological order of leg ids, or None on a cycle."""
    ids = {l.id for l in legs}
    indeg = {l.id: 0 for l in legs}
    fwd: Dict[str, List[str]] = {l.id: [] for l in legs}
    for l in legs:
        for dep in l.deps:
            if dep in ids:
                fwd[dep].append(l.id)
                indeg[l.id] += 1
    ready = [i for i, n in indeg.items() if n == 0]
    out: List[str] = []
    while ready:
        cur = ready.pop()
        out.append(cur)
        for nxt in fwd[cur]:
            indeg[nxt] -= 1
            if indeg[nxt] == 0:
                ready.append(nxt)
    return out if len(out) == len(legs) else None


def verify(ir: ScheduleIR) -> List[Violation]:
    """Model-check one schedule program.  Pure and fast (no jax; linear
    passes plus one happens-before bitset closure,
    ``analysis/dataflow.py``) — viable as a pre-trace gate; rule ids in
    the module docstring and docs/schedule-ir.md.  Findings come back
    sorted by ``(rule id, leg id)`` so output is byte-stable."""
    out: List[Violation] = []
    legs = list(ir.legs)
    ids = [l.id for l in legs]
    id_set = set()
    unique_ids = True
    for l in legs:
        if l.id in id_set:
            unique_ids = False
            out.append(Violation(
                RULE_UNKNOWN_DEP, SEV_ERROR,
                f"duplicate leg id {l.id!r}: the partial order is "
                "ambiguous", leg=l.id))
        id_set.add(l.id)
    for l in legs:
        for dep in l.deps:
            if dep not in id_set:
                out.append(Violation(
                    RULE_UNKNOWN_DEP, SEV_ERROR,
                    f"dep edge names missing leg {dep!r}", leg=l.id))
    order = _topo_order(legs)
    acyclic = order is not None and unique_ids
    if order is None:
        out.append(Violation(
            RULE_DEP_CYCLE, SEV_ERROR,
            "the dep graph has a cycle: no execution order exists and "
            "every rank blocks"))
        # positional fallback so the remaining (local) rules still run
        order = ids
    pos = {lid: i for i, lid in enumerate(order)}
    by_id = {l.id: l for l in legs}

    # -- ring chains: degenerate axes + exact hop order -------------------
    # (fused_hop legs are ppermute hops with a fused compute boundary —
    # one chain grammar, so the order/degeneracy rules cover both.)
    chains: Dict[str, List[Leg]] = {}
    for l in legs:
        if l.kind in RING_HOP_KINDS:
            chains.setdefault(l.chain or l.id, []).append(l)
    for chain, hops in chains.items():
        axis = hops[0].axis
        n = int(ir.axes.get(axis, 0))
        if n <= 1:
            out.append(Violation(
                RULE_RING_DEGENERATE, SEV_ERROR,
                f"ppermute ring chain {chain!r} permutes over axis "
                f"{axis!r} of size {n}: there is no ring", leg=hops[0].id,
                location=chain))
            continue
        ordered = sorted(hops, key=lambda l: pos.get(l.id, 0))
        want = list(range(1, len(hops) + 1))
        got = [l.hop for l in ordered]
        bad = got != want
        if not bad:
            # connectivity: each hop must depend on its predecessor (a
            # re-wired chain with correct positions still deadlocks).
            for prev, cur in zip(ordered, ordered[1:]):
                if prev.id not in cur.deps:
                    bad = True
                    break
        if len(hops) != n - 1:
            out.append(Violation(
                RULE_RING_HOP_ORDER, SEV_ERROR,
                f"ring chain {chain!r} has {len(hops)} hop(s) but axis "
                f"{axis!r}={n} needs exactly {n - 1}", location=chain))
        elif bad:
            out.append(Violation(
                RULE_RING_HOP_ORDER, SEV_ERROR,
                f"ring chain {chain!r} hops execute as {got}, not the "
                f"consecutive dep-ordered {want}: ranks disagree on the "
                "chunk in flight and the ppermute deadlocks",
                location=chain))

    # -- quantized collectives: the per-slot pipelining contract ----------
    # Admitted shapes per bucket (see module docstring): exactly one
    # quantized reduce at end-of-step, OR — quantized-ring compressors
    # only — exactly one per microbatch slot 0..accum-1.  A quantized
    # all-reduce's stage-2 gather chain counts as its own role (one per
    # slot too).  Anything else is rejected.
    accum = max(int(ir.accum_steps), 1)
    quant_events: Dict[Tuple[str, int, str], int] = {}
    quant_slots: Dict[str, set] = {}
    for l in legs:
        if l.kind not in COLLECTIVE_KINDS or not is_quantizing(l.compressor):
            continue
        if l.kind == LEG_ALL_TO_ALL:
            # The MoE a2a wire quantizes statelessly — a fresh scale
            # grid per dispatch/combine payload, no error-feedback
            # state — so the one-quantized-reduce-per-slot contract
            # does not bind the pair (two quantized a2as per slot are
            # exactly the legal shape).
            continue
        if l.kind in TRANSPORT_KINDS:
            # The pipeline activation wire quantizes statelessly (a
            # fresh scale grid per microbatch boundary, no error
            # feedback) — the act-transport rule owns its pairing.
            continue
        if l.tier == TIER_DCN:
            # The DCN wire quantizes statelessly too (a fresh scale
            # grid per cross-slice exchange, no error feedback) — the
            # per-slot quantized contract does not bind it; the
            # hier-tier-order rule below owns its shape.
            continue
        capable = quant_ring.is_quant_ring_compressor(l.compressor)
        if l.kind in RING_HOP_KINDS:
            if not capable:
                out.append(Violation(
                    RULE_QUANTIZED_PIPELINED, SEV_ERROR,
                    f"{l.compressor} has no per-hop requantize lowering: "
                    f"a quantized ppermute ring chain for bucket "
                    f"{l.bucket!r} cannot exist", leg=l.id))
                continue
            if l.hop != 1:
                continue          # hop 1 opens the chain: one event
            role = "gather" if (l.chain or "").endswith("/ag") else "reduce"
        else:
            role = "gather" if l.kind == LEG_ALL_GATHER else "reduce"
        if l.slot != END_OF_STEP and not capable:
            out.append(Violation(
                RULE_QUANTIZED_PIPELINED, SEV_ERROR,
                f"{l.compressor} collective for bucket {l.bucket!r} is "
                f"scheduled into accumulation slot {l.slot}: this "
                "compressor quantizes once per bucket per step (only "
                "quantized-ring compressors own the per-slot contract)",
                leg=l.id))
        key3 = (l.bucket, l.slot, role)
        quant_events[key3] = quant_events.get(key3, 0) + 1
        if role == "reduce":
            quant_slots.setdefault(l.bucket, set()).add(l.slot)
    for (key, slot, role), n in sorted(quant_events.items()):
        if n > 1:
            where = "one step" if slot == END_OF_STEP \
                else f"microbatch slot {slot}"
            out.append(Violation(
                RULE_QUANTIZED_PIPELINED, SEV_ERROR,
                f"bucket {key!r} schedules {n} quantized {role} "
                f"collectives in {where}: error-feedback state and the "
                "per-chunk scale grid assume exactly one", location=key))
    for key, slots in sorted(quant_slots.items()):
        slotted = sorted(s for s in slots if s != END_OF_STEP)
        if not slotted:
            continue
        if END_OF_STEP in slots:
            out.append(Violation(
                RULE_QUANTIZED_PIPELINED, SEV_ERROR,
                f"bucket {key!r} mixes slotted and end-of-step quantized "
                "collectives: the pipelined contract is one quantized "
                "collective per slot, nothing more", location=key))
        if slotted != list(range(accum)):
            out.append(Violation(
                RULE_QUANTIZED_PIPELINED, SEV_ERROR,
                f"bucket {key!r} pipelines quantized collectives in "
                f"slots {slotted}, not one per slot 0..{accum - 1}: "
                "error feedback threads through EVERY microbatch slot "
                "or none", location=key))

    # -- reduction-order divergence (determinism lint) --------------------
    for node in ir.buckets:
        low_precision = np.dtype(node["dtype"]).itemsize < 4
        if node["alg"] == ALG_RING and (
                low_precision or is_quantizing(node["compressor"])):
            out.append(Violation(
                RULE_REDUCTION_ORDER, SEV_WARN,
                f"bucket {node['key']!r} ({node['dtype']}"
                f"{', ' + node['compressor'] if is_quantizing(node['compressor']) else ''}) "
                "reduces in ring order on the explicit lowering but psum "
                "tree order on GSPMD: low-precision rounding makes the "
                "two lowerings diverge beyond reordering tolerance",
                location=node["key"]))

    # -- MoE capacity overflow: predicted token drops (pure rule) ---------
    # The same ``moe_capacity_drop_fraction`` the runtime fallback path
    # warns with, evaluated over the IR's carried routing facts — so a
    # lossy capacity config surfaces pre-trace with exact numbers.
    for mf in ir.moe:
        frac = mf.drop_fraction()
        if frac > 0.0:
            dropped = int(round(frac * 2 * mf.groups * mf.seq))
            out.append(Violation(
                RULE_CAPACITY_OVERFLOW, SEV_WARN,
                f"MoE layer {mf.key!r}: capacity_factor "
                f"{mf.capacity_factor:g} keeps {mf.capacity()} slot(s) "
                f"per expert per group ({mf.num_experts} experts, "
                f"{mf.groups} group(s) x {mf.seq} tokens) — top-2 "
                f"routing drops ~{frac:.0%} of expert assignments "
                f"(~{dropped} per step) even under balanced load; "
                "skewed routing drops more", location=mf.key))

    # -- fused-kernel consistency: legs vs the IR's fused record ----------
    # A fused-kind leg in a program whose ``fused_kernels`` record does
    # not claim that kernel (or a fused hop for a compressor with no
    # per-hop requantize lowering) means the two halves of the lowering
    # disagree about what runs — the fused kernel would read state the
    # unfused path owns, or vice versa.
    claimed = set(ir.fused_kernels)
    _kind_kernel = {kind: k for k, kind in FUSED_KERNEL_KINDS.items()}
    for l in legs:
        kernel = _kind_kernel.get(l.kind)
        if kernel is None:
            continue
        if kernel not in claimed:
            out.append(Violation(
                RULE_FUSED_INCONSISTENT, SEV_ERROR,
                f"leg {l.id!r} has fused kind {l.kind!r} but the program "
                f"does not record fused kernel {kernel!r}: the fused and "
                "unfused halves of the lowering disagree", leg=l.id))
        if l.kind == LEG_FUSED_HOP \
                and not quant_ring.is_quant_ring_compressor(l.compressor):
            out.append(Violation(
                RULE_FUSED_INCONSISTENT, SEV_ERROR,
                f"fused ring hop {l.id!r} carries compressor "
                f"{l.compressor!r}, which has no per-hop requantize "
                "lowering to fuse", leg=l.id))
    for node in ir.buckets:
        if node.get("hop_fused") and "quant_hop" not in claimed:
            out.append(Violation(
                RULE_FUSED_INCONSISTENT, SEV_ERROR,
                f"bucket {node['key']!r} is marked hop_fused but the "
                "program does not record fused kernel 'quant_hop'",
                location=node["key"]))

    # -- dataflow sanitizer: races, leaks, donation races -----------------
    # (analysis/dataflow.py: happens-before bitset reachability over the
    # dep closure; skipped when the graph is cyclic or ids collide — no
    # happens-before relation exists to judge against, and the
    # structural ERRORs above already reject the program.)
    if acyclic:
        from autodist_tpu.analysis import dataflow
        out.extend(dataflow.race_violations(ir, order=order))

    out.extend(_check_hier_tiers(ir, legs, pos))
    # MPMD pipeline stages are SEPARATE programs on disjoint process
    # groups (parallel/mpmd): they never co-issue, so the SPMD
    # cross-stage sequence comparison does not apply between them (the
    # act-transport rule owns their coupling).  Within a stage the DP
    # replicas share this one IR, so uniformity holds by construction.
    mpmd_stages = frozenset(
        stage_name(i) for pf in ir.pipeline for i in range(pf.num_stages))
    out.extend(_check_stage_sequences(legs, pos, mpmd_stages=mpmd_stages))
    out.extend(_check_act_transport(legs, pos))
    # Deterministic diagnostics: CLI output and mutation goldens are
    # byte-stable across runs (and across set/dict iteration orders).
    out.sort(key=lambda v: (v.rule, v.leg, v.location, v.message))
    return out


def _check_hier_tiers(ir: ScheduleIR, legs: Sequence[Leg],
                      pos: Dict[str, int]) -> List[Violation]:
    """The two-tier ordering contract (``schedule/hier-tier-order``).

    Per bucket and microbatch slot: a slice-local ``hier_reduce_scatter``
    MUST be followed by exactly one cross-slice DCN leg (a missing one
    means slices never exchange gradients — silent divergence), the DCN
    leg must be ordered between its slice-local RS and AG, and the
    ZeRO-1 variant's two-tier param gather must run DCN-then-ICI after
    the shard exchange.  Tier tags must match kinds, and hier legs are
    only legal on a program whose ``num_slices`` actually factors the
    data axis."""
    out: List[Violation] = []
    hier_legs = [l for l in legs if l.kind in HIER_KINDS]
    if not hier_legs:
        return out
    s = max(int(ir.num_slices), 1)
    d = max(int(ir.axes.get(MESH_AXIS_DATA, 1)), 1)
    if not hier_applies(d, s):
        out.append(Violation(
            RULE_HIER_TIER_ORDER, SEV_ERROR,
            f"hierarchical legs on a program whose data axis ({d}) does "
            f"not factor into num_slices={s} slices of >= 2 chips: "
            "there is no (slice, within-slice) decomposition to run "
            "them over", leg=hier_legs[0].id))
    want_tier = {LEG_HIER_REDUCE_SCATTER: (TIER_ICI,),
                 LEG_DCN_ALL_REDUCE: (TIER_DCN,),
                 LEG_DCN_EXCHANGE: (TIER_DCN,),
                 LEG_HIER_ALL_GATHER: (TIER_ICI, TIER_DCN),
                 # pipeline transport is tiered too (always DCN) — the
                 # act-transport rule owns the full contract; admitted
                 # here so a mixed hier+pipeline program does not flag
                 # the tag as a single-tier violation.
                 LEG_SEND_ACT: (TIER_DCN,),
                 LEG_RECV_ACT: (TIER_DCN,)}
    for l in legs:
        tiers = want_tier.get(l.kind)
        if tiers is not None and l.tier not in tiers:
            out.append(Violation(
                RULE_HIER_TIER_ORDER, SEV_ERROR,
                f"leg {l.id!r} of kind {l.kind!r} carries tier "
                f"{l.tier!r}; this kind rides "
                f"{' or '.join(repr(t) for t in tiers)}", leg=l.id))
        elif tiers is None and l.tier:
            out.append(Violation(
                RULE_HIER_TIER_ORDER, SEV_ERROR,
                f"single-tier leg {l.id!r} ({l.kind}) carries tier tag "
                f"{l.tier!r}: only hierarchical kinds are tiered",
                leg=l.id))

    groups: Dict[Tuple[str, int], List[Leg]] = {}
    for l in hier_legs:
        groups.setdefault((l.bucket, l.slot), []).append(l)
    by_bucket: Dict[str, Dict[str, List[Leg]]] = {}
    for (bucket, slot), ls in sorted(groups.items()):
        rs_l = [l for l in ls if l.kind == LEG_HIER_REDUCE_SCATTER]
        dcn_l = [l for l in ls if l.kind in DCN_KINDS]
        ag_ici = [l for l in ls if l.kind == LEG_HIER_ALL_GATHER
                  and l.tier == TIER_ICI]
        bb = by_bucket.setdefault(bucket, {"ex": [], "ag_dcn": [],
                                           "ag_ici": []})
        bb["ex"].extend(l for l in dcn_l if l.kind == LEG_DCN_EXCHANGE)
        bb["ag_dcn"].extend(l for l in ls
                            if l.kind == LEG_HIER_ALL_GATHER
                            and l.tier == TIER_DCN)
        bb["ag_ici"].extend(ag_ici)
        where = f"slot {slot}" if slot != END_OF_STEP else "end of step"
        if rs_l and not dcn_l:
            out.append(Violation(
                RULE_HIER_TIER_ORDER, SEV_ERROR,
                f"bucket {bucket!r} ({where}) reduce-scatters within "
                "each slice but never exchanges the shards across "
                "slices: replicas in different slices silently diverge",
                location=bucket))
            continue
        if dcn_l and not rs_l:
            out.append(Violation(
                RULE_HIER_TIER_ORDER, SEV_ERROR,
                f"bucket {bucket!r} ({where}) issues a cross-slice DCN "
                "leg with no slice-local reduce-scatter before it: the "
                "DCN wire would carry the full unreduced bucket",
                location=bucket))
            continue
        if not dcn_l:
            continue
        if len(dcn_l) > 1:
            out.append(Violation(
                RULE_HIER_TIER_ORDER, SEV_ERROR,
                f"bucket {bucket!r} ({where}) schedules {len(dcn_l)} "
                "cross-slice DCN legs: the hierarchy owes exactly one "
                "shard exchange per bucket per slot", location=bucket))
        dcn0 = min(pos.get(l.id, 0) for l in dcn_l)
        if rs_l and max(pos.get(l.id, 0) for l in rs_l) > dcn0:
            out.append(Violation(
                RULE_HIER_TIER_ORDER, SEV_ERROR,
                f"bucket {bucket!r} ({where}) orders its cross-slice "
                "DCN leg before the slice-local reduce-scatter "
                "finishes: the exchange would ship unreduced data",
                location=bucket))
        if any(l.kind == LEG_DCN_ALL_REDUCE for l in dcn_l):
            if not ag_ici:
                out.append(Violation(
                    RULE_HIER_TIER_ORDER, SEV_ERROR,
                    f"bucket {bucket!r} ({where}) exchanges shards over "
                    "DCN but never all-gathers them back within the "
                    "slice: every chip keeps only 1/slice-size of the "
                    "reduced gradient", location=bucket))
            elif min(pos.get(l.id, 0) for l in ag_ici) < \
                    max(pos.get(l.id, 0) for l in dcn_l):
                out.append(Violation(
                    RULE_HIER_TIER_ORDER, SEV_ERROR,
                    f"bucket {bucket!r} ({where}) orders the slice-"
                    "local all-gather before the cross-slice exchange: "
                    "the gather would replicate slice-partial sums",
                    location=bucket))
    # ZeRO-1 variant: the two-tier param gather (DCN then ICI) must
    # follow the shard exchange at the bucket level (gathers are
    # end-of-step while pipelined exchanges are per-slot).
    for bucket, bb in sorted(by_bucket.items()):
        if not bb["ex"]:
            continue
        ex_last = max(pos.get(l.id, 0) for l in bb["ex"])
        if not bb["ag_dcn"] or not bb["ag_ici"]:
            out.append(Violation(
                RULE_HIER_TIER_ORDER, SEV_ERROR,
                f"bucket {bucket!r} exchanges ZeRO-1 shards over DCN "
                "but lacks the two-tier param gather (DCN then ICI): "
                "parameters are never reassembled", location=bucket))
            continue
        ag_dcn = min(pos.get(l.id, 0) for l in bb["ag_dcn"])
        ag_ici = min(pos.get(l.id, 0) for l in bb["ag_ici"])
        if not (ex_last < ag_dcn < ag_ici):
            out.append(Violation(
                RULE_HIER_TIER_ORDER, SEV_ERROR,
                f"bucket {bucket!r}: the ZeRO-1 two-tier gather must "
                "run cross-slice (DCN) then within-slice (ICI) after "
                "the shard exchange; this program orders them "
                "otherwise", location=bucket))
    return out


def _check_stage_sequences(legs: Sequence[Leg],
                           pos: Dict[str, int],
                           mpmd_stages: FrozenSet[str] = frozenset()
                           ) -> List[Violation]:
    """Exact cross-stage deadlock check: every participant stage must
    issue an identical ordered collective sequence per microbatch slot.
    Stages compare within a kind family (stage* with stage*, expert*
    with expert*); all-rank (``""``) legs are uniform by construction.
    ``mpmd_stages`` names stages that are separate MPMD programs on
    disjoint process groups — those never co-issue, so they are exempt
    from the comparison (an unbalanced pipeline legitimately gives its
    stages different intra-stage collective sequences)."""
    out: List[Violation] = []
    by_stage: Dict[str, List[Leg]] = {}
    for l in legs:
        # Pipeline transport legs are point-to-point: adjacent stages
        # issue CONJUGATE (send vs recv) sequences by design, and edge
        # stages issue fewer than middle stages — the pairwise
        # act-transport rule owns their deadlock check.
        if l.kind in TRANSPORT_KINDS:
            continue
        if l.stage in mpmd_stages:
            continue
        if l.kind in COLLECTIVE_KINDS and l.stage:
            by_stage.setdefault(l.stage, []).append(l)
    families: Dict[str, Dict[int, List[Leg]]] = {}
    for stage, ls in by_stage.items():
        m = re.match(r"([a-z]+)(\d+)$", stage)
        if not m:
            continue
        families.setdefault(m.group(1), {})[int(m.group(2))] = ls

    def entry(l: Leg) -> Tuple:
        return (l.kind, l.alg,
                l.sig or f"{l.compressor}|{l.dtype}", l.slot, l.hop, l.axis)

    for kind, by_idx in families.items():
        if len(by_idx) < 2:
            continue
        seqs = {idx: [entry(l) for l in
                      sorted(ls, key=lambda l: pos.get(l.id, 0))]
                for idx, ls in by_idx.items()}
        base_idx = min(seqs)
        base = seqs[base_idx]
        for idx in sorted(seqs):
            if idx == base_idx:
                continue
            seq = seqs[idx]
            if len(seq) != len(base):
                out.append(Violation(
                    RULE_COLLECTIVE_MISMATCH, SEV_ERROR,
                    f"{kind} {idx} issues {len(seq)} collective(s) but "
                    f"{kind} {base_idx} issues {len(base)}: the manual "
                    "schedule's shards would block on unmatched "
                    "collectives", location=f"{kind}{idx}"))
                continue
            for e_a, e_b in zip(base, seq):
                if e_a != e_b:
                    out.append(Violation(
                        RULE_COLLECTIVE_MISMATCH, SEV_ERROR,
                        f"{kind} {idx} issues {e_b} where {kind} "
                        f"{base_idx} issues {e_a}: shards would issue "
                        "different collective sequences (deadlock under "
                        "manual scheduling)", location=f"{kind}{idx}"))
                    break
    return out


def _check_act_transport(legs: Sequence[Leg],
                         pos: Dict[str, int]) -> List[Violation]:
    """The pipeline transport pairing contract
    (``schedule/act-transport``).

    Every ``act:`` boundary buffer owes exactly one ``send_act`` and
    one ``recv_act`` (an orphaned half means one stage blocks forever
    on a peer that never posts/fetches); the pair must join DIFFERENT
    named stages (a same-stage pair moves nothing across the slice
    boundary), the recv must dep-order after its send, both halves must
    agree on the microbatch slot, the wire is always tier ``dcn``, and
    within one boundary chain the send slots must issue in order (a
    swapped pair means adjacent stages disagree on which microbatch is
    in flight — the MPMD wedge)."""
    out: List[Violation] = []
    t_legs = [l for l in legs if l.kind in TRANSPORT_KINDS]
    if not t_legs:
        return out
    pairs: Dict[str, Dict[str, List[Leg]]] = {}
    for l in t_legs:
        if l.tier != TIER_DCN:
            out.append(Violation(
                RULE_ACT_TRANSPORT, SEV_ERROR,
                f"transport leg {l.id!r} carries tier {l.tier!r}: "
                "pipeline activation transport rides the DCN tier",
                leg=l.id))
        bufs = l.writes if l.kind == LEG_SEND_ACT else l.reads
        act = [b for b in bufs if b.startswith("act:")]
        if len(act) != 1:
            out.append(Violation(
                RULE_ACT_TRANSPORT, SEV_ERROR,
                f"transport leg {l.id!r} names {len(act)} act: "
                "buffer(s); a send writes exactly one boundary "
                "activation and a recv reads exactly one", leg=l.id))
            continue
        side = "send" if l.kind == LEG_SEND_ACT else "recv"
        pairs.setdefault(act[0], {"send": [], "recv": []})[side].append(l)
    for buf, halves in sorted(pairs.items()):
        sends, recvs = halves["send"], halves["recv"]
        if len(sends) != 1 or len(recvs) != 1:
            out.append(Violation(
                RULE_ACT_TRANSPORT, SEV_ERROR,
                f"boundary buffer {buf!r} has {len(sends)} send_act and "
                f"{len(recvs)} recv_act leg(s): an orphaned transport "
                "half blocks its peer stage forever", location=buf))
            continue
        send, recv = sends[0], recvs[0]
        if not send.stage or not recv.stage or send.stage == recv.stage:
            out.append(Violation(
                RULE_ACT_TRANSPORT, SEV_ERROR,
                f"boundary buffer {buf!r} moves from stage "
                f"{send.stage or '<all-rank>'!r} to "
                f"{recv.stage or '<all-rank>'!r}: transport must join "
                "two DIFFERENT named stages", location=buf))
        if send.id not in recv.deps:
            out.append(Violation(
                RULE_ACT_TRANSPORT, SEV_ERROR,
                f"recv_act {recv.id!r} does not depend on its send_act "
                f"{send.id!r}: the fetch may observe a stale or absent "
                "payload", leg=recv.id))
        elif pos.get(send.id, 0) > pos.get(recv.id, 0):
            out.append(Violation(
                RULE_ACT_TRANSPORT, SEV_ERROR,
                f"recv_act {recv.id!r} is ordered before its send_act "
                f"{send.id!r}", leg=recv.id))
        if send.slot != recv.slot:
            out.append(Violation(
                RULE_ACT_TRANSPORT, SEV_ERROR,
                f"boundary buffer {buf!r}: send slot {send.slot} != "
                f"recv slot {recv.slot}: the pair must move ONE "
                "microbatch", location=buf))
    # Slot monotonicity per boundary chain: the sender must post
    # microbatches in issue order, or adjacent stages disagree on which
    # payload is in flight.
    chains: Dict[str, List[Leg]] = {}
    for l in t_legs:
        if l.kind == LEG_SEND_ACT and l.chain:
            chains.setdefault(l.chain, []).append(l)
    for chain, ls in sorted(chains.items()):
        ordered = sorted(ls, key=lambda l: pos.get(l.id, 0))
        slots = [l.slot for l in ordered]
        if slots != sorted(slots):
            out.append(Violation(
                RULE_ACT_TRANSPORT, SEV_ERROR,
                f"boundary chain {chain!r} posts microbatch slots "
                f"{slots}, not in order: adjacent stages disagree on "
                "the payload in flight (mis-ordered send chain)",
                location=chain))
    return out


def errors(violations: Sequence[Violation]) -> List[Violation]:
    return [v for v in violations if v.severity == SEV_ERROR]


def assert_verified(ir: ScheduleIR, context: str = "schedule") -> None:
    """The pre-trace gate: raise ``ValueError`` listing every ERROR rule
    the verifier fires on ``ir`` (used by the explicit build and by
    bench.py before timing a mode)."""
    errs = errors(verify(ir))
    if errs:
        lines = "\n  ".join(str(v) for v in errs[:8])
        raise ValueError(
            f"{context}: schedule verifier rejected the sync program "
            f"({len(errs)} error(s)):\n  {lines}")
