"""Explicit (shard_map) synchronization path, bucketed.

The GSPMD path lets XLA insert collectives; this path takes manual control
of the gradient reduction so a :class:`Compressor` can wrap it — the analog
of the reference's AllReduceSynchronizer inserting ``collective_ops.all_reduce``
through a compressor (``all_reduce_synchronizer.py:100-127``,
``compressor.py:85-96``) — and so the sync hot path can be scheduled as
**gradient buckets** instead of one collective per variable.

Semantics: the whole train step runs inside ``shard_map`` over the mesh.
The batch is sharded over ``data``; each device computes local gradients
(accumulated over ``capture(accum_steps=N)`` microbatches of its local slice
when asked — still one compressed collective per bucket per step), and the
gradients synchronize in three tiers:

1. **Buckets** (the default): replicated vars' gradients are flattened
   into size-capped, dtype-grouped contiguous buckets (``bucketing.py``)
   keyed by the strategy's collective group — ONE collective per bucket.
   Compressors quantize per bucket (one scale grid per collective, the
   EQuARX formulation).  Each bucket's chain is data-independent of the
   others, so XLA overlaps one bucket's collective with other buckets'
   compute and with backward work that does not feed it.
2. **ZeRO-1 buckets** (``sync="reduce_scatter"`` plans): the bucket is
   reduce-scattered ((N−1)/N of the all-reduce's reduce bytes), the
   optimizer update runs on the LOCAL 1/N shard of a flat, bucket-major
   optimizer state (the weight-update sharding of arXiv:2004.13336 —
   optimizer HBM drops by the data-axis size), and updated parameters
   are all-gathered back to their replicated layout.  The uneven tail
   bucket is zero-padded to shard evenly; elementwise optimizers
   (SGD/Adam family) make the sharded update exactly equal to the
   replicated one.
3. **Per-variable fallback**: partitioned vars keep their per-shard
   compressed reduction (see below), and non-bucketable compressors
   (PowerSGD needs the 2-D gradient) keep the per-variable collective.

Per-device compressor state (error-feedback residuals, PowerSGD factors)
is carried as a *sync state* pytree with a leading per-shard axis, sharded
over ``data`` so each device owns its slice — bucket-level residuals are
keyed by the bucket id.

With ``capture(numerics=...)`` the **fused numerics guard**
(docs/numerics.md) rides the bucket chain: per-bucket finiteness bits
are a byproduct of the pack, squared-norm partials come from the
reduced values (the reduce-scattered SHARDS under ZeRO-1 — their psum
is exactly the full norm), compressors report pre-quantization wire
saturation, and one small all-axis psum rolls everything into a
``GradHealth`` struct returned with the step metrics.  The same scalars
drive exact global-norm clipping (applied before the local 1/N update),
dynamic loss scaling (state carried under ``"~numerics"`` in the sync
state, checkpointed), and the skip gate (a non-finite step keeps params
and optimizer state bit-identical).

Partitioned variables COMPOSE with compression (the reference can express
PartitionedAR + compressor — ``proto/synchronizers.proto:24-57``): a var
sharded over a non-data mesh axis stays sharded outside the step; inside,
it is all-gathered for the user's loss, its gradient is sliced back to the
local shard, and the data-axis reduction of the SHARD runs through the
compressor — per-shard compressed reduction, each partition reduced
independently, with the parameter + optimizer-state memory of true
partitioning.  Per-variable fallback to replication (with a warning)
covers the cases where the composition is not defined: vars sharded over
``data`` itself, pad-to-divisible vars, multi-axis shardings, and
PowerSGD (its low-rank state is not grad-shaped).
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from autodist_tpu.const import MESH_AXIS_DATA
from autodist_tpu.graph_item import GraphItem, path_name
from autodist_tpu.kernel.synchronization import bucketing
from autodist_tpu.kernel.synchronization.bucketing import (
    Bucket,
    MODE_ALL_REDUCE,
    MODE_REDUCE_SCATTER,
    pack_bucket,
    unpack_bucket,
)
from autodist_tpu.kernel.synchronization.compressor import (
    Compressor,
    get_compressor,
)
from autodist_tpu.kernel.synchronization import overlap as overlap_mod
from autodist_tpu.kernel.synchronization import quant_ring
from autodist_tpu.kernel.synchronization import schedule_ir
from autodist_tpu.strategy.compiler import CompiledStrategy
from autodist_tpu.telemetry.timeline import sync_span
from autodist_tpu.utils import compat, logging


def uses_explicit_path(compiled: CompiledStrategy) -> bool:
    """Compressors need manual collectives; fused grouping and explicit
    bucketing need them too (one concat-and-reduce per bucket — the
    reference's scoped-allocator merge done literally); ZeRO-1
    (reduce-scatter weight-update sharding) owns its whole
    reduce→update→gather chain, and an explicit ``overlap=`` schedule
    request needs the schedulable shard_map lowering."""
    for plan in compiled.var_plans.values():
        if plan.compressor not in ("", "NoneCompressor"):
            return True
        if getattr(plan, "sync_mode", "all_reduce") == MODE_REDUCE_SCATTER:
            return True
        if getattr(plan, "bucket_bytes", 0) > 0:
            return True
        if getattr(plan, "overlap", "auto") in (
                overlap_mod.OVERLAP_PIPELINE, overlap_mod.OVERLAP_RING,
                overlap_mod.OVERLAP_FULL):
            return True
        if getattr(plan, "hier", False):
            # two-tier ICI+DCN sync only exists on the shard_map path
            return True
    return (any(plan.fused for plan in compiled.var_plans.values())
            and bool(compiled.fusable_groups()))


def chaos_grad_events_probe():
    """The ``nan_grad``/``inf_grad`` chaos events for this process, or
    [] when none apply / the harness is unavailable — probed so a grad
    injection requested without the numerics guard warns instead of
    silently never firing."""
    try:
        from autodist_tpu.resilience import chaos as chaos_mod
        return chaos_mod.grad_injections()
    except Exception:  # pragma: no cover - chaos env parse errors
        return []


def _compressors_for(gi: GraphItem, compiled: CompiledStrategy
                     ) -> Dict[str, Compressor]:
    out: Dict[str, Compressor] = {}
    for name, leaf in gi.name_to_leaf().items():
        plan = compiled.var_plans.get(name)
        comp_name = plan.compressor if plan else "NoneCompressor"
        out[name] = get_compressor(comp_name or "NoneCompressor")
    return out


def _grad_shaped_state(comp: Compressor, shape: tuple, dtype) -> bool:
    """True when ``comp``'s per-device state for a value of ``shape`` is
    None or a single array of exactly that shape — the structural
    requirement for the per-shard partitioned state layout (one leading
    data axis + the var's own sharding applied to every leaf).  Probed
    abstractly (eval_shape): no state is materialized."""
    probe = jax.eval_shape(comp.init_state,
                           jax.ShapeDtypeStruct(shape, dtype))
    if probe is None:
        return True
    leaves = jax.tree_util.tree_leaves(probe)
    return (len(leaves) == 1 and tuple(leaves[0].shape) == tuple(shape)
            and leaves[0].dtype == dtype)


def partition_drop_reason(spec_axes, shape, dtype, axis_sizes, padded,
                          comp: Compressor) -> Optional[str]:
    """Why the explicit path would drop a partitioned var's sharding, or
    None when the partitioning is kept.

    ``spec_axes`` is the flattened ``[(tensor_dim, mesh_axis_name), ...]``
    of the param layout; ``axis_sizes`` maps axis name → size (a plain
    dict — no mesh needed, so the static analyzer
    (``autodist_tpu.analysis``) shares this exact rule and the lint can
    never drift from the runtime fallback)."""
    spec_axes = list(spec_axes)
    if not spec_axes:
        return None
    if padded:
        return "pad-to-divisible sharding"
    if len(spec_axes) != 1:
        return f"multi-axis sharding {spec_axes}"
    part_axis, axis_name = spec_axes[0]
    if axis_name == MESH_AXIS_DATA:
        return "sharded over the data (reduction) axis"
    n = int(axis_sizes.get(axis_name, 1))
    if n > 1 and shape[part_axis] % n:  # pragma: no cover - padded
        return f"dim {shape[part_axis]} not divisible by {n}"
    shard = list(shape)
    if n > 1:
        shard[part_axis] //= n
    if not _grad_shaped_state(comp, tuple(shard), dtype):
        return (f"{comp.name} state is not grad-shaped"
                f" (e.g. PowerSGD low-rank factors)")
    return None


def _partition_support(gi: GraphItem, compiled: CompiledStrategy,
                       comps: Dict[str, Compressor]) -> Dict[str, tuple]:
    """Which partitioned vars keep their sharding on the explicit path:
    ``{name: (axis_name, part_axis, n_shards)}``.  Unsupported cases
    (see module docstring) are replicated per-variable with a warning."""
    part: Dict[str, tuple] = {}
    pad_names = set(compiled.pad_plans())
    leaves = gi.name_to_leaf()
    axis_sizes = dict(compiled.mesh.shape)
    for name, plan in compiled.var_plans.items():
        spec = plan.param_spec
        if spec == P():
            continue
        spec_axes = []
        for i, e in enumerate(spec):
            if e is None:
                continue
            for a in ([e] if isinstance(e, str) else list(e)):
                spec_axes.append((i, a))
        leaf = jnp.asarray(leaves[name])
        why = partition_drop_reason(spec_axes, leaf.shape, leaf.dtype,
                                    axis_sizes, name in pad_names,
                                    comps[name])
        if why is not None:
            logging.warning(
                "explicit sync path: replicating %s (%s); its "
                "partitioning is dropped for this program", name, why)
            continue
        (part_axis, axis_name), = spec_axes
        part[name] = (axis_name, part_axis, axis_sizes[axis_name])
    return part


def plan_step_buckets(gi: GraphItem, compiled: CompiledStrategy,
                      part: Dict[str, tuple], d: int) -> List[Bucket]:
    """Bucket assignment for this program: every replicated synced var
    whose compressor composes with flat buckets, in flatten order, keyed
    by (mode, dtype, compressor, group).  Shared with the analyzer and
    bench byte accounting — the planner the runtime executes."""
    entries = []
    cap = 0
    for path, leaf in jax.tree_util.tree_flatten_with_path(gi.params)[0]:
        name = path_name(path)
        plan = compiled.var_plans.get(name)
        if plan is None or name in part:
            continue
        comp_name = plan.compressor or "NoneCompressor"
        if bucketing.bucket_drop_reason((), False, comp_name) is not None:
            continue
        mode = getattr(plan, "sync_mode", MODE_ALL_REDUCE) or MODE_ALL_REDUCE
        arr = jnp.asarray(leaf)
        entries.append((name, tuple(arr.shape), str(arr.dtype), comp_name,
                        plan.group, mode))
        cap = max(cap, getattr(plan, "bucket_bytes", 0))
    return bucketing.assign_buckets(
        entries, bucket_bytes=cap or bucketing.DEFAULT_BUCKET_BYTES,
        shard_divisor=max(d, 1))


def make_explicit_step(gi: GraphItem, compiled: CompiledStrategy):
    """Returns (step_fn, init_opt_fn, init_sync_state_fn, param_sh_tree,
    opt_sh_tree, rs_buckets, schedule_ir) consumed by the
    GraphTransformer — ``rs_buckets`` is the planned ZeRO-1 bucket list
    (empty without reduce-scatter plans), exposed so checkpoints can
    record the flat optimizer layout for elastic resume;
    ``schedule_ir`` is the verified sync-schedule program this lowering
    consumed (docs/schedule-ir.md)."""
    import optax

    from autodist_tpu.kernel import sharding_utils as su

    mesh = compiled.mesh
    d = mesh.shape.get(MESH_AXIS_DATA, 1)
    mesh_axis_names = tuple(mesh.axis_names)
    n_devices = 1
    for _a in mesh_axis_names:
        n_devices *= int(mesh.shape[_a])
    comps = _compressors_for(gi, compiled)
    part = _partition_support(gi, compiled, comps)
    name_leaves = {n: jnp.asarray(v) for n, v in gi.name_to_leaf().items()}

    # Effective per-var specs: the plan's spec for supported partitioned
    # vars, replicated for everything else.
    eff_specs = {name: (plan.param_spec if name in part else P())
                 for name, plan in compiled.var_plans.items()}
    param_spec_tree = su.spec_tree_for_params(gi.params, eff_specs)
    param_sh_tree = su.sharding_tree(mesh, param_spec_tree)

    vg = jax.value_and_grad(gi.loss_fn, has_aux=gi.has_aux)
    has_aux = gi.has_aux

    # -- bucket plan -------------------------------------------------------
    buckets = plan_step_buckets(gi, compiled, part, d)
    bucketed_names = {n for b in buckets for n in b.names}
    rs_buckets = [b for b in buckets if b.mode == MODE_REDUCE_SCATTER]
    rs_names = {n for b in rs_buckets for n in b.names}
    # -- hierarchical two-tier sync (docs/schedule-ir.md) ------------------
    # A bucket lowers ICI->DCN->ICI only when EVERY member var's plan
    # opted in AND the data axis factors into >1 slices of >1 devices;
    # the IR builder applies the same gate (plus linear-compressor /
    # no-quantized-wire), so the effective set below is read back from
    # the built IR's bucket nodes — one source of truth.
    num_slices = int(getattr(compiled, "num_slices", 1) or 1)
    hier_on = schedule_ir.hier_applies(d, num_slices)
    hier_keys = [
        b.key for b in buckets
        if hier_on and b.names
        and all(bool(getattr(compiled.var_plans.get(n), "hier", False))
                for n in b.names)]
    for name, plan in compiled.var_plans.items():
        if (getattr(plan, "sync_mode", MODE_ALL_REDUCE)
                == MODE_REDUCE_SCATTER and name not in rs_names):
            logging.warning(
                "explicit sync path: %s requested reduce_scatter (ZeRO-1) "
                "but cannot join a flat bucket (partitioned or "
                "non-bucketable compressor); falling back to its "
                "per-variable/per-shard collective with replicated "
                "optimizer state", name)

    # -- overlap schedule --------------------------------------------------
    # Resolve the step-level overlap plan (``overlap.py``): which buckets
    # pipeline with the microbatch loop, which ring-decompose, and the
    # ZeRO-1 param-gather issue order.  Decisions share one rule set with
    # the analyzer (`sync/overlap-*`) and the cost model.
    ov = overlap_mod.resolve_overlap(
        [getattr(p, "overlap", "auto") or "auto"
         for p in compiled.var_plans.values()],
        accum_steps=gi.accum_steps, buckets=buckets, d=d,
        has_rs=bool(rs_buckets))
    for key, why in ov.drops:
        logging.warning(
            "explicit sync path: overlap scheduling skipped for bucket "
            "%s (%s)", key, why)
    overlap_active = (ov.pipeline or ov.prefetch
                      or ov.mode in (overlap_mod.OVERLAP_PIPELINE,
                                     overlap_mod.OVERLAP_RING,
                                     overlap_mod.OVERLAP_FULL))
    if overlap_active:
        known_names = set(gi.name_to_leaf())
        for name, plan in compiled.var_plans.items():
            if name in bucketed_names or name not in known_names:
                continue
            why = overlap_mod.overlap_drop_reason(
                getattr(plan, "overlap", "auto") or "auto",
                accum_steps=gi.accum_steps,
                compressor=plan.compressor or "NoneCompressor",
                bucketable=False, explicit_path=True)
            if why is not None:
                logging.warning(
                    "explicit sync path: overlap scheduling skipped for "
                    "%s (%s)", name, why)
    # -- numerics guard (docs/numerics.md) ---------------------------------
    # Resolved at build time: loss-scale activation (auto = any
    # low-precision param/bucket dtype), the wire-saturation safety
    # check, and any chaos grad injections (compiled into the step).
    num_cfg = getattr(gi, "numerics", None)
    num_active = bool(num_cfg is not None and num_cfg.guard)
    num_ls = None
    injections: Dict[str, Any] = {}
    if num_active:
        from autodist_tpu.numerics import guard as guard_mod
        from autodist_tpu.numerics import loss_scale as ls_mod

        leaf_dtypes = [str(jnp.asarray(v).dtype)
                       for v in gi.name_to_leaf().values()]
        num_ls = ls_mod.resolve_loss_scale(
            num_cfg.loss_scale,
            leaf_dtypes + [b.dtype for b in buckets])
        for b in buckets:
            why = ls_mod.scale_saturates_wire(num_ls, b.compressor)
            if why is not None:
                raise ValueError(
                    f"numerics: bucket {b.key}: {why}; lower the loss "
                    "scale ceiling or drop the quantizing compressor "
                    "(rule numerics/loss-scale-saturates-wire)")
        injections = guard_mod.resolve_injections(
            buckets, list(gi.name_to_leaf()))
        logging.info(
            "numerics guard: ON (%d buckets, loss_scale=%s, clip_norm=%s, "
            "on_nonfinite=%s)", len(buckets),
            "off" if num_ls is None else
            ("%g dynamic" % num_ls.init if num_ls.dynamic
             else "%g static" % num_ls.init),
            num_cfg.clip_norm, num_cfg.on_nonfinite)
    elif list(chaos_grad_events_probe()):
        logging.warning(
            "AUTODIST_CHAOS requests a gradient injection but the "
            "numerics guard is off — nan_grad/inf_grad need "
            "capture(numerics=...) (the guard owns the device step "
            "counter the injection keys on); ignoring the event")

    def _shard_shape(name: str, leaf) -> tuple:
        shape = list(jnp.asarray(leaf).shape)
        if name in part:
            _, ax, n = part[name]
            shape[ax] //= n
        return tuple(shape)

    # -- sync state --------------------------------------------------------
    # Which vars/buckets carry state and under which spec, probed
    # abstractly ONCE (eval_shape — no full-model state is materialized
    # just to test for None); consumed by the schedule IR below, the
    # shard_map specs, and init_sync_state.  Bucket-level residuals are
    # keyed by bucket id (per-bucket error feedback — the EQuARX
    # composition); per-variable state remains only for partitioned and
    # non-bucketable vars.
    sync_specs: Dict[str, P] = {}
    sync_builders: Dict[str, Any] = {}
    for name, leaf in name_leaves.items():
        if name in bucketed_names or name not in comps:
            continue
        if compiled.var_plans.get(name) is None and name not in part:
            continue
        probe = jax.eval_shape(
            comps[name].init_state,
            jax.ShapeDtypeStruct(_shard_shape(name, leaf), leaf.dtype))
        if probe is None:
            continue
        sync_specs[name] = P(MESH_AXIS_DATA,
                             *compiled.var_plans[name].param_spec) \
            if name in part else P(MESH_AXIS_DATA)
        sync_builders[name] = ("var", name)
    for b in buckets:
        comp = get_compressor(b.compressor)
        probe = jax.eval_shape(
            comp.init_state,
            jax.ShapeDtypeStruct((b.padded_total,), jnp.dtype(b.dtype)))
        if probe is None:
            continue
        sync_specs[b.key] = P(MESH_AXIS_DATA)
        sync_builders[b.key] = ("bucket", b)
    if num_active:
        # Numerics state (loss scale + health counters): replicated
        # scalars carried in the step like optimizer state — and
        # checkpointed with the sync state, so resume keeps the scale.
        from autodist_tpu.numerics.guard import NUMERICS_KEY
        sync_specs[NUMERICS_KEY] = P()
        sync_builders[NUMERICS_KEY] = ("numerics", None)
    # Donation audit: params and optimizer state are rewritten every step,
    # so donating them is always safe.  Sync state is donated ONLY when
    # every entry is a bucket residual (rewritten unconditionally by the
    # bucket compressor each step).  Per-variable fallback entries
    # (partitioned / PowerSGD tier) can pass through a step untouched —
    # e.g. a compressor that returns its state unchanged — and returning
    # a donated input aliases a buffer whose old handle (held by a
    # checkpoint saver or a caller inspecting ``session.sync_state``
    # across steps) is now marked deleted.  Fallback programs keep their
    # sync state undonated; its footprint is small (residual tensors for
    # the handful of vars the buckets could not absorb).
    # (Numerics state is rewritten unconditionally every step, so it is
    # donation-safe like bucket residuals.)  The schedule verifier
    # re-proves this as the schedule/read-after-donate rule.
    donate_sync = all(kind in ("bucket", "numerics")
                      for kind, _ in sync_builders.values())

    # -- fused Pallas kernels (docs/kernels.md) ----------------------------
    # Opt-in via AUTODIST_FUSED_KERNELS; every requested kernel this
    # program cannot lower falls back to the unfused path with the
    # SHARED drop-reason string (ops.fused_kernels.fused_drop_reason —
    # the analysis schedule pass surfaces the same rule).  The active
    # set is recorded in the schedule IR below, so the fingerprint, the
    # verifier, and the cost model all see the fused program.
    from autodist_tpu.ops import fused_kernels as fk

    opt_fusable = getattr(gi.optimizer, "fused_spec", None) is not None
    adam_shaped = True
    if opt_fusable and rs_buckets:
        opt_probe = jax.eval_shape(
            gi.optimizer.init,
            {"x": jax.ShapeDtypeStruct((8,), jnp.float32)})
        adam_shaped = fk.find_adam_state(opt_probe) is not None
    active_fused, fused_drops = fk.resolve_fused(
        guard=num_active, has_rs=bool(rs_buckets),
        has_quant_ring=any(quant_ring.wire_format_of(b.compressor)
                           is not None for b in buckets),
        optimizer_fusable=opt_fusable, adam_state_shaped=adam_shaped,
        f32_buckets=all(b.dtype == "float32" for b in rs_buckets))
    for kernel, why in fused_drops:
        logging.warning(
            "explicit sync path: fused kernel %s falls back to the "
            "unfused lowering (%s)", kernel, why)
    # Interpret-mode decision resolved HERE, at build — not at trace —
    # the ops/flash_attention.py convention (off-TPU is only reachable
    # under the AUTODIST_FUSED_INTERPRET escape hatch).
    fused_interpret = not fk.kernels_runnable()[0]
    guard_fused = fk.KERNEL_GUARD in active_fused
    update_fused = fk.KERNEL_UPDATE in active_fused
    if active_fused:
        logging.info("explicit sync path: fused Pallas kernels active: "
                     "%s%s", ",".join(active_fused),
                     " (interpret mode)" if fused_interpret else "")

    # -- schedule IR (docs/schedule-ir.md) ---------------------------------
    # The sync program as a first-class artifact: one IR instance built
    # from the planner + overlap + guard + donation facts above; this
    # lowering CONSUMES it (pipeline membership, per-bucket reduce
    # algorithm, ZeRO-1 gather issue order), and the static verifier
    # model-checks it before anything traces.  The same instance rides
    # the DistributedStep for telemetry fingerprints and checkpoints.
    per_var_entries = []
    for name, plan in compiled.var_plans.items():
        if name in bucketed_names or name not in name_leaves:
            continue
        vi = gi.info.by_name(name)
        if vi is None:
            continue
        leaf = name_leaves[name]
        per_var_entries.append(schedule_ir.PerVarEntry(
            name=name, dtype=str(leaf.dtype),
            nbytes=int(leaf.size) * leaf.dtype.itemsize,
            sync_kind="AllReduce",
            compressor=plan.compressor or "NoneCompressor",
            sig=schedule_ir.fact_from_varplan(plan, vi).sig(),
            stateful=name in sync_builders))
    ir_axes = {str(a): int(mesh.shape[a]) for a in mesh_axis_names}
    ir = schedule_ir.build_schedule_ir(
        axes=ir_axes,
        accum_steps=gi.accum_steps, buckets=buckets, plan=ov,
        num_slices=num_slices, hier_keys=hier_keys,
        per_var=per_var_entries, guard=num_active,
        donated=tuple(f"sync:{k}" for k in sync_builders) if donate_sync
        else (),
        stateful_keys={k for k, (kind, _) in sync_builders.items()
                       if kind == "bucket"},
        fused_kernels=active_fused,
        # MoE expert a2as (docs/schedule-ir.md): derived from the SAME
        # expert-flagged catalog the analyzer sees, so both sides carry
        # identical dispatch/combine legs and fingerprints.
        moe=schedule_ir.moe_facts_from_vars(gi.info.variables,
                                            axes=ir_axes))
    schedule_ir.assert_verified(ir, "explicit sync build")
    logging.info(
        "explicit sync path: schedule IR %s (%d bucket(s), %d leg(s), "
        "overlap=%s)", ir.fingerprint(), len(ir.buckets), len(ir.legs),
        ir.overlap_mode)

    pipe_keys = ir.pipelined_keys()
    pipe_buckets = [b for b in buckets if b.key in pipe_keys]

    # -- flight-recorder leg stamps (docs/observability.md) ----------------
    # Under AUTODIST_FLIGHTREC=legs (the automatic choice on TPU) the
    # step stamps a host-callback cursor at every leg GROUP boundary —
    # per-bucket reduce, ZeRO-1 update, per-bucket param gather, guard
    # rollup — keyed by the IR's own leg ids, so a wedge localizes to
    # the exact leg the happens-before relation knows.  Resolved at
    # build: the default off-TPU path compiles no callbacks at all.
    from autodist_tpu.telemetry import flightrec

    leg_stamps = flightrec.trace_stamps_enabled()
    stamp_reduce: Dict[str, tuple] = {}   # key -> (leg id/template, kind)
    stamp_gather: Dict[str, tuple] = {}
    stamp_update: Dict[str, tuple] = {}
    if leg_stamps:
        import re as _re
        for b in buckets:
            finals = [l for l in ir.legs
                      if l.bucket == b.key and f"red:{b.key}" in l.writes]
            if not finals:
                continue
            if b.key in pipe_keys:
                # Per-slot ids ("<key>@<slot>/..."): a {slot} template
                # the callback resolves with the live microbatch index.
                stamp_reduce[b.key] = (
                    _re.sub(r"@\d+/", "@{slot}/", finals[0].id),
                    finals[0].kind)
            else:
                stamp_reduce[b.key] = (finals[-1].id, finals[-1].kind)
        for l in ir.legs:
            if l.id.startswith("update/"):
                stamp_update[l.bucket] = (l.id, l.kind)
        for b in rs_buckets:
            finals = [l for l in ir.legs
                      if l.bucket == b.key and "@gather" in l.id
                      and f"param:{b.key}" in l.writes]
            if finals:
                stamp_gather[b.key] = (finals[-1].id, finals[-1].kind)
    # Mean-reduction lowering per UNCOMPRESSED bucket under the IR's
    # resolved algorithm (ring / one-shot / XLA fused); compressed
    # buckets keep their compressor's own wire format.
    # Effective hier set: read back from the built IR's bucket nodes so
    # the runtime closures and the verified program can never disagree
    # about which buckets went two-tier.
    hier_bucket_keys = {n["key"] for n in ir.buckets if n.get("hier")}
    hier_dcn_fmt = quant_ring.wire_format_of(
        schedule_ir.dcn_wire_compressor_default())
    reduce_fns = {b.key: (
        overlap_mod.hier_bucket_reduce_fn(
            b, MESH_AXIS_DATA, d, num_slices, dcn_wire=hier_dcn_fmt)
        if b.key in hier_bucket_keys else
        overlap_mod.bucket_reduce_fn(
            b, ov, MESH_AXIS_DATA, d, alg=ir.reduce_alg(b.key)))
        for b in buckets
        if overlap_mod.is_linear_compressor(b.compressor)}
    # Quantized-wire buckets (int8/fp8, docs/overlap.md) lower through
    # the stateful bucket entry point under the IR-resolved algorithm:
    # (vec, error-feedback state) -> (reduced, new state, saturation
    # count).  The same closures serve the end-of-step tier and the
    # per-microbatch-slot pipeline.
    quant_fns = {}
    for b in buckets:
        if quant_ring.wire_format_of(b.compressor) is None:
            continue
        comp = get_compressor(b.compressor)
        node = ir.bucket_node(b.key) or {}
        hop_fused = bool(node.get("hop_fused", False))
        if b.mode == MODE_REDUCE_SCATTER:
            quant_fns[b.key] = (
                lambda v, s, comp=comp, alg=ir.reduce_alg(b.key),
                hf=hop_fused:
                comp.bucket_reduce_scatter(v, s, MESH_AXIS_DATA, d,
                                           alg=alg, hop_fused=hf))
        else:
            quant_fns[b.key] = (
                lambda v, s, comp=comp, alg=ir.reduce_alg(b.key),
                hf=hop_fused:
                comp.bucket_reduce(v, s, MESH_AXIS_DATA, d, alg=alg,
                                   hop_fused=hf))
    pipe_quant_fns = {k: f for k, f in quant_fns.items() if k in pipe_keys}
    # Saturation counters are per-data-rank events replicated across the
    # other mesh axes; this factor makes the guard's all-axis psum
    # return the true global count.
    sat_norm = d / float(n_devices)
    reduced_sizes = {b.key: (b.padded_total // max(d, 1)
                             if b.mode == MODE_REDUCE_SCATTER
                             else b.padded_total) for b in buckets}
    use_pipeline = bool(pipe_buckets) and gi.accum_steps > 1
    if gi.accum_steps > 1 and not use_pipeline and not num_active:
        # Gradient accumulation composes with compression exactly where it
        # matters most (bandwidth-starved links): the f32 accumulator scan
        # runs INSIDE the shard_map step over the device's LOCAL microbatch
        # slices, so each bucket still sees ONE averaged gradient — one
        # compressed collective per bucket per step, N microbatches of
        # activations.  (With the numerics guard the wrap happens inside
        # local_step instead — the loss scale and chaos injections bind
        # to per-step state first.)
        from autodist_tpu.kernel.graph_transformer import _accumulate_grads
        vg = _accumulate_grads(vg, gi.accum_steps, gi.has_aux)

    if num_ls is not None:
        # Loss scaling: the loss is multiplied by the (power-of-two)
        # scale BEFORE the backward pass so small gradients survive a
        # low-precision exponent range; reduced gradients are divided by
        # it before clipping and the update.  Built as a 3-arg
        # value-and-grad so the scale can come from the step's state.
        def _scaled_loss(p, batch, scale):
            if has_aux:
                loss_, aux_ = gi.loss_fn(p, batch)
                return loss_ * scale, aux_
            return gi.loss_fn(p, batch) * scale
        vg_scaled = jax.value_and_grad(_scaled_loss, has_aux=has_aux)
    else:
        vg_scaled = None

    # -- optimizer split ---------------------------------------------------
    # ZeRO-1 vars' optimizer state lives as flat bucket-major shards (one
    # leaf per bucket, sharded over 'data'); everything else keeps the
    # tree-shaped state.  The tree optimizer masks ZeRO-1 vars (and frozen
    # vars) to zero updates / no state — the 1/N state memory win.
    if rs_buckets:
        frozen = {v.name for v in gi.info.untrainable_variables}

        def label_of(path, _):
            name = path_name(path)
            return "zero" if (name in rs_names or name in frozen) \
                else "train"
        labels = jax.tree_util.tree_map_with_path(label_of, gi.params)
        tree_optimizer = optax.multi_transform(
            {"train": gi.optimizer, "zero": optax.set_to_zero()}, labels)
        bucket_optimizer = gi.optimizer
    else:
        tree_optimizer = gi.frozen_aware_optimizer()
        bucket_optimizer = None

    # Optimizer-state layout: param-shaped blocks follow the effective
    # param spec (shard-local moments for partitioned vars — the real
    # memory win of keeping the partitioning); scalars replicate.  ZeRO-1
    # bucket shards ride a parallel {"zero1": ...} subtree sharded flat
    # over 'data' (each device owns 1/d of every bucket's moments).
    tree_opt_shape = jax.eval_shape(tree_optimizer.init, gi.params)
    tree_opt_spec = su.opt_spec_tree(tree_opt_shape, gi.params,
                                     param_spec_tree)

    def _bucket_template():
        return {b.key: jax.ShapeDtypeStruct((b.padded_total,),
                                            jnp.dtype(b.dtype))
                for b in rs_buckets}

    def _pack_params_vecs(params):
        by_name = {path_name(p): x for p, x in
                   jax.tree_util.tree_flatten_with_path(params)[0]}
        return {b.key: pack_bucket(b, [by_name[n] for n in b.names])
                for b in rs_buckets}

    if rs_buckets:
        template = _bucket_template()
        z_shape = jax.eval_shape(bucket_optimizer.init, template)
        z_spec = su.opt_spec_tree(
            z_shape, template, {b.key: P(MESH_AXIS_DATA)
                                for b in rs_buckets})
        opt_spec_tree = {"vars": tree_opt_spec, "zero1": z_spec}

        def init_opt(params):
            return {"vars": tree_optimizer.init(params),
                    "zero1": bucket_optimizer.init(_pack_params_vecs(params))}
    else:
        opt_spec_tree = tree_opt_spec
        init_opt = tree_optimizer.init
    opt_sh_tree = su.sharding_tree(mesh, opt_spec_tree)

    def init_sync_state(current_params=None):
        # Compressor residuals start at zero regardless of parameter values,
        # so current_params only matters for shape (identical to capture-time).
        state: Dict[str, Any] = {}
        for key, (kind, ref) in sync_builders.items():
            spec = sync_specs[key]
            if kind == "numerics":
                from autodist_tpu.numerics import loss_scale as ls_mod
                state[key] = jax.device_put(
                    ls_mod.init_state(num_ls), NamedSharding(mesh, spec))
                continue
            if kind == "bucket":
                b = ref
                per_dev = get_compressor(b.compressor).init_state(
                    jnp.zeros((b.padded_total,), jnp.dtype(b.dtype)))
                stacked = jax.tree_util.tree_map(
                    lambda s: jnp.broadcast_to(s[None],
                                               (d,) + s.shape).copy(),
                    per_dev)
                state[key] = jax.device_put(
                    stacked, NamedSharding(mesh, spec))
                continue
            name = ref
            leaf = name_leaves[name]
            if name in part:
                # Partitioned state is built THROUGH the compressor's own
                # init_state on a shard-shaped zero input (the gate and
                # the construction cannot diverge), tiled to (d,) + FULL
                # shape directly in its target sharding — each device
                # owns its shard's state.
                _, ax, n = part[name]
                shard = _shard_shape(name, leaf)

                def _build(comp=comps[name], shard=shard, dt=leaf.dtype,
                           ax=ax, n=n):
                    def expand(s):
                        reps = [n if i == ax else 1
                                for i in range(s.ndim)]
                        tiled = jnp.tile(s, reps)
                        return jnp.broadcast_to(tiled[None],
                                                (d,) + tiled.shape)
                    return jax.tree_util.tree_map(
                        expand, comp.init_state(jnp.zeros(shard, dt)))

                state[name] = jax.jit(
                    _build, out_shardings=NamedSharding(mesh, spec))()
            else:
                per_dev = comps[name].init_state(leaf)
                stacked = jax.tree_util.tree_map(
                    lambda s: jnp.broadcast_to(s[None],
                                               (d,) + s.shape).copy(),
                    per_dev)
                state[name] = jax.device_put(
                    stacked, NamedSharding(mesh, spec))
        return state

    # -- the local (per-shard) step ---------------------------------------
    def local_step(params, opt_state, sync_state, batch):
        params_in, opt_in = params, opt_state
        # Reconstruct full tensors for the user's loss: sharded vars are
        # all-gathered over their partition axis (what GSPMD inserts for
        # a fully-consumed sharded param; here it is explicit).
        flat_p, ptree = jax.tree_util.tree_flatten_with_path(params)
        full_leaves = []
        for path, x in flat_p:
            info = part.get(path_name(path))
            if info is not None:
                axis_name, ax, _ = info
                x = lax.all_gather(x, axis_name, axis=ax, tiled=True)
            full_leaves.append(x)
        full_params = jax.tree_util.tree_unflatten(ptree, full_leaves)

        # Numerics guard: bind this step's loss scale / device step
        # counter, then assemble the value-and-grad the tiers below run
        # (scale → chaos injection → accumulation, innermost first).
        if num_active:
            ns = sync_state[NUMERICS_KEY]
            scale = ns["scale"] if num_ls is not None else None
            health = guard_mod.HealthAccumulator(
                n_devices, fused=guard_fused,
                interpret=fused_interpret if guard_fused else None)
            if scale is None:
                vg_local = vg
            else:
                vg_local = lambda p, b: vg_scaled(p, b, scale)  # noqa: E731
            if injections:
                vg_local = guard_mod.wrap_injections(
                    vg_local, injections, ns["step"])
            if gi.accum_steps > 1 and not use_pipeline:
                from autodist_tpu.kernel.graph_transformer import \
                    _accumulate_grads
                vg_local = _accumulate_grads(vg_local, gi.accum_steps,
                                             has_aux)
        else:
            scale = None
            vg_local = vg
        guarded_idx: List[int] = []

        pipe_reduced: Dict[str, Any] = {}
        pipe_qstates: Dict[str, Any] = {}
        pipe_qsats: Dict[str, Any] = {}
        if use_pipeline:
            # Accumulation pipelining (overlap.py): microbatch k's bucket
            # collectives are issued alongside microbatch k+1's backward;
            # only the last microbatch's reduction is exposed.  `grads`
            # carries the locally averaged tree for the per-variable and
            # non-pipelined compressed-bucket tiers, whose single
            # end-of-step collective is unchanged.  Quantized pipelined
            # buckets issue one quantized collective per slot with their
            # error-feedback residual threaded through the loop.
            def single_vg(p, mb):
                if has_aux:
                    (loss_, aux_), g_ = vg_local(p, mb)
                else:
                    loss_, g_ = vg_local(p, mb)
                    aux_ = None
                return loss_, aux_, g_

            qstates0 = {
                k: jax.tree_util.tree_map(lambda x: jnp.squeeze(x, 0),
                                          sync_state[k])
                for k in pipe_quant_fns if k in sync_state}
            (loss, aux, grads, pipe_reduced, pipe_qstates,
             pipe_qsats) = overlap_mod.pipelined_accumulate(
                single_vg, gi.accum_steps, has_aux, pipe_buckets,
                reduce_fns, reduced_sizes, full_params, batch,
                quant_fns=pipe_quant_fns, quant_states=qstates0,
                stamps={k: v for k, v in stamp_reduce.items()
                        if k in pipe_keys} if leg_stamps else None)
        elif has_aux:
            (loss, aux), grads = vg_local(full_params, batch)
        else:
            loss, grads = vg_local(full_params, batch)
            aux = None

        flat, treedef = jax.tree_util.tree_flatten_with_path(grads)
        idx_of = {path_name(path): i for i, (path, _) in enumerate(flat)}
        new_sync = dict(sync_state)
        synced = [g for _, g in flat]   # pass-through default (frozen vars)

        def local_state_of(key):
            st = sync_state.get(key)
            return None if st is None else jax.tree_util.tree_map(
                lambda x: jnp.squeeze(x, 0), st)

        def store_state(key, st2):
            if st2 is not None and key in new_sync:
                new_sync[key] = jax.tree_util.tree_map(
                    lambda x: jnp.expand_dims(x, 0), st2)

        # Tier 3: per-variable fallbacks — partitioned per-shard reduction
        # and non-bucketable compressors (PowerSGD).
        for i, (path, g) in enumerate(flat):
            name = path_name(path)
            if name in bucketed_names or compiled.var_plans.get(name) is None:
                continue
            info = part.get(name)
            if info is not None:
                # Per-shard compressed reduction: slice this device's
                # shard of the full gradient, then compress its data-axis
                # mean.  Slicing commutes with the mean, so the result is
                # exact; only the shard crosses the compressed wire.
                axis_name, ax, n = info
                size = g.shape[ax] // n
                idx = lax.axis_index(axis_name)
                g = lax.dynamic_slice_in_dim(g, idx * size, size, ax)
            with sync_span(f"per_var_reduce/{name}"):
                g2, st2 = comps[name].reduce(g, local_state_of(name),
                                             MESH_AXIS_DATA)
            store_state(name, st2)
            synced[i] = g2
            guarded_idx.append(i)
            if num_active:
                # Finiteness from the PRE-compress local gradient (the
                # injected/overflowed value a lossy compressor could
                # mask); the norm partial from the reduced value the
                # update will consume.  Partitioned shards psum over
                # their model axis too, so nothing is double counted.
                health.add(
                    name, g2,
                    shard_axes_size=part[name][2] if info is not None else 1,
                    finite_src=g,
                    saturation=guard_mod.wire_saturation(
                        g, ls_mod.wire_dtype_of(comps[name].name)))

        # Tiers 1+2: one collective per bucket.  Each bucket's chain
        # (pack → collective [→ shard update → all-gather]) depends only
        # on its own members' gradients, so XLA's scheduler is free to
        # overlap bucket collectives with other buckets' math and with
        # backward compute that does not feed them.  Pipelined buckets
        # arrive already reduced (per microbatch, see above); uncompressed
        # buckets reduce through the overlap schedule's lowering (ring
        # decomposition / one-shot / XLA fused collective).
        rs_grad_shards: Dict[str, Any] = {}
        for b in buckets:
            rs = b.mode == MODE_REDUCE_SCATTER
            if b.key in pipe_keys:
                red = pipe_reduced[b.key]
                if num_active:
                    # Linear pipelined buckets: a NaN survives the linear
                    # per-microbatch reduction — the accumulated reduced
                    # value IS the finiteness source.  Quantized
                    # pipelined buckets additionally report the
                    # saturation events counted inside their ring legs
                    # (a quantizer can mask a NaN on the wire; the
                    # counter cannot).
                    health.add(b.key, red, shard_axes_size=d if rs else 1,
                               sat_count=pipe_qsats[b.key] * sat_norm
                               if b.key in pipe_qsats else None)
                if b.mode == MODE_ALL_REDUCE:
                    for n, arr in zip(b.names, unpack_bucket(b, red)):
                        synced[idx_of[n]] = arr
                        guarded_idx.append(idx_of[n])
                else:
                    rs_grad_shards[b.key] = red
                store_state(b.key, pipe_qstates.get(b.key))
                continue
            if b.key in stamp_reduce:   # flight-recorder leg boundary
                lid, lkind = stamp_reduce[b.key]
                flightrec.traced_stamp(lid, leg_kind=lkind)
            vec = pack_bucket(b, [flat[idx_of[n]][1] for n in b.names])
            if b.key in reduce_fns:   # uncompressed: schedule-lowered
                # Profiler attribution (docs/observability.md): the
                # named scope prefixes this bucket's lowered collective
                # ops, so a trace shows reduce-scatter vs all-gather vs
                # update time per bucket by name.
                with sync_span(f"bucket_reduce/{b.key}"):
                    red = reduce_fns[b.key](vec)
                st2 = None
                if num_active:
                    # The per-bucket finiteness bit is a byproduct of the
                    # pack (the local packed vector); the norm partial
                    # comes from the reduced value — the scattered SHARD
                    # for ZeRO-1 buckets, whose shard sq-norms psum to
                    # exactly the full bucket norm.
                    health.add(b.key, red, shard_axes_size=d if rs else 1,
                               finite_src=vec)
                if b.mode == MODE_ALL_REDUCE:
                    for n, arr in zip(b.names, unpack_bucket(b, red)):
                        synced[idx_of[n]] = arr
                        guarded_idx.append(idx_of[n])
                else:
                    rs_grad_shards[b.key] = red
            elif b.key in quant_fns:
                # Quantized wire (int8/fp8): the bucket lowers through
                # quant_ring under the IR-resolved algorithm (per-hop
                # requantizing ring or one-shot all_to_all), and the
                # post-quantization saturation counter — clipped-to-rail
                # / fp8-overflow elements, counted INSIDE the legs —
                # rides the health rollup.
                with sync_span(f"bucket_quant_reduce/{b.key}"):
                    red, st2, qsat = quant_fns[b.key](
                        vec, local_state_of(b.key))
                if b.mode == MODE_ALL_REDUCE:
                    if num_active:
                        health.add(b.key, red, shard_axes_size=1,
                                   finite_src=vec,
                                   sat_count=qsat * sat_norm)
                    for n, arr in zip(b.names, unpack_bucket(b, red)):
                        synced[idx_of[n]] = arr
                        guarded_idx.append(idx_of[n])
                else:
                    rs_grad_shards[b.key] = red
                    if num_active:
                        health.add(b.key, red, shard_axes_size=d,
                                   finite_src=vec,
                                   sat_count=qsat * sat_norm)
            else:
                comp = get_compressor(b.compressor)
                sat = guard_mod.wire_saturation(
                    vec, ls_mod.wire_dtype_of(b.compressor)) \
                    if num_active else None
                if b.mode == MODE_ALL_REDUCE:
                    with sync_span(f"bucket_compressed_reduce/{b.key}"):
                        red, st2 = comp.reduce(vec, local_state_of(b.key),
                                               MESH_AXIS_DATA)
                    if num_active:
                        health.add(b.key, red, shard_axes_size=1,
                                   finite_src=vec, saturation=sat)
                    for n, arr in zip(b.names, unpack_bucket(b, red)):
                        synced[idx_of[n]] = arr
                        guarded_idx.append(idx_of[n])
                else:
                    with sync_span(f"bucket_compressed_reduce/{b.key}"):
                        rs_grad_shards[b.key], st2 = comp.reduce_scatter(
                            vec, local_state_of(b.key), MESH_AXIS_DATA)
                    if num_active:
                        health.add(b.key, rs_grad_shards[b.key],
                                   shard_axes_size=d, finite_src=vec,
                                   saturation=sat)
            store_state(b.key, st2)

        # -- fused guard roll-up: ONE psum combines every bucket/var
        # partial; unscale + global-norm clip multiply into the synced
        # gradients before any update (exact under ZeRO-1: the factor is
        # computed from the psum of shard norms, identical on every
        # device).
        all_finite = gnorm = per_bucket = new_ns = None
        fused_mult = None
        if num_active:
            inv_scale = jnp.float32(1.0) if scale is None \
                else jnp.float32(1.0) / scale
            if leg_stamps:
                flightrec.traced_stamp("guard/rollup",
                                       leg_kind=schedule_ir.LEG_PSUM_GUARD)
            with sync_span("guard_rollup"):
                all_finite, gnorm, per_bucket = health.finalize(
                    mesh_axis_names, loss, inv_scale)
            mult = inv_scale
            clip = guard_mod.clip_multiplier(gnorm, num_cfg.clip_norm)
            if clip is not None:
                mult = mult * clip
            if clip is not None or scale is not None:
                for i in set(guarded_idx):
                    g_i = synced[i]
                    synced[i] = (g_i.astype(jnp.float32)
                                 * mult).astype(g_i.dtype)
                if update_fused:
                    # The fused unscale/clip/update kernel folds the
                    # multiplier into the shard update itself — the
                    # gradient shards stay untouched here (one fewer
                    # full pass over every ZeRO-1 bucket).
                    fused_mult = mult
                else:
                    rs_grad_shards = {
                        k: (v.astype(jnp.float32) * mult).astype(v.dtype)
                        for k, v in rs_grad_shards.items()}
        grads = jax.tree_util.tree_unflatten(treedef, synced)

        # Shard-local update: grads, params, and opt state all carry the
        # per-device shard shapes, so elementwise optimizers (SGD, Adam*)
        # update each partition in place.  (Global-norm clipping — the
        # one cross-parameter coupling that matters — is handled by the
        # numerics guard above, whose psum'd norm makes the sharded clip
        # exact; other coupled optimizers still need the GSPMD path.)
        if rs_buckets:
            # ZeRO-1: update the local 1/d shard of every reduce-scattered
            # bucket, then all-gather fresh parameters ("broadcast from
            # the PS" in reference terms).  Params are replicated inside
            # the step, so slicing this device's shard is local.
            shard_idx = lax.axis_index(MESH_AXIS_DATA)
            by_name = {path_name(p): x for p, x in flat_p}
            p_shards = {}
            for b in rs_buckets:
                vec = pack_bucket(b, [by_name[n] for n in b.names])
                sz = b.padded_total // d
                if b.key in hier_bucket_keys:
                    # Two-tier scatter permutes ownership: device
                    # g*d_in+i ends with global chunk i*s+g, so slice
                    # the matching param chunk for the shard update.
                    d_in = d // num_slices
                    owner = ((shard_idx % d_in) * num_slices
                             + shard_idx // d_in)
                    p_shards[b.key] = lax.dynamic_slice_in_dim(
                        vec, owner * sz, sz, 0)
                else:
                    p_shards[b.key] = lax.dynamic_slice_in_dim(
                        vec, shard_idx * sz, sz, 0)
            if rs_buckets and rs_buckets[0].key in stamp_update:
                lid, lkind = stamp_update[rs_buckets[0].key]
                flightrec.traced_stamp(lid, leg_kind=lkind)
            if update_fused:
                # Fused unscale/clip/Adam update (docs/kernels.md): one
                # kernel per bucket shard over (p, g, m, v) — exact vs
                # the optax chain (fusable_adam pins the hyperparams);
                # the shared step counter increments once, like optax.
                spec = gi.optimizer.fused_spec
                with sync_span("fused_shard_update"):
                    adam_st = fk.find_adam_state(opt_state["zero1"])
                    new_shards, new_mu, new_nu = {}, {}, {}
                    for b in rs_buckets:
                        key = b.key
                        (new_shards[key], new_mu[key],
                         new_nu[key]) = fk.fused_adam_update(
                            p_shards[key], rs_grad_shards[key],
                            adam_st.mu[key], adam_st.nu[key],
                            adam_st.count, spec, mult=fused_mult,
                            interpret=fused_interpret)
                    z_state = fk.replace_adam_state(
                        opt_state["zero1"],
                        adam_st._replace(count=adam_st.count + 1,
                                         mu=new_mu, nu=new_nu))
            else:
                with sync_span("zero1_shard_update"):
                    z_updates, z_state = bucket_optimizer.update(
                        rs_grad_shards, opt_state["zero1"], p_shards)
                    new_shards = optax.apply_updates(p_shards, z_updates)

            with sync_span("tree_update"):
                t_updates, t_state = tree_optimizer.update(
                    grads, opt_state["vars"], params)
                params = optax.apply_updates(params, t_updates)

            new_flat = [x for _, x in
                        jax.tree_util.tree_flatten_with_path(params)[0]]
            # Param prefetch: gathers issue in the IR's recorded order —
            # reverse bucket order under prefetch (the last bucket's
            # shard update completes first under the backward-interleaved
            # schedule), and large buckets ring-decompose the gather so
            # its legs interleave with the remaining shard updates.
            rs_by_key = {b.key: b for b in rs_buckets}
            for key, gather_alg in ir.gather_plan():
                b = rs_by_key[key]
                shard = new_shards[b.key]
                if key in stamp_gather:   # flight-recorder leg boundary
                    lid, lkind = stamp_gather[key]
                    flightrec.traced_stamp(lid, leg_kind=lkind)
                with sync_span(f"param_gather/{b.key}"):
                    if key in hier_bucket_keys:
                        # DCN gather (across slices, chunk order) then
                        # ICI gather (within slice) undoes the two-tier
                        # ownership permutation exactly.
                        full_vec = overlap_mod.hier_gather_fn(
                            MESH_AXIS_DATA, d, num_slices)(shard)
                    elif gather_alg == schedule_ir.ALG_RING and d > 1:
                        full_vec = overlap_mod.ring_all_gather(
                            shard, MESH_AXIS_DATA, d)
                    else:
                        full_vec = lax.all_gather(shard, MESH_AXIS_DATA,
                                                  axis=0, tiled=True)
                for n, arr in zip(b.names, unpack_bucket(b, full_vec)):
                    new_flat[idx_of[n]] = arr
            params = jax.tree_util.tree_unflatten(treedef, new_flat)
            opt_state = {"vars": t_state, "zero1": z_state}
        else:
            if "~tree" in stamp_update:
                lid, lkind = stamp_update["~tree"]
                flightrec.traced_stamp(lid, leg_kind=lkind)
            with sync_span("tree_update"):
                updates, opt_state = tree_optimizer.update(grads, opt_state,
                                                           params)
                params = optax.apply_updates(params, updates)
        mean_loss = lax.pmean(loss, MESH_AXIS_DATA)
        metrics = {"loss": mean_loss}
        if num_active:
            # Skip gate: a non-finite step keeps params AND optimizer
            # state bit-identical (zero-update), backs the loss scale
            # off, and counts the skip — the step policy's device half.
            params = guard_mod.tree_select(all_finite, params, params_in)
            opt_state = guard_mod.tree_select(all_finite, opt_state, opt_in)
            # Compressor state (error-feedback residuals, PowerSGD
            # factors) must roll back too: a skipped step's poisoned
            # residual would otherwise re-contaminate every later step.
            for key in list(new_sync):
                if key != NUMERICS_KEY and key in sync_state:
                    new_sync[key] = guard_mod.tree_select(
                        all_finite, new_sync[key], sync_state[key])
            new_ns = ls_mod.update_state(ns, all_finite, num_ls)
            new_sync[NUMERICS_KEY] = new_ns
            if scale is not None:
                metrics["loss"] = mean_loss * inv_scale
            metrics["grad_health"] = guard_mod.GradHealth(
                all_finite=all_finite, global_norm=gnorm,
                loss_scale=ns["scale"], skipped_steps=new_ns["skipped"],
                per_bucket=per_bucket)
        if aux is not None:
            metrics["aux"] = jax.tree_util.tree_map(
                lambda x: lax.pmean(x, MESH_AXIS_DATA), aux)
        # extra metrics_fn runs OUTSIDE this shard_map (graph_transformer
        # wraps the step) so it sees the global batch, not a local shard.
        return params, opt_state, new_sync, metrics

    # check_vma=False: this path OWNS its collectives.  With vma tracking on
    # (the jax 0.9 default), replicated (P()) params get pvary'd on entry and
    # the loss's backward transpose AUTO-INSERTS a psum per variable — the
    # gradients would arrive pre-summed and the compressor pmean would then
    # scale them by the data-axis size (d x too large), while the real
    # collective escapes the compressor entirely.
    mapped = compat.shard_map(
        local_step, mesh=mesh,
        in_specs=(param_spec_tree, opt_spec_tree, dict(sync_specs),
                  P(MESH_AXIS_DATA)),
        out_specs=(param_spec_tree, opt_spec_tree, dict(sync_specs), P()),
        check_vma=False)
    # Donation decision proven above (schedule/read-after-donate): sync
    # state is donated only when every entry is a bucket residual or the
    # numerics scalars — both rewritten unconditionally every step.
    step_fn = jax.jit(mapped,
                      donate_argnums=(0, 1, 2) if donate_sync else (0, 1))

    init_opt_fn = jax.jit(init_opt, out_shardings=opt_sh_tree)
    return (step_fn, init_opt_fn, init_sync_state, param_sh_tree,
            opt_sh_tree, list(rs_buckets), ir)
