"""Explicit (shard_map) synchronization path.

The GSPMD path lets XLA insert collectives; this path takes manual control of
the gradient all-reduce so a :class:`Compressor` can wrap it — the analog of
the reference's AllReduceSynchronizer inserting ``collective_ops.all_reduce``
through a compressor (``all_reduce_synchronizer.py:100-127``,
``compressor.py:85-96``).

Semantics: the whole train step runs inside ``shard_map`` over the mesh.
Parameters and optimizer state are replicated; the batch is sharded over
``data``; each device computes local gradients (accumulated over
``capture(accum_steps=N)`` microbatches of its local slice when asked —
still ONE compressed collective per step), every variable's gradient is
averaged over ``data`` through its compressor, and the (identical) update is
applied on all devices.  Per-device compressor state (error-feedback
residuals, PowerSGD factors) is carried as a *sync state* pytree with a
leading per-shard axis, sharded over ``data`` so each device owns its slice.

Restriction: compressors require replicated parameters — model-axis
partitioned variables would make the user's loss function responsible for
manual tensor-parallel math inside shard_map.  The transformer falls back to
replication (with a warning) for such variables when a compressor is active.
"""
from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from autodist_tpu.const import MESH_AXIS_DATA
from autodist_tpu.graph_item import GraphItem, path_name
from autodist_tpu.kernel.synchronization.compressor import (
    Compressor,
    get_compressor,
)
from autodist_tpu.strategy.compiler import CompiledStrategy
from autodist_tpu.utils import logging


def uses_explicit_path(compiled: CompiledStrategy) -> bool:
    """Compressors need manual collectives; fused grouping needs them too
    (one concat-and-pmean per group — the reference's scoped-allocator
    merge done literally)."""
    if any(plan.compressor not in ("", "NoneCompressor")
           for plan in compiled.var_plans.values()):
        return True
    return (any(plan.fused for plan in compiled.var_plans.values())
            and bool(compiled.fusable_groups()))


def _compressors_for(gi: GraphItem, compiled: CompiledStrategy
                     ) -> Dict[str, Compressor]:
    out: Dict[str, Compressor] = {}
    for name, leaf in gi.name_to_leaf().items():
        plan = compiled.var_plans.get(name)
        comp_name = plan.compressor if plan else "NoneCompressor"
        out[name] = get_compressor(comp_name or "NoneCompressor")
    return out


def make_explicit_step(gi: GraphItem, compiled: CompiledStrategy,
                       has_partitioned_vars: bool):
    """Returns (step_fn, init_opt_fn, init_sync_state_fn, shardings...)
    consumed by the GraphTransformer."""
    import optax

    mesh = compiled.mesh
    d = mesh.shape.get(MESH_AXIS_DATA, 1)
    if has_partitioned_vars:
        logging.warning(
            "compressors force replicated parameters on the explicit sync "
            "path; model-axis partitioning is ignored for this program")

    comps = _compressors_for(gi, compiled)
    vg = jax.value_and_grad(gi.loss_fn, has_aux=gi.has_aux)
    if gi.accum_steps > 1:
        # Gradient accumulation composes with compression exactly where it
        # matters most (bandwidth-starved links): the f32 accumulator scan
        # runs INSIDE the shard_map step over the device's LOCAL microbatch
        # slices, so the compressor still sees ONE averaged gradient — one
        # compressed all-reduce per step, N microbatches of activations.
        from autodist_tpu.kernel.graph_transformer import _accumulate_grads
        vg = _accumulate_grads(vg, gi.accum_steps, gi.has_aux)
    optimizer = gi.frozen_aware_optimizer()
    has_aux = gi.has_aux

    # Trace-time fusion table (reference chunk merge): vars in the same
    # group are concatenated into ONE pmean.  Split by dtype — a fused
    # vector must be homogeneous.
    fuse_member: Dict[str, tuple] = {}
    if d > 1:
        leaves = gi.name_to_leaf()
        for group, names in compiled.fusable_groups().items():
            by_dtype: Dict[str, list] = {}
            for n in names:
                by_dtype.setdefault(str(jnp.asarray(leaves[n]).dtype),
                                    []).append(n)
            for dt, ns in by_dtype.items():
                if len(ns) >= 2:
                    for n in ns:
                        fuse_member[n] = (group, dt)

    # -- sync state --------------------------------------------------------
    def init_sync_state(current_params=None):
        # Compressor residuals start at zero regardless of parameter values,
        # so current_params only matters for shape (identical to capture-time).
        state: Dict[str, Any] = {}
        for name, leaf in gi.name_to_leaf().items():
            per_dev = comps[name].init_state(jnp.asarray(leaf))
            if per_dev is None:
                continue
            state[name] = jax.tree_util.tree_map(
                lambda s: jnp.broadcast_to(s[None], (d,) + s.shape).copy(),
                per_dev)
        return jax.device_put(state, NamedSharding(mesh, P(MESH_AXIS_DATA)))

    # -- the local (per-shard) step ---------------------------------------
    def local_step(params, opt_state, sync_state, batch):
        if has_aux:
            (loss, aux), grads = vg(params, batch)
        else:
            loss, grads = vg(params, batch)
            aux = None

        flat, treedef = jax.tree_util.tree_flatten_with_path(grads)
        new_sync = dict(sync_state)
        synced = [None] * len(flat)
        fused_parts: Dict[tuple, list] = {}
        for i, (path, g) in enumerate(flat):
            name = path_name(path)
            key = fuse_member.get(name)
            if key is not None:
                fused_parts.setdefault(key, []).append((i, g))
                continue
            st = sync_state.get(name)
            local_st = None if st is None else jax.tree_util.tree_map(
                lambda x: jnp.squeeze(x, 0), st)
            g2, st2 = comps[name].reduce(g, local_st, MESH_AXIS_DATA)
            if st2 is not None and name in new_sync:
                new_sync[name] = jax.tree_util.tree_map(
                    lambda x: jnp.expand_dims(x, 0), st2)
            synced[i] = g2
        # One pmean per fused group: concat raveled grads, reduce, split.
        for parts in fused_parts.values():
            vec = jnp.concatenate([jnp.ravel(g) for _, g in parts])
            vec = lax.pmean(vec, MESH_AXIS_DATA)
            offset = 0
            for i, g in parts:
                size = g.size
                synced[i] = jnp.reshape(vec[offset:offset + size], g.shape)
                offset += size
        grads = jax.tree_util.tree_unflatten(
            treedef, synced) if synced else grads

        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        metrics = {"loss": lax.pmean(loss, MESH_AXIS_DATA)}
        if aux is not None:
            metrics["aux"] = jax.tree_util.tree_map(
                lambda x: lax.pmean(x, MESH_AXIS_DATA), aux)
        # extra metrics_fn runs OUTSIDE this shard_map (graph_transformer
        # wraps the step) so it sees the global batch, not a local shard.
        return params, opt_state, new_sync, metrics

    # check_vma=False: this path OWNS its collectives.  With vma tracking on
    # (the jax 0.9 default), replicated (P()) params get pvary'd on entry and
    # the loss's backward transpose AUTO-INSERTS a psum per variable — the
    # gradients would arrive pre-summed and the compressor pmean would then
    # scale them by the data-axis size (d x too large), while the real
    # collective escapes the compressor entirely.
    mapped = jax.shard_map(
        local_step, mesh=mesh,
        in_specs=(P(), P(), P(MESH_AXIS_DATA), P(MESH_AXIS_DATA)),
        out_specs=(P(), P(), P(MESH_AXIS_DATA), P()),
        check_vma=False)
    step_fn = jax.jit(mapped, donate_argnums=(0, 1, 2))

    replicated = NamedSharding(mesh, P())
    init_opt_fn = jax.jit(optimizer.init, out_shardings=replicated)
    return step_fn, init_opt_fn, init_sync_state, replicated
