"""Explicit (shard_map) synchronization path.

The GSPMD path lets XLA insert collectives; this path takes manual control of
the gradient all-reduce so a :class:`Compressor` can wrap it — the analog of
the reference's AllReduceSynchronizer inserting ``collective_ops.all_reduce``
through a compressor (``all_reduce_synchronizer.py:100-127``,
``compressor.py:85-96``).

Semantics: the whole train step runs inside ``shard_map`` over the mesh.
The batch is sharded over ``data``; each device computes local gradients
(accumulated over ``capture(accum_steps=N)`` microbatches of its local slice
when asked — still ONE compressed collective per step), every variable's
gradient is averaged over ``data`` through its compressor, and the update is
applied on all devices.  Per-device compressor state (error-feedback
residuals, PowerSGD factors) is carried as a *sync state* pytree with a
leading per-shard axis, sharded over ``data`` so each device owns its slice.

Partitioned variables COMPOSE with compression (the reference can express
PartitionedAR + compressor — ``proto/synchronizers.proto:24-57``): a var
sharded over a non-data mesh axis stays sharded outside the step; inside,
it is all-gathered for the user's loss, its gradient is sliced back to the
local shard, and the data-axis reduction of the SHARD runs through the
compressor — per-shard compressed reduction, each partition reduced
independently (the reference's per-shard synchronizer structure), with the
parameter + optimizer-state memory of true partitioning.  Per-variable
fallback to replication (with a warning) covers the cases where the
composition is not defined: vars sharded over ``data`` itself (PS shards on
a pure-DP mesh — the reduction axis and the shard axis coincide),
pad-to-divisible vars, multi-axis shardings, and PowerSGD (its low-rank
state is not grad-shaped, so the per-shard state layout does not apply).
"""
from __future__ import annotations

from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from autodist_tpu.const import MESH_AXIS_DATA
from autodist_tpu.graph_item import GraphItem, path_name
from autodist_tpu.kernel.synchronization.compressor import (
    Compressor,
    get_compressor,
)
from autodist_tpu.strategy.compiler import CompiledStrategy
from autodist_tpu.utils import compat, logging


def uses_explicit_path(compiled: CompiledStrategy) -> bool:
    """Compressors need manual collectives; fused grouping needs them too
    (one concat-and-pmean per group — the reference's scoped-allocator
    merge done literally)."""
    if any(plan.compressor not in ("", "NoneCompressor")
           for plan in compiled.var_plans.values()):
        return True
    return (any(plan.fused for plan in compiled.var_plans.values())
            and bool(compiled.fusable_groups()))


def _compressors_for(gi: GraphItem, compiled: CompiledStrategy
                     ) -> Dict[str, Compressor]:
    out: Dict[str, Compressor] = {}
    for name, leaf in gi.name_to_leaf().items():
        plan = compiled.var_plans.get(name)
        comp_name = plan.compressor if plan else "NoneCompressor"
        out[name] = get_compressor(comp_name or "NoneCompressor")
    return out


def _grad_shaped_state(comp: Compressor, shape: tuple, dtype) -> bool:
    """True when ``comp``'s per-device state for a value of ``shape`` is
    None or a single array of exactly that shape — the structural
    requirement for the per-shard partitioned state layout (one leading
    data axis + the var's own sharding applied to every leaf).  Probed
    abstractly (eval_shape): no state is materialized."""
    probe = jax.eval_shape(comp.init_state,
                           jax.ShapeDtypeStruct(shape, dtype))
    if probe is None:
        return True
    leaves = jax.tree_util.tree_leaves(probe)
    return (len(leaves) == 1 and tuple(leaves[0].shape) == tuple(shape)
            and leaves[0].dtype == dtype)


def partition_drop_reason(spec_axes, shape, dtype, axis_sizes, padded,
                          comp: Compressor) -> Optional[str]:
    """Why the explicit path would drop a partitioned var's sharding, or
    None when the partitioning is kept.

    ``spec_axes`` is the flattened ``[(tensor_dim, mesh_axis_name), ...]``
    of the param layout; ``axis_sizes`` maps axis name → size (a plain
    dict — no mesh needed, so the static analyzer
    (``autodist_tpu.analysis``) shares this exact rule and the lint can
    never drift from the runtime fallback)."""
    spec_axes = list(spec_axes)
    if not spec_axes:
        return None
    if padded:
        return "pad-to-divisible sharding"
    if len(spec_axes) != 1:
        return f"multi-axis sharding {spec_axes}"
    part_axis, axis_name = spec_axes[0]
    if axis_name == MESH_AXIS_DATA:
        return "sharded over the data (reduction) axis"
    n = int(axis_sizes.get(axis_name, 1))
    if n > 1 and shape[part_axis] % n:  # pragma: no cover - padded
        return f"dim {shape[part_axis]} not divisible by {n}"
    shard = list(shape)
    if n > 1:
        shard[part_axis] //= n
    if not _grad_shaped_state(comp, tuple(shard), dtype):
        return (f"{comp.name} state is not grad-shaped"
                f" (e.g. PowerSGD low-rank factors)")
    return None


def _partition_support(gi: GraphItem, compiled: CompiledStrategy,
                       comps: Dict[str, Compressor]) -> Dict[str, tuple]:
    """Which partitioned vars keep their sharding on the explicit path:
    ``{name: (axis_name, part_axis, n_shards)}``.  Unsupported cases
    (see module docstring) are replicated per-variable with a warning."""
    part: Dict[str, tuple] = {}
    pad_names = set(compiled.pad_plans())
    leaves = gi.name_to_leaf()
    axis_sizes = dict(compiled.mesh.shape)
    for name, plan in compiled.var_plans.items():
        spec = plan.param_spec
        if spec == P():
            continue
        spec_axes = []
        for i, e in enumerate(spec):
            if e is None:
                continue
            for a in ([e] if isinstance(e, str) else list(e)):
                spec_axes.append((i, a))
        leaf = jnp.asarray(leaves[name])
        why = partition_drop_reason(spec_axes, leaf.shape, leaf.dtype,
                                    axis_sizes, name in pad_names,
                                    comps[name])
        if why is not None:
            logging.warning(
                "explicit sync path: replicating %s (%s); its "
                "partitioning is dropped for this program", name, why)
            continue
        (part_axis, axis_name), = spec_axes
        part[name] = (axis_name, part_axis, axis_sizes[axis_name])
    return part


def make_explicit_step(gi: GraphItem, compiled: CompiledStrategy):
    """Returns (step_fn, init_opt_fn, init_sync_state_fn, param_sh_tree,
    opt_sh_tree) consumed by the GraphTransformer."""
    import optax

    from autodist_tpu.kernel import sharding_utils as su

    mesh = compiled.mesh
    d = mesh.shape.get(MESH_AXIS_DATA, 1)
    comps = _compressors_for(gi, compiled)
    part = _partition_support(gi, compiled, comps)

    # Effective per-var specs: the plan's spec for supported partitioned
    # vars, replicated for everything else.
    eff_specs = {name: (plan.param_spec if name in part else P())
                 for name, plan in compiled.var_plans.items()}
    param_spec_tree = su.spec_tree_for_params(gi.params, eff_specs)
    param_sh_tree = su.sharding_tree(mesh, param_spec_tree)

    vg = jax.value_and_grad(gi.loss_fn, has_aux=gi.has_aux)
    if gi.accum_steps > 1:
        # Gradient accumulation composes with compression exactly where it
        # matters most (bandwidth-starved links): the f32 accumulator scan
        # runs INSIDE the shard_map step over the device's LOCAL microbatch
        # slices, so the compressor still sees ONE averaged gradient — one
        # compressed all-reduce per step, N microbatches of activations.
        from autodist_tpu.kernel.graph_transformer import _accumulate_grads
        vg = _accumulate_grads(vg, gi.accum_steps, gi.has_aux)
    optimizer = gi.frozen_aware_optimizer()
    has_aux = gi.has_aux

    # Optimizer-state layout: param-shaped blocks follow the effective
    # param spec (shard-local moments for partitioned vars — the real
    # memory win of keeping the partitioning); scalars replicate.
    opt_shape = jax.eval_shape(optimizer.init, gi.params)
    opt_spec_tree = su.opt_spec_tree(opt_shape, gi.params, param_spec_tree)
    opt_sh_tree = su.sharding_tree(mesh, opt_spec_tree)

    # Trace-time fusion table (reference chunk merge): vars in the same
    # group are concatenated into ONE pmean.  Split by dtype — a fused
    # vector must be homogeneous.  Partitioned vars own their per-shard
    # collective and never fuse.
    fuse_member: Dict[str, tuple] = {}
    if d > 1:
        leaves = gi.name_to_leaf()
        for group, names in compiled.fusable_groups().items():
            by_dtype: Dict[str, list] = {}
            for n in names:
                # fusable_groups() already excludes partitioned and
                # compressed vars (strategy/compiler.py); a partitioned
                # var in a fused group would double-own its collective.
                assert n not in part, n
                by_dtype.setdefault(str(jnp.asarray(leaves[n]).dtype),
                                    []).append(n)
            for dt, ns in by_dtype.items():
                if len(ns) >= 2:
                    for n in ns:
                        fuse_member[n] = (group, dt)

    def _shard_shape(name: str, leaf) -> tuple:
        shape = list(jnp.asarray(leaf).shape)
        if name in part:
            _, ax, n = part[name]
            shape[ax] //= n
        return tuple(shape)

    # -- sync state --------------------------------------------------------
    # Which vars carry state and under which spec, probed abstractly ONCE
    # (eval_shape — no full-model state is materialized just to test for
    # None); consumed by both the shard_map specs and init_sync_state.
    name_leaves = {n: jnp.asarray(v) for n, v in gi.name_to_leaf().items()}
    sync_specs: Dict[str, P] = {}
    for name, leaf in name_leaves.items():
        probe = jax.eval_shape(
            comps[name].init_state,
            jax.ShapeDtypeStruct(_shard_shape(name, leaf), leaf.dtype))
        if probe is None:
            continue
        sync_specs[name] = P(MESH_AXIS_DATA,
                             *compiled.var_plans[name].param_spec) \
            if name in part else P(MESH_AXIS_DATA)

    def init_sync_state(current_params=None):
        # Compressor residuals start at zero regardless of parameter values,
        # so current_params only matters for shape (identical to capture-time).
        state: Dict[str, Any] = {}
        for name, spec in sync_specs.items():
            leaf = name_leaves[name]
            if name in part:
                # Partitioned state is built THROUGH the compressor's own
                # init_state on a shard-shaped zero input (the gate and
                # the construction cannot diverge), tiled to (d,) + FULL
                # shape directly in its target sharding — each device
                # owns its shard's state.
                _, ax, n = part[name]
                shard = _shard_shape(name, leaf)

                def _build(comp=comps[name], shard=shard, dt=leaf.dtype,
                           ax=ax, n=n):
                    def expand(s):
                        reps = [n if i == ax else 1
                                for i in range(s.ndim)]
                        tiled = jnp.tile(s, reps)
                        return jnp.broadcast_to(tiled[None],
                                                (d,) + tiled.shape)
                    return jax.tree_util.tree_map(
                        expand, comp.init_state(jnp.zeros(shard, dt)))

                state[name] = jax.jit(
                    _build, out_shardings=NamedSharding(mesh, spec))()
            else:
                per_dev = comps[name].init_state(leaf)
                stacked = jax.tree_util.tree_map(
                    lambda s: jnp.broadcast_to(s[None],
                                               (d,) + s.shape).copy(),
                    per_dev)
                state[name] = jax.device_put(
                    stacked, NamedSharding(mesh, spec))
        return state

    # -- the local (per-shard) step ---------------------------------------
    def local_step(params, opt_state, sync_state, batch):
        # Reconstruct full tensors for the user's loss: sharded vars are
        # all-gathered over their partition axis (what GSPMD inserts for
        # a fully-consumed sharded param; here it is explicit).
        flat_p, ptree = jax.tree_util.tree_flatten_with_path(params)
        full_leaves = []
        for path, x in flat_p:
            info = part.get(path_name(path))
            if info is not None:
                axis_name, ax, _ = info
                x = lax.all_gather(x, axis_name, axis=ax, tiled=True)
            full_leaves.append(x)
        full_params = jax.tree_util.tree_unflatten(ptree, full_leaves)

        if has_aux:
            (loss, aux), grads = vg(full_params, batch)
        else:
            loss, grads = vg(full_params, batch)
            aux = None

        flat, treedef = jax.tree_util.tree_flatten_with_path(grads)
        new_sync = dict(sync_state)
        synced = [None] * len(flat)
        fused_parts: Dict[tuple, list] = {}
        for i, (path, g) in enumerate(flat):
            name = path_name(path)
            key = fuse_member.get(name)
            if key is not None:
                fused_parts.setdefault(key, []).append((i, g))
                continue
            info = part.get(name)
            if info is not None:
                # Per-shard compressed reduction: slice this device's
                # shard of the full gradient, then compress its data-axis
                # mean.  Slicing commutes with the mean, so the result is
                # exact; only the shard crosses the compressed wire.
                axis_name, ax, n = info
                size = g.shape[ax] // n
                idx = lax.axis_index(axis_name)
                g = lax.dynamic_slice_in_dim(g, idx * size, size, ax)
            st = sync_state.get(name)
            local_st = None if st is None else jax.tree_util.tree_map(
                lambda x: jnp.squeeze(x, 0), st)
            g2, st2 = comps[name].reduce(g, local_st, MESH_AXIS_DATA)
            if st2 is not None and name in new_sync:
                new_sync[name] = jax.tree_util.tree_map(
                    lambda x: jnp.expand_dims(x, 0), st2)
            synced[i] = g2
        # One pmean per fused group: concat raveled grads, reduce, split.
        for parts in fused_parts.values():
            vec = jnp.concatenate([jnp.ravel(g) for _, g in parts])
            vec = lax.pmean(vec, MESH_AXIS_DATA)
            offset = 0
            for i, g in parts:
                size = g.size
                synced[i] = jnp.reshape(vec[offset:offset + size], g.shape)
                offset += size
        grads = jax.tree_util.tree_unflatten(
            treedef, synced) if synced else grads

        # Shard-local update: grads, params, and opt state all carry the
        # per-device shard shapes, so elementwise optimizers (SGD, Adam*)
        # update each partition in place.  (An optimizer coupling across
        # parameters — e.g. global-norm clipping — would need its own
        # collectives here; use the GSPMD path for those.)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        metrics = {"loss": lax.pmean(loss, MESH_AXIS_DATA)}
        if aux is not None:
            metrics["aux"] = jax.tree_util.tree_map(
                lambda x: lax.pmean(x, MESH_AXIS_DATA), aux)
        # extra metrics_fn runs OUTSIDE this shard_map (graph_transformer
        # wraps the step) so it sees the global batch, not a local shard.
        return params, opt_state, new_sync, metrics

    # check_vma=False: this path OWNS its collectives.  With vma tracking on
    # (the jax 0.9 default), replicated (P()) params get pvary'd on entry and
    # the loss's backward transpose AUTO-INSERTS a psum per variable — the
    # gradients would arrive pre-summed and the compressor pmean would then
    # scale them by the data-axis size (d x too large), while the real
    # collective escapes the compressor entirely.
    mapped = compat.shard_map(
        local_step, mesh=mesh,
        in_specs=(param_spec_tree, opt_spec_tree, dict(sync_specs),
                  P(MESH_AXIS_DATA)),
        out_specs=(param_spec_tree, opt_spec_tree, dict(sync_specs), P()),
        check_vma=False)
    step_fn = jax.jit(mapped, donate_argnums=(0, 1, 2))

    init_opt_fn = jax.jit(optimizer.init, out_shardings=opt_sh_tree)
    return step_fn, init_opt_fn, init_sync_state, param_sh_tree, opt_sh_tree
