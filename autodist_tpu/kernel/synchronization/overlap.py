"""Overlap-aware scheduling of the explicit bucketed sync path.

PR 2 made gradient buckets the unit of synchronization; this module makes
them the unit of *scheduling*.  The phase-serial step the explicit path
used to emit — full backward, then every bucket collective, then the
update — leaves the interconnect idle during compute and the MXU idle
during sync.  The MLPerf TPU-v3 report (arXiv:1909.09756) attributes a
large share of its scaling wins to overlapping gradient summation with
backprop, and EQuARX (arXiv:2506.17615) argues the collective itself is
a schedulable program, not one opaque op.  Three mechanisms, selected by
the ``overlap=`` knob on :class:`AllReduceSynchronizerConfig` /
:class:`~autodist_tpu.strategy.Zero1` (default ``"auto"``):

1. **Accumulation pipelining** (``"pipeline"``): with gradient
   accumulation active, the microbatch loop becomes a software pipeline —
   microbatch *k*'s bucket reduce-scatter/all-reduce is issued in the
   same loop iteration that computes microbatch *k+1*'s backward, so the
   two are data-independent and XLA's latency-hiding scheduler runs them
   concurrently.  Only the LAST microbatch's collective is exposed.
   Under ``"auto"`` only numerics-preserving buckets join: linear
   (``NoneCompressor``) f32 reductions, where mean-of-means equals the
   mean exactly (1e-6 vs the sequential loop).  Explicit ``"pipeline"``
   / ``"full"`` additionally admits quantized-ring compressors
   (int8/fp8, ``quant_ring.WIRE_FORMATS``) under the relaxed contract:
   ONE quantized collective per bucket per microbatch slot, with the
   stage-1 error-feedback residual threaded through the slots (slot
   *k*'s quantization error corrects slot *k+1*'s input, the last
   slot's persists to the next step) — the shape the schedule
   verifier's ``schedule/quantized-pipelined`` rule admits exactly.
   Cast-based compressors (``HorovodCompressor*``) still keep their
   one-collective-per-step contract and fall back (see
   :func:`overlap_drop_reason`).
2. **Ring decomposition** (``"ring"``): buckets at or above
   :data:`RING_THRESHOLD_BYTES` lower their reduce-scatter/all-gather
   into explicit per-chunk ``ppermute`` ring steps
   (:func:`ring_reduce_scatter` / :func:`ring_all_gather`), so the
   scheduler can interleave individual ring legs with pack/unpack and
   optimizer math instead of seeing one monolithic collective.  Buckets
   below the threshold use a latency-optimal ONE-SHOT algorithm
   (single all-gather + local reduction: one launch, no (d−1)-step
   latency chain) when ring mode is requested explicitly.
3. **ZeRO-1 param prefetch** (on under ``"auto"``/``"full"``): the
   post-update parameter all-gather is issued bucket-by-bucket in
   REVERSE bucket order.  Backward produces gradients last-layer-first,
   so under the pipelined schedule the LAST bucket's shard update
   completes first and its gather can start while earlier buckets are
   still reducing; the first-needed (first-bucket) params then land
   last-issued-first-complete-free of the reduce traffic, and the tail
   of the gather overlaps the next step's host→device batch transfer
   under async dispatch.

``"full"`` enables all three; ``"auto"`` enables whichever applies
without changing numerics (pipelining when ``accum_steps > 1`` and the
bucket is uncompressed, ring only for large buckets, prefetch for
ZeRO-1); ``"none"`` restores the phase-serial PR 2 schedule.

Everything here that *decides* (rather than lowers) is a pure function
of plan facts — no mesh, no arrays — so the static analyzer
(``autodist_tpu.analysis``), the cost model, and the runtime share one
rule and cannot drift (the ``bucket_drop_reason`` pattern).
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from autodist_tpu.kernel.synchronization import quant_ring
from autodist_tpu.kernel.synchronization.bucketing import Bucket

#: overlap-mode vocabulary for AllReduce-family plans.
OVERLAP_AUTO = "auto"
OVERLAP_NONE = "none"
OVERLAP_PIPELINE = "pipeline"
OVERLAP_RING = "ring"
OVERLAP_FULL = "full"
OVERLAP_MODES = (OVERLAP_AUTO, OVERLAP_NONE, OVERLAP_PIPELINE,
                 OVERLAP_RING, OVERLAP_FULL)

#: buckets at or above this byte size ring-decompose (below it, the
#: (d−1)-step ring latency chain costs more than it hides; a one-shot
#: gather-and-reduce or XLA's fused collective is latency-optimal).
RING_THRESHOLD_BYTES = 256 << 10

#: fraction of the ZeRO-1 param all-gather the cost model treats as
#: hidden behind the next step's input pipeline / forward prologue when
#: prefetch issue order is active (a ranking constant, not a prediction).
PREFETCH_OVERLAP_FRACTION = 0.5

_LINEAR_COMPRESSORS = ("", "NoneCompressor")


def is_linear_compressor(compressor: str) -> bool:
    return (compressor or "NoneCompressor") in _LINEAR_COMPRESSORS


# -- shared decision rules (pure; consumed by runtime, analysis, cost) -------

def overlap_drop_reason(overlap: str, *, accum_steps: int, compressor: str,
                        bucketable: bool, explicit_path: bool,
                        dtype: str = "float32") -> Optional[str]:
    """Why overlap scheduling does NOT apply to one variable, or None.

    The single eligibility rule shared by the runtime warning, the
    ``sync/overlap-fallback`` analysis WARN, and the cost model's
    overlap-aware estimate — same strings everywhere, so the lint can
    never drift from the lowering (the ``bucket_drop_reason`` pattern).

    ``overlap="none"`` is an explicit opt-out, never a fallback.  Under
    ``"auto"`` a reason is only returned when an overlap win was
    plausibly on the table (explicit path, or accumulation active) but a
    property of THIS variable blocks it — quiet otherwise, so plain
    GSPMD strategies don't warn.
    """
    if overlap not in OVERLAP_MODES:
        return (f"unknown overlap mode {overlap!r}; expected one of "
                f"{OVERLAP_MODES}")
    if overlap == OVERLAP_NONE:
        return None
    if not explicit_path:
        if overlap == OVERLAP_AUTO:
            return None
        return ("GSPMD path (no explicit bucketing): set bucket_bytes, a "
                "compressor, or sync='reduce_scatter' to route the "
                "program through the schedulable shard_map path")
    if not bucketable:
        return ("per-variable fallback path (partitioned or "
                "non-bucketable compressor, e.g. PowerSGD): its "
                "collective is issued once at end of step and cannot "
                "join the overlapped bucket schedule")
    wants_pipeline = overlap in (OVERLAP_PIPELINE, OVERLAP_FULL) \
        or (overlap == OVERLAP_AUTO and accum_steps > 1)
    if wants_pipeline and not is_linear_compressor(compressor):
        if quant_ring.is_quant_ring_compressor(compressor):
            # Quantized-ring compressors own the relaxed contract: one
            # quantized collective per bucket PER MICROBATCH SLOT, with
            # error feedback threaded across slots.  Per-slot
            # quantization adds one rounding per microbatch, so auto
            # (numerics-preserving) keeps the end-of-step collective
            # and only an explicit pipeline/full request opts in.
            if overlap == OVERLAP_AUTO:
                return (f"{compressor} adds one quantization rounding "
                        "per microbatch when pipelined; auto keeps the "
                        "single end-of-step quantized collective (set "
                        "overlap='pipeline' or 'full' to pipeline one "
                        "quantized collective per microbatch slot)")
        else:
            return (f"{compressor} quantizes once per bucket per step; "
                    "per-microbatch pipelined reduction would change the "
                    "wire numerics, so the bucket keeps the end-of-step "
                    "compressed collective")
    if (overlap == OVERLAP_AUTO and wants_pipeline
            and np.dtype(dtype) != np.float32):
        return (f"{np.dtype(dtype).name} bucket: per-microbatch reduction "
                "adds a low-precision rounding per microbatch; auto keeps "
                "the end-of-step collective (set overlap='pipeline' or "
                "'full' to force pipelining)")
    if overlap == OVERLAP_PIPELINE and accum_steps <= 1:
        return ("accum_steps=1: there is no microbatch loop to "
                "pipeline (single-microbatch degenerate case)")
    return None


def pipeline_applies(overlap: str, *, accum_steps: int, compressor: str,
                     bucketable: bool = True, explicit_path: bool = True,
                     dtype: str = "float32") -> bool:
    """Does accumulation pipelining take effect for this variable?"""
    if overlap not in (OVERLAP_AUTO, OVERLAP_PIPELINE, OVERLAP_FULL):
        return False
    if accum_steps <= 1 or not explicit_path or not bucketable:
        return False
    return overlap_drop_reason(
        overlap, accum_steps=accum_steps, compressor=compressor,
        bucketable=bucketable, explicit_path=explicit_path,
        dtype=dtype) is None


def pipeline_eligible(bucket: Bucket, mode: str, accum_steps: int) -> bool:
    """Does THIS bucket join the software pipeline under ``mode``?
    Mirrors :func:`overlap_drop_reason`: under ``auto`` only linear f32
    buckets pipeline (per-microbatch reduction of a bf16 bucket adds a
    low-precision rounding per microbatch, a quantized bucket a
    quantization rounding), while explicit ``pipeline``/``full``
    additionally forces bf16 linear buckets and quantized-ring
    (int8/fp8) buckets — one quantized collective per slot."""
    if accum_steps <= 1:
        return False
    return overlap_drop_reason(
        mode, accum_steps=accum_steps, compressor=bucket.compressor,
        bucketable=True, explicit_path=True, dtype=bucket.dtype) is None \
        and mode in (OVERLAP_AUTO, OVERLAP_PIPELINE, OVERLAP_FULL)


def prefetch_applies(overlap: str, *, sync_mode: str,
                     explicit_path: bool = True) -> bool:
    """Is the reverse-order ZeRO-1 param all-gather issue order active?"""
    return (overlap in (OVERLAP_AUTO, OVERLAP_RING, OVERLAP_FULL)
            and sync_mode == "reduce_scatter" and explicit_path)


def explicit_hint(compressor: str, sync_mode: str, bucket_bytes: int,
                  fused: bool = False, overlap: str = OVERLAP_AUTO,
                  hier: bool = False) -> bool:
    """Mirror of ``explicit_sync.uses_explicit_path`` for ONE plan —
    mesh-free, so the analyzer and cost model can tell whether this
    variable's sync runs on the schedulable shard_map path."""
    if (compressor or "NoneCompressor") != "NoneCompressor":
        return True
    if sync_mode == "reduce_scatter":
        return True
    if int(bucket_bytes or 0) > 0:
        return True
    if overlap in (OVERLAP_PIPELINE, OVERLAP_RING, OVERLAP_FULL):
        return True
    if hier:
        # the GSPMD psum tree cannot express the two-tier ICI+DCN
        # decomposition — a hier request forces the shard_map lowering
        return True
    return bool(fused)


@dataclass(frozen=True)
class OverlapPlan:
    """The resolved step-level overlap schedule."""

    mode: str                      # the winning knob value
    pipeline: bool                 # accumulation pipelining active
    ring: bool                     # ring-decompose large buckets
    one_shot_small: bool           # small buckets use one-shot gather+reduce
    prefetch: bool                 # reverse-order ZeRO-1 param all-gather
    ring_threshold: int = RING_THRESHOLD_BYTES
    #: per-key (var or bucket) drop reasons, for trace-time warnings.
    drops: Tuple[Tuple[str, str], ...] = ()


def resolve_overlap(modes: Sequence[str], *, accum_steps: int,
                    buckets: Sequence[Bucket], d: int,
                    has_rs: bool) -> OverlapPlan:
    """Resolve the per-plan ``overlap=`` values into one step schedule.

    Precedence: an explicit ``"none"`` anywhere wins (safety opt-out),
    then the first explicit non-auto mode in plan order, else ``"auto"``.
    Mechanisms then gate on program facts: pipelining needs
    ``accum_steps > 1`` and at least one linear (uncompressed) bucket;
    ring needs a data axis (> 1 device) to permute over; prefetch needs
    ZeRO-1 buckets.  Explicit ring mode additionally switches
    below-threshold buckets to the one-shot algorithm (under ``auto``
    they keep XLA's fused collective, which is already one launch).
    """
    explicit = [m for m in modes if m and m != OVERLAP_AUTO]
    if OVERLAP_NONE in explicit:
        mode = OVERLAP_NONE
    elif explicit:
        mode = explicit[0]
    else:
        mode = OVERLAP_AUTO

    drops: List[Tuple[str, str]] = []
    pipeline = False
    if mode in (OVERLAP_AUTO, OVERLAP_PIPELINE, OVERLAP_FULL) \
            and accum_steps > 1:
        pipeline = any(pipeline_eligible(b, mode, accum_steps)
                       for b in buckets)
        for b in buckets:
            why = overlap_drop_reason(
                mode, accum_steps=accum_steps, compressor=b.compressor,
                bucketable=True, explicit_path=True, dtype=b.dtype)
            if why is not None:
                drops.append((b.key, why))
    elif mode == OVERLAP_PIPELINE and accum_steps <= 1:
        for b in buckets:
            drops.append((b.key, overlap_drop_reason(
                OVERLAP_PIPELINE, accum_steps=accum_steps,
                compressor=b.compressor, bucketable=True,
                explicit_path=True, dtype=b.dtype)))

    ring = mode in (OVERLAP_AUTO, OVERLAP_RING, OVERLAP_FULL) and d > 1
    one_shot_small = mode in (OVERLAP_RING, OVERLAP_FULL) and d > 1
    prefetch = (mode in (OVERLAP_AUTO, OVERLAP_RING, OVERLAP_FULL)
                and has_rs)
    return OverlapPlan(mode=mode, pipeline=pipeline, ring=ring,
                       one_shot_small=one_shot_small, prefetch=prefetch,
                       drops=tuple((k, w) for k, w in drops if w))


def gather_schedule(buckets: Sequence[Bucket],
                    prefetch: bool) -> List[Bucket]:
    """ZeRO-1 param all-gather issue order.  With prefetch, reverse
    bucket order: backward fills buckets last-layer-first, so the
    highest-``order`` bucket's shard update finishes first and its
    gather is issued before earlier buckets finish reducing — the
    first-needed (lowest-order) params then arrive unobstructed by
    reduce traffic, overlapping the next step's forward prologue."""
    ordered = sorted(buckets, key=lambda b: b.order)
    return list(reversed(ordered)) if prefetch else ordered


# -- ring-decomposed collectives (trace-time, inside shard_map) --------------

def ring_reduce_scatter(vec, axis_name: str, n: int):
    """Sum-reduce-scatter of a flat ``vec`` (length divisible by ``n``)
    as n−1 explicit ``ppermute`` ring steps.

    Device ``r`` ends with ``sum_d chunks_d[r]`` — the same result as
    ``lax.psum_scatter`` up to floating-point summation order, but as
    n−1 individually schedulable sends interleaved with n−1 chunk adds,
    so XLA can slot unrelated compute between the legs.  The partial
    destined for device ``r`` starts at its right neighbor ``r+1`` and
    travels the full ring, accumulating each host's contribution.

    Each leg carries a ``telemetry.sync_span`` named scope, so profiler
    traces attribute device time to individual ring hops.
    """
    import jax.numpy as jnp
    from jax import lax

    from autodist_tpu.telemetry.timeline import sync_span

    if n <= 1:
        return vec
    chunks = jnp.reshape(vec, (n, -1))
    idx = lax.axis_index(axis_name)
    perm = [(i, (i + 1) % n) for i in range(n)]
    acc = jnp.take(chunks, (idx - 1) % n, axis=0)
    for s in range(1, n):
        with sync_span(f"ring_reduce_scatter/leg{s}"):
            acc = lax.ppermute(acc, axis_name, perm)
            acc = acc + jnp.take(chunks, (idx - 1 - s) % n, axis=0)
    return acc


def ring_all_gather(shard, axis_name: str, n: int):
    """All-gather of per-device ``shard``s as n−1 ``ppermute`` ring steps;
    returns the flat concatenation in device order (what
    ``lax.all_gather(..., tiled=True)`` produces)."""
    import jax.numpy as jnp
    from jax import lax

    from autodist_tpu.telemetry.timeline import sync_span

    if n <= 1:
        return shard
    idx = lax.axis_index(axis_name)
    perm = [(i, (i + 1) % n) for i in range(n)]
    out = jnp.zeros((n,) + shard.shape, shard.dtype)
    out = out.at[idx].set(shard)
    cur = shard
    for s in range(1, n):
        with sync_span(f"ring_all_gather/leg{s}"):
            cur = lax.ppermute(cur, axis_name, perm)
            # after s hops rightward, ``cur`` originated at device idx − s
            out = out.at[(idx - s) % n].set(cur)
    return jnp.reshape(out, (n * shard.shape[0],) + shard.shape[1:])


def ring_all_reduce_mean(vec, axis_name: str, n: int):
    """Mean all-reduce = ring reduce-scatter + ring all-gather (the
    standard 2(n−1)-step decomposition, each leg schedulable)."""
    if n <= 1:
        return vec
    shard = ring_reduce_scatter(vec, axis_name, n) / n
    return ring_all_gather(shard, axis_name, n)


def one_shot_all_reduce_mean(vec, axis_name: str, n: int):
    """Latency-optimal mean all-reduce for SMALL buckets: one all-gather
    launch + a local reduction.  Moves (n−1)·n/(n·…) ≈ n× the ring's
    bytes but pays ONE collective latency instead of 2(n−1) ring steps —
    the right trade below :data:`RING_THRESHOLD_BYTES`."""
    import jax.numpy as jnp
    from jax import lax

    from autodist_tpu.telemetry.timeline import sync_span

    if n <= 1:
        return vec
    with sync_span("one_shot_all_reduce"):
        gathered = lax.all_gather(vec, axis_name, axis=0)
        return jnp.sum(gathered, axis=0) / n


def bucket_reduce_fn(bucket: Bucket, plan: OverlapPlan, axis_name: str,
                     n: int, alg: Optional[str] = None) -> Callable:
    """The mean-reduction lowering for one UNCOMPRESSED bucket under
    ``plan``: ring decomposition at/above the threshold, one-shot below
    it when explicitly requested, XLA's fused collective otherwise.
    Returns ``vec -> mean(vec)`` for ``all_reduce`` buckets and
    ``vec -> local shard of mean(vec)`` for ``reduce_scatter`` ones.

    ``alg`` pins the algorithm a schedule-IR bucket node resolved to
    (``"ring"`` | ``"one_shot"`` | ``"fused"`` — the explicit path
    passes ``ScheduleIR.reduce_alg``); None re-derives it from ``plan``
    with the identical rule, so the two can never disagree."""
    from jax import lax

    from autodist_tpu.kernel.synchronization.bucketing import (
        MODE_REDUCE_SCATTER,
    )
    from autodist_tpu.telemetry.timeline import sync_span

    rs = bucket.mode == MODE_REDUCE_SCATTER
    if alg is None:
        if plan.ring and n > 1 and bucket.nbytes >= plan.ring_threshold:
            alg = "ring"
        elif plan.one_shot_small and n > 1 and not rs:
            alg = "one_shot"
        else:
            alg = "fused"

    def named(leg: str, fn):
        # Named scope around the fused-collective lowerings too, so a
        # profiler trace splits reduce-scatter from all-gather from
        # all-reduce time regardless of which algorithm lowered the leg
        # (ring legs additionally carry their own per-hop scopes).
        def wrapped(v):
            with sync_span(leg):
                return fn(v)
        return wrapped

    if alg == "ring" and n > 1:
        if rs:
            return named("reduce_scatter",
                         lambda v: ring_reduce_scatter(v, axis_name, n) / n)
        return named("all_reduce",
                     lambda v: ring_all_reduce_mean(v, axis_name, n))
    if alg == "one_shot" and n > 1 and not rs:
        return named("all_reduce",
                     lambda v: one_shot_all_reduce_mean(v, axis_name, n))
    if rs:
        return named("reduce_scatter", lambda v: lax.psum_scatter(
            v, axis_name, scatter_dimension=0, tiled=True) / n)
    return named("all_reduce", lambda v: lax.pmean(v, axis_name))


# -- hierarchical ICI+DCN collectives (trace-time, inside shard_map) ---------

def hier_groups(d: int, s: int) -> Tuple[List[List[int]], List[List[int]]]:
    """``(within, across)`` axis-index groups for a ``d``-device data
    axis factored into ``s`` slices of ``d // s`` devices each, laid out
    slice-major (device ``g * d_in + i`` is position ``i`` of slice
    ``g``).  ``within`` groups share a slice (ICI-tier legs); ``across``
    groups hold the same within-slice position in every slice (DCN-tier
    legs — exactly one participant per slice)."""
    d_in = d // s
    within = [[g * d_in + i for i in range(d_in)] for g in range(s)]
    across = [[g * d_in + i for g in range(s)] for i in range(d_in)]
    return within, across


def _dcn_quantized_sum(sh, axis_name: str, s: int, fmt,
                       across: List[List[int]]):
    """The int8/fp8 DCN leg: quantize the local partial on the shared
    per-chunk scale grid (:mod:`quant_ring`'s one quantization rule),
    all-gather payload + scales over the ``across`` groups, dequantize
    every slice's contribution and sum.  Wire per device ≈
    ``s × (1 byte/elem + scales)`` instead of ``s × 4`` — the honest
    bytes the schedule IR's ``dcn_all_reduce``/``dcn_exchange`` legs
    book when the bucket carries a DCN wire compressor."""
    import jax.numpy as jnp
    from jax import lax

    from autodist_tpu.kernel.synchronization import quant_ring

    q, scales, _sat = quant_ring.quantize_blocks(sh, fmt)
    qs = lax.all_gather(q, axis_name, axis=0, axis_index_groups=across)
    ss = lax.all_gather(scales, axis_name, axis=0,
                        axis_index_groups=across)
    out = jnp.zeros_like(sh)
    for j in range(s):
        out = out + quant_ring.dequantize_blocks(qs[j], ss[j])
    return out


def hier_bucket_reduce_fn(bucket: Bucket, axis_name: str, d: int, s: int,
                          *, dcn_wire=None) -> Callable:
    """Two-level mean reduction for one bucket on a ``d``-device axis
    factored into ``s`` slices: ICI reduce-scatter within each slice,
    one cross-slice leg over DCN, then (for ``all_reduce`` buckets) an
    ICI all-gather back.  Same contract as :func:`bucket_reduce_fn` —
    ``vec -> mean(vec)`` for AR buckets, ``vec -> local shard of
    mean(vec)`` for reduce-scatter ones (device ``g·d_in + i`` ends
    holding global chunk ``i·s + g``; the explicit path's owner-index
    arithmetic and two-stage gather account for that permutation).

    ``dcn_wire`` (a :class:`quant_ring.WireFormat` or None) quantizes
    only the cross-slice leg — the narrow DCN hop — leaving ICI legs
    full precision."""
    import jax.numpy as jnp
    from jax import lax

    from autodist_tpu.kernel.synchronization.bucketing import (
        MODE_REDUCE_SCATTER,
    )
    from autodist_tpu.telemetry.timeline import sync_span

    rs = bucket.mode == MODE_REDUCE_SCATTER
    within, across = hier_groups(d, s)

    def reduce_ar(v):
        with sync_span("hier_reduce_scatter"):
            sh = lax.psum_scatter(v, axis_name, scatter_dimension=0,
                                  tiled=True, axis_index_groups=within)
        with sync_span("dcn_all_reduce"):
            if dcn_wire is not None:
                sh = _dcn_quantized_sum(sh, axis_name, s, dcn_wire,
                                        across)
            else:
                sh = lax.psum(sh, axis_name, axis_index_groups=across)
        sh = sh / d
        with sync_span("hier_all_gather"):
            return lax.all_gather(sh, axis_name, axis=0, tiled=True,
                                  axis_index_groups=within)

    def reduce_rs(v):
        with sync_span("hier_reduce_scatter"):
            sh = lax.psum_scatter(v, axis_name, scatter_dimension=0,
                                  tiled=True, axis_index_groups=within)
        with sync_span("dcn_exchange"):
            if dcn_wire is not None:
                sh = _dcn_quantized_sum(sh, axis_name, s, dcn_wire,
                                        across)
                sh = jnp.reshape(sh, (s, -1))[
                    lax.axis_index(axis_name) // (d // s)]
            else:
                sh = lax.psum_scatter(sh, axis_name, scatter_dimension=0,
                                      tiled=True,
                                      axis_index_groups=across)
        return sh / d

    return reduce_rs if rs else reduce_ar


def hier_gather_fn(axis_name: str, d: int, s: int) -> Callable:
    """ZeRO-1 param reconstruction for hier buckets: the within+across
    scatters leave device ``g·d_in + i`` holding global chunk
    ``i·s + g``, so gathering over the ``across`` groups first (chunks
    ``i·s .. i·s+s-1`` in order) then over ``within`` (blocks ``0·s ..``
    upward) re-assembles the flat vector in original chunk order."""
    from jax import lax

    from autodist_tpu.telemetry.timeline import sync_span

    within, across = hier_groups(d, s)

    def gather(shard):
        with sync_span("hier_all_gather/dcn"):
            part = lax.all_gather(shard, axis_name, axis=0, tiled=True,
                                  axis_index_groups=across)
        with sync_span("hier_all_gather/ici"):
            return lax.all_gather(part, axis_name, axis=0, tiled=True,
                                  axis_index_groups=within)

    return gather


# -- accumulation pipelining (trace-time, inside shard_map) ------------------

def microbatch_slices(length: int, accum: int) -> List[Tuple[int, int]]:
    """Static ``(offset, rows)`` per microbatch.  Even split when
    ``accum`` divides ``length``; otherwise the first ``length % accum``
    microbatches carry one extra row (the uneven tail — every row is
    consumed exactly once, and contributions are weighted by rows)."""
    if accum > length:
        raise ValueError(
            f"accum_steps={accum} exceeds the local batch rows ({length})")
    base, rem = divmod(length, accum)
    sizes = [base + 1] * rem + [base] * (accum - rem)
    out, off = [], 0
    for s in sizes:
        out.append((off, s))
        off += s
    return out


def pipelined_accumulate(single_vg: Callable, accum: int, has_aux: bool,
                         pipe_buckets: Sequence[Bucket],
                         reduce_fns: Dict[str, Callable],
                         reduced_sizes: Dict[str, int],
                         params, batch,
                         quant_fns: Optional[Dict[str, Callable]] = None,
                         quant_states: Optional[Dict] = None,
                         stamps: Optional[Dict[str, tuple]] = None):
    """Software-pipelined gradient accumulation over ``accum``
    microbatches: iteration *k* issues the bucket collectives for
    microbatch *k−1*'s gradients and THEN computes microbatch *k*'s
    backward — the two are data-independent, so the collective overlaps
    the backward and only the final microbatch's reduction is exposed.

    Returns ``(loss, aux, grads, reduced, quant_state, quant_sat)``:

    * ``loss`` — the row-weighted mean microbatch loss (== the full
      local-batch mean for row-mean losses);
    * ``aux`` — per-microbatch auxes stacked on a leading [accum] axis
      (the :func:`_accumulate_grads` contract), or None;
    * ``grads`` — the row-weighted mean LOCAL gradient tree (consumed by
      the per-variable fallback tier and non-pipelined compressed
      buckets — their single end-of-step collective is unchanged);
    * ``reduced`` — ``{bucket.key: reduced mean vector or shard}`` for
      every bucket in ``pipe_buckets``, already globally averaged by
      its ``reduce_fns[key]`` / ``quant_fns[key]`` leg;
    * ``quant_state`` — the final error-feedback residual per quantized
      pipelined bucket (slot *k*'s quantization error corrected slot
      *k+1* inside the step; the LAST slot's residual persists to the
      next step's first slot via sync_state);
    * ``quant_sat`` — ``{key: f32 count}`` of post-quantization
      saturation events summed over this step's slots (GradHealth).

    Exactness: a linear ``reduce_fns`` leg makes the weighted sum of
    per-microbatch means equal the mean of the weighted gradient sum —
    bit-close (summation order) to the sequential accumulate-then-reduce
    schedule.  A quantized ``quant_fns`` leg (``quant_fns[key](vec,
    state) -> (reduced, new_state, sat)``; int8/fp8 buckets under
    explicit ``overlap="pipeline"``/``"full"``) issues ONE quantized
    collective per slot — the relaxed ``schedule/quantized-pipelined``
    contract — trading one extra quantization rounding per microbatch,
    error-compensated across slots, for a fully hidden reduce leg.

    Equal microbatches run as a ``lax.scan`` whose carries (gradient
    accumulators, the previous microbatch's packed buckets, and the
    quantized residuals) are donated by XLA's loop buffer reuse; an
    uneven tail unrolls the loop (shapes differ per microbatch) with
    the same weighting.

    ``stamps`` (``{bucket.key: (leg-id template, leg kind)}``) arms
    flight-recorder leg cursors (telemetry/flightrec.py): each slot's
    bucket reduce stamps a host-callback cursor whose ``{slot}``
    placeholder resolves to the live microbatch index — the per-slot
    leg id the hang localizer diffs against the happens-before
    relation.  None (the default off-TPU) compiles no callbacks.
    """
    import jax
    import jax.numpy as jnp
    from jax import lax

    from autodist_tpu.graph_item import path_name
    from autodist_tpu.kernel.synchronization.bucketing import pack_bucket

    quant_fns = quant_fns or {}
    qstate0 = dict(quant_states or {})
    leaves = jax.tree_util.tree_leaves(batch)
    if not leaves:
        raise ValueError("pipelined accumulation needs a non-empty batch")
    length = leaves[0].shape[0]
    slices = microbatch_slices(length, accum)
    even = len({rows for _, rows in slices}) == 1
    weights = [rows / length for _, rows in slices]

    def run_vg(mb):
        loss, aux, grads = single_vg(params, mb)
        flat, treedef = jax.tree_util.tree_flatten_with_path(grads)
        by_name = {path_name(p): g for p, g in flat}
        packed = {b.key: pack_bucket(b, [by_name[n] for n in b.names])
                  for b in pipe_buckets}
        return loss, aux, grads, packed

    def f32(tree):
        return jax.tree_util.tree_map(
            lambda x: x.astype(jnp.float32), tree)

    def add_scaled(acc, tree, w):
        return jax.tree_util.tree_map(
            lambda a, x: a + w * x.astype(jnp.float32), acc, tree)

    leg_stamps = dict(stamps or {})

    def reduce_packed(packed, qstate, sat, slot=None):
        red = {}
        new_q = dict(qstate)
        new_sat = dict(sat)
        for k, v in packed.items():
            if slot is not None and k in leg_stamps:
                from autodist_tpu.telemetry import flightrec

                lid, lkind = leg_stamps[k]
                flightrec.traced_stamp(lid, slot=slot, leg_kind=lkind)
            if k in quant_fns:
                red[k], new_q[k], cnt = quant_fns[k](v, qstate.get(k))
                new_sat[k] = new_sat[k] + cnt
            else:
                red[k] = reduce_fns[k](v)
        return red, new_q, new_sat

    off0, rows0 = slices[0]
    mb0 = jax.tree_util.tree_map(
        lambda x: lax.dynamic_slice_in_dim(x, off0, rows0, 0), batch)
    loss0, aux0, g0, packed0 = run_vg(mb0)

    g_shapes = jax.tree_util.tree_map(
        lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), g0)
    loss_acc = weights[0] * loss0.astype(jnp.float32)
    g_acc = add_scaled(jax.tree_util.tree_map(
        lambda s: jnp.zeros(s.shape, jnp.float32), g_shapes), g0, weights[0])
    red_acc = {b.key: jnp.zeros((reduced_sizes[b.key],), jnp.float32)
               for b in pipe_buckets}
    sat_acc = {k: jnp.float32(0.0) for k in quant_fns}
    auxes = [aux0] if has_aux else None

    if even and accum > 1:
        w = weights[0]  # all equal
        mbs = jax.tree_util.tree_map(
            lambda x: x[rows0:].reshape((accum - 1, rows0) + x.shape[1:]),
            batch)

        def body(carry, x):
            mb, idx = x
            loss_a, g_a, red_a, prev, qs, sat_a = carry
            # the collective for the PREVIOUS microbatch's buckets: no
            # data dependence on this microbatch's backward below, so
            # the scheduler overlaps them.  ``idx`` is the PREVIOUS
            # microbatch's slot — what a flight-recorder stamp records.
            red, qs, sat_a = reduce_packed(
                prev, qs, sat_a, slot=idx if leg_stamps else None)
            red_a = {k: red_a[k] + w * red[k].astype(jnp.float32)
                     for k in red_a}
            loss, aux, g, packed = run_vg(mb)
            loss_a = loss_a + w * loss.astype(jnp.float32)
            g_a = add_scaled(g_a, g, w)
            return (loss_a, g_a, red_a, packed, qs, sat_a), aux

        (loss_acc, g_acc, red_acc, prev, qstate0, sat_acc), scanned = \
            lax.scan(body, (loss_acc, g_acc, red_acc, packed0, qstate0,
                            sat_acc), (mbs, jnp.arange(accum - 1)))
        # the one exposed reduction
        red, qstate0, sat_acc = reduce_packed(
            prev, qstate0, sat_acc,
            slot=accum - 1 if leg_stamps else None)
        red_acc = {k: red_acc[k] + w * red[k].astype(jnp.float32)
                   for k in red_acc}
        if has_aux:
            aux = jax.tree_util.tree_map(
                lambda a, rest: jnp.concatenate([a[None], rest]),
                aux0, scanned)
        else:
            aux = None
    else:
        prev, prev_w = packed0, weights[0]
        for k in range(1, accum):
            red, qstate0, sat_acc = reduce_packed(
                prev, qstate0, sat_acc,
                slot=k - 1 if leg_stamps else None)
            red_acc = {key: red_acc[key] + prev_w * red[key].astype(
                jnp.float32) for key in red_acc}
            off, rows = slices[k]
            mb = jax.tree_util.tree_map(
                lambda x: lax.dynamic_slice_in_dim(x, off, rows, 0), batch)
            loss, aux_k, g, packed = run_vg(mb)
            loss_acc = loss_acc + weights[k] * loss.astype(jnp.float32)
            g_acc = add_scaled(g_acc, g, weights[k])
            prev, prev_w = packed, weights[k]
            if has_aux:
                auxes.append(aux_k)
        red, qstate0, sat_acc = reduce_packed(
            prev, qstate0, sat_acc,
            slot=accum - 1 if leg_stamps else None)
        red_acc = {key: red_acc[key] + prev_w * red[key].astype(jnp.float32)
                   for key in red_acc}
        if has_aux:
            aux = jax.tree_util.tree_map(
                lambda *xs: jnp.stack(xs), *auxes)
        else:
            aux = None

    grads = jax.tree_util.tree_map(
        lambda g, s: g.astype(s.dtype), g_acc, g_shapes)
    reduced = {b.key: red_acc[b.key].astype(np.dtype(b.dtype))
               for b in pipe_buckets}
    return loss_acc, aux, grads, reduced, qstate0, sat_acc
