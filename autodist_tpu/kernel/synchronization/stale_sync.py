"""Bounded staleness (SSP) and proxy variables under bulk-synchronous XLA.

The reference implements stale-synchronous parallel with token FIFOQueues on
the PS: a worker may dequeue up to ``staleness`` tokens ahead of the chief's
enqueues, so fast workers run at most ``staleness`` steps ahead of the
slowest (``ps_synchronizer.py:385-455``; integration case c9 asserts exactly
this run-ahead bound).  XLA programs are bulk-synchronous — per-worker step
counts cannot diverge inside one jitted SPMD program — so the TPU-native
translation models the *observable* of SSP instead of its mechanism:

    a gradient computed at step t is applied at step t + s.

That is the delayed-gradient pipeline: a rolling queue of ``s`` in-flight
gradient pytrees rides in the synchronizer state; each step pops the oldest
gradient (zeros during the first ``s`` warm-up steps — "no worker has
reported yet"), applies it, and pushes the fresh one.  Fast workers running
``s`` ahead of the PS and the PS applying s-step-old gradients are the same
semantics viewed from opposite ends; convergence behavior (the reason SSP
exists) is identical, and unlike token queues it is deterministic and
profile-friendly.  Per-variable staleness from the strategy is honored:
variables with ``staleness == 0`` keep their fresh gradient.

Proxy variables (reference ``kernel/common/proxy_variable.py:46-190``): a
worker-local mirror of a PS variable, refreshed after each update, so replica
reads don't re-fetch from the PS.  Under GSPMD a replicated read *is* the
all-gather XLA inserts, so a per-step proxy is free/implicit; the useful
TPU analog is a *periodically refreshed* mirror — gradients are computed
against a cached replicated copy refreshed every ``refresh_period`` steps,
cutting the per-step all-gather traffic for weight-update-sharded variables
at the price of (further) bounded parameter staleness.  ``local_replication``
in the strategy opts a variable in; ``AUTODIST_PROXY_REFRESH`` (default 1 =
reference semantics, always fresh) sets the period.
"""
from __future__ import annotations

import os
from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from autodist_tpu.graph_item import GraphItem, path_name
from autodist_tpu.strategy.compiler import CompiledStrategy
from autodist_tpu.utils import logging


def stale_var_depths(compiled: CompiledStrategy) -> Dict[str, int]:
    """Per-variable staleness depths (>0 only)."""
    return {name: plan.staleness
            for name, plan in compiled.var_plans.items() if plan.staleness > 0}


def proxy_vars(compiled: CompiledStrategy) -> Tuple[str, ...]:
    return tuple(name for name, plan in compiled.var_plans.items()
                 if plan.local_replication)


def proxy_refresh_period() -> int:
    return max(1, int(os.environ.get("AUTODIST_PROXY_REFRESH", "1")))


def uses_stale_path(compiled: CompiledStrategy) -> bool:
    """Whether the step needs synchronizer state: any stale variable, or any
    proxy variable with a refresh period > 1."""
    if stale_var_depths(compiled):
        return True
    return bool(proxy_vars(compiled)) and proxy_refresh_period() > 1


class StaleSync:
    """Builds the gradient-delay queue and proxy cache around a step.

    Used by the GraphTransformer: ``init_state(params)`` makes the sync-state
    pytree; ``before_grads(params, state)`` substitutes proxy mirrors;
    ``exchange(grads, state)`` returns (grads-to-apply, new-state);
    ``after_update(params, state)`` refreshes proxy mirrors.
    """

    def __init__(self, gi: GraphItem, compiled: CompiledStrategy):
        self.compiled = compiled
        self.depths = stale_var_depths(compiled)
        self.refresh = proxy_refresh_period()
        self.proxied = proxy_vars(compiled) if self.refresh > 1 else ()
        if self.depths:
            logging.info("SSP: delayed-gradient pipeline active, depths=%s",
                         self.depths)
        if self.proxied:
            logging.info("proxy variables (refresh every %d steps): %s",
                         self.refresh, list(self.proxied))

    # -- state -------------------------------------------------------------
    def init_state(self, params: Any) -> Dict[str, Any]:
        leaves = {path_name(p): leaf for p, leaf in
                  jax.tree_util.tree_flatten_with_path(params)[0]}
        queue = {}
        for name, s in self.depths.items():
            leaf = leaves[name]
            queue[name] = jnp.zeros((s,) + tuple(leaf.shape),
                                    dtype=leaf.dtype)
        cache = {name: jnp.asarray(leaves[name]) for name in self.proxied}
        return {"queue": queue, "cache": cache,
                "step": jnp.zeros((), jnp.int32)}

    def state_shardings(self, mesh, params) -> Any:
        """Sharding tree matching init_state's output: queue leaves follow
        the variable's opt layout with a leading (stacked) axis; caches are
        replicated mirrors; the counter replicates."""
        rep = NamedSharding(mesh, P())
        queue_sh = {}
        for name in self.depths:
            spec = self.compiled.var_plans[name].opt_spec
            queue_sh[name] = NamedSharding(mesh, P(None, *spec))
        cache_sh = {name: rep for name in self.proxied}
        return {"queue": queue_sh, "cache": cache_sh, "step": rep}

    # -- step hooks --------------------------------------------------------
    def before_grads(self, params: Any, state: Dict[str, Any]) -> Any:
        """Parameters to differentiate against: proxied vars read their
        (possibly stale) mirror."""
        if not self.proxied:
            return params
        cache = state["cache"]

        def swap(path, leaf):
            name = path_name(path)
            return cache[name] if name in cache else leaf

        return jax.tree_util.tree_map_with_path(swap, params)

    def exchange(self, grads: Any, state: Dict[str, Any]
                 ) -> Tuple[Any, Dict[str, Any]]:
        """Rolls stale variables' gradients through their delay queues."""
        if not self.depths:
            return grads, state
        queue = dict(state["queue"])

        def roll(path, g):
            name = path_name(path)
            if name not in queue:
                return g
            q = queue[name]
            delayed = q[0]
            queue[name] = jnp.concatenate([q[1:], g[None].astype(q.dtype)],
                                          axis=0)
            return delayed.astype(g.dtype)

        grads = jax.tree_util.tree_map_with_path(roll, grads)
        return grads, {**state, "queue": queue}

    def after_update(self, params: Any, state: Dict[str, Any]
                     ) -> Dict[str, Any]:
        """Advance the step counter; refresh proxy mirrors on period."""
        step = state["step"]
        new_state = {**state, "step": step + 1}
        if self.proxied:
            leaves = {path_name(p): leaf for p, leaf in
                      jax.tree_util.tree_flatten_with_path(params)[0]}
            fresh = {name: leaves[name] for name in state["cache"]}
            # lax.cond (not where): the fresh branch's all-gather of
            # weight-update-sharded params into the replicated cache must
            # only execute on refresh steps — that traffic saving is the
            # whole point of refresh_period > 1.
            new_state["cache"] = jax.lax.cond(
                (step + 1) % self.refresh == 0,
                lambda: fresh,
                lambda: dict(state["cache"]))
        return new_state
