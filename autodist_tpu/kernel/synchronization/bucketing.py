"""Gradient-bucket planning for the explicit sync path.

One collective per VARIABLE is the reference's layout (an all-reduce per
``tf.Variable``, ``all_reduce_synchronizer.py:100-127``); at transformer
scale that is hundreds of launch latencies on the sync critical path.
This module plans **size-capped, dtype-grouped buckets**: gradients are
flattened and concatenated into contiguous vectors of at most
``bucket_bytes``, and the explicit path issues ONE collective per bucket
(the scoped-allocator/Horovod-fusion idea, done at trace time).  Buckets
are the unit the whole sync stack now composes over:

* compressors quantize **per bucket**, not per variable (the EQuARX
  formulation, arXiv:2506.17615 — one scale grid per collective);
* ZeRO-1 weight-update sharding (arXiv:2004.13336) reduce-scatters each
  bucket, updates the local shard, and all-gathers fresh parameters —
  bucket totals are padded to a multiple of the data-axis size so the
  uneven tail shards evenly;
* per-bucket chains are data-independent, so XLA's scheduler can overlap
  one bucket's collective with another bucket's update math (and with
  whatever backward compute does not feed that bucket).

The planning rules here are PURE functions of ``(name, shape, dtype,
compressor, group, mode)`` — no mesh, no arrays — so the static analyzer
(``autodist_tpu.analysis``) and the cost model share the exact planner
the runtime executes and can never drift from it.

Bucket keying: ``(mode, dtype, compressor, group)``.  Mixed dtypes never
share a bucket (a fused vector must be homogeneous — bf16 and f32 grads
concatenate into separate buckets), different compressors never share a
scale grid, and the strategy's ``group`` ids are respected so explicit
``fused=True`` groups keep their collective identity.  Within a key,
variables fill greedily in catalog order until ``bucket_bytes`` is
reached; a single variable larger than the cap gets a bucket of its own
(never split — slicing one gradient across collectives would serialize
its producer).
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

#: default bucket size cap; chosen so a handful of buckets cover a
#: transformer block (big enough to amortize launch latency, small
#: enough that the first collective starts long before the last
#: gradient is produced).
DEFAULT_BUCKET_BYTES = 4 << 20

#: sync-mode vocabulary for AllReduce-family plans.
MODE_ALL_REDUCE = "all_reduce"
MODE_REDUCE_SCATTER = "reduce_scatter"
SYNC_MODES = (MODE_ALL_REDUCE, MODE_REDUCE_SCATTER)


@dataclass(frozen=True)
class BucketVar:
    """One variable's slot inside a bucket."""

    name: str
    shape: Tuple[int, ...]
    offset: int          # element offset into the bucket vector

    @property
    def size(self) -> int:
        return int(np.prod(self.shape or (1,)))


@dataclass(frozen=True)
class Bucket:
    """A planned contiguous gradient bucket (one collective)."""

    key: str             # stable id, also the sync/opt-state dict key
    mode: str            # MODE_ALL_REDUCE | MODE_REDUCE_SCATTER
    dtype: str
    compressor: str
    group: int
    vars: Tuple[BucketVar, ...]
    total: int           # sum of member sizes (elements, unpadded)
    padded_total: int    # total rounded up to the shard divisor
    # Plan position (catalog/flatten order) — the scheduling metadata the
    # overlap scheduler keys on: backward produces gradients roughly in
    # REVERSE ``order``, so the ZeRO-1 param prefetch issues all-gathers
    # highest-order-first (``overlap.gather_schedule``) and the
    # first-needed (lowest-order) params land clear of reduce traffic.
    order: int = 0

    @property
    def names(self) -> Tuple[str, ...]:
        return tuple(v.name for v in self.vars)

    @property
    def nbytes(self) -> int:
        return self.total * np.dtype(self.dtype).itemsize

    @property
    def pad(self) -> int:
        return self.padded_total - self.total


def bucket_drop_reason(placement: Sequence, padded: bool,
                       compressor: str) -> Optional[str]:
    """Why a variable cannot join a gradient bucket, or None when it can.

    Mirrors the runtime eligibility in ``explicit_sync`` and is consumed
    by the static analyzer so the lint and the lowering share one rule
    (the ``partition_drop_reason`` pattern).  ``placement`` is the
    non-trivial part of the param layout ([(dim, axis), ...] or a
    PartitionSpec's entries); partitioned variables own a per-shard
    collective and never fuse into a flat bucket.
    """
    if list(placement):
        return "partitioned/structurally sharded (owns a per-shard collective)"
    if padded:
        return "pad-to-divisible sharding"
    from autodist_tpu.kernel.synchronization.compressor import _REGISTRY
    cls = _REGISTRY.get(compressor or "NoneCompressor")
    if cls is None:
        return f"unknown compressor {compressor!r}"
    if not getattr(cls, "bucketable", True):
        return (f"{compressor} state is not flat-composable "
                f"(e.g. PowerSGD low-rank factors)")
    return None


def assign_buckets(entries: Sequence[Tuple[str, Tuple[int, ...], str, str,
                                           int, str]],
                   bucket_bytes: int = DEFAULT_BUCKET_BYTES,
                   shard_divisor: int = 1) -> List[Bucket]:
    """Plan buckets over ``entries`` = [(name, shape, dtype, compressor,
    group, mode), ...] in catalog (flatten) order.

    ``bucket_bytes`` caps each bucket's UNPADDED byte size; 0 or None
    means the default cap.  ``shard_divisor`` (the data-axis size for
    reduce-scatter mode) rounds each bucket's ``padded_total`` up so the
    vector splits into equal shards; the zero-padded tail is how the
    uneven remainder is handled.
    """
    cap = int(bucket_bytes) if bucket_bytes else DEFAULT_BUCKET_BYTES
    d = max(int(shard_divisor), 1)
    open_buckets: Dict[Tuple, List[BucketVar]] = {}
    order: List[Tuple] = []          # first-touch order of keys
    closed: List[Tuple[Tuple, List[BucketVar]]] = []
    seq: Dict[Tuple, int] = {}

    def close(bkey: Tuple) -> None:
        members = open_buckets.pop(bkey, None)
        if members:
            closed.append((bkey + (seq[bkey],), members))
            seq[bkey] = seq[bkey] + 1

    for name, shape, dtype, compressor, group, mode in entries:
        if mode not in SYNC_MODES:
            raise ValueError(f"unknown sync mode {mode!r} for {name}; "
                             f"expected one of {SYNC_MODES}")
        size = int(np.prod(tuple(shape) or (1,)))
        nbytes = size * np.dtype(dtype).itemsize
        bkey = (mode, str(dtype), compressor or "NoneCompressor", int(group))
        if bkey not in seq:
            seq[bkey] = 0
            order.append(bkey)
        members = open_buckets.get(bkey)
        current = sum(v.size for v in members) if members else 0
        current_bytes = current * np.dtype(dtype).itemsize
        if members and current_bytes + nbytes > cap:
            close(bkey)   # cap reached: next member starts a fresh bucket
            members = None
            current = 0
        if members is None:
            members = open_buckets.setdefault(bkey, [])
        members.append(BucketVar(name=name, shape=tuple(shape),
                                 offset=current))
        # a single oversized variable still gets exactly one bucket
        if (current + size) * np.dtype(dtype).itemsize >= cap:
            close(bkey)
    for bkey in order:
        close(bkey)

    buckets: List[Bucket] = []
    for order, ((mode, dtype, compressor, group, idx), members) \
            in enumerate(closed):
        total = sum(v.size for v in members)
        padded = -(-total // d) * d
        # The compressor is part of the bucket IDENTITY (it is part of
        # the grouping key above), so it must be part of the key too:
        # without it, a compressed and an uncompressed bucket of the
        # same (mode, dtype, group) collide — and the key is the
        # sync-state / reduce-fn / opt-shard dict key downstream.
        # Uncompressed buckets keep the historical short form (stable
        # checkpoint bucket layouts for every linear plan).
        comp_tag = "" if compressor in ("", "NoneCompressor") \
            else f"{compressor}:"
        buckets.append(Bucket(
            key=f"{mode}:{dtype}:{comp_tag}g{group}:{idx}",
            mode=mode, dtype=dtype, compressor=compressor, group=int(group),
            vars=tuple(members), total=total, padded_total=padded,
            order=order))
    return buckets


# -- pack/unpack (trace-time helpers) ----------------------------------------

def pack_bucket(bucket: Bucket, leaves: Sequence) -> "jax.Array":
    """Concatenate ``leaves`` (bucket order) into the padded flat vector."""
    import jax.numpy as jnp

    parts = [jnp.ravel(x) for x in leaves]
    if bucket.pad:
        parts.append(jnp.zeros((bucket.pad,),
                               dtype=np.dtype(bucket.dtype)))
    return jnp.concatenate(parts) if len(parts) > 1 else parts[0]


def unpack_bucket(bucket: Bucket, vec) -> List:
    """Split the flat vector back into member-shaped arrays."""
    import jax.numpy as jnp

    out = []
    for v in bucket.vars:
        out.append(jnp.reshape(vec[v.offset:v.offset + v.size], v.shape))
    return out
