"""Sharding-tree utilities for the kernel layer.

The reference's kernel layer rewires TF graphs per variable
(``autodist/kernel/common/utils.py:24-272``); the TPU-native kernel instead
manipulates *sharding trees* — pytrees of ``PartitionSpec``/``NamedSharding``
aligned with parameter and optimizer-state pytrees.
"""
from __future__ import annotations

from typing import Any, Dict

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from autodist_tpu.graph_item import path_name


def spec_tree_for_params(params: Any, var_specs: Dict[str, P],
                         default: P = P()) -> Any:
    """params-shaped pytree of PartitionSpecs, looked up by variable name."""

    def spec_of(path, leaf):
        return var_specs.get(path_name(path), default)

    return jax.tree_util.tree_map_with_path(spec_of, params)


def sharding_tree(mesh: Mesh, spec_tree: Any) -> Any:
    return jax.tree_util.tree_map(
        lambda s: NamedSharding(mesh, s), spec_tree,
        is_leaf=lambda x: isinstance(x, P))


def _shapes_compatible(node: Any, params: Any) -> bool:
    """Leaf-wise shape equality between two isomorphic pytrees."""
    node_leaves = jax.tree_util.tree_leaves(node)
    param_leaves = jax.tree_util.tree_leaves(params)
    if len(node_leaves) != len(param_leaves):
        return False
    for a, b in zip(node_leaves, param_leaves):
        sa = tuple(getattr(a, "shape", ()) or ())
        sb = tuple(getattr(b, "shape", ()) or ())
        if sa != sb:
            return False
    return True


def opt_spec_tree(opt_state: Any, params: Any, param_block_specs: Any) -> Any:
    """Build a PartitionSpec tree for an optax optimizer state.

    Any sub-pytree of ``opt_state`` that is isomorphic to ``params`` (same
    structure AND same leaf shapes — e.g. Adam's ``mu``/``nu``) receives the
    per-variable ``param_block_specs`` tree; every other leaf (step counts,
    scalars) is replicated.  This is how weight-update sharding reaches the
    optimizer slots (cf. arxiv 2004.13336; the reference instead re-created
    the optimizer inside each PS scope, kernel/partitioner.py:481-574).
    """
    pstruct = jax.tree_util.tree_structure(params)

    def is_param_block(x):
        try:
            if jax.tree_util.tree_structure(x) != pstruct:
                return False
        except Exception:
            return False
        return _shapes_compatible(x, params)

    leaves, treedef = jax.tree_util.tree_flatten(
        opt_state, is_leaf=lambda x: is_param_block(x) or x is None)
    mapped = [param_block_specs if is_param_block(leaf) else P()
              for leaf in leaves]
    return jax.tree_util.tree_unflatten(treedef, mapped)


def constrain(tree: Any, sharding_or_spec_tree: Any) -> Any:
    """with_sharding_constraint over aligned (value, sharding) trees.
    NamedSharding leaves work anywhere; bare PartitionSpec leaves require an
    active mesh context."""
    return jax.tree_util.tree_map(
        lambda x, s: jax.lax.with_sharding_constraint(x, s)
        if isinstance(s, (P, NamedSharding)) else x,
        tree, sharding_or_spec_tree,
        is_leaf=lambda x: x is None)


def host_local(tree: Any) -> Any:
    """Fetch a (possibly sharded) pytree to host numpy arrays."""
    return jax.tree_util.tree_map(lambda x: np.asarray(jax.device_get(x)), tree)
