"""Sharding-tree utilities for the kernel layer.

The reference's kernel layer rewires TF graphs per variable
(``autodist/kernel/common/utils.py:24-272``); the TPU-native kernel instead
manipulates *sharding trees* — pytrees of ``PartitionSpec``/``NamedSharding``
aligned with parameter and optimizer-state pytrees.
"""
from __future__ import annotations

from typing import Any, Dict

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from autodist_tpu.graph_item import path_name


def spec_tree_for_params(params: Any, var_specs: Dict[str, P],
                         default: P = P()) -> Any:
    """params-shaped pytree of PartitionSpecs, looked up by variable name."""

    def spec_of(path, leaf):
        return var_specs.get(path_name(path), default)

    return jax.tree_util.tree_map_with_path(spec_of, params)


def sharding_tree(mesh: Mesh, spec_tree: Any) -> Any:
    return jax.tree_util.tree_map(
        lambda s: NamedSharding(mesh, s), spec_tree,
        is_leaf=lambda x: isinstance(x, P))


def _shapes_compatible(node: Any, params: Any) -> bool:
    """Leaf-wise shape equality between two isomorphic pytrees."""
    node_leaves = jax.tree_util.tree_leaves(node)
    param_leaves = jax.tree_util.tree_leaves(params)
    if len(node_leaves) != len(param_leaves):
        return False
    for a, b in zip(node_leaves, param_leaves):
        sa = tuple(getattr(a, "shape", ()) or ())
        sb = tuple(getattr(b, "shape", ()) or ())
        if sa != sb:
            return False
    return True


def opt_spec_tree(opt_state: Any, params: Any, param_block_specs: Any,
                  default: Any = P()) -> Any:
    """Build a PartitionSpec tree for an optax optimizer state.

    Any sub-pytree of ``opt_state`` that is isomorphic to ``params`` (same
    structure AND same leaf shapes — e.g. Adam's ``mu``/``nu``) receives the
    per-variable ``param_block_specs`` tree; every other leaf (step counts,
    scalars) gets ``default`` (replicated specs unless overridden — also used
    to project pad-info trees onto optimizer states).  This is how
    weight-update sharding reaches the optimizer slots (cf. arxiv 2004.13336;
    the reference instead re-created the optimizer inside each PS scope,
    kernel/partitioner.py:481-574).
    """
    pstruct = jax.tree_util.tree_structure(params)

    def is_param_block(x):
        try:
            if jax.tree_util.tree_structure(x) != pstruct:
                return False
        except Exception:
            return False
        return _shapes_compatible(x, params)

    leaves, treedef = jax.tree_util.tree_flatten(
        opt_state, is_leaf=lambda x: is_param_block(x) or x is None)
    mapped = [param_block_specs if is_param_block(leaf) else default
              for leaf in leaves]
    return jax.tree_util.tree_unflatten(treedef, mapped)


def constrain(tree: Any, sharding_or_spec_tree: Any) -> Any:
    """with_sharding_constraint over aligned (value, sharding) trees.
    NamedSharding leaves work anywhere; bare PartitionSpec leaves require an
    active mesh context."""
    return jax.tree_util.tree_map(
        lambda x, s: jax.lax.with_sharding_constraint(x, s)
        if isinstance(s, (P, NamedSharding)) else x,
        tree, sharding_or_spec_tree,
        is_leaf=lambda x: x is None)


def host_local(tree: Any) -> Any:
    """Fetch a (possibly sharded) pytree to host numpy arrays.

    Multi-controller safe: arrays with non-addressable shards (variables
    sharded across processes) are gathered collectively first — every
    process must therefore call this at the same point, which the SPMD
    execution model already guarantees (all processes run the same
    script)."""

    def fetch(x):
        if (isinstance(x, jax.Array) and not x.is_fully_addressable
                and not x.is_fully_replicated):
            from jax.experimental import multihost_utils

            return np.asarray(multihost_utils.process_allgather(x,
                                                                tiled=True))
        # Fully-replicated arrays need no collective even when some shards
        # live on other processes: the local shard already holds the value.
        return np.asarray(jax.device_get(x))

    return jax.tree_util.tree_map(fetch, tree)


def abstract_like(tree: Any) -> Any:
    """ShapeDtypeStruct tree mirroring ``tree`` (shardings kept when
    present) — the restore-target shape for checkpointing."""
    return jax.tree_util.tree_map(
        lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=x.sharding)
        if hasattr(x, "sharding") else jax.ShapeDtypeStruct(
            np.shape(x), np.asarray(x).dtype),
        tree)


# -- pad-to-divisible sharding ------------------------------------------------
# Variables whose partitioned dim does not divide the mesh axis are stored
# PHYSICALLY padded to the next multiple (VarPlan.pad_axis/pad_dim) so jit's
# even-tiling requirement is met; the loss consumes the LOGICAL view via an
# unpad slice (whose autodiff scatters exactly-zero gradients into pad rows),
# and updates are masked so pad rows stay zero.  Real lowering of the
# reference's uneven partitioner (kernel/partitioner.py:376-426).
#
# Pad metadata rides in params-shaped "info trees" of strings
# ("axis:logical:padded", or "" for unpadded leaves) — strings are pytree
# leaves, so info trees map cleanly over params AND project onto optimizer
# states through opt_spec_tree.

def pad_info_tree(params: Any, pad_map: Dict[str, tuple]) -> Any:
    """params-shaped info tree from ``{name: (axis, logical_dim, padded_dim)}``."""

    def info_of(path, leaf):
        entry = pad_map.get(path_name(path))
        return "" if entry is None else f"{entry[0]}:{entry[1]}:{entry[2]}"

    return jax.tree_util.tree_map_with_path(info_of, params)


def _parse_info(info: str):
    axis, logical, padded = (int(x) for x in info.split(":"))
    return axis, logical, padded


def pad_tree(tree: Any, info_tree: Any) -> Any:
    """Zero-pad each annotated leaf to its physical (padded) shape."""
    import jax.numpy as jnp

    def pad_leaf(x, info):
        if not info:
            return x
        axis, logical, padded = _parse_info(info)
        widths = [(0, 0)] * jnp.ndim(x)
        widths[axis] = (0, padded - x.shape[axis])
        return jnp.pad(jnp.asarray(x), widths)

    return jax.tree_util.tree_map(pad_leaf, tree, info_tree)


def unpad_tree(tree: Any, info_tree: Any) -> Any:
    """Slice each annotated leaf back to its logical shape (differentiable:
    the backward pass scatters zeros into the pad region)."""

    def unpad_leaf(x, info):
        if not info:
            return x
        axis, logical, _ = _parse_info(info)
        return jax.lax.slice_in_dim(x, 0, logical, axis=axis)

    return jax.tree_util.tree_map(unpad_leaf, tree, info_tree)


def mask_pad_tree(tree: Any, info_tree: Any) -> Any:
    """Force the pad region of each annotated leaf to zero (keeps the
    padded-rows-are-zero invariant exact even for optimizers whose update is
    not zero-preserving)."""
    import jax.numpy as jnp

    def mask_leaf(x, info):
        if not info:
            return x
        axis, logical, _ = _parse_info(info)
        idx = jax.lax.broadcasted_iota(jnp.int32, jnp.shape(x), axis)
        return jnp.where(idx < logical, x, jnp.zeros_like(x))

    return jax.tree_util.tree_map(mask_leaf, tree, info_tree)


def unpad_host_tree(tree: Any, info_tree: Any) -> Any:
    """Host-side unpad: plain numpy slicing, numpy in → numpy out."""

    def unpad_leaf(x, info):
        if not info:
            return x
        axis, logical, _ = _parse_info(info)
        index = [slice(None)] * np.ndim(x)
        index[axis] = slice(0, logical)
        return np.asarray(x)[tuple(index)]

    return jax.tree_util.tree_map(unpad_leaf, tree, info_tree)
