"""Execution layer: the distributed session.

Parity target: reference ``WrappedSession`` (``autodist/runner.py:33-132``) —
the object users run steps against — and the feed/fetch ``Remapper``
(``autodist/remapper.py:29-313``).  Functionally:

* feed remapping (split one host batch across replicas) becomes placing the
  global batch with the data-axis sharding;
* fetch remapping (gather per-replica outputs) is unnecessary — jitted
  outputs are already global arrays; ``.params`` gathers to host layout.
"""
from __future__ import annotations

import time
from typing import Any, Callable, Dict, Optional

import jax
import numpy as np

from autodist_tpu.graph_item import GraphItem
from autodist_tpu.kernel import sharding_utils as su
from autodist_tpu.kernel.graph_transformer import DistributedStep
from autodist_tpu.telemetry import flightrec
from autodist_tpu.utils import logging, metrics, tracing


class DistributedSession:
    """Holds sharded training state and runs compiled steps.

    Like the reference's WrappedSession, construction places/initializes all
    state (the reference ran initializers on construction, runner.py:86-100).
    """

    def __init__(self, graph_item: GraphItem, dist_step: DistributedStep):
        self._gi = graph_item
        self._step = dist_step
        self._params = dist_step.place_params(graph_item.params)
        self._opt_state = dist_step.init_fn(self._params)
        self._sync_state = dist_step.init_sync_state(self._params)
        self._step_count = 0
        self._meter = metrics.ThroughputMeter()
        self._last_batch = None     # for on-demand FLOPs estimation
        self._flops_per_step: Optional[float] = None
        # Tracing/dumps (SURVEY §5.1): keyed by the strategy id, the same
        # run identifier the reference used for its artifact paths.
        self._run_id = dist_step.compiled_strategy.strategy.id
        self._tracer = tracing.RunTracer(self._run_id)
        # Telemetry (docs/observability.md): one StepRecord per step —
        # wall step time, host-phase breakdown, and the cost model's
        # prediction for this strategy (the calibration bridge).  None
        # when AUTODIST_TELEMETRY=0, so the hot loop pays one identity
        # check.
        from autodist_tpu.telemetry.timeline import StepRecorder
        self._telemetry = StepRecorder.create(self._run_id,
                                              predictor=self._predict_cost)
        # Flight recorder (docs/observability.md "Flight recorder"):
        # stamp the schedule fingerprint onto this process's cursors,
        # publish the IR into the run dir so the chief can localize
        # hangs against the exact program, and arm the fatal paths
        # (faulthandler stacks + crash-bundle-on-uncaught).  Advisory:
        # any failure here must not block training.
        try:
            ir = getattr(dist_step, "schedule_ir", None)
            if ir is not None and flightrec.enabled():
                flightrec.set_fingerprint(ir.fingerprint())
                if self._telemetry is not None \
                        and self._telemetry.directory:
                    flightrec.publish_ir(ir, self._telemetry.directory)
                    flightrec.install_fatal_handlers(
                        self._telemetry.directory)
        except Exception:  # pragma: no cover - advisory only
            pass
        if tracing.dumps_enabled():
            tracing.dump_stage(self._run_id, "1-strategy-plans",
                               tracing.plan_table(dist_step.compiled_strategy))
            from autodist_tpu.utils import visualization
            visualization.log_shardings(self)

    # -- state -------------------------------------------------------------
    @property
    def params(self):
        """Current parameters, gathered to host numpy in the original
        single-device LOGICAL layout (pad rows stripped — the reference's
        checkpoint-compatibility invariant, checkpoint/saver.py:42-58)."""
        return self._step.unpad_host(su.host_local(self._params))

    @property
    def sharded_params(self):
        """Device-resident parameters in the step's PHYSICAL layout (padded
        when pad-to-divisible sharding is active)."""
        return self._params

    def export_state(self):
        """(params, opt_state) as sharded device arrays in the LOGICAL
        layout — what checkpoints store, so they interchange with
        single-device programs and across mesh topologies."""
        return (self._step.export_params(self._params),
                self._step.export_opt_state(self._opt_state))

    def import_state(self, params, opt_state, step: int = 0,
                     sync_state=None) -> None:
        """Load LOGICAL-layout state (e.g. from a checkpoint): params and
        optimizer state are padded/re-placed to the physical layout."""
        self._params = self._step.place_params(params)
        self._opt_state = self._step.import_opt_state(opt_state)
        self._sync_state = (sync_state if sync_state is not None
                            else self._step.init_sync_state(self._params))
        self._step_count = step

    @property
    def opt_state(self):
        return self._opt_state

    @property
    def sync_state(self):
        """Per-device synchronizer state (compressor residuals etc.); empty
        dict on the GSPMD path."""
        return self._sync_state

    @property
    def step_count(self) -> int:
        return self._step_count

    @property
    def mesh(self):
        return self._step.mesh

    @property
    def data_axis_size(self) -> int:
        from autodist_tpu.const import MESH_AXIS_DATA

        return int(self._step.mesh.shape.get(MESH_AXIS_DATA, 1))

    @property
    def schedule_fingerprint(self):
        """Short hash of the step's sync-schedule IR
        (docs/schedule-ir.md), or None for steps built before the IR
        existed.  Stamped into telemetry StepRecords and checkpoint
        meta so planned-vs-executed schedule drift is detectable across
        resume and elastic resize."""
        ir = getattr(self._step, "schedule_ir", None)
        try:
            return ir.fingerprint() if ir is not None else None
        except Exception:   # pragma: no cover - advisory only
            return None

    @property
    def schedule_ir(self):
        """The step's sync-schedule IR (docs/schedule-ir.md)."""
        return getattr(self._step, "schedule_ir", None)

    @property
    def zero1_buckets(self):
        """The ZeRO-1 flat-bucket plan of the compiled step (empty unless
        the explicit reduce-scatter path is active).  Checkpoints record
        it so elastic resume can reslice the flat optimizer shards at a
        different data-axis size (``resilience/elastic.py``)."""
        return tuple(getattr(self._step, "zero1_buckets", ()) or ())

    # -- running -----------------------------------------------------------
    def place_batch(self, batch: Any) -> Any:
        """Pre-place a host batch with the strategy's input shardings.
        Re-running a pre-placed batch skips the host→device transfer — use
        for input pipelines that prefetch (placing an already-placed batch
        is a no-op)."""
        return self._step.place_batch(batch)

    def place_local_batch(self, local_batch: Any) -> Any:
        """Assemble a global batch from this PROCESS-LOCAL shard (each host
        reads disjoint rows; leading dims concatenate over the data axis) —
        the multi-host input-pipeline path.  See
        :meth:`DistributedStep.place_local_batch`."""
        return self._step.place_local_batch(local_batch)

    def run(self, batch: Any, sync: bool = True) -> Dict[str, Any]:
        """Run one training step on a global batch.

        The batch is split along its leading dimension across the data axis
        (the Remapper's polymorphic-dim splitting, remapper.py:81-123).
        Returns metrics (at least ``{"loss": ...}``) — as host numpy when
        ``sync`` (the default), or as device arrays when ``sync=False`` so
        back-to-back steps dispatch asynchronously without a host round-trip
        per step."""
        rec = self._telemetry
        t0 = time.perf_counter() if rec is not None else 0.0
        # Host-phase flight-recorder cursor: "entered step N" — the
        # coarsest progress beacon, paired with the "exit" stamp
        # record_step makes.  One object + one ring store when enabled.
        flightrec.record_cursor("step", kind="phase", event="enter",
                                step=self._step_count)
        batch = self._step.place_batch(batch)
        if self._step_count == 0 and tracing.dumps_enabled():
            self._dump_programs(batch)
        with self._tracer.step(self._step_count):
            self._params, self._opt_state, self._sync_state, out = \
                self._step.step_fn(self._params, self._opt_state,
                                   self._sync_state, batch)
        self._tracer.after_step(self._step_count)
        step_index = self._step_count
        self._step_count += 1
        # Shapes/dtypes only — retaining the real batch would pin multi-GB
        # host buffers for the session lifetime.
        self._last_batch = jax.tree_util.tree_map(
            lambda x: jax.ShapeDtypeStruct(np.shape(x), x.dtype), batch)
        self._meter.tick()
        if rec is not None:
            # Dispatch time is the host-side cost of issuing the step
            # (async: excludes device execution — the wall step_time_s
            # converges to true step time once the pipeline fills).
            rec.add_phase("dispatch", time.perf_counter() - t0)
            items, tokens = self._batch_sizes()
            rec.record_step(step_index, items=items, tokens=tokens)
        if not sync:
            return out
        return jax.tree_util.tree_map(lambda x: np.asarray(x), out)

    def _batch_sizes(self):
        """(items, tokens) of the last batch from shapes alone: items =
        leading dim; tokens = rows x seq for a 2-D integer leaf (token
        ids) when one exists."""
        if self._last_batch is None:
            return None, None
        items = tokens = None
        for leaf in jax.tree_util.tree_leaves(self._last_batch):
            shape = leaf.shape
            if not shape:
                continue
            if items is None:
                items = int(shape[0])
            if (tokens is None and len(shape) == 2
                    and np.issubdtype(leaf.dtype, np.integer)):
                tokens = int(shape[0]) * int(shape[1])
        return items, tokens

    def _predict_cost(self) -> Optional[dict]:
        """The cost model's estimate for this session's strategy on a
        spec synthesized from the mesh — stamped into every StepRecord
        (measured-vs-predicted is the calibration bridge,
        telemetry/calibration.py).  Advisory: any failure returns None."""
        try:
            from autodist_tpu.resource_spec import ResourceSpec
            from autodist_tpu.strategy.cost_model import estimate_cost

            n = int(self.mesh.devices.size)
            spec = ResourceSpec(resource_info={"nodes": [
                {"address": "localhost", "chips": n, "chief": True}]})
            report = estimate_cost(self._step.compiled_strategy.strategy,
                                   self._gi, spec)
            return {
                "time_s": report.time_s,
                "wire_bytes": report.wire_bytes,
                "exposed_wire_bytes": report.exposed_wire_bytes,
                "num_collectives": report.num_collectives,
                "schedule_fingerprint": self.schedule_fingerprint,
            }
        except Exception:
            return None

    def _dump_programs(self, batch) -> None:
        """Staged program dumps at first run, when concrete shapes exist:
        the traced StableHLO (transformed program) and the XLA-optimized
        HLO (what executes — sharded, fused, collectives inserted).  Note
        AOT lower().compile() is not guaranteed to seed jit's dispatch
        cache, so the first run may compile the step a second time —
        a debug-only cost, paid only under AUTODIST_DUMP_GRAPHS=1."""
        lowered = self._step.step_fn.lower(self._params, self._opt_state,
                                           self._sync_state, batch)
        tracing.dump_stage(self._run_id, "2-step-stablehlo",
                           lowered.as_text())
        try:
            compiled = lowered.compile()
            tracing.dump_stage(self._run_id, "3-step-optimized-hlo",
                               compiled.as_text())
        except Exception as e:  # pragma: no cover - backend-dependent
            logging.warning("optimized-HLO dump unavailable: %r", e)

    def evaluate(self, batches, sync: bool = True
                 ) -> Optional[Dict[str, Any]]:
        """Loss (and aux) on the CURRENT parameters with NO state change —
        the reference's fetch-only ``sess.run(loss)``.  ``batches`` is one
        batch dict or an iterable; an iterable returns the MEAN of every
        metric over batches (each batch weighted equally, numeric aux
        included).  Returns None for an empty iterable."""
        if isinstance(batches, dict):
            batches = [batches]
        acc, n = None, 0
        for b in batches:
            out = self._step.eval_fn(self._params, self._step.place_batch(b))
            acc = out if acc is None else jax.tree_util.tree_map(
                lambda a, x: a + x, acc, out)
            n += 1
        if acc is None:
            return None
        acc = jax.tree_util.tree_map(lambda a: a / n, acc)
        if not sync:
            return acc
        return jax.tree_util.tree_map(lambda x: np.asarray(x), acc)

    def run_many(self, batches) -> Dict[str, Any]:
        """Run a sequence of batches with async dispatch (no host round-trip
        per step); returns the last step's metrics on host."""
        out = None
        for b in batches:
            out = self.run(b, sync=False)
        if out is None:
            return None
        return jax.tree_util.tree_map(lambda x: np.asarray(x), out)

    def prefetch(self, batches, depth: int = 2):
        """Yield device-placed batches keeping ``depth`` host→device
        transfers in flight ahead of compute (device_put is async, so the
        next batch's copy overlaps the current step) — the device-side half
        of the input pipeline whose host side is
        :class:`autodist_tpu.runtime.data_loader.DataLoader`."""
        from collections import deque

        q: deque = deque()
        for b in batches:
            q.append(self.place_batch(b))
            if len(q) >= depth:
                yield q.popleft()
        while q:
            yield q.popleft()

    def run_epoch(self, batches, prefetch_depth: int = 2) -> Dict[str, Any]:
        """Run every batch of an epoch with device prefetch + async
        dispatch; returns the last step's metrics on host (None for an
        empty iterable)."""
        return self.run_many(self.prefetch(batches, prefetch_depth))

    def fit(self, data, **kwargs):
        """High-level epochs×steps training loop with callbacks, periodic
        logging, and checkpoint/resume — the reference's ``Model.fit``
        path (see :mod:`autodist_tpu.fit` for arguments)."""
        from autodist_tpu import fit as _fit

        return _fit.fit(self, data, **kwargs)

    # -- instrumentation (SURVEY §5: the reference only measured throughput
    # in example scripts; here it's a session feature) ----------------------
    @property
    def telemetry(self):
        """The session's :class:`~autodist_tpu.telemetry.timeline.
        StepRecorder` (None when AUTODIST_TELEMETRY=0).  One StepRecord
        per step; ``fit`` adds host-phase timings and health
        annotations; JSONL flushes under AUTODIST_TELEMETRY_DIR."""
        return self._telemetry

    def throughput(self, items_per_step: Optional[int] = None
                   ) -> Dict[str, Any]:
        """Sliding-window step timing: step_time_ms / steps_per_sec (+
        items_per_sec given a batch size).  With async dispatch this
        converges to true step time once the pipeline fills."""
        return self._meter.stats(items_per_step)

    def flops_per_step(self) -> Optional[float]:
        """Model FLOPs of the compiled step from XLA's cost analysis
        (cached — including the unavailable outcome, so polling mfu() never
        re-runs the AOT compile; needs at least one run).  None when
        unavailable."""
        if self._flops_per_step is None and self._last_batch is not None:
            flops = metrics.step_flops(
                self._step.step_fn, self._params, self._opt_state,
                self._sync_state, self._last_batch)
            # step_flops never yields 0.0 (it maps flops<=0 to None), so
            # False is an unambiguous unavailable-sentinel.
            self._flops_per_step = False if flops is None else flops
        if self._flops_per_step is None or self._flops_per_step is False:
            return None
        return self._flops_per_step

    def mfu(self) -> Optional[float]:
        """Model-FLOPs utilization of the last measurement window
        (None off-TPU / before 2 steps).  XLA's cost analysis reports
        PER-DEVICE flops for an SPMD program, so the denominator is a
        single chip's peak — the ratio is the whole mesh's utilization."""
        st = self._meter.step_time()
        if st is None:  # before the compile-triggering flops lookup
            return None
        flops = self.flops_per_step()
        if flops is None:
            return None
        return metrics.mfu(flops, st, [self.mesh.devices.flat[0]])

    def restore_targets(self):
        """Abstract (ShapeDtypeStruct + sharding) trees of the LOGICAL
        (params, opt_state) — the restore targets matching
        :meth:`export_state`'s layout."""
        st = self._step
        if st.pad_info is None:
            return (su.abstract_like(self._params),
                    su.abstract_like(self._opt_state))
        pa = jax.eval_shape(st.export_params, self._params)
        oa = jax.eval_shape(st.export_opt_state, self._opt_state)
        pa = jax.tree_util.tree_map(
            lambda a, s: jax.ShapeDtypeStruct(a.shape, a.dtype, sharding=s),
            pa, st.logical_param_shardings)
        oa = jax.tree_util.tree_map(
            lambda a, s: jax.ShapeDtypeStruct(a.shape, a.dtype, sharding=s),
            oa, st.logical_opt_shardings)
        return pa, oa

    def set_params(self, params) -> None:
        """Load new parameter values (e.g. from a checkpoint), re-placing
        them with the strategy's shardings.  Optimizer state is re-initialized."""
        self._params = self._step.place_params(params)
        self._opt_state = self._step.init_fn(self._params)
        # Seed from the NEW params — proxy caches must mirror the restored
        # values, not the capture-time ones.
        self._sync_state = self._step.init_sync_state(self._params)

    def load_state(self, params, opt_state, step: int = 0,
                   sync_state=None) -> None:
        """Full resume: params + optimizer state + step counter (+ optional
        synchronizer state, e.g. compressor residuals — without it, resume of
        a compressed run is approximate).  Values must already be
        placed/resharded."""
        self._params = params
        self._opt_state = opt_state
        self._sync_state = (sync_state if sync_state is not None
                            else self._step.init_sync_state(self._params))
        self._step_count = step
