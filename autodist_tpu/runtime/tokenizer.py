"""Byte-level BPE tokenizer: native encode (``native/tokenizer.cpp``)
with a bit-identical pure-Python fallback, a pure-Python trainer, and a
JSON file format.

The reference framework has no text pipeline (its examples feed
pre-tokenized ids — e.g. lm1b's pre-built vocab files); this completes
the TPU build's serving story: :class:`BPETokenizer` plugs directly into
``EngineServer(tokenizer=...)`` so ``{"prompt": "text"}`` round-trips.

Model: the 256 single bytes are the base vocabulary (ids 0..255 — every
string is encodable, no unknown tokens); ranked pair merges apply with
repeated-best-merge semantics (global lowest rank, leftmost occurrence
first) WITHIN pretoken segments.  Pretokenization is GPT-2-style — the
contraction/space pattern ``'s|'t|'re|'ve|'m|'ll|'d| ?L+| ?N+| ?P+|
\\s+(?!\\S)|\\s+`` — realized as a hand-rolled byte-class scanner
(L = ASCII letters plus every byte >= 0x80, N = ASCII digits, \\s =
ASCII whitespace, P = the rest) so the native and Python paths match
bit-for-bit without Unicode tables.  Merges never cross word/space
boundaries, the quality property that motivates pretokenization.
``pretokenize=False`` keeps the old whole-string behavior (and loads
v1 files).

Special tokens are atomic strings with ids above the merge vocab.
``encode`` never produces them from plain text (their literal text
encodes as ordinary bytes); ``encode(text, with_special=True)`` splits
on them first.  ``eos_id``/``pad_id`` surface ``<eos>``/``<pad>`` when
registered — ``serving.server.serve`` wires ``eos_id`` into the engine.

Encode is heap-based best-merge — a (rank, pos) priority queue with
lazy invalidation over a linked symbol list, O(n log n) per segment
(the old full-rescan loop was O(n * merges), pathological on long
uniform inputs — a single no-space request body could pin a handler
thread).  Native and Python implement the same algorithm; the test
suite pins their bit-parity.
"""
from __future__ import annotations

import ctypes
import heapq
import json
import threading
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

import numpy as np

from autodist_tpu.runtime import native

_BASE = 256

_SPACE = frozenset(b" \t\n\r\f\v")


def _cls(b: int) -> int:
    """Byte class: 0 space, 1 letter (ASCII alpha or >= 0x80), 2 digit,
    3 punct.  Must match ``classify`` in native/tokenizer.cpp."""
    if b in _SPACE:
        return 0
    if 97 <= b <= 122 or 65 <= b <= 90 or b >= 0x80:
        return 1
    if 48 <= b <= 57:
        return 2
    return 3


def _contraction_len(data: bytes, i: int) -> int:
    """Length of a lowercase contraction ('s 't 'm 'd 're 've 'll) at
    ``i``, else 0.  Must match native/tokenizer.cpp."""
    n = len(data)
    if data[i] != 0x27 or i + 1 >= n:   # 0x27 = apostrophe
        return 0
    c = data[i + 1:i + 2]
    if c in (b"s", b"t", b"m", b"d"):
        return 2
    if data[i + 1:i + 3] in (b"re", b"ve", b"ll"):
        return 3
    return 0


def _pretokenize(data: bytes) -> List[Tuple[int, int]]:
    """GPT-2-style pretoken boundaries as (start, end) byte offsets.
    Must match ``pretokenize`` in native/tokenizer.cpp — the two are
    kept in lockstep and pinned by the parity tests."""
    segs: List[Tuple[int, int]] = []
    n, i = len(data), 0
    while i < n:
        cl = _contraction_len(data, i)
        if cl:
            segs.append((i, i + cl))
            i += cl
            continue
        if _cls(data[i]) == 0:
            j = i
            while j < n and _cls(data[j]) == 0:
                j += 1
            if j == n:            # trailing whitespace run: one token
                segs.append((i, j))
                break
            if j - i > 1:         # \s+(?!\S): all but the last space
                segs.append((i, j - 1))
                i = j - 1
                continue
            if data[i] != 0x20:   # the ' ?' prefix is a LITERAL space:
                segs.append((i, j))   # lone \t or \n is its own token
                i = j
                continue
            # single literal space before non-space: falls into ' ?class+'
        start = i
        if data[i] == 0x20:
            i += 1                # the ' ?' space (literal 0x20 only)
        cls = _cls(data[i])
        i += 1
        while i < n and _cls(data[i]) == cls:
            i += 1
        segs.append((start, i))
    return segs


class BPETokenizer:
    """``merges`` is rank-ordered ``(left_id, right_id, new_id)``; new ids
    must start at 256 (the byte base vocab is implicit).
    ``special_tokens`` maps literal strings to ids at/above the merge
    vocab (dense allocation via :meth:`add_special_tokens`)."""

    def __init__(self, merges: Sequence[Tuple[int, int, int]], *,
                 pretokenize: bool = True,
                 special_tokens: Optional[Dict[str, int]] = None):
        self.merges: List[Tuple[int, int, int]] = [
            (int(a), int(b), int(c)) for a, b, c in merges]
        self.pretokenize = bool(pretokenize)
        # token id -> bytes (decode table)
        self._bytes: List[bytes] = [bytes([i]) for i in range(_BASE)]
        for left, right, out in self.merges:
            if out != len(self._bytes):
                raise ValueError(
                    f"merge output ids must be dense from {_BASE}: "
                    f"expected {len(self._bytes)}, got {out}")
            if not (0 <= left < out and 0 <= right < out):
                raise ValueError(f"merge ({left},{right})->{out} refers "
                                 f"to an id not yet defined")
            self._bytes.append(self._bytes[left] + self._bytes[right])
        # (left, right) -> (rank, new_id); first rank wins on duplicates.
        self._ranks: Dict[Tuple[int, int], Tuple[int, int]] = {}
        for rank, (left, right, out) in enumerate(self.merges):
            self._ranks.setdefault((left, right), (rank, out))
        self.special_tokens: Dict[str, int] = {}
        self._special_by_id: Dict[int, str] = {}
        for text, sid in (special_tokens or {}).items():
            self._register_special(text, int(sid))
        self._native: Optional[ctypes.c_void_p] = None
        self._native_tried = False
        # encode() is called from concurrent server handler threads;
        # without this lock two first encodes could both ad_bpe_create
        # and leak one native handle.
        self._native_lock = threading.Lock()

    def _register_special(self, text: str, sid: int) -> None:
        if sid < len(self._bytes):
            raise ValueError(
                f"special token {text!r} id {sid} collides with the "
                f"merge vocab (size {len(self._bytes)})")
        if not text:
            raise ValueError("special token text must be non-empty")
        if sid in self._special_by_id or text in self.special_tokens:
            raise ValueError(f"special token {text!r}/{sid} already "
                             f"registered")
        self.special_tokens[text] = sid
        self._special_by_id[sid] = text

    def add_special_tokens(self, texts: Sequence[str]) -> Dict[str, int]:
        """Register ``texts`` as atomic special tokens with dense ids
        above the current vocab; returns {text: id} for the new ones."""
        out = {}
        nxt = self.vocab_size
        for t in texts:
            self._register_special(t, nxt)
            out[t] = nxt
            nxt += 1
        return out

    @property
    def vocab_size(self) -> int:
        ids = self._special_by_id
        return max(ids) + 1 if ids else len(self._bytes)

    @property
    def eos_id(self) -> Optional[int]:
        return self.special_tokens.get("<eos>")

    @property
    def pad_id(self) -> Optional[int]:
        return self.special_tokens.get("<pad>")

    # -- encode / decode ---------------------------------------------------

    def _get_native(self):
        with self._native_lock:
            if not self._native_tried:
                self._native_tried = True
                lib = native.get_lib()
                if lib is not None and self.merges:
                    flat = np.asarray(self.merges, np.int32).reshape(-1)
                    self._native = lib.ad_bpe_create_v2(
                        flat.ctypes.data_as(
                            ctypes.POINTER(ctypes.c_int32)),
                        np.int32(len(self.merges)),
                        np.int32(1 if self.pretokenize else 0))
            return self._native

    def encode(self, text: str, *, with_special: bool = False) -> List[int]:
        """Token ids for ``text``.  Plain encode never emits special
        ids — their literal text encodes as ordinary bytes; pass
        ``with_special=True`` to split on registered special strings
        first (longest-first, leftmost occurrence)."""
        if with_special and self.special_tokens:
            out: List[int] = []
            for part, sid in self._split_special(text):
                out.extend([sid] if sid is not None
                           else self._encode_plain(part))
            return out
        return self._encode_plain(text)

    def _split_special(self, text: str):
        """Yield (segment, None) / (special_text, id) pairs, scanning
        leftmost with longest-match on ties."""
        specials = sorted(self.special_tokens, key=len, reverse=True)
        pos = 0
        while pos < len(text):
            best, best_at = None, len(text)
            for s in specials:
                at = text.find(s, pos)
                if at != -1 and (at < best_at
                                 or (at == best_at
                                     and len(s) > len(best or ""))):
                    best, best_at = s, at
            if best is None:
                yield text[pos:], None
                return
            if best_at > pos:
                yield text[pos:best_at], None
            yield best, self.special_tokens[best]
            pos = best_at + len(best)

    def _encode_plain(self, text: str) -> List[int]:
        data = text.encode("utf-8")
        if not data:
            return []
        handle = self._get_native()
        if handle is not None:
            lib = native.get_lib()
            out = np.empty(len(data), np.int32)
            n = lib.ad_bpe_encode(
                handle, data, np.int32(len(data)),
                out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)))
            return out[:n].tolist()
        return self._encode_py(data)

    def _encode_py(self, data: bytes) -> List[int]:
        """Pure-Python reference: must match the native path exactly."""
        segs = _pretokenize(data) if self.pretokenize \
            else [(0, len(data))]
        out: List[int] = []
        for lo, hi in segs:
            out.extend(self._merge_segment(list(data[lo:hi])))
        return out

    def _merge_segment(self, ids: List[int]) -> List[int]:
        """Heap-based best-merge (see module docstring): pop candidates
        by (rank, pos), skip stale entries, push the two pairs a merge
        creates.  Identical order to the native implementation."""
        n = len(ids)
        if n < 2:
            return ids
        ranks = self._ranks
        nxt = list(range(1, n)) + [-1]
        prv = [-1] + list(range(n - 1))
        heap: List[Tuple[int, int, int, int]] = []

        def push(i: int) -> None:
            j = nxt[i]
            if j == -1:
                return
            r = ranks.get((ids[i], ids[j]))
            if r is not None:
                heap.append((r[0], i, ids[i], ids[j]))

        for i in range(n - 1):
            push(i)
        heapq.heapify(heap)
        while heap:
            _, i, a, b = heapq.heappop(heap)
            j = nxt[i]
            if ids[i] != a or j == -1 or ids[j] != b:
                continue   # stale
            ids[i] = ranks[(a, b)][1]
            k = nxt[j]
            ids[j] = -1    # tombstone
            nxt[i] = k
            if k != -1:
                prv[k] = i
            p = prv[i]
            if p != -1:
                r = ranks.get((ids[p], ids[i]))
                if r is not None:
                    heapq.heappush(heap, (r[0], p, ids[p], ids[i]))
            if k != -1:
                r = ranks.get((ids[i], ids[k]))
                if r is not None:
                    heapq.heappush(heap, (r[0], i, ids[i], ids[k]))
        i, out = 0, []
        while i != -1:
            out.append(ids[i])
            i = nxt[i]
        return out

    def decode(self, ids: Iterable[int]) -> str:
        ids = list(ids)
        parts: List[bytes] = []
        for i in ids:
            if i in self._special_by_id:
                parts.append(self._special_by_id[i].encode("utf-8"))
            elif 0 <= i < len(self._bytes):
                parts.append(self._bytes[i])
            else:
                raise ValueError(
                    f"token id {i} out of range for vocab_size "
                    f"{self.vocab_size} — is the model's vocab larger "
                    f"than the tokenizer's?")
        return b"".join(parts).decode("utf-8", errors="replace")

    # -- persistence -------------------------------------------------------

    def save(self, path: str) -> None:
        with open(path, "w") as f:
            json.dump({"format": "autodist-bpe-v2",
                       "merges": self.merges,
                       "pretokenize": self.pretokenize,
                       "special_tokens": self.special_tokens}, f)

    @classmethod
    def load(cls, path: str) -> "BPETokenizer":
        with open(path) as f:
            obj = json.load(f)
        fmt = obj.get("format")
        if fmt == "autodist-bpe-v1":   # pre-pretokenization files
            return cls(obj["merges"], pretokenize=False)
        if fmt != "autodist-bpe-v2":
            raise ValueError(f"{path}: not an autodist-bpe file")
        return cls(obj["merges"],
                   pretokenize=obj.get("pretokenize", True),
                   special_tokens=obj.get("special_tokens") or None)

    # -- training ----------------------------------------------------------

    @classmethod
    def train(cls, texts: Iterable[str], vocab_size: int, *,
              pretokenize: bool = True,
              special_tokens: Sequence[str] = ()) -> "BPETokenizer":
        """Learn merges by iterated most-frequent-pair counting (the
        classic BPE trainer) until ``vocab_size`` is reached (special
        tokens excluded) or no pair repeats.  With pretokenization the
        corpus collapses to WEIGHTED UNIQUE pretokens — counting and
        merging touch each distinct word once per iteration, which is
        what makes multi-MB corpora practical in pure Python (training
        is offline/one-time; encode is the hot path and is native)."""
        if vocab_size < _BASE:
            raise ValueError(f"vocab_size must be >= {_BASE}")
        # word (tuple of ids) -> count
        words: Dict[Tuple[int, ...], int] = {}
        for t in texts:
            if not t:
                continue
            data = t.encode("utf-8")
            segs = _pretokenize(data) if pretokenize \
                else [(0, len(data))]
            for lo, hi in segs:
                w = tuple(data[lo:hi])
                words[w] = words.get(w, 0) + 1
        merges: List[Tuple[int, int, int]] = []
        next_id = _BASE
        while next_id < vocab_size:
            counts: Dict[Tuple[int, int], int] = {}
            for w, c in words.items():
                for i in range(len(w) - 1):
                    pair = (w[i], w[i + 1])
                    counts[pair] = counts.get(pair, 0) + c
            if not counts:
                break
            # Deterministic: max count, ties by smallest pair ids.
            pair, cnt = min(counts.items(), key=lambda kv: (-kv[1], kv[0]))
            if cnt < 2:
                break
            merges.append((pair[0], pair[1], next_id))
            new_words: Dict[Tuple[int, ...], int] = {}
            for w, c in words.items():
                i, out = 0, []
                while i < len(w):
                    if i + 1 < len(w) and (w[i], w[i + 1]) == pair:
                        out.append(next_id)
                        i += 2
                    else:
                        out.append(w[i])
                        i += 1
                nw = tuple(out)
                new_words[nw] = new_words.get(nw, 0) + c
            words = new_words
            next_id += 1
        tok = cls(merges, pretokenize=pretokenize)
        if special_tokens:
            tok.add_special_tokens(list(special_tokens))
        return tok

    def __del__(self):  # pragma: no cover - interpreter teardown
        try:
            if self._native is not None:
                lib = native.get_lib()
                if lib is not None:
                    lib.ad_bpe_destroy(self._native)
        except Exception:
            pass
