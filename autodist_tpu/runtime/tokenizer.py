"""Byte-level BPE tokenizer: native encode (``native/tokenizer.cpp``)
with a bit-identical pure-Python fallback, a pure-Python trainer, and a
JSON file format.

The reference framework has no text pipeline (its examples feed
pre-tokenized ids — e.g. lm1b's pre-built vocab files); this completes
the TPU build's serving story: :class:`BPETokenizer` plugs directly into
``EngineServer(tokenizer=...)`` so ``{"prompt": "text"}`` round-trips.

Model: the 256 single bytes are the base vocabulary (ids 0..255 — every
string is encodable, no unknown tokens), merges apply in rank order with
repeated-best-merge semantics (global lowest rank, leftmost occurrence
first).  No regex pretokenization — merges may cross word boundaries;
for the model sizes this framework serves that trade-off favors the
simpler, exactly-reproducible pipeline.
"""
from __future__ import annotations

import ctypes
import json
import threading
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

import numpy as np

from autodist_tpu.runtime import native

_BASE = 256


class BPETokenizer:
    """``merges`` is rank-ordered ``(left_id, right_id, new_id)``; new ids
    must start at 256 (the byte base vocab is implicit)."""

    def __init__(self, merges: Sequence[Tuple[int, int, int]]):
        self.merges: List[Tuple[int, int, int]] = [
            (int(a), int(b), int(c)) for a, b, c in merges]
        # token id -> bytes (decode table)
        self._bytes: List[bytes] = [bytes([i]) for i in range(_BASE)]
        for left, right, out in self.merges:
            if out != len(self._bytes):
                raise ValueError(
                    f"merge output ids must be dense from {_BASE}: "
                    f"expected {len(self._bytes)}, got {out}")
            if not (0 <= left < out and 0 <= right < out):
                raise ValueError(f"merge ({left},{right})->{out} refers "
                                 f"to an id not yet defined")
            self._bytes.append(self._bytes[left] + self._bytes[right])
        # (left, right) -> (rank, new_id); first rank wins on duplicates.
        self._ranks: Dict[Tuple[int, int], Tuple[int, int]] = {}
        for rank, (left, right, out) in enumerate(self.merges):
            self._ranks.setdefault((left, right), (rank, out))
        self._native: Optional[ctypes.c_void_p] = None
        self._native_tried = False
        # encode() is called from concurrent server handler threads;
        # without this lock two first encodes could both ad_bpe_create
        # and leak one native handle.
        self._native_lock = threading.Lock()

    @property
    def vocab_size(self) -> int:
        return len(self._bytes)

    # -- encode / decode ---------------------------------------------------

    def _get_native(self):
        with self._native_lock:
            if not self._native_tried:
                self._native_tried = True
                lib = native.get_lib()
                if lib is not None and self.merges:
                    flat = np.asarray(self.merges, np.int32).reshape(-1)
                    self._native = lib.ad_bpe_create(
                        flat.ctypes.data_as(
                            ctypes.POINTER(ctypes.c_int32)),
                        np.int32(len(self.merges)))
            return self._native

    def encode(self, text: str) -> List[int]:
        data = text.encode("utf-8")
        if not data:
            return []
        handle = self._get_native()
        if handle is not None:
            lib = native.get_lib()
            out = np.empty(len(data), np.int32)
            n = lib.ad_bpe_encode(
                handle, data, np.int32(len(data)),
                out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)))
            return out[:n].tolist()
        return self._encode_py(data)

    def _encode_py(self, data: bytes) -> List[int]:
        """Pure-Python reference: must match the native loop exactly —
        repeatedly merge the globally lowest-rank pair, leftmost
        occurrence first."""
        ids = list(data)
        ranks = self._ranks
        while True:
            best_rank, best_pos = None, -1
            for i in range(len(ids) - 1):
                r = ranks.get((ids[i], ids[i + 1]))
                if r is not None and (best_rank is None
                                      or r[0] < best_rank[0]):
                    best_rank, best_pos = r, i
            if best_pos < 0:
                break
            ids[best_pos:best_pos + 2] = [best_rank[1]]
        return ids

    def decode(self, ids: Iterable[int]) -> str:
        ids = list(ids)
        bad = [i for i in ids if not 0 <= i < len(self._bytes)]
        if bad:
            raise ValueError(
                f"token ids {bad[:5]} out of range for vocab_size "
                f"{len(self._bytes)} — is the model's vocab larger than "
                f"the tokenizer's?")
        buf = b"".join(self._bytes[i] for i in ids)
        return buf.decode("utf-8", errors="replace")

    # -- persistence -------------------------------------------------------

    def save(self, path: str) -> None:
        with open(path, "w") as f:
            json.dump({"format": "autodist-bpe-v1",
                       "merges": self.merges}, f)

    @classmethod
    def load(cls, path: str) -> "BPETokenizer":
        with open(path) as f:
            obj = json.load(f)
        if obj.get("format") != "autodist-bpe-v1":
            raise ValueError(f"{path}: not an autodist-bpe-v1 file")
        return cls(obj["merges"])

    # -- training ----------------------------------------------------------

    @classmethod
    def train(cls, texts: Iterable[str], vocab_size: int) -> "BPETokenizer":
        """Learn merges by iterated most-frequent-pair counting (the
        classic BPE trainer) until ``vocab_size`` is reached or no pair
        repeats.  Pure Python — training is offline/one-time; encode is
        the hot path and is native."""
        if vocab_size < _BASE:
            raise ValueError(f"vocab_size must be >= {_BASE}")
        corpus: List[List[int]] = [list(t.encode("utf-8")) for t in texts
                                   if t]
        merges: List[Tuple[int, int, int]] = []
        next_id = _BASE
        while next_id < vocab_size:
            counts: Dict[Tuple[int, int], int] = {}
            for seq in corpus:
                for i in range(len(seq) - 1):
                    pair = (seq[i], seq[i + 1])
                    counts[pair] = counts.get(pair, 0) + 1
            if not counts:
                break
            # Deterministic: max count, ties by smallest pair ids.
            pair, cnt = min(counts.items(), key=lambda kv: (-kv[1], kv[0]))
            if cnt < 2:
                break
            merges.append((pair[0], pair[1], next_id))
            for seq in corpus:
                i, out = 0, []
                while i < len(seq):
                    if (i + 1 < len(seq)
                            and (seq[i], seq[i + 1]) == pair):
                        out.append(next_id)
                        i += 2
                    else:
                        out.append(seq[i])
                        i += 1
                seq[:] = out
            next_id += 1
        return cls(merges)

    def __del__(self):  # pragma: no cover - interpreter teardown
        try:
            if self._native is not None:
                lib = native.get_lib()
                if lib is not None:
                    lib.ad_bpe_destroy(self._native)
        except Exception:
            pass
