"""Native host runtime: buffer pool, prefetching data loader, bf16 cast,
byte-level BPE tokenizer.

The device-side runtime on TPU is XLA/PJRT (the analog of the TF C++ runtime
the reference delegated to, SURVEY.md §2.9); this package is the *host*-side
native layer — the piece that must overlap with device steps to keep the MXU
fed (and, for serving, keep per-request encode latency off the decode loop).
"""
from autodist_tpu.runtime.data_loader import DataLoader  # noqa: F401
from autodist_tpu.runtime.native import (fp32_to_bf16,  # noqa: F401
                                         native_available)
from autodist_tpu.runtime.tokenizer import BPETokenizer  # noqa: F401
