"""ctypes bindings for the native host runtime (``native/runtime.cpp``).

The shared library is built on first use with ``make`` (g++ is in the image;
pybind11 is not, hence the C ABI + ctypes).  Every entry point has a
pure-Python fallback so the package works where no toolchain exists — the
loader then runs in numpy, losing only throughput, not behavior.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

import numpy as np

from autodist_tpu.utils import logging

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))), "native")
_LIB_NAME = "libautodist_runtime.so"

_lib: Optional[ctypes.CDLL] = None
_lib_lock = threading.Lock()
_build_failed = False


_SRC_NAMES = ("runtime.cpp", "tokenizer.cpp")


def _src_mtime() -> float:
    """Newest mtime across the sources compiled into the library."""
    return max(os.path.getmtime(os.path.join(_NATIVE_DIR, n))
               for n in _SRC_NAMES if os.path.exists(
                   os.path.join(_NATIVE_DIR, n)))


def _build_and_load() -> Optional[ctypes.CDLL]:
    lib_path = os.path.join(_NATIVE_DIR, _LIB_NAME)
    if not os.path.exists(os.path.join(_NATIVE_DIR, "runtime.cpp")):
        return None
    if (not os.path.exists(lib_path)
            or os.path.getmtime(lib_path) < _src_mtime()):
        # Serialize concurrent builds across processes (several workers can
        # land on one host): flock a sidecar, then re-check staleness — the
        # loser of the race finds a fresh .so and skips its own make.
        import fcntl

        lock_path = os.path.join(_NATIVE_DIR, ".build.lock")
        try:
            with open(lock_path, "w") as lock_f:
                fcntl.flock(lock_f, fcntl.LOCK_EX)
                if (not os.path.exists(lib_path)
                        or os.path.getmtime(lib_path) < _src_mtime()):
                    subprocess.run(["make", "-C", _NATIVE_DIR], check=True,
                                   capture_output=True)
        except (subprocess.CalledProcessError, OSError) as e:
            # OSError covers missing make, unwritable or read-only
            # native/ dir (EROFS), etc. — all fall back to pure Python.
            err = getattr(e, "stderr", b"") or b""
            logging.warning("native runtime build failed (%s); using "
                            "pure-Python fallback. %s", e,
                            err.decode(errors="replace")[-500:])
            return None
    try:
        lib = ctypes.CDLL(lib_path)
    except OSError as e:
        logging.warning("could not load %s: %s", lib_path, e)
        return None

    try:
        _bind_signatures(lib)
    except AttributeError as e:
        # A stale prebuilt .so missing newer symbols (copied artifact,
        # mtime-preserving sync): honor the module contract — fall back
        # to pure Python everywhere rather than raise from get_lib().
        logging.warning("native runtime library is stale (%s); using "
                        "pure-Python fallback — run `make -C native` to "
                        "rebuild", e)
        return None
    return lib


def _bind_signatures(lib: ctypes.CDLL) -> None:
    lib.ad_buffer_alloc.restype = ctypes.c_void_p
    lib.ad_buffer_alloc.argtypes = [ctypes.c_size_t, ctypes.c_size_t]
    lib.ad_buffer_free.argtypes = [ctypes.c_void_p]
    lib.ad_fp32_to_bf16.argtypes = [ctypes.c_void_p, ctypes.c_void_p,
                                    ctypes.c_size_t, ctypes.c_int]
    lib.ad_loader_create.restype = ctypes.c_void_p
    lib.ad_loader_create.argtypes = [
        ctypes.POINTER(ctypes.c_void_p), ctypes.POINTER(ctypes.c_size_t),
        ctypes.POINTER(ctypes.c_int), ctypes.c_int, ctypes.c_size_t,
        ctypes.c_size_t, ctypes.c_int, ctypes.c_int, ctypes.c_uint64,
        ctypes.c_int, ctypes.c_int]
    lib.ad_loader_next.restype = ctypes.c_size_t
    lib.ad_loader_next.argtypes = [ctypes.c_void_p,
                                   ctypes.POINTER(ctypes.c_void_p)]
    lib.ad_loader_release.argtypes = [ctypes.c_void_p,
                                      ctypes.POINTER(ctypes.c_void_p),
                                      ctypes.c_int]
    lib.ad_loader_num_batches.restype = ctypes.c_size_t
    lib.ad_loader_num_batches.argtypes = [ctypes.c_void_p]
    lib.ad_loader_destroy.argtypes = [ctypes.c_void_p]
    # _v2: the pretokenize flag changed the arity; the rename makes a
    # stale .so (which still exports the 2-arg ad_bpe_create) hit the
    # AttributeError staleness guard above instead of silently ignoring
    # the third argument.
    lib.ad_bpe_create_v2.restype = ctypes.c_void_p
    lib.ad_bpe_create_v2.argtypes = [ctypes.POINTER(ctypes.c_int32),
                                     ctypes.c_int32, ctypes.c_int32]
    lib.ad_bpe_encode.restype = ctypes.c_int32
    lib.ad_bpe_encode.argtypes = [ctypes.c_void_p, ctypes.c_void_p,
                                  ctypes.c_int32,
                                  ctypes.POINTER(ctypes.c_int32)]
    lib.ad_bpe_destroy.argtypes = [ctypes.c_void_p]


def get_lib() -> Optional[ctypes.CDLL]:
    """The loaded native library, building it if needed; None when
    unavailable (fallback mode)."""
    global _lib, _build_failed
    if _lib is not None or _build_failed:
        return _lib
    with _lib_lock:
        if _lib is None and not _build_failed:
            if os.environ.get("AUTODIST_NO_NATIVE"):
                _build_failed = True
            else:
                _lib = _build_and_load()
                if _lib is None:
                    _build_failed = True
    return _lib


def native_available() -> bool:
    return get_lib() is not None


def fp32_to_bf16(src: np.ndarray, num_threads: int = 4) -> np.ndarray:
    """Round-to-nearest-even fp32 → bfloat16 on the host.

    Returns an array of dtype ``ml_dtypes.bfloat16`` (numpy's jax-compatible
    bf16).  Native path is multi-threaded; fallback uses numpy."""
    import ml_dtypes

    src = np.ascontiguousarray(src, dtype=np.float32)
    lib = get_lib()
    if lib is None:
        return src.astype(ml_dtypes.bfloat16)  # numpy RNE cast
    out = np.empty(src.shape, dtype=np.uint16)
    lib.ad_fp32_to_bf16(src.ctypes.data_as(ctypes.c_void_p),
                        out.ctypes.data_as(ctypes.c_void_p),
                        src.size, num_threads)
    return out.view(ml_dtypes.bfloat16)


class NativeLoader:
    """Thin RAII wrapper over the C loader. One epoch per instance."""

    def __init__(self, arrays, batch_size: int, drop_last: bool,
                 shuffle: bool, seed: int, num_threads: int,
                 prefetch_depth: int, cast_bf16_flags):
        self._lib = get_lib()
        assert self._lib is not None
        self._arrays = [np.ascontiguousarray(a) for a in arrays]  # keep alive
        n = len(self._arrays)
        arr_ptrs = (ctypes.c_void_p * n)(
            *[a.ctypes.data_as(ctypes.c_void_p).value for a in self._arrays])
        row_bytes = (ctypes.c_size_t * n)(
            *[a.strides[0] for a in self._arrays])
        casts = (ctypes.c_int * n)(*[int(c) for c in cast_bf16_flags])
        self._handle = self._lib.ad_loader_create(
            arr_ptrs, row_bytes, casts, n, self._arrays[0].shape[0],
            batch_size, int(drop_last), int(shuffle), seed & (2**64 - 1),
            num_threads, prefetch_depth)
        if not self._handle:
            raise RuntimeError("ad_loader_create failed")
        self._n = n

    @property
    def num_batches(self) -> int:
        return self._lib.ad_loader_num_batches(self._handle)

    def next(self):
        """Returns (rows, ptrs) — ptrs must be passed to release(); rows == 0
        signals end of epoch."""
        ptrs = (ctypes.c_void_p * self._n)()
        rows = self._lib.ad_loader_next(self._handle, ptrs)
        return rows, ptrs

    def release(self, ptrs) -> None:
        self._lib.ad_loader_release(self._handle, ptrs, self._n)

    def close(self) -> None:
        if self._handle:
            self._lib.ad_loader_destroy(self._handle)
            self._handle = None

    def __del__(self):  # pragma: no cover
        try:
            self.close()
        except Exception:
            pass
