"""Prefetching batch loader over in-memory arrays.

The host-side half of the input pipeline: while the device runs step N, the
native threads assemble batch N+1..N+k into staging buffers (shuffle + gather
+ optional fp32→bf16 cast).  This replaces the reference's feed-dict split
machinery (``autodist/remapper.py:81-123``) — there the per-replica split
happened at ``session.run`` time in Python; here batches stream through a
bounded native queue and the mesh sharding does the splitting on device.

Yielded arrays are views of pooled staging buffers, valid until the next
iteration (copy them to keep them — the usual pinned-buffer contract).
Fallback mode (no native lib) does the same work in numpy, preserving the
exact batch order for a given seed.
"""
from __future__ import annotations

import ctypes
from typing import Dict, List, Optional, Sequence, Union

import numpy as np

from autodist_tpu.runtime import native as _native
from autodist_tpu.utils import logging

ArrayDict = Union[Dict[str, np.ndarray], Sequence[np.ndarray]]


class DataLoader:
    """Iterate minibatches of one or more aligned arrays.

    Args:
      data: dict name→array or sequence of arrays; all share dim 0.
      batch_size: rows per batch.
      shuffle: permute rows each epoch (reshuffled per epoch from ``seed``).
      drop_last: drop the final short batch.
      to_bf16: names (or indices) of float32 arrays to convert to bfloat16
        during gathering — host-side cast halves the bytes sent to HBM.
      num_threads / prefetch_depth: native pipeline parallelism and queue
        depth.
      seed: epoch-0 shuffle seed; epoch k uses ``seed + k``.
    """

    def __init__(self, data: ArrayDict, batch_size: int,
                 shuffle: bool = True, drop_last: bool = True,
                 to_bf16: Sequence = (), num_threads: int = 4,
                 prefetch_depth: int = 2, seed: int = 0):
        if isinstance(data, dict):
            self._names: Optional[List[str]] = list(data.keys())
            arrays = [data[k] for k in self._names]
        else:
            self._names = None
            arrays = list(data)
        if not arrays:
            raise ValueError("DataLoader needs at least one array")
        n0 = arrays[0].shape[0]
        for a in arrays:
            if a.shape[0] != n0:
                raise ValueError("all arrays must share dim 0 "
                                 f"({a.shape[0]} != {n0})")
        self._arrays = [np.ascontiguousarray(a) for a in arrays]
        self._batch_size = int(batch_size)
        self._shuffle = shuffle
        self._drop_last = drop_last
        self._num_threads = num_threads
        self._prefetch_depth = prefetch_depth
        self._seed = seed
        self._epoch = 0

        keys = self._names if self._names is not None else range(len(arrays))
        self._cast = []
        for i, k in enumerate(keys):
            wants = (k in to_bf16) or (i in to_bf16 and self._names is None)
            if wants and self._arrays[i].dtype != np.float32:
                raise ValueError(f"to_bf16 target {k!r} is not float32")
            self._cast.append(bool(wants))
        if any(self._cast):
            import ml_dtypes  # noqa: F401  (required for bf16 views)

        self._use_native = _native.native_available()
        if not self._use_native:
            logging.debug("DataLoader: native runtime unavailable, "
                          "numpy fallback active")

    # -- shapes ------------------------------------------------------------
    @property
    def num_batches(self) -> int:
        n = self._arrays[0].shape[0]
        return n // self._batch_size if self._drop_last else -(-n // self._batch_size)

    def _out_dtype(self, i: int):
        if self._cast[i]:
            import ml_dtypes
            return ml_dtypes.bfloat16
        return self._arrays[i].dtype

    def _wrap(self, batch_list: List[np.ndarray]):
        if self._names is None:
            return tuple(batch_list)
        return dict(zip(self._names, batch_list))

    # -- iteration ---------------------------------------------------------
    def __len__(self) -> int:
        return self.num_batches

    def __iter__(self):
        epoch_seed = self._seed + self._epoch
        self._epoch += 1
        if self._arrays[0].shape[0] == 0:
            return  # empty split: zero batches in both modes
        if self._use_native:
            yield from self._iter_native(epoch_seed)
        else:
            yield from self._iter_numpy(epoch_seed)

    def _iter_native(self, epoch_seed: int):
        loader = _native.NativeLoader(
            self._arrays, self._batch_size, self._drop_last, self._shuffle,
            epoch_seed, self._num_threads, self._prefetch_depth, self._cast)
        held = None
        try:
            while True:
                rows, ptrs = loader.next()
                if held is not None:
                    loader.release(held)   # previous batch's buffers
                    held = None
                if rows == 0:
                    break
                held = ptrs
                out = []
                for i, a in enumerate(self._arrays):
                    dt = self._out_dtype(i)
                    shape = (rows,) + a.shape[1:]
                    nbytes = int(np.prod(shape, dtype=np.int64)) * np.dtype(dt).itemsize
                    buf = (ctypes.c_char * nbytes).from_address(ptrs[i])
                    out.append(np.frombuffer(buf, dtype=dt).reshape(shape))
                yield self._wrap(out)
        finally:
            # Early break / GeneratorExit: return the buffer-set still held
            # by the consumer, else destroy() can't free it (it only frees
            # pool/ready/out-of-order sets).
            if held is not None:
                loader.release(held)
            loader.close()

    # Above this row count the fallback stops paying for bit-exact parity
    # with the native permutation (pure-Python Fisher-Yates is ~µs/row) and
    # uses numpy's shuffle instead — same distribution, different order.
    _EXACT_PARITY_MAX_ROWS = 1_000_000

    def _iter_numpy(self, epoch_seed: int):
        n = self._arrays[0].shape[0]
        perm = np.arange(n, dtype=np.uint32)
        if self._shuffle:
            if n <= self._EXACT_PARITY_MAX_ROWS:
                perm = _mt19937_64_permutation(n, epoch_seed)
            else:
                logging.debug("fallback shuffle: %d rows > parity threshold,"
                              " using numpy permutation", n)
                np.random.default_rng(epoch_seed).shuffle(perm)
        for b in range(self.num_batches):
            idx = perm[b * self._batch_size:(b + 1) * self._batch_size]
            out = []
            for i, a in enumerate(self._arrays):
                rows = a[idx]
                if self._cast[i]:
                    import ml_dtypes
                    rows = rows.astype(ml_dtypes.bfloat16)
                out.append(rows)
            yield self._wrap(out)


def _mt19937_64_permutation(n: int, seed: int) -> np.ndarray:
    """The exact Fisher-Yates permutation the native loader produces (C++
    ``std::mt19937_64`` + modulo draw), so fallback and native mode yield
    identical epochs for a given seed."""
    perm = np.arange(n, dtype=np.uint32)
    rng = _MT19937_64(seed)
    for i in range(n - 1, 0, -1):
        j = rng.next() % (i + 1)
        perm[i], perm[j] = perm[j], perm[i]
    return perm


class _MT19937_64:
    """Minimal mt19937_64 (values match std::mt19937_64)."""

    _NN, _MM = 312, 156
    _MATRIX_A = 0xB5026F5AA96619E9
    _UM, _LM = 0xFFFFFFFF80000000, 0x7FFFFFFF

    def __init__(self, seed: int):
        self.mt = [0] * self._NN
        self.mt[0] = seed & 0xFFFFFFFFFFFFFFFF
        for i in range(1, self._NN):
            self.mt[i] = (6364136223846793005 *
                          (self.mt[i - 1] ^ (self.mt[i - 1] >> 62)) + i) \
                & 0xFFFFFFFFFFFFFFFF
        self.mti = self._NN

    def next(self) -> int:
        if self.mti >= self._NN:
            for i in range(self._NN):
                x = (self.mt[i] & self._UM) | \
                    (self.mt[(i + 1) % self._NN] & self._LM)
                xA = x >> 1
                if x & 1:
                    xA ^= self._MATRIX_A
                self.mt[i] = self.mt[(i + self._MM) % self._NN] ^ xA
            self.mti = 0
        x = self.mt[self.mti]
        self.mti += 1
        x ^= (x >> 29) & 0x5555555555555555
        x ^= (x << 17) & 0x71D67FFFEDA60000
        x ^= (x << 37) & 0xFFF7EEE000000000
        x ^= x >> 43
        return x
