"""Prefetching batch loader over in-memory arrays.

The host-side half of the input pipeline: while the device runs step N, the
native threads assemble batch N+1..N+k into staging buffers (shuffle + gather
+ optional fp32→bf16 cast).  This replaces the reference's feed-dict split
machinery (``autodist/remapper.py:81-123``) — there the per-replica split
happened at ``session.run`` time in Python; here batches stream through a
bounded native queue and the mesh sharding does the splitting on device.

Yielded arrays are views of pooled staging buffers, valid until the next
iteration (copy them to keep them — the usual pinned-buffer contract).
Fallback mode (no native lib) does the same work in numpy, preserving the
exact batch order for a given seed.
"""
from __future__ import annotations

import ctypes
from typing import Dict, List, Optional, Sequence, Union

import numpy as np

from autodist_tpu.runtime import native as _native
from autodist_tpu.utils import logging

ArrayDict = Union[Dict[str, np.ndarray], Sequence[np.ndarray]]


class DataLoader:
    """Iterate minibatches of one or more aligned arrays.

    Args:
      data: dict name→array or sequence of arrays; all share dim 0.
      batch_size: rows per batch.
      shuffle: permute rows each epoch (reshuffled per epoch from ``seed``).
      drop_last: drop the final short batch.
      to_bf16: names (or indices) of float32 arrays to convert to bfloat16
        during gathering — host-side cast halves the bytes sent to HBM.
      num_threads / prefetch_depth: native pipeline parallelism and queue
        depth.
      seed: epoch-0 shuffle seed; epoch k uses ``seed + k``.
      shard: ``(index, count)`` — keep only this host's strided subset of
        rows (``rows[index::count]``, trimmed to ``n // count`` rows so
        every host sees the SAME number of rows and therefore the same
        number of batches — unequal counts would deadlock lockstep
        collectives; the ``n % count`` remainder rows are dropped).  The
        multi-host input split: every host constructs the same loader
        over the same (or identically ordered) data with its own
        ``index``, shards are disjoint, and each host feeds its local
        batches through ``session.place_local_batch`` (the mesh's data
        axis concatenates them logically).  Shuffling then permutes the
        host's OWN subset per epoch — no cross-host coordination is ever
        needed.
    """

    def __init__(self, data: ArrayDict, batch_size: int,
                 shuffle: bool = True, drop_last: bool = True,
                 to_bf16: Sequence = (), num_threads: int = 4,
                 prefetch_depth: int = 2, seed: int = 0,
                 shard: Optional[tuple] = None):
        if isinstance(data, dict):
            self._names: Optional[List[str]] = list(data.keys())
            arrays = [data[k] for k in self._names]
        else:
            self._names = None
            arrays = list(data)
        if not arrays:
            raise ValueError("DataLoader needs at least one array")
        n0 = arrays[0].shape[0]
        for a in arrays:
            if a.shape[0] != n0:
                raise ValueError("all arrays must share dim 0 "
                                 f"({a.shape[0]} != {n0})")
        if shard is not None:
            index, count = shard
            if not 0 <= index < count:
                raise ValueError(
                    f"shard=(index, count) needs 0 <= index < count, "
                    f"got {shard}")
            # Strided split: contiguous block splits would starve the
            # tail hosts of later-file rows under sorted datasets; the
            # stride interleaves whatever order the caller stored.
            # Trim every shard to the SAME row count (drop the n % count
            # remainder): unequal per-host batch counts would deadlock
            # lockstep collectives when hosts drive `sess.run` per local
            # batch.
            arrays = [a[index::count][:n0 // count] for a in arrays]
        self._arrays = [np.ascontiguousarray(a) for a in arrays]
        self._batch_size = int(batch_size)
        self._shuffle = shuffle
        self._drop_last = drop_last
        self._num_threads = num_threads
        self._prefetch_depth = prefetch_depth
        self._seed = seed
        self._epoch = 0
        # Exact mid-epoch resume plumbing (state()/load_state()): the next
        # __iter__ replays epoch `_epoch` skipping its first
        # `_pending_offset` batches; `_live` tracks the in-flight epoch.
        self._pending_offset = 0
        self._live: Optional[dict] = None

        keys = self._names if self._names is not None else range(len(arrays))
        self._cast = []
        for i, k in enumerate(keys):
            wants = (k in to_bf16) or (i in to_bf16 and self._names is None)
            if wants and self._arrays[i].dtype != np.float32:
                raise ValueError(f"to_bf16 target {k!r} is not float32")
            self._cast.append(bool(wants))
        if any(self._cast):
            import ml_dtypes  # noqa: F401  (required for bf16 views)

        self._use_native = _native.native_available()
        if not self._use_native:
            logging.debug("DataLoader: native runtime unavailable, "
                          "numpy fallback active")

    # -- shapes ------------------------------------------------------------
    @property
    def num_batches(self) -> int:
        n = self._arrays[0].shape[0]
        return n // self._batch_size if self._drop_last else -(-n // self._batch_size)

    def _out_dtype(self, i: int):
        if self._cast[i]:
            import ml_dtypes
            return ml_dtypes.bfloat16
        return self._arrays[i].dtype

    def _wrap(self, batch_list: List[np.ndarray]):
        if self._names is None:
            return tuple(batch_list)
        return dict(zip(self._names, batch_list))

    # -- exact resume ------------------------------------------------------
    def state(self, consumed: Optional[int] = None) -> Dict[str, int]:
        """Snapshot the iteration position for exact resume.

        Returns ``{"epoch": e, "offset": o, "seed": s}`` — the next batch
        to produce is batch ``o`` of epoch ``e`` (the MT19937 per-epoch
        permutation makes replay deterministic for a given seed, in both
        native and numpy modes).  ``consumed`` overrides the within-epoch
        count with the CALLER's number of consumed batches — required
        when a prefetcher pulls batches ahead of the training step, since
        this loader cannot know how many of its yields were actually
        stepped (``fit`` passes its own step count).
        """
        live = self._live
        if live is not None and not live["done"]:
            off = live["base"] + (live["yielded"] if consumed is None
                                  else int(consumed))
            epoch = live["epoch"]
            if self.num_batches and off >= self.num_batches:
                epoch, off = epoch + 1, 0
            return {"epoch": epoch, "offset": off, "seed": self._seed}
        return {"epoch": self._epoch, "offset": 0, "seed": self._seed}

    def load_state(self, state: Dict[str, int]) -> Dict[str, int]:
        """Position the loader so its next iteration continues exactly at
        the snapshot: epoch ``state['epoch']`` from batch
        ``state['offset']`` (earlier batches of that epoch are replayed
        and discarded — cheap host work).  Returns the normalized
        position.  The snapshot's shuffle seed must match this loader's;
        a different seed cannot reproduce the recorded batch order."""
        if "seed" in state and int(state["seed"]) != self._seed:
            raise ValueError(
                f"data state was recorded with seed {state['seed']} but "
                f"this loader uses seed {self._seed}; exact resume needs "
                "the identical shuffle stream")
        epoch = int(state["epoch"])
        offset = int(state.get("offset", 0))
        nb = self.num_batches
        if nb and offset >= nb:       # snapshot at an epoch boundary
            epoch += offset // nb
            offset = offset % nb
        self._epoch = epoch
        self._pending_offset = offset
        self._live = None
        return {"epoch": epoch, "offset": offset, "seed": self._seed}

    def reseed(self, seed: int) -> None:
        """Switch the shuffle seed for FUTURE epochs (the numerics
        rollback's re-seeding hook, docs/numerics.md: after restoring a
        verified-good checkpoint, replaying the epochs under a different
        permutation avoids re-hitting a pathological batch ordering).
        Deliberately NOT part of ``load_state`` — exact resume requires
        the identical stream, so changing the seed is an explicit act."""
        self._seed = int(seed)
        self._live = None

    # -- iteration ---------------------------------------------------------
    def __len__(self) -> int:
        return self.num_batches

    def __iter__(self):
        epoch = self._epoch
        epoch_seed = self._seed + epoch
        self._epoch += 1
        start = self._pending_offset
        self._pending_offset = 0
        live = self._live = {"epoch": epoch, "base": start, "yielded": 0,
                             "done": False}
        if self._arrays[0].shape[0] == 0:
            live["done"] = True
            return  # empty split: zero batches in both modes
        it = self._iter_native(epoch_seed) if self._use_native \
            else self._iter_numpy(epoch_seed)
        for i, batch in enumerate(it):
            if i < start:
                continue   # replaying a resumed epoch up to the offset
            # Count BEFORE yielding: the generator suspends at the yield,
            # so a post-yield increment would lag the consumer by one.
            live["yielded"] += 1
            yield batch
        live["done"] = True

    def _iter_native(self, epoch_seed: int):
        loader = _native.NativeLoader(
            self._arrays, self._batch_size, self._drop_last, self._shuffle,
            epoch_seed, self._num_threads, self._prefetch_depth, self._cast)
        held = None
        try:
            while True:
                rows, ptrs = loader.next()
                if held is not None:
                    loader.release(held)   # previous batch's buffers
                    held = None
                if rows == 0:
                    break
                held = ptrs
                out = []
                for i, a in enumerate(self._arrays):
                    dt = self._out_dtype(i)
                    shape = (rows,) + a.shape[1:]
                    nbytes = int(np.prod(shape, dtype=np.int64)) * np.dtype(dt).itemsize
                    buf = (ctypes.c_char * nbytes).from_address(ptrs[i])
                    out.append(np.frombuffer(buf, dtype=dt).reshape(shape))
                yield self._wrap(out)
        finally:
            # Early break / GeneratorExit: return the buffer-set still held
            # by the consumer, else destroy() can't free it (it only frees
            # pool/ready/out-of-order sets).
            if held is not None:
                loader.release(held)
            loader.close()

    # Above this row count the (always bit-exact) fallback shuffle gets
    # noticeably slow (~0.5 s per 1M rows for the swap loop) — warn so the
    # user knows the native loader is the fix, not a different shuffle.
    _SLOW_SHUFFLE_WARN_ROWS = 4_000_000

    def _iter_numpy(self, epoch_seed: int):
        n = self._arrays[0].shape[0]
        perm = np.arange(n, dtype=np.uint32)
        if self._shuffle:
            if n > self._SLOW_SHUFFLE_WARN_ROWS:
                logging.warning(
                    "pure-Python fallback shuffling %d rows; this keeps "
                    "bit-exact parity with the native loader but is slow — "
                    "fix the native build for large datasets", n)
            perm = _mt19937_64_permutation(n, epoch_seed)
        for b in range(self.num_batches):
            idx = perm[b * self._batch_size:(b + 1) * self._batch_size]
            out = []
            for i, a in enumerate(self._arrays):
                rows = a[idx]
                if self._cast[i]:
                    import ml_dtypes
                    rows = rows.astype(ml_dtypes.bfloat16)
                out.append(rows)
            yield self._wrap(out)


def _mt19937_64_permutation(n: int, seed: int) -> np.ndarray:
    """The exact Fisher-Yates permutation the native loader produces (C++
    ``std::mt19937_64`` + modulo draw), so fallback and native mode yield
    identical epochs for a given seed — at ANY row count (multi-host jobs
    where only some hosts fall back must still assemble identical global
    batches).  RNG draws and the per-step modulo are vectorized in blocks;
    only the swap chain itself is a Python loop."""
    perm = list(range(n))
    rng = _MT19937_64(seed)
    i = n - 1
    while i >= 1:
        block = min(i, 8192)
        draws = rng.next_array(block)
        # Fisher-Yates steps i, i-1, ..., i-block+1 use divisors i+1 .. .
        divisors = np.arange(i + 1, i + 1 - block, -1, dtype=np.uint64)
        for j in (draws % divisors).tolist():
            perm[i], perm[j] = perm[j], perm[i]
            i -= 1
    return np.asarray(perm, dtype=np.uint32)


class _MT19937_64:
    """Minimal mt19937_64 (values match std::mt19937_64), with the
    state twist and output tempering vectorized over the 312-word state."""

    _NN, _MM = 312, 156
    _MATRIX_A = 0xB5026F5AA96619E9
    _UM, _LM = 0xFFFFFFFF80000000, 0x7FFFFFFF

    def __init__(self, seed: int):
        mt = [0] * self._NN
        mt[0] = seed & 0xFFFFFFFFFFFFFFFF
        for i in range(1, self._NN):
            mt[i] = (6364136223846793005 *
                     (mt[i - 1] ^ (mt[i - 1] >> 62)) + i) \
                & 0xFFFFFFFFFFFFFFFF
        self.mt = np.array(mt, dtype=np.uint64)
        self.mti = self._NN

    def _twist(self) -> None:
        mt, NN, MM = self.mt, self._NN, self._MM
        u64 = np.uint64
        UM, LM, MA = u64(self._UM), u64(self._LM), u64(self._MATRIX_A)
        one, zero = u64(1), u64(0)

        def mix(cur, nxt, far):
            x = (cur & UM) | (nxt & LM)
            return far ^ (x >> one) ^ np.where(x & one, MA, zero)

        # i < NN-MM reads only pre-twist words; NN-MM <= i < NN-1 reads
        # mt[i-156] already updated this twist; i = NN-1 reads mt[0] (new).
        mt[:NN - MM] = mix(mt[:NN - MM], mt[1:NN - MM + 1], mt[MM:])
        mt[NN - MM:NN - 1] = mix(mt[NN - MM:NN - 1], mt[NN - MM + 1:],
                                 mt[:MM - 1])
        mt[NN - 1:] = mix(mt[NN - 1:], mt[:1], mt[MM - 1:MM])
        self.mti = 0

    @staticmethod
    def _temper(x: np.ndarray) -> np.ndarray:
        u64 = np.uint64
        x = x ^ ((x >> u64(29)) & u64(0x5555555555555555))
        x = x ^ ((x << u64(17)) & u64(0x71D67FFFEDA60000))
        x = x ^ ((x << u64(37)) & u64(0xFFF7EEE000000000))
        return x ^ (x >> u64(43))

    def next_array(self, k: int) -> np.ndarray:
        """Next ``k`` tempered outputs as a uint64 array."""
        out = np.empty(k, dtype=np.uint64)
        filled = 0
        while filled < k:
            if self.mti >= self._NN:
                self._twist()
            take = min(self._NN - self.mti, k - filled)
            out[filled:filled + take] = self._temper(
                self.mt[self.mti:self.mti + take])
            self.mti += take
            filled += take
        return out

    def next(self) -> int:
        return int(self.next_array(1)[0])
