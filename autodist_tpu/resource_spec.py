"""Cluster resource description.

TPU-native analog of the reference's ``autodist/resource_spec.py:45-331``.
The reference parses a ``resource_spec.yml`` naming nodes (address, cpus,
gpus, chief flag, ssh config, network bandwidth) plus SSH credentials.  Here a
node is a TPU-VM worker host with some number of attached TPU chips; SSH
configs are retained for the coordinator's launcher, and an optional explicit
``mesh`` section lets users pin logical mesh-axis sizes (data/model/seq/pipe/
expert) instead of leaving the choice to the strategy builder.

Example yaml::

    nodes:
      - address: 10.0.0.1
        chips: 4
        chief: true
      - address: 10.0.0.2
        chips: 4
        ssh_config: conf1
    ssh:
      conf1:
        username: ubuntu
        key_file: ~/.ssh/id_rsa
        port: 22
        python_venv: source /opt/venv/bin/activate
        shared_envs: {TPU_NAME: my-pod}
    network_bandwidth: 100   # Gbps, used by load-balancing strategies
    hbm_gb: 16               # per-chip HBM budget (pre-flight analyzer)
    num_slices: 2            # optional: two-tier pod = slices joined by DCN
    dcn_gbps: 25             # optional: cross-slice DCN bandwidth per stream
    mesh:                    # optional
      data: 4
      model: 2
"""
from __future__ import annotations

import enum
import os
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import yaml

from autodist_tpu.utils import logging


class DeviceType(enum.Enum):
    """Accelerator kind in a :class:`DeviceSpec` (reference resource_spec.py:218-233)."""

    CPU = "CPU"
    TPU = "TPU"
    GPU = "GPU"  # accepted for spec compatibility; mapped to TPU semantics


@dataclass(frozen=True)
class DeviceSpec:
    """AutoDist-level device name ``address:TPU:index``.

    Parity: the reference's ``DeviceSpec`` with ``address:GPU:idx`` naming and
    a string parser (``autodist/resource_spec.py:218-277``).
    """

    host_address: str
    device_type: DeviceType = DeviceType.TPU
    device_index: int = 0

    def _sort_key(self):
        return (self.host_address, self.device_type.value, self.device_index)

    def __lt__(self, other: "DeviceSpec"):
        return self._sort_key() < other._sort_key()

    def name_string(self) -> str:
        return f"{self.host_address}:{self.device_type.value}:{self.device_index}"

    def __str__(self) -> str:  # pragma: no cover - convenience
        return self.name_string()

    @classmethod
    def from_string(cls, name: str) -> "DeviceSpec":
        parts = name.split(":")
        if len(parts) == 1:
            return cls(host_address=parts[0], device_type=DeviceType.CPU, device_index=0)
        if len(parts) == 2:
            # "address:index" — assume TPU
            return cls(parts[0], DeviceType.TPU, int(parts[1]))
        if len(parts) == 3:
            return cls(parts[0], DeviceType(parts[1].upper()), int(parts[2]))
        raise ValueError(f"Cannot parse device string: {name!r}")


@dataclass
class SSHConfig:
    """SSH credentials for one named config (reference resource_spec.py:160-215)."""

    username: str = ""
    port: int = 22
    key_file: Optional[str] = None
    python_venv: str = ""
    env: Dict[str, str] = field(default_factory=dict)


@dataclass
class NodeSpec:
    address: str
    chips: int = 0
    cpus: List[int] = field(default_factory=list)
    chief: bool = False
    ssh_config: Optional[str] = None


class ResourceSpecError(ValueError):
    pass


#: Single source of truth for the slice/device divisibility rule — quoted by
#: both the session-build fail-fast (``ResourceSpec._validate``) and the
#: static analyzer (``autodist_tpu/analysis/legality.py``).
RULE_SLICE_MISMATCH = "legality/slice-mismatch"


def slice_mismatch_reason(num_devices: int, num_slices: int) -> Optional[str]:
    """Reason string when ``num_slices`` cannot tile ``num_devices``, else None.

    A two-tier (ICI within a slice, DCN across slices) topology only makes
    sense when every slice holds the same whole number of chips; a slice count
    that does not divide the device count would leave a ragged slice whose
    cross-slice exchange has no peer.
    """
    if num_slices <= 1:
        return None
    if num_devices <= 0:
        return None  # device count unknown at this point; checked elsewhere
    if num_devices % num_slices != 0:
        return (f"{RULE_SLICE_MISMATCH}: num_slices={num_slices} does not "
                f"divide device count {num_devices}")
    return None


class ResourceSpec:
    """Parsed cluster description.

    Accepts a yaml path, a pre-parsed dict, or nothing (in which case the
    local JAX devices are used — the common single-host TPU-VM case, a
    convenience the reference lacked because TF required explicit specs).
    """

    def __init__(self, resource_file: Optional[str] = None,
                 resource_info: Optional[dict] = None):
        self._nodes: List[NodeSpec] = []
        self._ssh_configs: Dict[str, SSHConfig] = {}
        self.network_bandwidth_gbps: float = 1.0
        self.ici_connected: bool = False
        self.mesh_hint: Dict[str, int] = {}
        # Second network tier: a pod is `num_slices` ICI-connected slices
        # joined by data-center network at `dcn_gbps` per chip-pair stream.
        # num_slices=1 means the flat single-slice model (all pre-hier specs).
        self.num_slices: int = 1
        self.dcn_gbps: Optional[float] = None
        # Per-chip HBM budget in GiB (yaml `hbm_gb`): consumed by the
        # static analyzer's pre-flight footprint check
        # (autodist_tpu/analysis/memory.py).  None = no budget declared.
        self.hbm_gb: Optional[float] = None
        # Remembered so the Coordinator can ship the spec file to workers
        # (the reference relied on shared paths; we copy explicitly).
        self.source_file: Optional[str] = (
            os.path.abspath(resource_file) if resource_file else None)

        if resource_file is None and resource_info is None:
            # Launcher plumbing (reference const.py SYS_RESOURCE_PATH): the
            # `python -m autodist_tpu.run` CLI ships the spec path via env
            # so user scripts can construct a bare AutoDist().
            from autodist_tpu.const import ENV

            env_path = ENV.SYS_RESOURCE_PATH.val
            if env_path:
                resource_file = env_path
                self.source_file = os.path.abspath(env_path)
        if resource_info is None and resource_file is not None:
            if not os.path.exists(resource_file):
                raise ResourceSpecError(f"Resource spec file not found: {resource_file}")
            with open(resource_file, "r", encoding="utf-8") as f:
                resource_info = yaml.safe_load(f)
        if resource_info is not None:
            self._parse(resource_info)
        else:
            self._from_local_devices()
        self._validate()

    # -- construction ------------------------------------------------------
    def _parse(self, info: dict) -> None:
        nodes = info.get("nodes")
        if not nodes:
            raise ResourceSpecError("resource spec must contain a non-empty 'nodes' list")
        for raw in nodes:
            if "address" not in raw:
                raise ResourceSpecError(f"node entry missing 'address': {raw}")
            chips = int(raw.get("chips", raw.get("tpus", 0)) or 0)
            # Accept the reference's 'gpus' key, treating listed accelerator
            # indices as chips (spec-file compatibility).
            if not chips and raw.get("gpus"):
                chips = len(raw["gpus"])
            node = NodeSpec(
                address=str(raw["address"]),
                chips=chips,
                cpus=[int(c) for c in raw.get("cpus", [])],
                chief=bool(raw.get("chief", False)),
                ssh_config=raw.get("ssh_config"),
            )
            self._nodes.append(node)
        for name, raw in (info.get("ssh") or {}).items():
            self._ssh_configs[name] = SSHConfig(
                username=raw.get("username", ""),
                port=int(raw.get("port", 22)),
                key_file=raw.get("key_file"),
                python_venv=raw.get("python_venv", ""),
                env={str(k): str(v) for k, v in (raw.get("shared_envs") or {}).items()},
            )
        self.network_bandwidth_gbps = float(info.get("network_bandwidth", 1.0))
        # TPU pod slice: hosts are ICI-connected (one interconnect domain),
        # so cross-host collectives do NOT drop to NIC/DCN bandwidth — the
        # defining difference from the reference's GPU clusters.  Yaml key:
        # `ici_connected: true`.
        self.ici_connected = bool(info.get("ici_connected", False))
        if info.get("num_slices") is not None:
            self.num_slices = int(info["num_slices"])
            if self.num_slices < 1:
                raise ResourceSpecError(
                    f"num_slices must be >= 1, got {self.num_slices}")
        if info.get("dcn_gbps") is not None:
            self.dcn_gbps = float(info["dcn_gbps"])
            if self.dcn_gbps <= 0:
                raise ResourceSpecError(
                    f"dcn_gbps must be positive, got {self.dcn_gbps}")
        if info.get("hbm_gb") is not None:
            self.hbm_gb = float(info["hbm_gb"])
            if self.hbm_gb <= 0:
                raise ResourceSpecError(
                    f"hbm_gb must be positive, got {self.hbm_gb}")
        self.mesh_hint = {str(k): int(v) for k, v in (info.get("mesh") or {}).items()}
        # Reference behavior: exactly-one-chief check, defaulting the single
        # node to chief (resource_spec.py:120-150).
        if len(self._nodes) == 1:
            self._nodes[0].chief = True

    def _from_local_devices(self) -> None:
        import jax  # local import: keep spec parsing importable without jax

        n = len(jax.devices())
        self._nodes = [NodeSpec(address="localhost", chips=n, chief=True)]
        logging.info("ResourceSpec auto-derived from local devices: %d chip(s)", n)

    def _validate(self) -> None:
        chiefs = [n for n in self._nodes if n.chief]
        if len(chiefs) != 1:
            raise ResourceSpecError(
                f"resource spec must designate exactly one chief node, got {len(chiefs)}"
            )
        seen = set()
        for n in self._nodes:
            if n.address in seen:
                raise ResourceSpecError(f"duplicate node address {n.address}")
            seen.add(n.address)
            if n.chips == 0 and not n.cpus:
                n.cpus = [0]  # CPU-only node, mirrors reference's cpu fallback
        for n in self._nodes:
            if n.ssh_config and n.ssh_config not in self._ssh_configs:
                raise ResourceSpecError(f"node {n.address} names unknown ssh config "
                                        f"{n.ssh_config!r}")
        reason = slice_mismatch_reason(self.num_chips, self.num_slices)
        if reason is not None:
            raise ResourceSpecError(reason)

    # -- queries -----------------------------------------------------------
    @property
    def nodes(self) -> List[NodeSpec]:
        return list(self._nodes)

    @property
    def num_nodes(self) -> int:
        return len(self._nodes)

    @property
    def chief(self) -> str:
        """Chief node address (reference resource_spec.py:120-135)."""
        return next(n.address for n in self._nodes if n.chief)

    @property
    def ssh_config_map(self) -> Dict[str, SSHConfig]:
        return dict(self._ssh_configs)

    def ssh_config_for(self, address: str) -> Optional[SSHConfig]:
        node = next((n for n in self._nodes if n.address == address), None)
        if node is None or node.ssh_config is None:
            return None
        return self._ssh_configs[node.ssh_config]

    @property
    def num_chips(self) -> int:
        return sum(n.chips for n in self._nodes)

    @property
    def dcn_bytes_per_s(self) -> Optional[float]:
        """Declared cross-slice DCN bandwidth in bytes/s (None when the spec
        does not carry one) — the per-tier constant used to price ``dcn``
        legs before any fitted calibration exists."""
        if self.dcn_gbps is None:
            return None
        return self.dcn_gbps * 1e9 / 8.0

    @property
    def hbm_bytes_per_chip(self) -> Optional[int]:
        """Declared per-chip HBM budget in bytes (None when the spec does
        not carry one) — the default budget for the pre-flight analyzer's
        static footprint check."""
        if self.hbm_gb is None:
            return None
        return int(self.hbm_gb * (1 << 30))

    @property
    def tpu_devices(self) -> List[DeviceSpec]:
        """All accelerator devices, ordered by node then index."""
        out = []
        for n in self._nodes:
            for i in range(n.chips):
                out.append(DeviceSpec(n.address, DeviceType.TPU, i))
        return out

    @property
    def cpu_devices(self) -> List[DeviceSpec]:
        out = []
        for n in self._nodes:
            for i in (n.cpus or [0]):
                out.append(DeviceSpec(n.address, DeviceType.CPU, i))
        return out

    @property
    def devices(self) -> List[DeviceSpec]:
        """Compute devices used for replicas: TPU chips, or CPUs of chip-less
        nodes (parity with reference PS strategy device choice,
        strategy/ps_strategy.py:45-60)."""
        out: List[DeviceSpec] = []
        for n in self._nodes:
            if n.chips:
                out.extend(DeviceSpec(n.address, DeviceType.TPU, i) for i in range(n.chips))
            else:
                out.extend(DeviceSpec(n.address, DeviceType.CPU, i) for i in (n.cpus or [0]))
        return out

    def node_address_to_chips(self) -> Dict[str, int]:
        return {n.address: n.chips for n in self._nodes}

    def __repr__(self) -> str:  # pragma: no cover
        return (f"ResourceSpec(nodes={len(self._nodes)}, chips={self.num_chips}, "
                f"chief={self.chief!r})")
