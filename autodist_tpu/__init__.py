"""autodist_tpu — a TPU-native distributed training framework.

A ground-up JAX/XLA re-design with the capabilities of AutoDist (Petuum):
distribution expressed as a compilation problem.  A declarative cluster
description (:class:`ResourceSpec`), a per-variable :class:`Strategy`
(synchronizer + partitioner + placement), and a strategy compiler that lowers
the strategy onto a :class:`jax.sharding.Mesh` as shardings and XLA
collectives — instead of the reference's TF graph rewriting
(see /root/reference/autodist/autodist.py:297-322 for the original facade).
"""
from autodist_tpu.const import ENV  # noqa: F401
from autodist_tpu.resource_spec import DeviceSpec, ResourceSpec  # noqa: F401

__version__ = "0.1.0"

__all__ = ["AutoDist", "ResourceSpec", "DeviceSpec", "ENV", "Callback",
           "TimeHistory", "History", "__version__"]


def __getattr__(name):
    # Lazy: importing the facade pulls in jax; keep `import autodist_tpu` light.
    if name == "AutoDist":
        from autodist_tpu.autodist import AutoDist
        return AutoDist
    if name in ("Callback", "TimeHistory", "History"):
        from autodist_tpu import fit as _fit
        return getattr(_fit, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
