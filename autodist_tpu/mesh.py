"""Device-mesh construction.

This is the TPU-native replacement for the reference's device-resolution layer
(``autodist/kernel/device/resolver.py:25-67`` maps ``ip:GPU:i`` names to TF
device strings).  Here, abstract :class:`DeviceSpec` lists resolve to
coordinates on a :class:`jax.sharding.Mesh`; strategies then express placement
as ``PartitionSpec`` over named mesh axes instead of per-op device strings.

Axis convention (outermost → innermost): ``pipe, data, expert, seq, model``.
``model`` is innermost so tensor-parallel collectives ride nearest-neighbor
ICI links; ``data``/``pipe`` are outermost so their (smaller, less frequent)
collectives can cross DCN on multi-slice topologies — the layout recipe of the
scaling-book / GSPMD literature.
"""
from __future__ import annotations

import math
from typing import Dict, List, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

from autodist_tpu.const import (
    MESH_AXIS_DATA,
    MESH_AXIS_EXPERT,
    MESH_AXIS_MODEL,
    MESH_AXIS_PIPE,
    MESH_AXIS_SEQ,
)
from autodist_tpu.resource_spec import ResourceSpec
from autodist_tpu.utils import logging

# Canonical ordering, outermost first.
AXIS_ORDER = (MESH_AXIS_PIPE, MESH_AXIS_DATA, MESH_AXIS_EXPERT, MESH_AXIS_SEQ,
              MESH_AXIS_MODEL)


def _canonical_axes(axes: Dict[str, int]) -> Dict[str, int]:
    """Order user axes canonically; unknown axis names keep insertion order at
    the end (allowed, but the five standard names get optimal placement).
    Explicitly requested size-1 axes are preserved — strategies may emit
    PartitionSpecs naming them."""
    ordered: Dict[str, int] = {}
    for name in AXIS_ORDER:
        if name in axes:
            ordered[name] = axes[name]
    for name, size in axes.items():
        if name not in ordered:
            ordered[name] = size
    if not ordered:
        # Degenerate no-axes mesh still needs one axis.
        ordered[MESH_AXIS_DATA] = 1
    return ordered


def build_mesh(axes: Optional[Dict[str, int]] = None,
               resource_spec: Optional[ResourceSpec] = None,
               devices: Optional[Sequence] = None) -> Mesh:
    """Build a :class:`jax.sharding.Mesh`.

    Args:
      axes: mapping axis name → size.  Missing total capacity is absorbed into
        the ``data`` axis.  If ``None``, uses ``resource_spec.mesh_hint`` or
        pure data parallelism over all devices.
      resource_spec: optional cluster description (used for the mesh hint and
        for sanity-checking device counts).
      devices: explicit device list; defaults to ``jax.devices()``.
    """
    if devices is None:
        devices = jax.devices()
    devices = list(devices)
    n = len(devices)

    if axes is None:
        axes = dict(resource_spec.mesh_hint) if (
            resource_spec is not None and resource_spec.mesh_hint) else {}
    axes = dict(axes)

    specified = math.prod(axes.values()) if axes else 1
    if n % specified != 0:
        raise ValueError(
            f"mesh axes {axes} (product {specified}) do not divide device count {n}")
    remainder = n // specified
    if remainder > 1:
        # Absorb leftover capacity into the data axis.
        axes[MESH_AXIS_DATA] = axes.get(MESH_AXIS_DATA, 1) * remainder

    axes = _canonical_axes(axes)
    shape = tuple(axes.values())
    names = tuple(axes.keys())

    if resource_spec is not None and resource_spec.num_chips not in (0, n):
        logging.warning(
            "ResourceSpec declares %d chips but %d JAX devices are visible; "
            "using the visible devices.", resource_spec.num_chips, n)

    if math.prod(shape) != n:
        raise ValueError(f"mesh shape {dict(zip(names, shape))} != {n} devices")

    if devices[0].platform == "tpu":
        # Topology-aware placement so the innermost axes ride ICI neighbors.
        # Genuine shape/topology mismatches must propagate — a silently
        # misplaced mesh costs performance with no diagnostic.
        from jax.experimental import mesh_utils
        mesh_devices = mesh_utils.create_device_mesh(shape, devices=devices)
    else:
        mesh_devices = np.asarray(devices).reshape(shape)

    return Mesh(mesh_devices, names)


def build_hybrid_mesh(ici_axes: Dict[str, int], dcn_axes: Dict[str, int],
                      devices: Optional[Sequence] = None) -> Mesh:
    """Multi-slice mesh: ``dcn_axes`` shard across slices (over DCN), while
    ``ici_axes`` shard within a slice (over ICI).  The reference's
    inter-node/intra-node split (gRPC between hosts, NCCL within,
    ``autodist/kernel/synchronization/ps_synchronizer.py:248-329``) maps to
    exactly this DCN/ICI distinction.

    On real multi-slice TPU hardware the per-slice topology is read from
    device attributes (``mesh_utils.create_hybrid_device_mesh``); a TPU
    fleet whose metadata says ONE physical slice fails loudly rather than
    emulate a DCN split that would actually ride ICI.  Devices without
    slice metadata (CPU test meshes) get an emulated layout: the device
    list is split into ``prod(dcn_axes)`` equal "slices" in order,
    preserving the same axis semantics — each combined axis is
    (DCN-outer, ICI-inner)."""
    if devices is None:
        devices = jax.devices()
    devices = list(devices)

    merged = dict(dcn_axes)
    for k, v in ici_axes.items():
        merged.setdefault(k, v)
    names = list(_canonical_axes(merged).keys())
    ici_shape = [ici_axes.get(name, 1) for name in names]
    dcn_shape = [dcn_axes.get(name, 1) for name in names]

    num_slices = math.prod(dcn_shape)
    slice_ids = {getattr(d, "slice_index", None) for d in devices}
    if None not in slice_ids and (len(slice_ids) > 1 or num_slices == 1):
        # Real multi-slice metadata present (or a trivial 1-slice request):
        # always delegate — a shape/topology mismatch must fail LOUDLY
        # there, never silently emulate (axes the user declared ICI would
        # cross real DCN boundaries).
        from jax.experimental import mesh_utils

        mesh_devices = mesh_utils.create_hybrid_device_mesh(
            tuple(ici_shape), tuple(dcn_shape), devices=devices)
        return Mesh(mesh_devices, tuple(names))

    if None not in slice_ids and devices[0].platform == "tpu":
        # Real TPU metadata says ONE physical slice, yet the caller
        # declared a multi-slice topology: fail loudly.  Emulating here
        # would let a misdeclared fleet run with a fabricated DCN-outer
        # split — axes the user believes cross DCN would all ride one
        # slice's ICI, silently mispricing every collective.
        raise ValueError(
            f"dcn_axes={dict(zip(names, dcn_shape))} requests "
            f"{num_slices} slices but all {len(devices)} TPU devices "
            f"report slice_index={next(iter(slice_ids))}; this fleet is "
            f"single-slice (use build_mesh, or fix the topology)")

    # No slice metadata (CPU test meshes — incl. multi-process gloo
    # runtimes whose CPU devices all carry no usable slice split):
    # emulated layout — contiguous equal slices, DCN-outer / ICI-inner.
    if len(devices) != num_slices * math.prod(ici_shape):
        raise ValueError(
            f"hybrid mesh {dict(zip(names, dcn_shape))} x "
            f"{dict(zip(names, ici_shape))} needs "
            f"{num_slices * math.prod(ici_shape)} devices, "
            f"have {len(devices)}")
    arr = np.asarray(devices).reshape(tuple(dcn_shape) + tuple(ici_shape))
    k = len(names)
    perm: List[int] = []
    for i in range(k):
        perm += [i, k + i]
    arr = arr.transpose(perm).reshape(
        [dcn_shape[i] * ici_shape[i] for i in range(k)])
    return Mesh(arr, tuple(names))


def data_axis_size(mesh: Mesh) -> int:
    return mesh.shape.get(MESH_AXIS_DATA, 1)


def mesh_coords_of(mesh: Mesh, device) -> Dict[str, int]:
    """Coordinates of ``device`` on each mesh axis — the TPU analog of the
    reference's resolved TF device string (``/job:worker/task:k/device:GPU:i``)."""
    idx = np.argwhere(mesh.devices == device)
    if idx.size == 0:
        raise ValueError(f"device {device} not in mesh")
    return {name: int(c) for name, c in zip(mesh.axis_names, idx[0])}
