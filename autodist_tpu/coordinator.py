"""Coordinator: fan the user script out to every worker host.

Parity with reference ``autodist/coordinator.py:41-110``: the chief re-launches
the *same user script* (``python sys.argv``) on each non-chief node over SSH,
after shipping the serialized strategy, with environment variables telling the
worker who it is.  A watcher thread per remote process fails the whole job
fast (``os._exit(1)``) when any worker dies — the reference's only failure-
detection mechanism, kept here verbatim in spirit.

The execution model is identical to SPMD: every process runs the same program.
What the env adds on top of plain JAX multi-process is (a) strategy shipping —
workers deserialize instead of rebuilding, so all processes provably use one
strategy (``autodist.py:100-109``), and (b) rendezvous bootstrap
(``AUTODIST_COORDINATOR_ADDRESS`` etc. consumed by ``Cluster.start``).
"""
from __future__ import annotations

import os
import subprocess
import sys
import threading
from typing import List, Optional, Tuple

from autodist_tpu.cluster import Cluster
from autodist_tpu.const import DEFAULT_STRATEGY_DIR, ENV
from autodist_tpu.utils import logging


class Coordinator:
    """Launches and babysits worker client processes (chief only)."""

    def __init__(self, strategy, cluster: Cluster):
        self._strategy = strategy
        self._cluster = cluster
        self._procs: List[Tuple[str, object]] = []
        self._watchers: List[threading.Thread] = []
        self._terminating = False

    def launch_clients(self, argv: Optional[List[str]] = None) -> None:
        """Re-run the user script on every non-chief node
        (reference ``coordinator.py:46-90``)."""
        argv = list(argv if argv is not None else sys.argv)
        if argv and not os.path.isabs(argv[0]):
            argv[0] = os.path.abspath(argv[0])
        spec = self._cluster.resource_spec

        # Reuse the file build_strategy() already wrote; serialize only if
        # the strategy was constructed out-of-band.
        strategy_path = self._strategy.path
        if not os.path.exists(strategy_path):
            strategy_path = self._strategy.serialize()
        for node in spec.nodes:
            if self._cluster.is_chief(node.address):
                continue
            # Ship the strategy file so the worker deserializes the chief's
            # strategy (reference coordinator.py:84-88), and the resource
            # spec so the worker's AutoDist(<same argv>) finds it at the
            # same path.
            remote_path = os.path.join(DEFAULT_STRATEGY_DIR,
                                       self._strategy.id)
            self._cluster.remote_copy(strategy_path, remote_path, node.address)
            if spec.source_file:
                self._cluster.remote_copy(spec.source_file, spec.source_file,
                                          node.address)
            # Best-effort: ship the user script itself so workers don't need
            # a shared filesystem for the code (the reference assumed
            # identically-deployed code; we copy the entry script when we
            # have it — packages still must be pre-deployed).
            if argv and os.path.isfile(argv[0]):
                try:
                    self._cluster.remote_copy(argv[0], argv[0], node.address)
                except Exception as e:  # genuinely best-effort: the code may
                    # already be deployed at a read-only path on the worker
                    logging.warning("could not ship %s to %s (%s); assuming "
                                    "it is already deployed", argv[0],
                                    node.address, e)
            env = {
                ENV.AUTODIST_WORKER.name: node.address,
                ENV.AUTODIST_STRATEGY_ID.name: self._strategy.id,
                # Launcher plumbing: a worker script constructing a bare
                # AutoDist() finds the shipped spec via env (run.py CLI).
                **({ENV.SYS_RESOURCE_PATH.name: spec.source_file}
                   if spec.source_file else {}),
                ENV.AUTODIST_COORDINATOR_ADDRESS.name:
                    self._cluster.coordinator_address,
                ENV.AUTODIST_NUM_PROCESSES.name:
                    str(self._cluster.num_processes),
                ENV.AUTODIST_PROCESS_ID.name:
                    str(self._cluster.process_id_for(node.address)),
                ENV.AUTODIST_MIN_LOG_LEVEL.name:
                    str(ENV.AUTODIST_MIN_LOG_LEVEL.val),
            }
            # Keep the cluster flavor consistent across processes: a pod
            # chief must produce pod workers (metadata rendezvous), not SSH
            # workers pointed at a nonexistent coordination service.  Same
            # for the workdir — the worker must deserialize the strategy
            # from the directory the chief copied it into.
            for passthrough in (ENV.AUTODIST_TPU_POD.name,
                                "AUTODIST_TPU_WORKDIR"):
                if os.environ.get(passthrough):
                    env[passthrough] = os.environ[passthrough]
            proc = self._cluster.remote_exec(
                [sys.executable or "python", "-u"] + argv,
                address=node.address, env=env)
            if proc is None:  # AUTODIST_DEBUG_REMOTE
                continue
            self._procs.append((node.address, proc))
            watcher = threading.Thread(
                target=self._watch, args=(node.address, proc), daemon=True)
            watcher.start()
            self._watchers.append(watcher)
            logging.info("launched worker client on %s (pid %d)",
                         node.address, proc.pid)

    def _watch(self, address: str, proc) -> None:
        """Fail-fast on worker death (reference ``coordinator.py:98-110``)."""
        code = proc.wait()
        if code != 0 and not self._terminating:
            logging.error("worker %s exited with code %s — aborting job",
                          address, code)
            os._exit(1)

    def join(self) -> None:
        """Wait for all workers (reference ``coordinator.py:92-96``)."""
        for address, proc in self._procs:
            code = proc.wait()
            logging.info("worker %s finished with code %s", address, code)

    def reap(self, timeout: float = 30.0) -> None:
        """Bounded exit-time join: wait up to ``timeout`` seconds total for
        workers, then terminate stragglers.  Used from atexit — an unbounded
        ``join()`` there would turn a chief-side crash after launch into an
        indefinite hang (workers blocked in collectives never exit on their
        own once the chief is gone)."""
        import time

        deadline = time.monotonic() + timeout
        for address, proc in self._procs:
            remaining = deadline - time.monotonic()
            try:
                if remaining > 0:
                    proc.wait(timeout=remaining)
                else:
                    raise subprocess.TimeoutExpired(cmd="worker",
                                                    timeout=timeout)
            except subprocess.TimeoutExpired:
                self._terminating = True
                logging.warning("worker %s still running at exit — "
                                "terminating", address)
                proc.terminate()

    def terminate(self) -> None:
        self._terminating = True
        for _, proc in self._procs:
            if proc.poll() is None:
                proc.terminate()
