"""Coordinator: fan the user script out to every worker host.

Parity with reference ``autodist/coordinator.py:41-110``: the chief re-launches
the *same user script* (``python sys.argv``) on each non-chief node over SSH,
after shipping the serialized strategy, with environment variables telling the
worker who it is.  A watcher thread per remote process observes worker death
— the reference fails the whole job fast (``os._exit(1)``), and that remains
the DEFAULT here; a :class:`~autodist_tpu.resilience.supervisor.FailurePolicy`
(constructor arg or ``AUTODIST_FAILURE_POLICY`` env) can instead ignore the
death, relaunch the dead worker in place through the same Cluster machinery,
or record the failing host for the job-level supervisor before aborting
(see docs/resilience.md).

The execution model is identical to SPMD: every process runs the same program.
What the env adds on top of plain JAX multi-process is (a) strategy shipping —
workers deserialize instead of rebuilding, so all processes provably use one
strategy (``autodist.py:100-109``), and (b) rendezvous bootstrap
(``AUTODIST_COORDINATOR_ADDRESS`` etc. consumed by ``Cluster.start``).
"""
from __future__ import annotations

import os
import subprocess
import sys
import threading
from typing import List, Optional, Tuple

from autodist_tpu.cluster import Cluster
from autodist_tpu.const import DEFAULT_STRATEGY_DIR, ENV
from autodist_tpu.utils import logging


class Coordinator:
    """Launches and babysits worker client processes (chief only)."""

    def __init__(self, strategy, cluster: Cluster, failure_policy=None):
        self._strategy = strategy
        self._cluster = cluster
        self._procs: List[Tuple[str, object]] = []
        self._watchers: List[threading.Thread] = []
        self._terminating = False
        self._argv: Optional[List[str]] = None
        if failure_policy is None:
            # Env-selected policy (AUTODIST_FAILURE_POLICY); None keeps the
            # reference fail-fast.  Lazy import: the resilience package must
            # not load on the worker bootstrap path unless asked for.
            try:
                from autodist_tpu.resilience.supervisor import policy_from_env
                failure_policy = policy_from_env()
            except Exception as e:
                logging.warning("failure policy from env unavailable (%s); "
                                "using fail-fast", e)
                failure_policy = None
        self._policy = failure_policy

    def launch_clients(self, argv: Optional[List[str]] = None) -> None:
        """Re-run the user script on every non-chief node
        (reference ``coordinator.py:46-90``)."""
        argv = list(argv if argv is not None else sys.argv)
        if argv and not os.path.isabs(argv[0]):
            argv[0] = os.path.abspath(argv[0])
        self._argv = argv
        for node in self._cluster.resource_spec.nodes:
            if self._cluster.is_chief(node.address):
                continue
            proc = self._launch_one(node.address, argv)
            if proc is None:  # AUTODIST_DEBUG_REMOTE
                continue
            self._procs.append((node.address, proc))
            watcher = threading.Thread(
                target=self._watch, args=(node.address, proc), daemon=True)
            watcher.start()
            self._watchers.append(watcher)
            logging.info("launched worker client on %s (pid %d)",
                         node.address, proc.pid)

    def _launch_one(self, address: str, argv: Optional[List[str]] = None):
        """Ship state and start ONE worker client — the unit
        ``launch_clients`` fans out and a relaunching failure policy
        re-invokes for a dead worker."""
        argv = list(argv if argv is not None else (self._argv or sys.argv))
        spec = self._cluster.resource_spec

        # Reuse the file build_strategy() already wrote; serialize only if
        # the strategy was constructed out-of-band.
        strategy_path = self._strategy.path
        if not os.path.exists(strategy_path):
            strategy_path = self._strategy.serialize()
        # Ship the strategy file so the worker deserializes the chief's
        # strategy (reference coordinator.py:84-88), and the resource
        # spec so the worker's AutoDist(<same argv>) finds it at the
        # same path.
        remote_path = os.path.join(DEFAULT_STRATEGY_DIR, self._strategy.id)
        self._cluster.remote_copy(strategy_path, remote_path, address)
        if spec.source_file:
            self._cluster.remote_copy(spec.source_file, spec.source_file,
                                      address)
        # Best-effort: ship the user script itself so workers don't need
        # a shared filesystem for the code (the reference assumed
        # identically-deployed code; we copy the entry script when we
        # have it — packages still must be pre-deployed).
        if argv and os.path.isfile(argv[0]):
            try:
                self._cluster.remote_copy(argv[0], argv[0], address)
            except Exception as e:  # genuinely best-effort: the code may
                # already be deployed at a read-only path on the worker
                logging.warning("could not ship %s to %s (%s); assuming "
                                "it is already deployed", argv[0],
                                address, e)
        env = {
            ENV.AUTODIST_WORKER.name: address,
            ENV.AUTODIST_STRATEGY_ID.name: self._strategy.id,
            # Launcher plumbing: a worker script constructing a bare
            # AutoDist() finds the shipped spec via env (run.py CLI).
            **({ENV.SYS_RESOURCE_PATH.name: spec.source_file}
               if spec.source_file else {}),
            ENV.AUTODIST_COORDINATOR_ADDRESS.name:
                self._cluster.coordinator_address,
            ENV.AUTODIST_NUM_PROCESSES.name:
                str(self._cluster.num_processes),
            ENV.AUTODIST_PROCESS_ID.name:
                str(self._cluster.process_id_for(address)),
            ENV.AUTODIST_MIN_LOG_LEVEL.name:
                str(ENV.AUTODIST_MIN_LOG_LEVEL.val),
        }
        # Keep the cluster flavor consistent across processes: a pod
        # chief must produce pod workers (metadata rendezvous), not SSH
        # workers pointed at a nonexistent coordination service.  Same
        # for the workdir — the worker must deserialize the strategy
        # from the directory the chief copied it into.  The resilience
        # vars ride along so workers share the chief's chaos spec,
        # attempt stamp, and supervisor marker dir.
        for passthrough in (ENV.AUTODIST_TPU_POD.name,
                            "AUTODIST_TPU_WORKDIR",
                            ENV.AUTODIST_CHAOS.name,
                            ENV.AUTODIST_ATTEMPT.name,
                            ENV.AUTODIST_SUPERVISOR_DIR.name,
                            # recovery-tier knobs (checkpoint/tiers.py):
                            # every worker snapshots on the chief's
                            # cadence into the shared mirror layout
                            ENV.AUTODIST_SNAPSHOT_EVERY.name,
                            ENV.AUTODIST_SNAPSHOT_KEEP.name,
                            ENV.AUTODIST_SNAPSHOT_DIR.name,
                            ENV.AUTODIST_PREEMPT_GRACE_S.name):
            if os.environ.get(passthrough):
                env[passthrough] = os.environ[passthrough]
        return self._cluster.remote_exec(
            [sys.executable or "python", "-u"] + argv,
            address=address, env=env)

    def _watch(self, address: str, proc) -> None:
        """Observe worker death; the failure policy decides what happens
        (default: the reference's fail-fast, ``coordinator.py:98-110``)."""
        while True:
            code = proc.wait()
            if code == 0 or self._terminating:
                return
            action = "abort"
            if self._policy is not None:
                try:
                    action = self._policy.on_worker_exit(address, code) \
                        or "abort"
                except Exception as e:
                    logging.error("failure policy raised (%s); falling back "
                                  "to abort", e)
            if action == "ignore":
                logging.warning("worker %s exited with code %s — ignored "
                                "by failure policy", address, code)
                return
            if action == "relaunch" and not self._terminating:
                try:
                    new_proc = self._launch_one(address)
                except Exception as e:
                    logging.error("relaunch of worker %s failed (%s) — "
                                  "aborting job", address, e)
                    new_proc = None
                if new_proc is not None:
                    logging.info("relaunched worker client on %s (pid %d)",
                                 address, new_proc.pid)
                    self._procs.append((address, new_proc))
                    proc = new_proc
                    continue
            logging.error("worker %s exited with code %s — aborting job",
                          address, code)
            os._exit(getattr(self._policy, "exit_code", 1))

    def join(self) -> None:
        """Wait for all workers (reference ``coordinator.py:92-96``)."""
        for address, proc in self._procs:
            code = proc.wait()
            logging.info("worker %s finished with code %s", address, code)

    def reap(self, timeout: float = 30.0) -> None:
        """Bounded exit-time join: wait up to ``timeout`` seconds total for
        workers, then terminate stragglers.  Used from atexit — an unbounded
        ``join()`` there would turn a chief-side crash after launch into an
        indefinite hang (workers blocked in collectives never exit on their
        own once the chief is gone)."""
        import time

        deadline = time.monotonic() + timeout
        for address, proc in self._procs:
            remaining = deadline - time.monotonic()
            try:
                if remaining > 0:
                    proc.wait(timeout=remaining)
                else:
                    raise subprocess.TimeoutExpired(cmd="worker",
                                                    timeout=timeout)
            except subprocess.TimeoutExpired:
                self._terminating = True
                logging.warning("worker %s still running at exit — "
                                "terminating", address)
                proc.terminate()

    def terminate(self) -> None:
        self._terminating = True
        for _, proc in self._procs:
            if proc.poll() is None:
                proc.terminate()
