"""Model zoo plumbing.

The reference ships its benchmark models as example scripts
(``examples/benchmark/imagenet.py`` — ResNet/VGG/DenseNet/Inception via
tf.keras.applications, ``examples/benchmark/bert.py``, ``examples/lm1b``,
NCF).  Here each model family is a first-class module exposing a
:class:`ModelSpec` that plugs straight into ``AutoDist.capture``:

    spec = resnet.resnet50(num_classes=1000)
    params = spec.init(jax.random.PRNGKey(0))
    ad.capture(params=params, optimizer=..., loss_fn=spec.loss_fn,
               sparse_vars=spec.sparse_vars)
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Sequence, Tuple

import jax
import numpy as np


@dataclass
class ModelSpec:
    """Everything AutoDist needs to distribute one model."""

    name: str
    init: Callable                 # rng -> params
    loss_fn: Callable              # (params, batch) -> scalar loss
    apply_fn: Callable             # (params, inputs) -> outputs (serving)
    make_batch: Callable           # (rng, batch_size) -> batch pytree
    # optional manual value-and-grad: (params, batch) -> (loss, grads);
    # when set, capture(grad_fn=spec.grad_fn) replaces autodiff (e.g. the
    # hand-scheduled 1F1B pipeline backward)
    grad_fn: Any = None
    sparse_vars: Tuple[str, ...] = ()
    untrainable_vars: Tuple[str, ...] = ()
    pipeline_vars: Tuple[str, ...] = ()  # leading dim = pipeline-stage axis
    expert_vars: Tuple[str, ...] = ()    # leading dim = MoE expert axis
    config: Dict[str, Any] = field(default_factory=dict)

    def sample_batch(self, batch_size: int, seed: int = 0):
        return self.make_batch(np.random.RandomState(seed), batch_size)


def layer_norm(x, scale, eps=1e-6) -> jax.Array:
    """Bias-free layer norm (matches flax ``nn.LayerNorm(use_bias=False)``)
    for the functional (non-flax) models."""
    mu = x.mean(-1, keepdims=True)
    var = ((x - mu) ** 2).mean(-1, keepdims=True)
    return (x - mu) * jax.lax.rsqrt(var + eps) * scale


def cross_entropy_loss(logits, labels) -> jax.Array:
    """Mean softmax cross entropy with integer labels."""
    import jax.numpy as jnp

    logz = jax.nn.log_softmax(logits, axis=-1)
    onehot = jax.nn.one_hot(labels, logits.shape[-1], dtype=logz.dtype)
    return -jnp.mean(jnp.sum(onehot * logz, axis=-1))
