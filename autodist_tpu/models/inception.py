"""InceptionV3 (reference ``examples/benchmark/imagenet.py`` InceptionV3
benchmark).  Faithful block structure (A/B/C/D/E mixed blocks per Szegedy et
al. 2015), GroupNorm for statelessness."""
from __future__ import annotations

from typing import Tuple

import flax.linen as nn
import jax.numpy as jnp

from autodist_tpu.models.base import ModelSpec
from autodist_tpu.models.resnet import _image_spec


class ConvNorm(nn.Module):
    filters: int
    kernel: Tuple[int, int]
    strides: Tuple[int, int] = (1, 1)
    padding: str = "SAME"

    @nn.compact
    def __call__(self, x):
        x = nn.Conv(self.filters, self.kernel, strides=self.strides,
                    padding=self.padding, use_bias=False, name="conv")(x)
        groups = 32 if self.filters % 32 == 0 else 1
        x = nn.GroupNorm(num_groups=groups, name="norm")(x)
        return nn.relu(x)


class InceptionA(nn.Module):
    pool_features: int

    @nn.compact
    def __call__(self, x):
        b1 = ConvNorm(64, (1, 1), name="b1")(x)
        b2 = ConvNorm(48, (1, 1), name="b2_1")(x)
        b2 = ConvNorm(64, (5, 5), name="b2_2")(b2)
        b3 = ConvNorm(64, (1, 1), name="b3_1")(x)
        b3 = ConvNorm(96, (3, 3), name="b3_2")(b3)
        b3 = ConvNorm(96, (3, 3), name="b3_3")(b3)
        b4 = nn.avg_pool(x, (3, 3), strides=(1, 1), padding="SAME")
        b4 = ConvNorm(self.pool_features, (1, 1), name="b4")(b4)
        return jnp.concatenate([b1, b2, b3, b4], axis=-1)


class InceptionB(nn.Module):
    @nn.compact
    def __call__(self, x):
        b1 = ConvNorm(384, (3, 3), strides=(2, 2), padding="VALID",
                      name="b1")(x)
        b2 = ConvNorm(64, (1, 1), name="b2_1")(x)
        b2 = ConvNorm(96, (3, 3), name="b2_2")(b2)
        b2 = ConvNorm(96, (3, 3), strides=(2, 2), padding="VALID",
                      name="b2_3")(b2)
        b3 = nn.max_pool(x, (3, 3), strides=(2, 2), padding="VALID")
        return jnp.concatenate([b1, b2, b3], axis=-1)


class InceptionC(nn.Module):
    channels7: int

    @nn.compact
    def __call__(self, x):
        c = self.channels7
        b1 = ConvNorm(192, (1, 1), name="b1")(x)
        b2 = ConvNorm(c, (1, 1), name="b2_1")(x)
        b2 = ConvNorm(c, (1, 7), name="b2_2")(b2)
        b2 = ConvNorm(192, (7, 1), name="b2_3")(b2)
        b3 = ConvNorm(c, (1, 1), name="b3_1")(x)
        b3 = ConvNorm(c, (7, 1), name="b3_2")(b3)
        b3 = ConvNorm(c, (1, 7), name="b3_3")(b3)
        b3 = ConvNorm(c, (7, 1), name="b3_4")(b3)
        b3 = ConvNorm(192, (1, 7), name="b3_5")(b3)
        b4 = nn.avg_pool(x, (3, 3), strides=(1, 1), padding="SAME")
        b4 = ConvNorm(192, (1, 1), name="b4")(b4)
        return jnp.concatenate([b1, b2, b3, b4], axis=-1)


class InceptionD(nn.Module):
    @nn.compact
    def __call__(self, x):
        b1 = ConvNorm(192, (1, 1), name="b1_1")(x)
        b1 = ConvNorm(320, (3, 3), strides=(2, 2), padding="VALID",
                      name="b1_2")(b1)
        b2 = ConvNorm(192, (1, 1), name="b2_1")(x)
        b2 = ConvNorm(192, (1, 7), name="b2_2")(b2)
        b2 = ConvNorm(192, (7, 1), name="b2_3")(b2)
        b2 = ConvNorm(192, (3, 3), strides=(2, 2), padding="VALID",
                      name="b2_4")(b2)
        b3 = nn.max_pool(x, (3, 3), strides=(2, 2), padding="VALID")
        return jnp.concatenate([b1, b2, b3], axis=-1)


class InceptionE(nn.Module):
    @nn.compact
    def __call__(self, x):
        b1 = ConvNorm(320, (1, 1), name="b1")(x)
        b2 = ConvNorm(384, (1, 1), name="b2_1")(x)
        b2 = jnp.concatenate([ConvNorm(384, (1, 3), name="b2_2a")(b2),
                              ConvNorm(384, (3, 1), name="b2_2b")(b2)], -1)
        b3 = ConvNorm(448, (1, 1), name="b3_1")(x)
        b3 = ConvNorm(384, (3, 3), name="b3_2")(b3)
        b3 = jnp.concatenate([ConvNorm(384, (1, 3), name="b3_3a")(b3),
                              ConvNorm(384, (3, 1), name="b3_3b")(b3)], -1)
        b4 = nn.avg_pool(x, (3, 3), strides=(1, 1), padding="SAME")
        b4 = ConvNorm(192, (1, 1), name="b4")(b4)
        return jnp.concatenate([b1, b2, b3, b4], axis=-1)


class InceptionV3(nn.Module):
    num_classes: int

    @nn.compact
    def __call__(self, x):
        x = ConvNorm(32, (3, 3), strides=(2, 2), padding="VALID",
                     name="stem1")(x)
        x = ConvNorm(32, (3, 3), padding="VALID", name="stem2")(x)
        x = ConvNorm(64, (3, 3), name="stem3")(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2))
        x = ConvNorm(80, (1, 1), padding="VALID", name="stem4")(x)
        x = ConvNorm(192, (3, 3), padding="VALID", name="stem5")(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2))
        x = InceptionA(32, name="mixed0")(x)
        x = InceptionA(64, name="mixed1")(x)
        x = InceptionA(64, name="mixed2")(x)
        x = InceptionB(name="mixed3")(x)
        x = InceptionC(128, name="mixed4")(x)
        x = InceptionC(160, name="mixed5")(x)
        x = InceptionC(160, name="mixed6")(x)
        x = InceptionC(192, name="mixed7")(x)
        x = InceptionD(name="mixed8")(x)
        x = InceptionE(name="mixed9")(x)
        x = InceptionE(name="mixed10")(x)
        x = jnp.mean(x, axis=(1, 2))
        return nn.Dense(self.num_classes, name="head")(x)


def inception_v3(num_classes: int = 1000, image_size: int = 299) -> ModelSpec:
    return _image_spec("inception_v3", InceptionV3(num_classes),
                       num_classes, image_size)
