"""Neural Collaborative Filtering (NeuMF).

Parity target: reference NCF benchmark on MovieLens
(``examples/benchmark/README.md``): GMF + MLP towers over user/item
embeddings, binary cross-entropy on implicit feedback.  Embedding gradients
are sparse (Parallax PS candidates).
"""
from __future__ import annotations

from typing import Sequence

import flax.linen as nn
import jax.numpy as jnp
import numpy as np

from autodist_tpu.models.base import ModelSpec


class NeuMF(nn.Module):
    num_users: int
    num_items: int
    mf_dim: int
    mlp_dims: Sequence[int]

    @nn.compact
    def __call__(self, users, items):
        mlp_dim0 = self.mlp_dims[0] // 2
        emb = lambda n, v, d: self.param(  # noqa: E731
            n, nn.initializers.normal(0.01), (v, d))
        mf_u = jnp.take(emb("mf_user_embedding", self.num_users, self.mf_dim),
                        users, axis=0)
        mf_i = jnp.take(emb("mf_item_embedding", self.num_items, self.mf_dim),
                        items, axis=0)
        mlp_u = jnp.take(emb("mlp_user_embedding", self.num_users, mlp_dim0),
                         users, axis=0)
        mlp_i = jnp.take(emb("mlp_item_embedding", self.num_items, mlp_dim0),
                         items, axis=0)
        gmf = mf_u * mf_i
        x = jnp.concatenate([mlp_u, mlp_i], axis=-1)
        for i, d in enumerate(self.mlp_dims[1:]):
            x = nn.relu(nn.Dense(d, name=f"mlp_{i}")(x))
        x = jnp.concatenate([gmf, x], axis=-1)
        return nn.Dense(1, name="prediction")(x)[..., 0]


def ncf(num_users: int = 138496, num_items: int = 26752, mf_dim: int = 64,
        mlp_dims: Sequence[int] = (256, 256, 128, 64)) -> ModelSpec:
    """MovieLens-20M-ish sizes, padded to multiples of 128."""
    model = NeuMF(num_users, num_items, mf_dim, tuple(mlp_dims))

    def init(rng):
        z = jnp.zeros((2,), jnp.int32)
        return model.init(rng, z, z)["params"]

    def apply_fn(params, users, items):
        return model.apply({"params": params}, users, items)

    def loss_fn(params, batch):
        logits = apply_fn(params, batch["users"], batch["items"])
        labels = batch["labels"].astype(logits.dtype)
        return jnp.mean(
            jnp.maximum(logits, 0) - logits * labels
            + jnp.log1p(jnp.exp(-jnp.abs(logits))))

    def make_batch(rng: np.random.RandomState, batch_size: int):
        return {
            "users": rng.randint(0, num_users, (batch_size,)).astype(np.int32),
            "items": rng.randint(0, num_items, (batch_size,)).astype(np.int32),
            "labels": (rng.rand(batch_size) > 0.5).astype(np.float32),
        }

    return ModelSpec(
        name="ncf",
        init=init, loss_fn=loss_fn, apply_fn=apply_fn, make_batch=make_batch,
        sparse_vars=("mf_user_embedding", "mf_item_embedding",
                     "mlp_user_embedding", "mlp_item_embedding"),
        config=dict(num_users=num_users, num_items=num_items),
    )
