"""Model zoo — the reference's benchmark families, TPU-first.

ResNet-50/101, VGG16, DenseNet121, InceptionV3 (imagenet.py parity),
BERT-base/large (bert.py parity), lm1b LSTM (examples/lm1b parity),
NCF (MovieLens parity), plus the flagship TransformerLM (new scope for
long-context/multi-dim parallelism).
"""
from autodist_tpu.models.base import ModelSpec, cross_entropy_loss  # noqa: F401
from autodist_tpu.models.bert import bert, bert_base, bert_large  # noqa: F401
from autodist_tpu.models.generate import make_generator  # noqa: F401
from autodist_tpu.models.quantize import (  # noqa: F401
    dequantize_lm_params,
    quantize_lm_params,
)
from autodist_tpu.models.speculative import (  # noqa: F401
    make_speculative_generator,
)
from autodist_tpu.models.densenet import densenet121  # noqa: F401
from autodist_tpu.models.inception import inception_v3  # noqa: F401
from autodist_tpu.models.lm1b import lm1b  # noqa: F401
from autodist_tpu.models.lora import (  # noqa: F401
    lora_init,
    lora_merge,
    lora_setup,
)
from autodist_tpu.models.moe_lm import moe_transformer_lm  # noqa: F401
from autodist_tpu.models.ncf import ncf  # noqa: F401
from autodist_tpu.models.pipelined_lm import pipelined_transformer_lm  # noqa: F401
from autodist_tpu.models.pipelined_moe_lm import (  # noqa: F401
    pipelined_moe_transformer_lm,
)
from autodist_tpu.models.resnet import resnet50, resnet101  # noqa: F401
from autodist_tpu.models.transformer_lm import transformer_lm  # noqa: F401
from autodist_tpu.models.vgg import vgg16  # noqa: F401

ALL_MODELS = {
    "resnet50": resnet50,
    "resnet101": resnet101,
    "vgg16": vgg16,
    "densenet121": densenet121,
    "inception_v3": inception_v3,
    "bert": bert,
    "lm1b": lm1b,
    "ncf": ncf,
    "transformer_lm": transformer_lm,
    # pipelined_transformer_lm / moe_transformer_lm are mesh-parameterized;
    # construct them directly.
}
