"""Speculative decoding for ``transformer_lm`` (draft-and-verify).

Beyond the reference (training-only) and beyond plain KV-cache decode
(``models/generate.py``): a small DRAFT model proposes ``gamma`` tokens
with cheap sequential steps, then the TARGET model verifies all of them
in ONE parallel cached forward — the classic latency lever for serving
(Leviathan et al. 2023, "Fast Inference from Transformers via
Speculative Decoding"), specialised here to greedy acceptance so the
output is EXACTLY the target model's greedy decode, token for token.

TPU-first shape discipline:

* one ``lax.while_loop`` whose carries are fixed-shape buffers — tokens
  ``[B, L]``, both models' KV caches, a per-row position vector ``[B]``
  (rows accept different amounts per iteration, so progress is per-row);
* the verify step feeds the target ``gamma + 1`` positions at once
  through the SAME shared ``TransformerLayer`` block math as training
  and single-token decode (``generate._token_step``), with a
  block-causal mask against the cache — MXU-batched verification is
  where the speedup comes from;
* rejected proposals leave stale KV entries behind; every stale position
  is overwritten by the next iteration's writes before any query can
  attend it (writes land at ``n'-1 .. n'+gamma-1`` which covers the
  stale range ``n'+.. .. n+gamma-1``), so no masking bookkeeping is
  needed beyond the per-position causal mask.

Greedy acceptance: accept the longest prefix of draft proposals that
matches the target's argmax, then emit the target's argmax at the first
mismatch ("bonus" token) — at least one target-correct token per
iteration, so the loop terminates in at most ``max_new_tokens``
iterations and the result equals target-greedy regardless of how bad
the draft is.
"""
from __future__ import annotations

import functools

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax import lax

from autodist_tpu.models.base import ModelSpec
from autodist_tpu.models.generate import unpack_lm_params as _unpack
from autodist_tpu.models.transformer import TransformerLayer


def _positions_step(layer_params, ln_final_scale, embed, x, k_cache,
                    v_cache, pos, total_len):
    """Process S consecutive positions per row in ONE pass against the
    KV cache.  ``x``: [B, S, D] embedded inputs, row b's slots at
    absolute positions ``pos[b] .. pos[b]+S-1`` (``pos``: [B] int32);
    caches [Layers, T, B, H, Dh] — time-major like ``generate.py``'s
    (contiguous slab updates; the batch-major layout's strided scatter
    measured ~10× slower per decode tick on TPU).
    Returns (logits [B, S, V], caches).

    The S=1 case is the single-token decode tick with a per-ROW position
    (generate._token_step takes one scalar position for the whole
    batch); larger S is the verify step.  The block math is the shared
    ``TransformerLayer`` — only the cached block-causal attention is
    specific to this path."""
    b, s, _ = x.shape
    heads, hd = k_cache.shape[-2], k_cache.shape[-1]
    d_ff = layer_params[0]["mlp"]["wi"]["kernel"].shape[1]
    rows = jnp.arange(b)[:, None]                       # [B, 1]
    cols = pos[:, None] + jnp.arange(s)[None, :]        # [B, S] absolute
    for i, lp in enumerate(layer_params):
        cache_out = {}

        def cached_attn(q, k, v, causal, _i=i, _out=cache_out):
            # q/k/v: [B, S, H, K].  Write this block's K/V (scatter at
            # [t, b] pairs — per-row positions differ, so this path
            # keeps advanced indexing), then attend each query over
            # cache entries <= its own absolute position (the S new
            # slots are written first, so the block is causally visible
            # to itself).
            kc = k_cache.at[_i, cols, rows].set(k.astype(k_cache.dtype))
            vc = v_cache.at[_i, cols, rows].set(v.astype(v_cache.dtype))
            _out["k"], _out["v"] = kc, vc
            depth = q.shape[-1]
            logits = jnp.einsum("bshk,tbhk->bsht", q, kc[_i]) \
                / jnp.sqrt(jnp.asarray(depth, q.dtype))
            mask = (jnp.arange(total_len)[None, None, :]
                    <= cols[:, :, None])                # [B, S, T]
            # logits: [B, S, H, T]; broadcast the mask over heads.
            logits = jnp.where(mask[:, :, None, :], logits,
                               jnp.finfo(logits.dtype).min)
            probs = jax.nn.softmax(logits.astype(jnp.float32),
                                   axis=-1).astype(q.dtype)
            return jnp.einsum("bsht,tbhk->bshk", probs, vc[_i])

        x = TransformerLayer(heads, hd, d_ff, causal=True,
                             attn_fn=cached_attn).apply({"params": lp}, x)
        k_cache, v_cache = cache_out["k"], cache_out["v"]
    x = nn.LayerNorm(use_bias=False).apply(
        {"params": {"scale": ln_final_scale}}, x)
    return jnp.einsum("bsd,vd->bsv", x, embed), k_cache, v_cache


def make_speculative_generator(target_spec: ModelSpec,
                               draft_spec: ModelSpec):
    """Build ``spec_gen(target_params, draft_params, prompt,
    max_new_tokens, gamma=4)`` → ``(tokens [B, P+N], stats)``.

    ``stats`` holds ``iterations`` (a device scalar: verify passes, the
    batch runs in lockstep) plus PER-REQUEST ``[B]`` int32 counters:
    ``proposed`` / ``accepted`` draft tokens and ``bonus`` (target
    tokens emitted at the first mismatch that landed inside the
    requested length).  ``accepted[b] / proposed[b]`` is row ``b``'s
    acceptance rate — per-request resolution is what lets a serving
    engine histogram acceptance length instead of averaging it away
    (sum over the batch recovers the old aggregate counters).
    ``accepted`` counts acceptance events; a fully-accepted tail that
    overshoots ``max_new_tokens`` is trimmed from the output but still
    counted.

    Requirements: both specs are transformer_lm-family and share the
    vocabulary (the draft proposes token ids the target scores); the
    buffer needs ``P + N + gamma`` positions of both models' max_len
    (proposals may overshoot the requested length before being
    trimmed)."""
    for which, spec in (("target", target_spec), ("draft", draft_spec)):
        if "num_layers" not in spec.config or "max_len" not in spec.config:
            raise ValueError(
                f"{which} spec must be transformer_lm-family, got "
                f"{spec.name!r}")
    t_cfg, d_cfg = target_spec.config, draft_spec.config
    if t_cfg["vocab_size"] != d_cfg["vocab_size"]:
        raise ValueError(
            f"target/draft vocab mismatch: {t_cfg['vocab_size']} vs "
            f"{d_cfg['vocab_size']}")

    @functools.partial(jax.jit, static_argnums=(3, 4))
    def spec_gen(target_params, draft_params, prompt, max_new_tokens,
                 gamma=4):
        if gamma < 1:
            raise ValueError(f"gamma must be >= 1, got {gamma}")
        b, p_len = prompt.shape
        if p_len < 1:
            raise ValueError("prompt must hold at least one token")
        end = p_len + max_new_tokens
        buf_len = end + gamma                 # proposals may overshoot
        for which, cfg in (("target", t_cfg), ("draft", d_cfg)):
            if buf_len > cfg["max_len"]:
                raise ValueError(
                    f"prompt + max_new_tokens + gamma = {buf_len} exceeds "
                    f"the {which} model's max_len {cfg['max_len']} "
                    f"(speculation needs gamma slack positions)")

        t_embed, t_pos, t_layers, t_ln = _unpack(target_params,
                                                 t_cfg["num_layers"])
        d_embed, d_pos, d_layers, d_ln = _unpack(draft_params,
                                                 d_cfg["num_layers"])
        rows = jnp.arange(b)

        def cache(cfg, params_embed):
            heads, hd = cfg["num_heads"], cfg["head_dim"]
            return jnp.zeros((cfg["num_layers"], buf_len, b, heads, hd),
                             params_embed.dtype)

        tokens0 = jnp.concatenate(
            [prompt, jnp.zeros((b, buf_len - p_len), prompt.dtype)], axis=1)

        # Prefill BOTH caches with one parallel pass over the prompt
        # (positions 0..P-1); the logits are discarded — the loop's
        # verify pass re-derives the first prediction from position P-1.
        zeros = jnp.zeros((b,), jnp.int32)

        def prefill(embed, pos_embed, layers, ln, kc, vc):
            x = jnp.take(embed, prompt, axis=0) + pos_embed[None, :p_len]
            _, kc, vc = _positions_step(layers, ln, embed, x, kc, vc,
                                        zeros, buf_len)
            return kc, vc

        tk, tv = prefill(t_embed, t_pos, t_layers, t_ln,
                         cache(t_cfg, t_embed), cache(t_cfg, t_embed))
        dk, dv = prefill(d_embed, d_pos, d_layers, d_ln,
                         cache(d_cfg, d_embed), cache(d_cfg, d_embed))

        def body(carry):
            (tokens, n, tk, tv, dk, dv, iters, proposed, accepted,
             bonus_ct) = carry
            active = n < end

            # -- draft: gamma cheap sequential proposals ---------------
            # Cache continuity: the draft only ever PROCESSES inputs up
            # to position n+gamma-2 (the last proposal and the bonus
            # token are emitted, never fed back within the iteration),
            # so after a full acceptance the next context tail is absent
            # from its cache.  The first step therefore processes a
            # 2-position catch-up window ending at n-1 — always enough,
            # since n advances by at most gamma+1 while the draft
            # processed through n+gamma-2.
            for i in range(gamma):
                if i == 0:
                    start = jnp.maximum(n - 2, 0)
                    cols0 = start[:, None] + jnp.arange(2)
                    toks0 = jnp.take_along_axis(tokens, cols0, axis=1)
                    x = jnp.take(d_embed, toks0, axis=0) + d_pos[cols0]
                    logits, dk, dv = _positions_step(
                        d_layers, d_ln, d_embed, x, dk, dv, start,
                        buf_len)
                    # the query AT position n-1 predicts slot n; its
                    # window index is n-1-start (0 when n==1 clamps).
                    idx = (n - 1 - start)[:, None, None]
                    logit_i = jnp.take_along_axis(
                        logits, jnp.broadcast_to(
                            idx, (logits.shape[0], 1, logits.shape[2])),
                        axis=1)[:, 0]
                else:
                    pos_i = jnp.minimum(n - 1 + i, buf_len - 1)
                    cur = tokens[rows, pos_i]
                    x = (jnp.take(d_embed, cur, axis=0)
                         + d_pos[pos_i])[:, None, :]
                    logits, dk, dv = _positions_step(
                        d_layers, d_ln, d_embed, x, dk, dv, pos_i,
                        buf_len)
                    logit_i = logits[:, 0]
                prop = jnp.argmax(logit_i, axis=-1).astype(tokens.dtype)
                slot = jnp.minimum(n + i, buf_len - 1)
                tokens = tokens.at[rows, slot].set(
                    jnp.where(active, prop, tokens[rows, slot]))

            # -- target: verify gamma+1 positions in ONE pass ----------
            v_pos = jnp.minimum(n - 1, buf_len - 1 - gamma)   # [B]
            v_cols = v_pos[:, None] + jnp.arange(gamma + 1)   # [B, G+1]
            v_tok = jnp.take_along_axis(tokens, v_cols, axis=1)
            x = jnp.take(t_embed, v_tok, axis=0) + t_pos[v_cols]
            logits, tk, tv = _positions_step(
                t_layers, t_ln, t_embed, x, tk, tv, v_pos, buf_len)
            preds = jnp.argmax(logits, axis=-1).astype(tokens.dtype)
            # preds[:, i] is the target's token for slot n+i.

            drafts = jnp.take_along_axis(
                tokens, n[:, None] + jnp.arange(gamma), axis=1)
            match = preds[:, :gamma] == drafts                # [B, G]
            a = jnp.sum(jnp.cumprod(match.astype(jnp.int32), axis=1),
                        axis=1)                               # [B] 0..G
            bonus = jnp.take_along_axis(preds, a[:, None], axis=1)[:, 0]
            slot = jnp.minimum(n + a, buf_len - 1)
            tokens = tokens.at[rows, slot].set(
                jnp.where(active, bonus, tokens[rows, slot]))

            iters = iters + 1
            proposed = proposed + jnp.where(active, gamma, 0)
            accepted = accepted + jnp.where(active, a, 0)
            bonus_ct = bonus_ct + jnp.where(active & (n + a < end), 1, 0)
            n = jnp.where(active, jnp.minimum(n + a + 1, end), n)
            return (tokens, n, tk, tv, dk, dv, iters, proposed, accepted,
                    bonus_ct)

        def cond(carry):
            return jnp.any(carry[1] < end)

        n0 = jnp.full((b,), p_len, jnp.int32)
        zero = jnp.zeros((), jnp.int32)
        zero_b = jnp.zeros((b,), jnp.int32)
        (tokens, n, *_rest, iters, proposed, accepted,
         bonus_ct) = lax.while_loop(
            cond, body,
            (tokens0, n0, tk, tv, dk, dv, zero, zero_b, zero_b, zero_b))
        stats = {"iterations": iters, "proposed": proposed,
                 "accepted": accepted, "bonus": bonus_ct}
        return tokens[:, :end], stats

    return spec_gen
