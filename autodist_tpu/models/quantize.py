"""Weight-only int8 decode for ``transformer_lm`` (serving memory/bandwidth).

Decode is bandwidth-bound — every tick re-reads every weight matrix (the
decode-tick anatomy in BASELINE.md) — so int8 weights halve both the HBM
footprint and the per-tick traffic.  The pieces:

* :func:`quantize_lm_params` — params → the same tree with every matmul
  weight (attention/MLP kernels + the tied embedding) replaced by an
  :class:`~autodist_tpu.ops.quant.Quantized` (int8 + per-output-channel
  scale); LayerNorm scales and positional embeddings stay full precision
  (tiny, and norms are precision-sensitive).
* :func:`quant_interceptor` — a ``flax.linen.intercept_methods``
  interceptor that reroutes ``nn.Dense`` / ``nn.DenseGeneral`` calls to
  the Pallas int8 kernel (``ops/quant.py``) when the layer's kernel leaf
  is ``Quantized``.  This is what keeps ONE definition of the block math:
  ``generate.py`` applies the SAME training-side ``TransformerLayer``
  module for quantized decode — only the linear-layer implementation is
  swapped underneath it, the r3 no-drift principle extended to
  quantization.
* :func:`dequantize_lm_params` — the exact full-precision tree the
  quantized program simulates (``q * scale``); the parity oracle for
  tests, and the export-back-to-training escape hatch.

Use: ``qparams = quantize_lm_params(params)`` then pass ``qparams`` to
``make_generator(spec)``'s returned function in place of ``params``
(greedy/sampled/beam; ``score`` needs full precision).  No reference
counterpart (training-only framework).
"""
from __future__ import annotations

from typing import Any, Dict

import flax.linen as nn
import jax.numpy as jnp

from autodist_tpu.ops.quant import Quantized, int8_matmul, quantize_weight


def _quantize_kernel(name: str, k) -> Quantized:
    """Kernel → 2-D Quantized with the contraction dim first.

    DenseGeneral kernels: q/k/v are ``[D, H, Dh]`` (axis=-1 → flatten the
    trailing feature dims); ``out`` is ``[H, Dh, D]`` (axis=(-2,-1) →
    flatten the leading contraction dims).  MLP kernels are already 2-D.
    """
    if name == "out":
        return quantize_weight(k.reshape((-1, k.shape[-1])))
    return quantize_weight(k.reshape((k.shape[0], -1)))


def quantize_lm_params(params: Dict[str, Any]) -> Dict[str, Any]:
    """``transformer_lm`` params → decode-ready weight-only int8 tree.

    The tied embedding is stored ONCE as ``Quantized([D, V])`` with
    per-vocab-row scales — right for both the head matmul (scales factor
    out per output column) and the input lookup (per-row rescale of a
    gathered int8 column).
    """
    out: Dict[str, Any] = {
        "embed": quantize_weight(params["embed"].T),       # [D, V]
        "pos_embed": params["pos_embed"],
        "decoder": {},
    }
    for lname, layer in params["decoder"].items():
        if lname == "ln_final":
            out["decoder"][lname] = layer
            continue
        qlayer: Dict[str, Any] = {}
        for mod, sub in layer.items():
            if mod.startswith("ln"):
                qlayer[mod] = sub
                continue
            qlayer[mod] = {
                proj: {"kernel": _quantize_kernel(proj, p["kernel"])}
                for proj, p in sub.items()
            }
        out["decoder"][lname] = qlayer
    return out


def dequantize_lm_params(qparams: Dict[str, Any], spec) -> Dict[str, Any]:
    """The full-precision tree the quantized program computes with
    (``q * scale``, original kernel shapes) — the parity oracle."""
    cfg = spec.config
    heads, hd = cfg["num_heads"], cfg["head_dim"]

    def deq(w: Quantized):
        return w.q.astype(jnp.float32) * w.scale

    out: Dict[str, Any] = {
        "embed": deq(qparams["embed"]).T,                  # [V, D]
        "pos_embed": qparams["pos_embed"],
        "decoder": {},
    }
    for lname, layer in qparams["decoder"].items():
        if lname == "ln_final":
            out["decoder"][lname] = layer
            continue
        dlayer: Dict[str, Any] = {}
        for mod, sub in layer.items():
            if mod.startswith("ln"):
                dlayer[mod] = sub
                continue
            dlayer[mod] = {}
            for proj, p in sub.items():
                w = deq(p["kernel"])
                if proj == "out":                          # [H*Dh, D]
                    w = w.reshape((heads, hd, -1))
                elif mod == "attn":                        # [D, H*Dh]
                    w = w.reshape((w.shape[0], heads, hd))
                dlayer[mod][proj] = {"kernel": w}
        out["decoder"][lname] = dlayer
    return out


def is_quantized(params: Dict[str, Any]) -> bool:
    return isinstance(params.get("embed"), Quantized)


def embed_lookup(embed, tok, dtype):
    """Rows of the (possibly quantized) tied embedding for tokens of
    any shape ``[...]`` → embeddings ``[..., D]``."""
    if isinstance(embed, Quantized):
        cols = jnp.take(embed.q, tok, axis=1)              # [D, ...]
        sc = jnp.take(embed.scale, tok, axis=1)            # [1, ...]
        out = cols.astype(jnp.float32) * sc
        return jnp.moveaxis(out, 0, -1).astype(dtype)      # [..., D]
    return jnp.take(embed, tok, axis=0)


def head_logits(embed, x):
    """Tied-head logits [B, V] for hidden x [B, D]."""
    if isinstance(embed, Quantized):                       # [D, V]
        return int8_matmul(x, embed)
    return jnp.einsum("bd,vd->bv", x, embed)


def quant_interceptor(layer_tree):
    """``nn.intercept_methods`` interceptor rerouting Dense/DenseGeneral
    to the int8 kernel when ``layer_tree``'s matching kernel leaf is
    ``Quantized``.  Anything it does not recognize falls through to the
    module's own implementation."""
    def interceptor(next_fun, args, kwargs, context):
        mod = context.module
        if (context.method_name != "__call__"
                or not isinstance(mod, (nn.DenseGeneral, nn.Dense))
                or getattr(mod, "use_bias", True)):
            return next_fun(*args, **kwargs)
        node = layer_tree
        for name in mod.path:
            if not isinstance(node, dict) or name not in node:
                return next_fun(*args, **kwargs)
            node = node[name]
        w = node.get("kernel") if isinstance(node, dict) else None
        if not isinstance(w, Quantized):
            return next_fun(*args, **kwargs)
        (x,) = args
        if isinstance(mod, nn.DenseGeneral):
            ax = mod.axis if isinstance(mod.axis, (tuple, list)) \
                else (mod.axis,)
            feats = mod.features if isinstance(mod.features, (tuple, list)) \
                else (mod.features,)
            # our models contract trailing axes only (axis=-1 or (-2,-1))
            lead = x.shape[:-len(ax)]
            y = int8_matmul(x.reshape(lead + (-1,)), w)
            return y.reshape(lead + tuple(feats))
        return int8_matmul(x, w)

    return interceptor
