"""BERT encoder for masked-LM pretraining.

Parity target: reference ``examples/benchmark/bert.py`` (BERT-base/large
pretraining benchmark, samples/sec).  Token/position/segment embeddings +
encoder stack + MLM head with tied decoder weights.
"""
from __future__ import annotations

from typing import Callable, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from autodist_tpu.models.base import ModelSpec
from autodist_tpu.models.transformer import TransformerStack, dense_attention


class BertModel(nn.Module):
    vocab_size: int
    num_layers: int
    num_heads: int
    head_dim: int
    d_ff: int
    max_len: int
    type_vocab: int = 2
    dtype: jnp.dtype = jnp.float32
    attn_fn: Optional[Callable] = None  # None -> backend default

    @nn.compact
    def __call__(self, tokens, segment_ids):
        d_model = self.num_heads * self.head_dim
        emb = self.param("word_embeddings", nn.initializers.normal(0.02),
                         (self.vocab_size, d_model), self.dtype)
        pos = self.param("position_embeddings", nn.initializers.normal(0.02),
                         (self.max_len, d_model), self.dtype)
        seg = self.param("token_type_embeddings", nn.initializers.normal(0.02),
                         (self.type_vocab, d_model), self.dtype)
        x = (jnp.take(emb, tokens, axis=0)
             + pos[None, :tokens.shape[1]]
             + jnp.take(seg, segment_ids, axis=0))
        x = nn.LayerNorm(name="embeddings_ln", use_bias=False)(x)
        from autodist_tpu.models.transformer import default_attention

        x = TransformerStack(self.num_layers, self.num_heads, self.head_dim,
                             self.d_ff, causal=False, name="encoder",
                             attn_fn=self.attn_fn or default_attention())(x)
        # MLM head: transform + tied decoder.
        h = nn.Dense(d_model, name="mlm_transform")(x)
        h = nn.gelu(h)
        h = nn.LayerNorm(name="mlm_ln", use_bias=False)(h)
        return jnp.einsum("btd,vd->btv", h, emb)


def bert(vocab_size: int = 30528, num_layers: int = 12, num_heads: int = 12,
         head_dim: int = 64, d_ff: int = 3072, max_len: int = 512,
         seq_len: int = 128, dtype=jnp.float32,
         attn_fn: Optional[Callable] = None) -> ModelSpec:
    """BERT-base defaults (vocab padded 30522→30528 for sharding/MXU).

    ``attn_fn=None`` → backend default (flash kernel on TPU)."""
    from autodist_tpu.models.transformer import default_attention

    model = BertModel(vocab_size, num_layers, num_heads, head_dim, d_ff,
                      max_len, dtype=dtype,
                      attn_fn=attn_fn or default_attention())

    def init(rng):
        t = jnp.zeros((2, seq_len), jnp.int32)
        return model.init(rng, t, t)["params"]

    def apply_fn(params, tokens, segment_ids):
        return model.apply({"params": params}, tokens, segment_ids)

    def loss_fn(params, batch):
        logits = apply_fn(params, batch["tokens"], batch["segment_ids"])
        # masked-LM: average over masked positions only
        logz = jax.nn.log_softmax(logits, axis=-1)
        tgt = jax.nn.one_hot(batch["labels"], logits.shape[-1],
                             dtype=logz.dtype)
        per_tok = -jnp.sum(tgt * logz, axis=-1)
        mask = batch["mlm_mask"].astype(per_tok.dtype)
        return jnp.sum(per_tok * mask) / jnp.maximum(jnp.sum(mask), 1.0)

    def make_batch(rng: np.random.RandomState, batch_size: int):
        return {
            "tokens": rng.randint(0, vocab_size,
                                  (batch_size, seq_len)).astype(np.int32),
            "segment_ids": (rng.rand(batch_size, seq_len) > 0.5
                            ).astype(np.int32),
            "labels": rng.randint(0, vocab_size,
                                  (batch_size, seq_len)).astype(np.int32),
            "mlm_mask": (rng.rand(batch_size, seq_len) < 0.15
                         ).astype(np.float32),
        }

    return ModelSpec(
        name="bert",
        init=init, loss_fn=loss_fn, apply_fn=apply_fn, make_batch=make_batch,
        sparse_vars=("word_embeddings", "token_type_embeddings"),
        config=dict(vocab_size=vocab_size, num_layers=num_layers,
                    num_heads=num_heads, head_dim=head_dim, d_ff=d_ff,
                    seq_len=seq_len),
    )


def bert_base(**kw) -> ModelSpec:
    return bert(**kw)


def bert_large(**kw) -> ModelSpec:
    kw.setdefault("num_layers", 24)
    kw.setdefault("num_heads", 16)
    kw.setdefault("head_dim", 64)
    kw.setdefault("d_ff", 4096)
    return bert(**kw)
