"""Shared transformer components (TPU-first).

Design notes:
* dims default to multiples of 128 so matmuls tile the MXU exactly;
* attention is a pluggable function so sequence-parallel implementations
  (ring attention, Ulysses — ``autodist_tpu/parallel/``) can replace the
  dense softmax without touching the model;
* parameter names are stable strategy keys (e.g. ``layers_0/attn/query/kernel``)
  — the analog of the reference's TF variable names in strategy node_configs.
"""
from __future__ import annotations

from typing import Callable, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np


def _resolve_default_attention(mesh=None) -> Callable:
    if jax.devices()[0].platform == "tpu":
        from autodist_tpu.ops.flash_attention import make_flash_attention

        return make_flash_attention(mesh)
    return dense_attention


def default_attention(mesh=None) -> Callable:
    """The attention implementation for the current backend: the Pallas
    flash kernel on TPU — the hot-op fast path
    (``autodist_tpu/ops/flash_attention.py``) — and dense softmax attention
    elsewhere.  Model factories use this when no explicit ``attn_fn`` is
    passed.

    Resolved at CONSTRUCTION time when the backend is already up (the
    AOT-friendly behavior).  When no backend has been initialized yet —
    a multi-node script building its model BEFORE
    ``jax.distributed.initialize`` — probing devices here would initialize
    the local backend and break the distributed bootstrap
    (``cluster.py:128-146``), so the decision is deferred to the first
    call and cached."""
    try:
        from jax._src import xla_bridge

        initialized = xla_bridge.backends_are_initialized()
    except Exception:  # pragma: no cover - private-API drift
        initialized = True
    if initialized:
        return _resolve_default_attention(mesh)

    resolved: list = []

    def lazy_attn(q, k, v, causal: bool):
        if not resolved:
            resolved.append(_resolve_default_attention(mesh))
        return resolved[0](q, k, v, causal)

    return lazy_attn


def dense_attention(q, k, v, causal: bool) -> jax.Array:
    """Reference attention: softmax(QKᵀ/√d)V.  [B, T, H, D] layout."""
    depth = q.shape[-1]
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) / jnp.sqrt(depth).astype(q.dtype)
    if causal:
        t_q, t_k = logits.shape[-2], logits.shape[-1]
        mask = jnp.tril(jnp.ones((t_q, t_k), bool))
        logits = jnp.where(mask, logits, jnp.finfo(logits.dtype).min)
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


class MultiHeadAttention(nn.Module):
    num_heads: int
    head_dim: int
    causal: bool = False
    attn_fn: Callable = staticmethod(dense_attention)

    @nn.compact
    def __call__(self, x):
        d = self.num_heads * self.head_dim
        proj = lambda name: nn.DenseGeneral(  # noqa: E731
            (self.num_heads, self.head_dim), use_bias=False, name=name)
        q, k, v = proj("query")(x), proj("key")(x), proj("value")(x)
        out = self.attn_fn(q, k, v, self.causal)
        return nn.DenseGeneral(x.shape[-1], axis=(-2, -1), use_bias=False,
                               name="out")(out)


class MlpBlock(nn.Module):
    d_ff: int

    @nn.compact
    def __call__(self, x):
        h = nn.Dense(self.d_ff, use_bias=False, name="wi")(x)
        h = nn.gelu(h)
        return nn.Dense(x.shape[-1], use_bias=False, name="wo")(h)


class TransformerLayer(nn.Module):
    num_heads: int
    head_dim: int
    d_ff: int
    causal: bool = False
    attn_fn: Callable = staticmethod(dense_attention)

    @nn.compact
    def __call__(self, x):
        h = nn.LayerNorm(name="ln_attn", use_bias=False)(x)
        x = x + MultiHeadAttention(self.num_heads, self.head_dim, self.causal,
                                   attn_fn=self.attn_fn, name="attn")(h)
        h = nn.LayerNorm(name="ln_mlp", use_bias=False)(x)
        x = x + MlpBlock(self.d_ff, name="mlp")(h)
        return x


class TransformerStack(nn.Module):
    num_layers: int
    num_heads: int
    head_dim: int
    d_ff: int
    causal: bool = False
    attn_fn: Callable = staticmethod(dense_attention)
    # Per-layer rematerialization: "none" keeps all activations; "full"
    # recomputes the whole layer in the backward pass (max memory saving,
    # +1 forward of FLOPs); "dots" saves matmul outputs and recomputes
    # the cheap elementwise tail (the usual MFU sweet spot: batch can
    # grow into the freed HBM while the recompute rides the idle MXU).
    remat: str = "none"

    @nn.compact
    def __call__(self, x):
        if self.remat not in ("none", "full", "dots"):
            raise ValueError(f"remat={self.remat!r}: expected 'none', "
                             f"'full', or 'dots'")
        layer_cls = TransformerLayer
        if self.remat != "none":
            policy = {
                "full": None,
                "dots": jax.checkpoint_policies.checkpoint_dots,
            }[self.remat]
            layer_cls = nn.remat(TransformerLayer, policy=policy,
                                 prevent_cse=False)
        for i in range(self.num_layers):
            x = layer_cls(self.num_heads, self.head_dim, self.d_ff,
                          self.causal, attn_fn=self.attn_fn,
                          name=f"layers_{i}")(x)
        return nn.LayerNorm(name="ln_final", use_bias=False)(x)
