"""Flagship decoder-only language model.

This is the model the framework's multi-dimensional parallelism is exercised
on (dp/tp/sp/pp/ep in ``__graft_entry__.dryrun_multichip``): a GPT-style
causal LM whose embedding table is a sparse-gradient variable (Parallax PS
lowering shards it along the vocab axis) and whose attention implementation
is pluggable for sequence parallelism (ring attention).

The reference has no decoder LM — its sequence models are the lm1b LSTM and
BERT (SURVEY §5.7); this model is the new-scope flagship that the long-context
machinery requires.
"""
from __future__ import annotations

from typing import Callable, Optional

import flax.linen as nn
import jax.numpy as jnp
import numpy as np

from autodist_tpu.models.base import ModelSpec, cross_entropy_loss
from autodist_tpu.models.transformer import TransformerStack, dense_attention


class TransformerLM(nn.Module):
    vocab_size: int
    num_layers: int
    num_heads: int
    head_dim: int
    d_ff: int
    max_len: int
    attn_fn: Callable = staticmethod(dense_attention)
    dtype: jnp.dtype = jnp.float32
    remat: str = "none"

    def setup(self):
        d_model = self.num_heads * self.head_dim
        self.embed = self.param("embed", nn.initializers.normal(0.02),
                                (self.vocab_size, d_model), self.dtype)
        self.pos_embed = self.param("pos_embed", nn.initializers.normal(0.02),
                                    (self.max_len, d_model), self.dtype)
        self.decoder = TransformerStack(
            self.num_layers, self.num_heads, self.head_dim, self.d_ff,
            causal=True, attn_fn=self.attn_fn, remat=self.remat)

    def features(self, tokens):
        """Pre-logits activations ``[B, T, D]`` — paired with the tied
        embedding through the chunked cross entropy when the training
        loss must not materialize ``[B, T, vocab]`` logits."""
        x = (jnp.take(self.embed, tokens, axis=0)
             + self.pos_embed[None, :tokens.shape[1]])
        return self.decoder(x)

    def __call__(self, tokens):
        # Tied output head: logits against the embedding table — keeps the
        # only vocab-sized variable the (sparse) embedding.
        return jnp.einsum("btd,vd->btv", self.features(tokens), self.embed)


def transformer_lm(vocab_size: int = 32128, num_layers: int = 12,
                   num_heads: int = 12, head_dim: int = 64,
                   d_ff: int = 3072, max_len: int = 1024,
                   attn_fn: Optional[Callable] = None,
                   dtype=jnp.float32, seq_len: Optional[int] = None,
                   xent_chunk: Optional[int] = None,
                   remat: str = "none") -> ModelSpec:
    """GPT-2-small-ish defaults; shrink for tests.

    ``attn_fn=None`` → backend default: the Pallas flash kernel on TPU,
    dense softmax elsewhere (``models/transformer.py:default_attention``).
    ``xent_chunk`` → train with the chunked-vocab cross entropy
    (``ops/chunked_xent.py``): the ``[B, T, vocab]`` logits never
    materialize — worth ~2 GB of peak HBM at batch 16 × seq 2048.
    ``remat`` → per-layer rematerialization ("none" | "dots" | "full",
    see ``TransformerStack.remat``): trade recompute FLOPs for
    activation HBM, usually to grow the batch into the freed memory."""
    from autodist_tpu.models.transformer import default_attention

    attn_fn = attn_fn or default_attention()
    seq_len = seq_len or max_len
    model = TransformerLM(vocab_size, num_layers, num_heads, head_dim, d_ff,
                          max_len, attn_fn=attn_fn, dtype=dtype,
                          remat=remat)

    def init(rng):
        tokens = jnp.zeros((2, seq_len), jnp.int32)
        return model.init(rng, tokens)["params"]

    def apply_fn(params, tokens):
        return model.apply({"params": params}, tokens)

    if xent_chunk:
        from autodist_tpu.ops.chunked_xent import \
            chunked_softmax_cross_entropy

        def loss_fn(params, batch):
            feats = model.apply({"params": params}, batch["tokens"],
                                method=TransformerLM.features)
            return chunked_softmax_cross_entropy(
                feats[:, :-1], params["embed"], batch["tokens"][:, 1:],
                chunk=xent_chunk)
    else:
        def loss_fn(params, batch):
            logits = apply_fn(params, batch["tokens"])
            return cross_entropy_loss(logits[:, :-1], batch["tokens"][:, 1:])

    def make_batch(rng: np.random.RandomState, batch_size: int):
        return {"tokens": rng.randint(
            0, vocab_size, (batch_size, seq_len)).astype(np.int32)}

    return ModelSpec(
        name="transformer_lm",
        init=init, loss_fn=loss_fn, apply_fn=apply_fn, make_batch=make_batch,
        sparse_vars=("embed",),
        config=dict(vocab_size=vocab_size, num_layers=num_layers,
                    num_heads=num_heads, head_dim=head_dim, d_ff=d_ff,
                    max_len=max_len, seq_len=seq_len),
    )
