"""Pipelined decoder LM: the flagship model with stage-stacked layers.

Same architecture as :mod:`autodist_tpu.models.transformer_lm` (GPT-style
causal LM, tied embedding head) but the transformer layers are *stacked*:
every layer parameter carries a leading ``[num_layers]`` axis, reshaped to
``[num_stages, layers_per_stage]`` at apply time and pipelined over the
``pipe`` mesh axis (``autodist_tpu/parallel/pipeline.py``).  With
``pipe == 1`` the stack runs as a plain ``lax.scan`` — the standard
weight-stacked transformer formulation (compile-time win over unrolled
layers as well).

No reference analog: pipeline parallelism is absent there (SURVEY §2.8).

Embedding/positional/final-norm parameters are ordinary variables — the
strategy layer shards or replicates them as usual; the stacked ``stack/*``
variables are flagged via ``ModelSpec.pipeline_vars`` so the compiler leads
their PartitionSpec with ``pipe``.
"""
from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh

from autodist_tpu.models.base import (
    ModelSpec,
    cross_entropy_loss,
    layer_norm as _layer_norm,
)
from autodist_tpu.utils import logging
from autodist_tpu.models.transformer import TransformerLayer, dense_attention
from autodist_tpu.parallel.pipeline import (
    default_num_microbatches,
    interleaved_stage_order,
    pipeline_apply,
    stack_stage_params,
)

# Replicated-f32-head-gradient size above which schedule='1f1b' without a
# 'model' mesh axis warns (the ADVICE threshold): 64 MB ~= a 16k x 1k head.
_HEAD_GRAD_WARN_BYTES = 64 * 2**20


def _warn_large_1f1b_head(mesh: Mesh, vocab_size: int, d_model: int) -> None:
    """Shared schedule='1f1b' guard: a big tied-vocab head with no 'model'
    mesh axis means a dense replicated f32 gradient through the schedule
    (with a model axis the whole path stays sharded — docs/parallelism.md)."""
    if (mesh.shape.get("model", 1) <= 1
            and 4 * vocab_size * d_model > _HEAD_GRAD_WARN_BYTES):
        logging.warning(
            "schedule='1f1b': vocab %d x d_model %d means a %.0f MB "
            "replicated f32 head gradient per device (no 'model' mesh "
            "axis to shard it over). Add a model axis with a "
            "vocab-sharding strategy, or use schedule='gpipe' (sharded "
            "embed grads).", vocab_size, d_model,
            4 * vocab_size * d_model / 2**20)


def _tied_head_1f1b_grad_fn(mesh: Mesh, *, stages: int, chunks: int,
                            num_layers: int, num_microbatches,
                            num_virtual_stages: int, stage_fn: Callable,
                            head_loss: Callable,
                            make_embed_fn: Callable) -> Callable:
    """The 1F1B value-and-grad shared by the pipelined LM family: embed
    lookup under ``jax.vjp`` (``make_embed_fn(tokens) -> ep -> x``), the
    hand-scheduled pipeline backward over the stacked layers, loss-side
    head/norm gradients via ``loss_params``, and the tied embedding
    receiving gradient from BOTH sides (input lookup + softmax head)."""
    from autodist_tpu.parallel.pipeline_1f1b import one_f_one_b

    def grad_fn(params, batch):
        tokens = batch["tokens"]
        # per-DATA-SHARD microbatch count (one_f_one_b semantics).
        local_b = tokens.shape[0] // max(mesh.shape.get("data", 1), 1)
        m = num_microbatches or default_num_microbatches(stages, local_b)
        ep = {"embed": params["embed"], "pos_embed": params["pos_embed"]}
        x, embed_vjp = jax.vjp(make_embed_fn(tokens), ep)
        stacked = jax.tree_util.tree_map(
            lambda a: a.reshape((chunks, num_layers // chunks)
                                + a.shape[1:]), params["stack"])
        lp = {"ln_final": params["ln_final"], "embed": params["embed"]}
        loss, dstack, dlp, dx = one_f_one_b(
            stage_fn, head_loss, stacked, x, tokens, mesh,
            num_microbatches=m, loss_params=lp,
            num_virtual_stages=num_virtual_stages)
        (dep,) = embed_vjp(dx)
        return loss, {
            "embed": dep["embed"] + dlp["embed"],
            "pos_embed": dep["pos_embed"],
            "stack": jax.tree_util.tree_map(
                lambda g, p: g.reshape(p.shape), dstack, params["stack"]),
            "ln_final": dlp["ln_final"],
        }

    return grad_fn


def _device_major_layers(per_layer, stages: int, num_virtual: int):
    """Reorder a pipeline-ordered layer list so the stored stack's leading
    axis is device-major (chunk block ``d·V + v`` = global stage ``v·S+d``)
    — then contiguous ``pipe`` sharding of the stack IS the interleaved
    chunk assignment, with no per-step resharding (see
    ``pipeline_apply``'s stage_params contract).  Identity for V=1."""
    if num_virtual <= 1:
        return per_layer
    lpc = len(per_layer) // (stages * num_virtual)
    order = interleaved_stage_order(stages, num_virtual)
    return [per_layer[g * lpc + k] for g in order for k in range(lpc)]


def pipelined_transformer_lm(
        mesh: Mesh, vocab_size: int = 32128, num_layers: int = 12,
        num_heads: int = 12, head_dim: int = 64, d_ff: int = 3072,
        max_len: int = 1024, attn_fn: Callable = dense_attention,
        dtype=jnp.float32, seq_len: Optional[int] = None,
        num_stages: Optional[int] = None,
        num_microbatches: Optional[int] = None,
        num_virtual_stages: int = 1, remat: bool = False,
        schedule: str = "gpipe") -> ModelSpec:
    """Stage-stacked GPT-style LM pipelined over ``mesh``'s ``pipe`` axis.

    ``num_virtual_stages > 1`` selects the interleaved schedule: each device
    holds that many chunks and the bubble shrinks proportionally (works
    with both schedules — for 1F1B see the circular-interleaved algebra in
    ``parallel/pipeline_1f1b.py``).
    ``schedule="1f1b"`` trains through the hand-scheduled 1F1B backward
    (``parallel/pipeline_1f1b.py``, O(S·V) activation memory): the spec's
    ``grad_fn`` replaces autodiff — pass it to ``capture(grad_fn=...)``
    (``loss_fn`` stays the autodiff version for evaluation).

    Large-vocab note: the tied-embedding head rides ``loss_params`` into
    the schedule.  With a ``model`` mesh axis and a vocab-sharding
    strategy (any PS builder shards sparse vars over ``model``), GSPMD
    keeps the table, its per-tick vjp gradient, and the f32 accumulator
    sharded end-to-end — no replicated ``[vocab, d_model]`` buffer exists
    (pinned by ``tests/test_pipeline_1f1b.py``), so 1F1B is the right
    schedule for large vocabs *given a model axis*.  WITHOUT one, the
    head gradient is a dense replicated f32 ``[vocab, d_model]`` carried
    through the schedule; a warning fires above
    ``_HEAD_GRAD_WARN_BYTES`` pointing at a model axis or GPipe."""
    if schedule not in ("gpipe", "1f1b"):
        raise ValueError(f"unknown schedule {schedule!r}")
    if schedule == "1f1b":
        _warn_large_1f1b_head(mesh, vocab_size, num_heads * head_dim)
    seq_len = seq_len or max_len
    d_model = num_heads * head_dim
    stages = num_stages or mesh.shape.get("pipe", 1) or 1
    chunks = stages * num_virtual_stages
    if num_layers % chunks:
        raise ValueError(f"{num_layers} layers not divisible into "
                         f"{chunks} pipeline stage chunks")
    layer = TransformerLayer(num_heads, head_dim, d_ff, causal=True,
                             attn_fn=attn_fn)

    def init(rng):
        r_emb, r_pos, r_stack = jax.random.split(rng, 3)
        x = jnp.zeros((2, seq_len, d_model), dtype)
        per_layer = [
            layer.init(r, x)["params"]
            for r in jax.random.split(r_stack, num_layers)]
        per_layer = _device_major_layers(per_layer, stages,
                                         num_virtual_stages)
        return {
            "embed": jax.random.normal(r_emb, (vocab_size, d_model),
                                       dtype) * 0.02,
            "pos_embed": jax.random.normal(r_pos, (max_len, d_model),
                                           dtype) * 0.02,
            "stack": stack_stage_params(per_layer),      # leading [L]
            "ln_final": {"scale": jnp.ones((d_model,), dtype)},
        }

    def stage_fn(stage_params, x):
        # One pipeline stage = scan over its layers_per_stage layers.
        def body(h, lp):
            return layer.apply({"params": lp}, h), None
        out, _ = lax.scan(body, x, stage_params)
        return out

    def apply_fn(params, tokens):
        x = jnp.take(params["embed"], tokens, axis=0) \
            + params["pos_embed"][None, :tokens.shape[1]]
        stacked = jax.tree_util.tree_map(
            lambda a: a.reshape((chunks, num_layers // chunks) + a.shape[1:]),
            params["stack"])
        x = pipeline_apply(stage_fn, stacked, x, mesh,
                           num_microbatches=num_microbatches,
                           num_virtual_stages=num_virtual_stages,
                           remat=remat)
        x = _layer_norm(x, params["ln_final"]["scale"])
        return jnp.einsum("btd,vd->btv", x, params["embed"])

    def loss_fn(params, batch):
        logits = apply_fn(params, batch["tokens"])
        return cross_entropy_loss(logits[:, :-1], batch["tokens"][:, 1:])

    def make_batch(rng: np.random.RandomState, batch_size: int):
        return {"tokens": rng.randint(
            0, vocab_size, (batch_size, seq_len)).astype(np.int32)}

    grad_fn = None
    if schedule == "1f1b":
        def head_loss(lp, y_mb, tok_mb):
            h = _layer_norm(y_mb, lp["ln_final"]["scale"])
            logits = jnp.einsum("btd,vd->btv", h, lp["embed"])
            return cross_entropy_loss(logits[:, :-1], tok_mb[:, 1:])

        def make_embed_fn(tokens):
            def embed_fn(ep):
                return (jnp.take(ep["embed"], tokens, axis=0)
                        + ep["pos_embed"][None, :tokens.shape[1]])
            return embed_fn

        grad_fn = _tied_head_1f1b_grad_fn(
            mesh, stages=stages, chunks=chunks, num_layers=num_layers,
            num_microbatches=num_microbatches,
            num_virtual_stages=num_virtual_stages, stage_fn=stage_fn,
            head_loss=head_loss, make_embed_fn=make_embed_fn)

    return ModelSpec(
        name="pipelined_transformer_lm",
        init=init, loss_fn=loss_fn, apply_fn=apply_fn, make_batch=make_batch,
        grad_fn=grad_fn,
        sparse_vars=("embed",),
        pipeline_vars=("stack",),
        config=dict(vocab_size=vocab_size, num_layers=num_layers,
                    num_heads=num_heads, head_dim=head_dim, d_ff=d_ff,
                    max_len=max_len, seq_len=seq_len, num_stages=stages,
                    schedule=schedule),
    )
