"""Autoregressive generation with a KV cache for ``transformer_lm``.

Beyond the reference (a training-only framework): serving-side decode,
built TPU-first —

* ONE ``lax.scan`` over sequence positions; each tick embeds one token,
  runs every layer against the **KV cache** (``[L, T, B, H, Dh]``,
  TIME-MAJOR: the per-tick write ``cache[i, pos]`` is then one
  contiguous slab for ``dynamic_update_slice`` — the batch-major layout
  ``[L, B, T, ...]`` scatters the same write across ``B`` strided rows
  and measured ~10× slower per tick on TPU), and emits the next token —
  O(T) per token instead of the O(T²) full re-forward of calling
  ``apply_fn`` on a growing prefix;
* static shapes throughout (prompt is right-padded into the scan's
  fixed ``[B, total_len]`` token buffer) so XLA compiles one program per
  ``(batch, total_len)``;
* teacher forcing for prompt positions, greedy or temperature sampling
  after — selected with ``jnp.where`` masks, no data-dependent control
  flow;
* pure function of ``(params, prompt, rng)``: jit-able, and under a jit
  with model-axis-sharded params the per-token einsums against the tied
  embedding stay GSPMD-sharded like the training program's.

The decode math is not a mirror of ``models/transformer.py`` — it IS
``models/transformer.py``: each tick applies the training-side
``TransformerLayer`` flax module at ``[B, 1, D]`` with a KV-cached
attention plugged into its pluggable ``attn_fn`` slot, so the block
structure (pre-norm residuals, gelu, LayerNorm semantics, tied head) has
exactly one definition and cannot drift.  Per-position parity with
``spec.apply_fn`` stays pinned in ``tests/test_generate.py``.
"""
from __future__ import annotations

import functools
from typing import Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax import lax

from autodist_tpu.models.base import ModelSpec
from autodist_tpu.models.quantize import (embed_lookup, head_logits,
                                          is_quantized, quant_interceptor)
from autodist_tpu.models.transformer import (TransformerLayer,
                                             dense_attention)
from autodist_tpu.ops.quant import Quantized


def _vocab_size(params) -> int:
    """Vocab size for either a full-precision ([V, D] embed) or a
    weight-only int8 tree (Quantized [D, V], models/quantize.py)."""
    e = params["embed"]
    return e.shape[1] if is_quantized(params) else e.shape[0]


def unpack_lm_params(params, num_layers: int):
    """The ONE definition of the ``transformer_lm`` param-tree layout
    used by decode: ``(embed, pos_embed, [layer_params], ln_final_scale)``.
    Shared by :func:`make_generator` and the serving engine so a layout
    change cannot silently diverge between them."""
    layer_params = [params["decoder"][f"layers_{i}"]
                    for i in range(num_layers)]
    return (params["embed"], params["pos_embed"], layer_params,
            params["decoder"]["ln_final"]["scale"])


def check_sampling_args(vocab: int, temperature: float, top_k: int,
                        top_p: float, eos_id, rng) -> None:
    """Shared validation of the sampling knobs (generator + engine):
    loud errors instead of opaque trace-time failures."""
    if temperature > 0.0 and rng is None:
        raise ValueError("temperature sampling needs an rng key")
    if (top_k or top_p) and temperature <= 0:
        raise ValueError("top_k/top_p filtering needs temperature > 0")
    if top_k and not 0 < top_k <= vocab:
        raise ValueError(
            f"top_k must be in [1, vocab_size={vocab}], got {top_k}")
    if top_p and not 0.0 < top_p <= 1.0:
        raise ValueError(
            f"top_p is a probability mass in (0, 1], got {top_p}")
    if eos_id is not None and not 0 <= eos_id < vocab:
        raise ValueError(
            f"eos_id must be in [0, vocab_size={vocab}), got {eos_id}")


def require_lm_spec(spec: ModelSpec, who: str) -> None:
    """Raise unless ``spec`` is a transformer_lm-family ModelSpec with
    the decode-relevant config keys."""
    required = ("num_layers", "num_heads", "head_dim", "max_len")
    if any(k not in spec.config for k in required):
        raise ValueError(
            f"{who} needs a transformer_lm-family ModelSpec "
            f"(config with {required}); got {spec.name!r} with "
            f"{sorted(spec.config)}")


def sample_next_token(logits, key, temperature=0.0, top_k=0, top_p=0.0):
    """Greedy / temperature / top-k / nucleus selection over ``logits``
    [B, V] → int32 [B].  The single definition of the sampling filters,
    shared by :func:`make_generator` and the continuous-batching
    :class:`autodist_tpu.serving.DecodeEngine`.  The knobs are static
    (they select trace-time branches)."""
    if not (temperature and temperature > 0.0):
        return jnp.argmax(logits, axis=-1)
    scaled = logits.astype(jnp.float32) / temperature
    if top_k:
        # keep only the top_k logits per row
        kth = lax.top_k(scaled, top_k)[0][..., -1:]
        scaled = jnp.where(scaled < kth, -jnp.inf, scaled)
    if top_p and top_p > 0.0:
        # nucleus: smallest prefix of the sorted distribution with
        # cumulative probability >= top_p
        sorted_lp = jnp.sort(scaled, axis=-1)[..., ::-1]
        probs = jax.nn.softmax(sorted_lp, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        # cutoff = last logit whose PRECEDING mass < top_p
        keep = cum - probs < top_p
        cutoff = jnp.min(jnp.where(keep, sorted_lp, jnp.inf),
                         axis=-1, keepdims=True)
        scaled = jnp.where(scaled < cutoff, -jnp.inf, scaled)
    return jax.random.categorical(key, scaled, axis=-1)


def _token_step(layer_params, ln_final_scale, embed, x, k_cache, v_cache,
                pos, total_len, attn_mask=None, prefix_kv=None,
                prefix_mask=None):
    """One decode position through all layers.  ``x``: [B, D] embedded
    input; ``k_cache``/``v_cache``: [L, T, B, H, Dh] — time-major so
    ``.at[i, pos].set`` with a traced position lowers to a CONTIGUOUS
    dynamic_update_slice on the scan carry (no per-token cache copy, no
    strided scatter).  Returns logits [B, V] and the updated caches.

    The block math is the SHARED ``TransformerLayer`` module (projections,
    residual order, gelu, LayerNorm) applied at sequence length 1; only
    the attention itself is decode-specific (single query over the cache),
    injected through the module's ``attn_fn`` seam.  The updated caches
    are smuggled out of the functional ``apply`` through a closure cell —
    standard under tracing (the arrays are traced values either way).

    ``attn_mask``: optional [B, total_len] bool of attendable cache
    positions; default is the single-sequence causal set
    ``arange(total_len) <= pos``.  The continuous-batching engine passes
    per-slot windows (``start[b] <= arange <= pos``) so slots admitted
    at different ticks share one uniform cache write index.

    ``prefix_kv``: optional ``(kp, vp)`` each [L, Pp, H, Dh] — a SHARED
    cached prefix (system prompt) held once and attended by every row
    whose ``prefix_mask`` [B, Pp] says so, logically preceding the
    per-row cache window (prefix-cache serving)."""
    heads, hd = k_cache.shape[-2], k_cache.shape[-1]
    d_ff = layer_params[0]["mlp"]["wi"]["kernel"].shape[1]
    quantized = isinstance(layer_params[0]["mlp"]["wi"]["kernel"],
                           Quantized)
    x = x[:, None, :]                                   # [B, 1, D]
    for i, lp in enumerate(layer_params):
        cache_out = {}

        def cached_attn(q, k, v, causal, _i=i, _out=cache_out):
            # q/k/v: [B, 1, H, K] — the single position's projections
            # computed by the SHARED TransformerLayer code.  Write k/v
            # into the cache, attend the query over positions <= pos.
            kc = k_cache.at[_i, pos].set(k[:, 0].astype(k_cache.dtype))
            vc = v_cache.at[_i, pos].set(v[:, 0].astype(v_cache.dtype))
            _out["k"], _out["v"] = kc, vc
            depth = q.shape[-1]
            logits = jnp.einsum("bhk,tbhk->bht", q[:, 0], kc[_i]) \
                / jnp.sqrt(jnp.asarray(depth, q.dtype))
            if attn_mask is None:
                mask = jnp.arange(total_len)[None, None, :] <= pos
            else:
                mask = attn_mask[:, None, :]
            logits = jnp.where(mask, logits, jnp.finfo(logits.dtype).min)
            if prefix_kv is not None:
                kp, vp = prefix_kv
                pl = jnp.einsum("bhk,phk->bhp", q[:, 0],
                                kp[_i].astype(q.dtype)) \
                    / jnp.sqrt(jnp.asarray(depth, q.dtype))
                pl = jnp.where(prefix_mask[:, None, :], pl,
                               jnp.finfo(logits.dtype).min)
                logits = jnp.concatenate([pl, logits], axis=-1)
            probs = jax.nn.softmax(logits.astype(jnp.float32),
                                   axis=-1).astype(q.dtype)
            if prefix_kv is not None:
                pp = prefix_kv[0].shape[1]
                out = jnp.einsum("bhp,phk->bhk", probs[..., :pp],
                                 prefix_kv[1][_i].astype(q.dtype))
                out = out + jnp.einsum("bht,tbhk->bhk",
                                       probs[..., pp:], vc[_i])
                return out[:, None]
            return jnp.einsum("bht,tbhk->bhk", probs, vc[_i])[:, None]

        layer = TransformerLayer(heads, hd, d_ff, causal=True,
                                 attn_fn=cached_attn)
        if quantized:
            # Same TransformerLayer math; only Dense/DenseGeneral are
            # rerouted to the int8 kernel (models/quantize.py).
            with nn.intercept_methods(quant_interceptor(lp)):
                x = layer.apply({"params": lp}, x)
        else:
            x = layer.apply({"params": lp}, x)
        k_cache, v_cache = cache_out["k"], cache_out["v"]
    x = nn.LayerNorm(use_bias=False).apply(
        {"params": {"scale": ln_final_scale}}, x)
    out_logits = head_logits(embed, x[:, 0])
    return out_logits, k_cache, v_cache


def _prefill_forward(layer_params, ln_final_scale, embed, pos_embed,
                     tokens_2d, heads, head_dim, prefix_kv=None,
                     plen: int = 0):
    """Parallel prompt prefill: ONE causal forward over ``tokens_2d``
    [K, P] (a batch of K prompts) that also returns every layer's K/V —
    the MXU-friendly way to charge a KV cache (one [P]-parallel matmul
    program instead of P sequential decode ticks, batched across
    concurrent admissions).  Returns ``(xs [K, P, D] final-normed
    activations, ks [L, K, P, H, Dh], vs [L, K, P, H, Dh])``; the
    caller picks which positions' logits it needs
    (``head_logits(embed, xs[i, p])``).

    Same single-definition block math as training/decode: the shared
    ``TransformerLayer`` with a K/V-capturing dense causal attention in
    its ``attn_fn`` seat.  Works on full-precision and weight-only int8
    trees (the ``quant_interceptor`` reroute, as in ``_token_step``);
    ``heads``/``head_dim`` come from the model config (the quantized
    tree's flattened kernels don't carry them).

    ``prefix_kv``/``plen`` (optional, as in :func:`_token_step`): a
    SHARED cached prefix ``(kp, vp)`` each [L, Ppb, H, Dh] that every
    query row attends in addition to its causal self-window, with
    positions offset by the static ``plen`` (pad bucket rows beyond
    ``plen`` masked; position ids clipped — bucket pad rows past
    ``max_len`` gather a clamped embedding whose K/V are overwritten
    before any read, per the engine's ring invariant)."""
    quantized = isinstance(layer_params[0]["mlp"]["wi"]["kernel"],
                           Quantized)
    d_ff = layer_params[0]["mlp"]["wi"]["kernel"].shape[1]
    p = tokens_2d.shape[1]
    x = embed_lookup(embed, tokens_2d, pos_embed.dtype)      # [K, P, D]
    if plen:
        pos_ids = jnp.clip(plen + jnp.arange(p), 0,
                           pos_embed.shape[0] - 1)
        x = x + pos_embed[pos_ids][None]
    else:
        x = x + pos_embed[None, :p]
    ks, vs = [], []

    # Dense attention deliberately: the flash kernel's own measured
    # crossover vs dense is near T~2048 (ops/flash_attention.py block
    # notes), far above engine prompt buckets, and dense keeps prefill
    # numerics closest to the tick-by-tick decode path.
    def capture_attn(q, k, v, causal):
        i = len(ks)                                   # layer index
        ks.append(k)                                  # [K, P, H, Dh]
        vs.append(v)
        if prefix_kv is None:
            return dense_attention(q, k, v, causal)
        # prefix-aware dense: each row attends [prefix | causal self]
        kp, vp = prefix_kv
        depth = q.shape[-1]
        scale = jnp.sqrt(depth).astype(q.dtype)
        sl = jnp.einsum("bqhd,bkhd->bhqk", q, k) / scale
        t_q, t_k = sl.shape[-2], sl.shape[-1]
        causal_m = jnp.tril(jnp.ones((t_q, t_k), bool))
        sl = jnp.where(causal_m, sl, jnp.finfo(sl.dtype).min)
        ppb = kp.shape[1]
        pl = jnp.einsum("bqhd,phd->bhqp", q,
                        kp[i].astype(q.dtype)) / scale
        pmask = (jnp.arange(ppb) < plen)[None, None, None, :]
        pl = jnp.where(pmask, pl, jnp.finfo(sl.dtype).min)
        probs = jax.nn.softmax(
            jnp.concatenate([pl, sl], axis=-1).astype(jnp.float32),
            axis=-1).astype(q.dtype)
        out = jnp.einsum("bhqp,phd->bqhd", probs[..., :ppb],
                         vp[i].astype(q.dtype))
        return out + jnp.einsum("bhqk,bkhd->bqhd", probs[..., ppb:], v)

    for lp in layer_params:
        layer = TransformerLayer(heads, head_dim, d_ff, causal=True,
                                 attn_fn=capture_attn)
        if quantized:
            with nn.intercept_methods(quant_interceptor(lp)):
                x = layer.apply({"params": lp}, x)
        else:
            x = layer.apply({"params": lp}, x)
    x = nn.LayerNorm(use_bias=False).apply(
        {"params": {"scale": ln_final_scale}}, x)
    return x, jnp.stack(ks), jnp.stack(vs)


def make_generator(spec: ModelSpec):
    """Build ``generate(params, prompt, max_new_tokens, rng=None,
    temperature=0.0)`` for a ``transformer_lm`` ModelSpec.

    Args (of the returned function):
      prompt: ``[B, P]`` int32 prompt tokens (P >= 1).
      max_new_tokens: how many tokens to append (static).
      rng: PRNG key for sampling; required when ``temperature > 0``.
      temperature: 0.0 = greedy argmax; > 0 scales logits before
        categorical sampling.
      top_k / top_p: optional sampling filters (top-k truncation /
        nucleus sampling); require ``temperature > 0``.
      eos_id: optional stop token — rows that generate it pad the rest
        of their slots with it (static-shape masking; see with_logits).

    The returned function also carries ``.with_logits`` (adds the
    per-position logits) and ``.beam_search`` (width-W beam decode
    returning ``(tokens, suffix_logprob)``).

    ``params`` may be a full-precision tree OR a weight-only int8 tree
    from :func:`autodist_tpu.models.quantize.quantize_lm_params` —
    greedy/sampled/beam decode then run the Pallas int8 matmul kernel
    with weights resident in HBM as int8 (half the per-tick weight
    traffic that bounds decode); ``score`` needs full precision.

    Returns ``[B, P + max_new_tokens]`` tokens (prompt included).
    """
    require_lm_spec(spec, "make_generator")
    cfg = spec.config
    num_layers = cfg["num_layers"]

    def _check_len(total):
        if total > cfg["max_len"]:
            raise ValueError(
                f"prompt + max_new_tokens = {total} exceeds the model's "
                f"max_len {cfg['max_len']}")

    def _unpack(params):
        return unpack_lm_params(params, num_layers)

    # max_new_tokens and the sampling knobs are static: they shape the
    # scan and select the sampling branch at trace time.
    @functools.partial(jax.jit, static_argnums=(2, 4, 5, 6, 7))
    def generate(params, prompt, max_new_tokens, rng=None,
                 temperature=0.0, top_k=0, top_p=0.0, eos_id=-1):
        b, p_len = prompt.shape
        total = p_len + max_new_tokens
        _check_len(total)
        embed, pos_embed, layer_params, ln_final = _unpack(params)
        heads, hd = cfg["num_heads"], cfg["head_dim"]
        dtype = pos_embed.dtype   # embed may be Quantized
        k0 = jnp.zeros((num_layers, total, b, heads, hd), dtype)
        tokens0 = jnp.concatenate(
            [prompt, jnp.zeros((b, max_new_tokens), prompt.dtype)], axis=1)
        rng0 = rng if rng is not None else jax.random.PRNGKey(0)
        done0 = jnp.zeros((b,), bool)

        def tick(carry, pos):
            tokens, k_cache, v_cache, key, done = carry
            tok = lax.dynamic_index_in_dim(tokens, pos, 1, keepdims=False)
            x = embed_lookup(embed, tok, pos_embed.dtype) + pos_embed[pos]
            logits, k_cache, v_cache = _token_step(
                layer_params, ln_final, embed, x, k_cache, v_cache, pos,
                total)
            key, sub = jax.random.split(key)
            nxt = sample_next_token(logits, sub, temperature, top_k,
                                    top_p).astype(tokens.dtype)
            if eos_id >= 0:
                # Stop-token semantics under static shapes: a finished
                # row keeps emitting eos (masking, not early exit — the
                # scan length is fixed, the XLA-idiomatic form).  Only
                # GENERATED eos finishes a row; eos inside the prompt is
                # data (e.g. a separator), not a stop.
                nxt = jnp.where(done, jnp.asarray(eos_id, tokens.dtype),
                                nxt)
            # Position pos predicts slot pos+1 (pos <= total-2, so the
            # write never overflows).  Teacher-force prompt positions:
            # keep the prompt token for slots still inside the prompt.
            cur = lax.dynamic_index_in_dim(tokens, pos + 1, 1,
                                           keepdims=False)
            in_gen = pos + 1 >= p_len
            tokens = lax.dynamic_update_index_in_dim(
                tokens, jnp.where(in_gen, nxt, cur), pos + 1, 1)
            if eos_id >= 0:
                done = done | (in_gen & (nxt == eos_id))
            return (tokens, k_cache, v_cache, key, done), logits

        (tokens, _, _, _, _), step_logits = lax.scan(
            tick, (tokens0, k0, k0, rng0, done0), jnp.arange(total - 1))
        return tokens, step_logits

    def with_logits(params, prompt, max_new_tokens: int,
                    rng: Optional[jax.Array] = None,
                    temperature: float = 0.0, top_k: int = 0,
                    top_p: float = 0.0, eos_id: Optional[int] = None):
        """Tokens plus the per-position logits ``[total-1, B, V]``
        (scoring/evaluation use).  ``top_k``/``top_p`` filter the
        sampling distribution (only with ``temperature > 0``).

        ``eos_id``: stop token — a row that GENERATES it keeps emitting
        ``eos_id`` for its remaining slots (masking under static shapes,
        not early exit; prompt-resident eos tokens are data and do not
        stop).  The returned logits are still the model's per-position
        logits for every slot."""
        check_sampling_args(_vocab_size(params), temperature, top_k,
                            top_p, eos_id, rng)
        return generate(params, prompt, int(max_new_tokens), rng,
                        float(temperature), int(top_k), float(top_p),
                        -1 if eos_id is None else int(eos_id))

    def wrapped(params, prompt, max_new_tokens: int,
                rng: Optional[jax.Array] = None,
                temperature: float = 0.0, top_k: int = 0,
                top_p: float = 0.0, eos_id: Optional[int] = None):
        tokens, _ = with_logits(params, prompt, max_new_tokens, rng,
                                temperature, top_k, top_p, eos_id)
        return tokens

    # Beam search: beams ride the batch dim ([B·W] rows through the same
    # KV-cache tick); per-position, scores = beam logprob + log-softmax
    # over the vocab, top-W of the W·V continuations survive, and the
    # caches are gathered along the beam dim to follow their histories.
    @functools.partial(jax.jit, static_argnums=(2, 3))
    def beam_generate(params, prompt, max_new_tokens, num_beams):
        b, p_len = prompt.shape
        w = num_beams
        total = p_len + max_new_tokens
        _check_len(total)
        embed, pos_embed, layer_params, ln_final = _unpack(params)
        heads, hd = cfg["num_heads"], cfg["head_dim"]
        tokens_b = jnp.concatenate(
            [prompt, jnp.zeros((b, max_new_tokens), prompt.dtype)],
            axis=1)                                       # [B, total]

        # Phase 1 — prefill at batch B (no beam fan-out yet: all beams
        # would be identical, so running W copies through the prompt
        # would be W× wasted FLOPs and cache copies).
        kb = jnp.zeros((num_layers, total, b, heads, hd),
                       pos_embed.dtype)

        def prefill(carry, pos):
            k_cache, v_cache = carry
            tok = lax.dynamic_index_in_dim(tokens_b, pos, 1, keepdims=False)
            x = embed_lookup(embed, tok, pos_embed.dtype) + pos_embed[pos]
            _, k_cache, v_cache = _token_step(
                layer_params, ln_final, embed, x, k_cache, v_cache, pos,
                total)
            return (k_cache, v_cache), None

        (kb, vb), _ = lax.scan(prefill, (kb, kb),
                               jnp.arange(max(p_len - 1, 0)))

        # Fan out once: beams ride the batch dim ([B·W] rows).
        tokens0 = jnp.repeat(tokens_b, w, axis=0)         # [B*W, total]
        k0 = jnp.repeat(kb, w, axis=2)                    # batch dim is 2
        v0 = jnp.repeat(vb, w, axis=2)
        # identical beams: suppress duplicates by starting beams 1..W-1
        # at -inf so the first free position fans out from beam 0.
        lp0 = jnp.tile(jnp.array([0.0] + [-1e30] * (w - 1), jnp.float32),
                       (b, 1))                            # [B, W]

        # Phase 2 — beam ticks from the first free position on (pos+1 is
        # never inside the prompt here, so no teacher-forcing branch).
        def tick(carry, pos):
            tokens, k_cache, v_cache, logprobs = carry
            tok = lax.dynamic_index_in_dim(tokens, pos, 1, keepdims=False)
            x = embed_lookup(embed, tok, pos_embed.dtype) + pos_embed[pos]
            logits, k_cache, v_cache = _token_step(
                layer_params, ln_final, embed, x, k_cache, v_cache, pos,
                total)
            logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
            v = logp.shape[-1]
            # scores over all W*V continuations of each batch row; the
            # beam score is exactly the logprob of the GENERATED suffix
            # (pinned in tests/test_generate.py against the full forward)
            scores = (logprobs[..., None]
                      + logp.reshape(b, w, v)).reshape(b, w * v)
            logprobs, top_idx = lax.top_k(scores, w)      # [B, W]
            beam_src = top_idx // v                       # which beam
            new_tok = (top_idx % v).astype(tokens.dtype)  # which token
            # gather histories: tokens + caches follow their source beam
            flat_src = (jnp.arange(b)[:, None] * w + beam_src).reshape(-1)
            tokens = jnp.take(tokens, flat_src, axis=0)
            k_cache = jnp.take(k_cache, flat_src, axis=2)
            v_cache = jnp.take(v_cache, flat_src, axis=2)
            tokens = lax.dynamic_update_index_in_dim(
                tokens, new_tok.reshape(-1), pos + 1, 1)
            return (tokens, k_cache, v_cache, logprobs), None

        (tokens, _, _, logprobs), _ = lax.scan(
            tick, (tokens0, k0, v0, lp0),
            jnp.arange(p_len - 1, total - 1))
        best = jnp.argmax(logprobs, axis=-1)              # [B]
        tokens = tokens.reshape(b, w, total)
        return (jnp.take_along_axis(tokens, best[:, None, None], 1)[:, 0],
                jnp.max(logprobs, axis=-1))

    def beam_search(params, prompt, max_new_tokens: int,
                    num_beams: int = 4):
        """Beam-search decode; returns ``(tokens [B, P+N], logprob [B])``
        — the total log-probability of the generated suffix.  No
        ``eos_id`` support here: finished-beam bookkeeping (freezing a
        beam's score while others grow) is a different algorithm from
        the masking trick greedy/sampled decode uses; use the greedy/
        sampled path when stop tokens matter."""
        if num_beams < 1:
            raise ValueError(f"num_beams must be >= 1, got {num_beams}")
        vocab = _vocab_size(params)
        if num_beams > vocab:
            # beyond V beams, the -1e30 duplicate-suppressed starter
            # beams would survive the first top-k as degenerate beams
            raise ValueError(
                f"num_beams must be <= vocab_size={vocab}, got {num_beams}")
        return beam_generate(params, prompt, int(max_new_tokens),
                             int(num_beams))

    def score(params, tokens):
        """Teacher-forced scoring: per-sequence log-likelihood of
        ``tokens[:, 1:]`` given the prefix and the perplexity —
        ``(log_likelihood [B], perplexity [B])``.  Uses ONE parallel
        forward (``spec.apply_fn``), not the sequential decode scan —
        scoring has no sequential dependence (the decode logits match it
        position-for-position, pinned in tests/test_generate.py)."""
        if tokens.shape[1] < 2:
            raise ValueError("score needs sequences of length >= 2 "
                             "(nothing to predict for a single token)")
        if is_quantized(params):
            raise ValueError(
                "score runs the full parallel forward (spec.apply_fn) "
                "and needs full-precision params — decode-only int8 "
                "trees (quantize_lm_params) are not scoreable; keep the "
                "original params for scoring")
        logits = spec.apply_fn(params, tokens)[:, :-1]   # [B, T-1, V]
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        tok_lp = jnp.take_along_axis(
            logp, tokens[:, 1:, None], axis=-1)[..., 0]  # [B, T-1]
        ll = tok_lp.sum(axis=1)                          # [B]
        ppl = jnp.exp(-ll / (tokens.shape[1] - 1))
        return ll, ppl

    wrapped.with_logits = with_logits
    wrapped.beam_search = beam_search
    wrapped.score = score
    return wrapped
