"""LoRA: low-rank adaptation for parameter-efficient finetuning.

Beyond the reference (training-only, full-parameter — it has no
finetuning story): freeze the base model, train rank-``r`` adapter pairs
``(A, B)`` on selected kernels (2-D by default; N-D DenseGeneral-style
kernels via a fan-in split), where the effective weight is
``W + (alpha / r) * A @ B`` with ``B`` zero-initialized (the adapted
model starts EXACTLY at the base model).

Composition with the framework is structural, not special-cased:

* the captured tree is ``{"base": params, "lora": adapters}`` with
  ``untrainable_vars=("base",)`` — the freeze machinery
  (``GraphItem.frozen_aware_optimizer``) gives the base zero updates and
  NO optimizer state, so optimizer memory scales with the adapters
  (the point of LoRA), and the strategy layer syncs only adapter grads;
* any strategy builder / mesh / remat / accum composes unchanged.

Usage::

    setup = lora_setup(params, spec.loss_fn, rank=8,
                       rng=jax.random.PRNGKey(0))
    with ad.scope():
        ad.capture(**setup.capture_args, optimizer=optax.adamw(1e-3))
    sess = ad.create_distributed_session()
    ...train...
    merged = setup.merge(sess.params)   # plain params tree for serving
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, Dict, Optional, Sequence

import jax
import jax.numpy as jnp

from autodist_tpu.graph_item import match_var_name, path_name


def _match(name: str, patterns: Sequence[str]) -> bool:
    # Same exact/prefix/glob semantics as capture()'s variable patterns,
    # so LoRA targets read like untrainable_vars.
    return match_var_name(name, tuple(patterns))


def _resolve_split(name: str, shape, targets) -> Optional[int]:
    """How many leading dims form the fan-in for this leaf, or None when
    the leaf is not adapted.  ``targets`` entries are patterns or
    ``(pattern, split)`` pairs — first match wins.  The split covers
    DenseGeneral-style N-D kernels: a ``[d_model, heads, head_dim]``
    projection splits at 1, its ``[heads, head_dim, d_model]`` output
    projection at 2.  Default targets (None): every 2-D leaf."""
    if targets is None:
        return 1 if len(shape) == 2 else None
    for entry in targets:
        pattern, split = entry if isinstance(entry, tuple) else (entry, 1)
        if _match(name, (pattern,)):
            if len(shape) < 2:
                raise ValueError(
                    f"LoRA target {name} has shape {shape}; need >= 2 "
                    f"dims to adapt")
            if not 0 < split < len(shape):
                raise ValueError(
                    f"LoRA target {name}: split {split} out of range "
                    f"for shape {shape} (use (pattern, split) with "
                    f"0 < split < ndim)")
            return split
    return None


def lora_init(rng: jax.Array, params: Any, *, rank: int = 8,
              targets: Optional[Sequence] = None) -> Any:
    """Build the adapter tree: for every matched leaf,
    ``{"a": [fan_in, r] (scaled normal), "b": [r, fan_out] (zeros)}``;
    non-target leaves are absent.  ``targets`` entries are name patterns
    (exact/prefix/glob, like ``untrainable_vars``) or ``(pattern,
    split)`` pairs for N-D kernels (see :func:`_resolve_split`); default
    is all 2-D leaves.  Returned tree is a flat ``{var_name: {"a","b"}}``
    dict keyed by the leaf's dotted path name (stable across pad/shard
    transforms)."""
    if rank < 1:
        raise ValueError(f"rank must be >= 1, got {rank}")
    import math

    leaves = jax.tree_util.tree_flatten_with_path(params)[0]
    adapters: Dict[str, Any] = {}
    for path, leaf in leaves:
        name = path_name(path)
        shape = tuple(getattr(leaf, "shape", ()) or ())
        split = _resolve_split(name, shape, targets)
        if split is None:
            continue
        fan_in = math.prod(shape[:split])
        fan_out = math.prod(shape[split:])
        rng, sub = jax.random.split(rng)
        adapters[name.replace("/", ".")] = {
            # He-style fan-in scaling on A; B zero => delta starts at 0.
            "a": (jax.random.normal(sub, (fan_in, rank), jnp.float32)
                  / jnp.sqrt(fan_in)),
            "b": jnp.zeros((rank, fan_out), jnp.float32),
        }
    if not adapters:
        raise ValueError("no leaves matched the LoRA targets")
    return adapters


def lora_merge(params: Any, adapters: Any, *, alpha: float,
               rank: int) -> Any:
    """``W + (alpha / rank) * A @ B`` on adapted leaves (cast back to the
    leaf dtype); identity elsewhere.  Jit-safe: called inside the loss."""
    scale = alpha / rank

    def merge_leaf(path, leaf):
        ad = adapters.get(path_name(path).replace("/", "."))
        if ad is None:
            return leaf
        delta = ((ad["a"] @ ad["b"]) * scale).reshape(leaf.shape)
        return (leaf.astype(jnp.float32) + delta).astype(leaf.dtype)

    return jax.tree_util.tree_map_with_path(merge_leaf, params)


@dataclass
class LoRASetup:
    """Bundle returned by :func:`lora_setup`: pass ``capture_args`` to
    ``AutoDist.capture`` (add your optimizer), train, then ``merge`` the
    session's params into a plain tree for serving/export."""
    capture_args: Dict[str, Any]
    alpha: float
    rank: int

    def merge(self, captured_params: Any) -> Any:
        return lora_merge(captured_params["base"],
                          captured_params["lora"],
                          alpha=self.alpha, rank=self.rank)

    @property
    def num_adapter_params(self) -> int:
        return sum(int(x.size) for x in jax.tree_util.tree_leaves(
            self.capture_args["params"]["lora"]))


def lora_setup(params: Any, loss_fn: Callable, *, rng: jax.Array,
               rank: int = 8, alpha: Optional[float] = None,
               targets: Optional[Sequence] = None,
               has_aux: bool = False) -> LoRASetup:
    """Everything ``capture()`` needs for LoRA finetuning of ``params``
    under ``loss_fn(params, batch)``: the ``{"base", "lora"}`` tree,
    a merged-view loss, and ``untrainable_vars=("base",)``."""
    alpha = float(alpha) if alpha is not None else float(2 * rank)
    adapters = lora_init(rng, params, rank=rank, targets=targets)

    def merged_loss(p, batch):
        merged = lora_merge(p["base"], p["lora"], alpha=alpha, rank=rank)
        return loss_fn(merged, batch)

    return LoRASetup(
        capture_args={
            "params": {"base": params, "lora": adapters},
            "loss_fn": merged_loss,
            "untrainable_vars": ("base",),
            "has_aux": has_aux,
        },
        alpha=alpha, rank=rank)
