"""MoE decoder LM: the expert-parallel flagship variant.

GPT-style causal LM where every layer's FFN is a top-2 routed
mixture-of-experts (``autodist_tpu/parallel/moe.py``), expert weights
sharded over the ``expert`` mesh axis via ``ModelSpec.expert_vars``.
Attention is pluggable (dense / flash / ring) like the other LMs.

Built functionally (plain parameter dicts, no flax) so the MoE layer's
router/expert parameters keep explicit strategy-addressable names
(``layers_i/moe/wi`` …).  No reference analog (SURVEY §2.8: EP absent).
"""
from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from autodist_tpu.models.base import (
    ModelSpec,
    cross_entropy_loss,
    layer_norm as _layer_norm,
)
from autodist_tpu.models.transformer import dense_attention
from autodist_tpu.parallel.moe import init_moe_params, moe_ffn


def _init_layer(rng, d_model, num_heads, head_dim, d_ff, num_experts, dtype):
    r_q, r_k, r_v, r_o, r_moe = jax.random.split(rng, 5)
    scale = 1.0 / (d_model ** 0.5)
    pshape = (d_model, num_heads, head_dim)
    return {
        "ln_attn": jnp.ones((d_model,), dtype),
        "wq": jax.random.normal(r_q, pshape, dtype) * scale,
        "wk": jax.random.normal(r_k, pshape, dtype) * scale,
        "wv": jax.random.normal(r_v, pshape, dtype) * scale,
        "wo": jax.random.normal(r_o, (num_heads, head_dim, d_model),
                                dtype) * scale,
        "ln_mlp": jnp.ones((d_model,), dtype),
        "moe": init_moe_params(r_moe, d_model, d_ff, num_experts, dtype),
    }


def _apply_layer(lp, x, attn_fn, mesh, capacity_factor):
    h = _layer_norm(x, lp["ln_attn"])
    q = jnp.einsum("btm,mhd->bthd", h, lp["wq"])
    k = jnp.einsum("btm,mhd->bthd", h, lp["wk"])
    v = jnp.einsum("btm,mhd->bthd", h, lp["wv"])
    a = attn_fn(q, k, v, True)
    x = x + jnp.einsum("bthd,hdm->btm", a, lp["wo"])
    h = _layer_norm(x, lp["ln_mlp"])
    y, aux = moe_ffn(lp["moe"], h, mesh=mesh,
                     capacity_factor=capacity_factor)
    return x + y, aux


def moe_transformer_lm(
        mesh: Mesh, vocab_size: int = 32128, num_layers: int = 12,
        num_heads: int = 12, head_dim: int = 64, d_ff: int = 3072,
        num_experts: int = 8, max_len: int = 1024,
        attn_fn: Callable = dense_attention, capacity_factor: float = 2.0,
        aux_weight: float = 1e-2, dtype=jnp.float32,
        seq_len: Optional[int] = None) -> ModelSpec:
    """Expert-parallel GPT-style LM; the load-balancing auxiliary loss is
    folded into the training loss with weight ``aux_weight``."""
    seq_len = seq_len or max_len
    d_model = num_heads * head_dim

    def init(rng):
        r_emb, r_pos, r_layers = jax.random.split(rng, 3)
        params = {
            "embed": jax.random.normal(r_emb, (vocab_size, d_model),
                                       dtype) * 0.02,
            "pos_embed": jax.random.normal(r_pos, (max_len, d_model),
                                           dtype) * 0.02,
            "ln_final": jnp.ones((d_model,), dtype),
        }
        for i, r in enumerate(jax.random.split(r_layers, num_layers)):
            params[f"layers_{i}"] = _init_layer(
                r, d_model, num_heads, head_dim, d_ff, num_experts, dtype)
        return params

    def forward(params, tokens):
        x = jnp.take(params["embed"], tokens, axis=0) \
            + params["pos_embed"][None, :tokens.shape[1]]
        aux_total = 0.0
        for i in range(num_layers):
            x, aux = _apply_layer(params[f"layers_{i}"], x, attn_fn, mesh,
                                  capacity_factor)
            aux_total = aux_total + aux
        x = _layer_norm(x, params["ln_final"])
        logits = jnp.einsum("btd,vd->btv", x, params["embed"])
        return logits, aux_total / num_layers

    def apply_fn(params, tokens):
        return forward(params, tokens)[0]

    def loss_fn(params, batch):
        logits, aux = forward(params, batch["tokens"])
        ce = cross_entropy_loss(logits[:, :-1], batch["tokens"][:, 1:])
        return ce + aux_weight * aux

    def make_batch(rng: np.random.RandomState, batch_size: int):
        return {"tokens": rng.randint(
            0, vocab_size, (batch_size, seq_len)).astype(np.int32)}

    return ModelSpec(
        name="moe_transformer_lm",
        init=init, loss_fn=loss_fn, apply_fn=apply_fn, make_batch=make_batch,
        sparse_vars=("embed",),
        expert_vars=("*/moe/wi", "*/moe/wo"),
        config=dict(vocab_size=vocab_size, num_layers=num_layers,
                    num_heads=num_heads, head_dim=head_dim, d_ff=d_ff,
                    num_experts=num_experts, max_len=max_len,
                    seq_len=seq_len),
    )
