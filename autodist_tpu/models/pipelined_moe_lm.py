"""Pipelined MoE decoder LM — pipeline AND expert parallelism in one model.

Stage-stacked MoE transformer layers: every layer parameter leads with a
``[num_layers]`` stage-stack axis (``pipeline_vars``), and the expert
weights additionally carry the expert axis right after it
(``stack/moe/wi``: ``[L, E, d_model, d_ff]`` → PartitionSpec
``('pipe', 'expert', ...)``).  The pipeline rotates microbatches over the
``pipe`` mesh axis (``parallel/pipeline.py``) while GSPMD lowers each
stage's MoE dispatch to all-to-alls over ``expert``
(``parallel/moe.py``).

No reference analog: both parallelisms are absent there (SURVEY §2.8).
"""
from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh

from autodist_tpu.models.base import (
    ModelSpec,
    cross_entropy_loss,
    layer_norm as _layer_norm,
)
from autodist_tpu.models.moe_lm import _apply_layer, _init_layer
from autodist_tpu.models.transformer import dense_attention
from autodist_tpu.models.pipelined_lm import _device_major_layers
from autodist_tpu.parallel.pipeline import pipeline_apply, stack_stage_params


def pipelined_moe_transformer_lm(
        mesh: Mesh, vocab_size: int = 32128, num_layers: int = 12,
        num_heads: int = 12, head_dim: int = 64, d_ff: int = 3072,
        num_experts: int = 8, max_len: int = 1024,
        attn_fn: Callable = dense_attention, capacity_factor: float = 2.0,
        aux_weight: float = 1e-2, dtype=jnp.float32,
        seq_len: Optional[int] = None, num_stages: Optional[int] = None,
        num_microbatches: Optional[int] = None,
        num_virtual_stages: int = 1, remat: bool = False,
        schedule: str = "gpipe") -> ModelSpec:
    """``schedule="1f1b"`` trains through the hand-scheduled 1F1B backward
    (``parallel/pipeline_1f1b.py``) — pipeline × expert × data with O(S·V)
    activation memory; the MoE balancing aux rides the activation channel
    through the schedule and the per-microbatch head loss peels it (mean
    of per-microbatch means == the GPipe loss, pinned in tests/test_moe.py).
    """
    if schedule not in ("gpipe", "1f1b"):
        raise ValueError(f"unknown schedule {schedule!r}")
    if schedule == "1f1b":
        from autodist_tpu.models.pipelined_lm import _warn_large_1f1b_head
        _warn_large_1f1b_head(mesh, vocab_size, num_heads * head_dim)
    seq_len = seq_len or max_len
    d_model = num_heads * head_dim
    stages = num_stages or mesh.shape.get("pipe", 1) or 1
    chunks = stages * num_virtual_stages
    if num_layers % chunks:
        raise ValueError(f"{num_layers} layers not divisible into "
                         f"{chunks} pipeline stage chunks")

    def init(rng):
        r_emb, r_pos, r_layers = jax.random.split(rng, 3)
        per_layer = [
            _init_layer(r, d_model, num_heads, head_dim, d_ff, num_experts,
                        dtype)
            for r in jax.random.split(r_layers, num_layers)]
        per_layer = _device_major_layers(per_layer, stages,
                                         num_virtual_stages)
        return {
            "embed": jax.random.normal(r_emb, (vocab_size, d_model),
                                       dtype) * 0.02,
            "pos_embed": jax.random.normal(r_pos, (max_len, d_model),
                                           dtype) * 0.02,
            "stack": stack_stage_params(per_layer),      # leading [L]
            "ln_final": jnp.ones((d_model,), dtype),
        }

    def stage_fn(stage_params, xa):
        # Carry = (activations, running aux loss) so the MoE balancing loss
        # survives the pipeline's homogeneous-activation requirement.
        x, aux = xa[..., :-1], xa[..., -1:]

        def body(carry, lp):
            h, a = carry
            h, aux_i = _apply_layer(lp, h, attn_fn, mesh, capacity_factor)
            return (h, a + aux_i), None
        (x, aux_s), _ = lax.scan(body, (x, jnp.mean(aux)), stage_params)
        aux_col = jnp.broadcast_to(aux_s, xa.shape[:-1] + (1,)).astype(
            xa.dtype)
        return jnp.concatenate([x, aux_col], axis=-1)

    def forward(params, tokens):
        x = jnp.take(params["embed"], tokens, axis=0) \
            + params["pos_embed"][None, :tokens.shape[1]]
        stacked = jax.tree_util.tree_map(
            lambda a: a.reshape((chunks, num_layers // chunks) + a.shape[1:]),
            params["stack"])
        # Append an aux-loss channel so stage outputs stay shape-homogeneous.
        xa = jnp.concatenate([x, jnp.zeros_like(x[..., :1])], axis=-1)
        xa = pipeline_apply(stage_fn, stacked, xa, mesh,
                            num_microbatches=num_microbatches,
                            num_virtual_stages=num_virtual_stages,
                            remat=remat)
        x, aux = xa[..., :-1], jnp.mean(xa[..., -1])
        x = _layer_norm(x, params["ln_final"])
        logits = jnp.einsum("btd,vd->btv", x, params["embed"])
        return logits, aux / num_layers

    def apply_fn(params, tokens):
        return forward(params, tokens)[0]

    def loss_fn(params, batch):
        logits, aux = forward(params, batch["tokens"])
        ce = cross_entropy_loss(logits[:, :-1], batch["tokens"][:, 1:])
        return ce + aux_weight * aux

    def make_batch(rng: np.random.RandomState, batch_size: int):
        return {"tokens": rng.randint(
            0, vocab_size, (batch_size, seq_len)).astype(np.int32)}

    grad_fn = None
    if schedule == "1f1b":
        from autodist_tpu.models.pipelined_lm import _tied_head_1f1b_grad_fn

        def head_loss(lp, ya_mb, tok_mb):
            y = ya_mb[..., :-1]
            aux = jnp.mean(ya_mb[..., -1]) / num_layers
            h = _layer_norm(y, lp["ln_final"])
            logits = jnp.einsum("btd,vd->btv", h, lp["embed"])
            ce = cross_entropy_loss(logits[:, :-1], tok_mb[:, 1:])
            return ce + aux_weight * aux

        def make_embed_fn(tokens):
            def embed_fn(ep):
                x = (jnp.take(ep["embed"], tokens, axis=0)
                     + ep["pos_embed"][None, :tokens.shape[1]])
                # aux-loss channel (zero at entry; stages accumulate into
                # it — its input cotangent vanishes with the zeros input)
                return jnp.concatenate([x, jnp.zeros_like(x[..., :1])],
                                       axis=-1)
            return embed_fn

        grad_fn = _tied_head_1f1b_grad_fn(
            mesh, stages=stages, chunks=chunks, num_layers=num_layers,
            num_microbatches=num_microbatches,
            num_virtual_stages=num_virtual_stages, stage_fn=stage_fn,
            head_loss=head_loss, make_embed_fn=make_embed_fn)

    return ModelSpec(
        name="pipelined_moe_transformer_lm",
        init=init, loss_fn=loss_fn, apply_fn=apply_fn, make_batch=make_batch,
        grad_fn=grad_fn,
        sparse_vars=("embed",),
        pipeline_vars=("stack",),
        expert_vars=("stack/moe/wi", "stack/moe/wo"),
        config=dict(vocab_size=vocab_size, num_layers=num_layers,
                    num_heads=num_heads, head_dim=head_dim, d_ff=d_ff,
                    num_experts=num_experts, max_len=max_len,
                    seq_len=seq_len, num_stages=stages,
                    schedule=schedule),
    )
