"""ResNet for image classification.

Parity target: reference ``examples/benchmark/imagenet.py`` ResNet101 (and
``examples/image_classifier.py`` ResNet-50) benchmarks.  TPU-first choices:
GroupNorm instead of BatchNorm — stateless (keeps the training program a pure
function of params, matching the framework's functional capture) and the
standard choice for large-batch TPU training; NHWC layout; bottleneck blocks
identical in structure to the original.
"""
from __future__ import annotations

from functools import partial
from typing import Sequence, Tuple

import flax.linen as nn
import jax.numpy as jnp
import numpy as np

from autodist_tpu.models.base import ModelSpec, cross_entropy_loss

Conv = partial(nn.Conv, use_bias=False)


def _norm(name: str):
    return nn.GroupNorm(num_groups=32, name=name)


class BottleneckBlock(nn.Module):
    filters: int
    strides: int = 1

    @nn.compact
    def __call__(self, x):
        residual = x
        y = Conv(self.filters, (1, 1), name="conv1")(x)
        y = nn.relu(_norm("norm1")(y))
        y = Conv(self.filters, (3, 3), strides=(self.strides, self.strides),
                 name="conv2")(y)
        y = nn.relu(_norm("norm2")(y))
        y = Conv(self.filters * 4, (1, 1), name="conv3")(y)
        y = _norm("norm3")(y)
        if residual.shape != y.shape:
            residual = Conv(self.filters * 4, (1, 1),
                            strides=(self.strides, self.strides),
                            name="proj")(x)
            residual = _norm("norm_proj")(residual)
        return nn.relu(residual + y)


class ResNet(nn.Module):
    stage_sizes: Sequence[int]
    num_classes: int

    @nn.compact
    def __call__(self, x):
        x = Conv(64, (7, 7), strides=(2, 2), name="conv_init")(x)
        x = nn.relu(_norm("norm_init")(x))
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        for i, num_blocks in enumerate(self.stage_sizes):
            for j in range(num_blocks):
                strides = 2 if i > 0 and j == 0 else 1
                x = BottleneckBlock(64 * 2 ** i, strides,
                                    name=f"stage{i}_block{j}")(x)
        x = jnp.mean(x, axis=(1, 2))
        return nn.Dense(self.num_classes, name="head")(x)


def _image_spec(name: str, model: nn.Module, num_classes: int,
                image_size: int) -> ModelSpec:
    def init(rng):
        x = jnp.zeros((2, image_size, image_size, 3), jnp.float32)
        return model.init(rng, x)["params"]

    def apply_fn(params, images):
        return model.apply({"params": params}, images)

    def loss_fn(params, batch):
        return cross_entropy_loss(apply_fn(params, batch["images"]),
                                  batch["labels"])

    def make_batch(rng: np.random.RandomState, batch_size: int):
        return {
            "images": rng.randn(batch_size, image_size, image_size, 3
                                ).astype(np.float32),
            "labels": rng.randint(0, num_classes, (batch_size,)
                                  ).astype(np.int32),
        }

    return ModelSpec(name=name, init=init, loss_fn=loss_fn, apply_fn=apply_fn,
                     make_batch=make_batch,
                     config=dict(num_classes=num_classes,
                                 image_size=image_size))


def resnet50(num_classes: int = 1000, image_size: int = 224) -> ModelSpec:
    return _image_spec("resnet50", ResNet([3, 4, 6, 3], num_classes),
                       num_classes, image_size)


def resnet101(num_classes: int = 1000, image_size: int = 224) -> ModelSpec:
    return _image_spec("resnet101", ResNet([3, 4, 23, 3], num_classes),
                       num_classes, image_size)
