"""ResNet for image classification.

Parity target: reference ``examples/benchmark/imagenet.py`` ResNet101 (and
``examples/image_classifier.py`` ResNet-50) benchmarks.  TPU-first choices:
GroupNorm instead of BatchNorm — stateless (keeps the training program a pure
function of params, matching the framework's functional capture) and the
standard choice for large-batch TPU training; NHWC layout; bottleneck blocks
identical in structure to the original.
"""
from __future__ import annotations

from functools import partial
from typing import Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from autodist_tpu.models.base import ModelSpec, cross_entropy_loss

Conv = partial(nn.Conv, use_bias=False)


def _norm(name: str):
    return nn.GroupNorm(num_groups=32, name=name)


class BottleneckBlock(nn.Module):
    filters: int
    strides: int = 1

    @nn.compact
    def __call__(self, x):
        residual = x
        y = Conv(self.filters, (1, 1), name="conv1")(x)
        y = nn.relu(_norm("norm1")(y))
        y = Conv(self.filters, (3, 3), strides=(self.strides, self.strides),
                 name="conv2")(y)
        y = nn.relu(_norm("norm2")(y))
        y = Conv(self.filters * 4, (1, 1), name="conv3")(y)
        y = _norm("norm3")(y)
        if residual.shape != y.shape:
            residual = Conv(self.filters * 4, (1, 1),
                            strides=(self.strides, self.strides),
                            name="proj")(x)
            residual = _norm("norm_proj")(residual)
        return nn.relu(residual + y)


def space_to_depth(x: jax.Array, block: int = 2) -> jax.Array:
    """[B, H, W, C] → [B, H/b, W/b, b·b·C]; channel packing order is
    (dy, dx, c).  The TPU stem transform: a 7×7/stride-2 conv on
    3-channel input runs the 128-wide MXU at 3/128 occupancy on its
    contraction dim; the SAME conv expressed over space-to-depth input
    contracts 4·4·12 = 192 elements instead of 7·7·3 = 147 spread over
    49 tiny steps (the MLPerf-era ResNet stem optimization)."""
    b, h, w, c = x.shape
    x = x.reshape(b, h // block, block, w // block, block, c)
    x = x.transpose(0, 1, 3, 2, 4, 5)
    return x.reshape(b, h // block, w // block, block * block * c)


def convert_stem_params(params):
    """Losslessly remap a ``stem='conv7'`` tree to ``stem='s2d'``: embed
    the [7,7,C,64] kernel into the [4,4,4C,64] layout so the s2d model
    computes the SAME function (pinned in tests/test_models.py).  The
    derivation (XLA SAME for k=7/s=2 pads (2, 3)): output[i,j] =
    Σ W7[u, v, c] · x[2i+u-2, 2j+v-2, c]; substituting the s2d
    coordinates 2i+u-2 = 2(i+a)+dy gives u = 2a+dy+2 with a ∈ -1..2,
    dy ∈ {0,1} — i.e. a 4×4 conv over s2d input with padding (1, 2)
    and kernel entry (a+1, b+1, (dy,dx,c)) = W7[2a+dy+2, 2b+dx+2]
    (zero where the index falls outside 0..6)."""
    w7 = np.asarray(params["conv_init"]["kernel"])       # [7,7,C,64]
    c_in, c_out = w7.shape[2], w7.shape[3]
    w4 = np.zeros((4, 4, 4 * c_in, c_out), w7.dtype)
    for a2 in range(4):
        for b2 in range(4):
            for dy in range(2):
                for dx in range(2):
                    r = 2 * a2 + dy
                    s = 2 * b2 + dx
                    if r < 7 and s < 7:
                        ch = (dy * 2 + dx) * c_in
                        w4[a2, b2, ch:ch + c_in] = w7[r, s]
    out = dict(params)
    out["conv_init"] = {"kernel": jnp.asarray(w4)}
    return out


class ResNet(nn.Module):
    stage_sizes: Sequence[int]
    num_classes: int
    stem: str = "conv7"

    @nn.compact
    def __call__(self, x):
        if self.stem == "s2d":
            # Same function as the 7×7/s2 conv (see convert_stem_params)
            # with the contraction shaped for the MXU.
            x = space_to_depth(x, 2)
            x = Conv(64, (4, 4), padding=((1, 2), (1, 2)),
                     name="conv_init")(x)
        else:
            x = Conv(64, (7, 7), strides=(2, 2), name="conv_init")(x)
        x = nn.relu(_norm("norm_init")(x))
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        for i, num_blocks in enumerate(self.stage_sizes):
            for j in range(num_blocks):
                strides = 2 if i > 0 and j == 0 else 1
                x = BottleneckBlock(64 * 2 ** i, strides,
                                    name=f"stage{i}_block{j}")(x)
        x = jnp.mean(x, axis=(1, 2))
        return nn.Dense(self.num_classes, name="head")(x)


def _image_spec(name: str, model: nn.Module, num_classes: int,
                image_size: int) -> ModelSpec:
    def init(rng):
        x = jnp.zeros((2, image_size, image_size, 3), jnp.float32)
        return model.init(rng, x)["params"]

    def apply_fn(params, images):
        return model.apply({"params": params}, images)

    def loss_fn(params, batch):
        return cross_entropy_loss(apply_fn(params, batch["images"]),
                                  batch["labels"])

    def make_batch(rng: np.random.RandomState, batch_size: int):
        return {
            "images": rng.randn(batch_size, image_size, image_size, 3
                                ).astype(np.float32),
            "labels": rng.randint(0, num_classes, (batch_size,)
                                  ).astype(np.int32),
        }

    return ModelSpec(name=name, init=init, loss_fn=loss_fn, apply_fn=apply_fn,
                     make_batch=make_batch,
                     config=dict(num_classes=num_classes,
                                 image_size=image_size))


def resnet50(num_classes: int = 1000, image_size: int = 224,
             stem: str = "conv7") -> ModelSpec:
    """``stem='s2d'`` uses the space-to-depth stem (same function as
    the 7×7 conv — see :func:`convert_stem_params` — shaped for the
    MXU; image_size must be even)."""
    return _image_spec("resnet50", ResNet([3, 4, 6, 3], num_classes,
                                          stem=stem),
                       num_classes, image_size)


def resnet101(num_classes: int = 1000, image_size: int = 224,
              stem: str = "conv7") -> ModelSpec:
    return _image_spec("resnet101", ResNet([3, 4, 23, 3], num_classes,
                                           stem=stem),
                       num_classes, image_size)
