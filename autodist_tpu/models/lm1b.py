"""lm1b LSTM language model.

Parity target: reference ``examples/lm1b/language_model.py:15-60`` — an LSTM
LM over the One Billion Word benchmark with a 793,471-word vocabulary whose
embedding + softmax variables dominate (the Parallax showcase: embedding
gradients are sparse and go to sharded PS; LSTM weights are dense and
all-reduce).  Vocab default padded to 793,472 (multiple of 128) so the table
shards evenly on TPU meshes.
"""
from __future__ import annotations

from typing import Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from autodist_tpu.models.base import ModelSpec, cross_entropy_loss


class LSTMLM(nn.Module):
    vocab_size: int
    emb_dim: int
    hidden_dim: int
    num_layers: int

    @nn.compact
    def __call__(self, tokens):
        emb = self.param("embedding", nn.initializers.normal(0.1),
                         (self.vocab_size, self.emb_dim))
        x = jnp.take(emb, tokens, axis=0)  # [B, T, E]
        for i in range(self.num_layers):
            x = nn.RNN(nn.OptimizedLSTMCell(self.hidden_dim),
                       name=f"lstm_{i}")(x)
        # project to softmax dim and tie with an output embedding
        x = nn.Dense(self.emb_dim, name="proj")(x)
        softmax_emb = self.param("softmax_embedding",
                                 nn.initializers.normal(0.1),
                                 (self.vocab_size, self.emb_dim))
        return jnp.einsum("bte,ve->btv", x, softmax_emb)


def lm1b(vocab_size: int = 793472, emb_dim: int = 512,
         hidden_dim: int = 2048, num_layers: int = 2,
         seq_len: int = 20) -> ModelSpec:
    model = LSTMLM(vocab_size, emb_dim, hidden_dim, num_layers)

    def init(rng):
        return model.init(rng, jnp.zeros((2, seq_len), jnp.int32))["params"]

    def apply_fn(params, tokens):
        return model.apply({"params": params}, tokens)

    def loss_fn(params, batch):
        logits = apply_fn(params, batch["tokens"])
        return cross_entropy_loss(logits[:, :-1], batch["tokens"][:, 1:])

    def make_batch(rng: np.random.RandomState, batch_size: int):
        return {"tokens": rng.randint(
            0, vocab_size, (batch_size, seq_len)).astype(np.int32)}

    return ModelSpec(
        name="lm1b",
        init=init, loss_fn=loss_fn, apply_fn=apply_fn, make_batch=make_batch,
        sparse_vars=("embedding", "softmax_embedding"),
        config=dict(vocab_size=vocab_size, emb_dim=emb_dim,
                    hidden_dim=hidden_dim, num_layers=num_layers,
                    seq_len=seq_len),
    )
