"""lm1b LSTM language model.

Parity target: reference ``examples/lm1b/language_model.py:15-60`` — an LSTM
LM over the One Billion Word benchmark with a 793,471-word vocabulary whose
embedding + softmax variables dominate (the Parallax showcase: embedding
gradients are sparse and go to sharded PS; LSTM weights are dense and
all-reduce).  Vocab default padded to 793,472 (multiple of 128) so the table
shards evenly on TPU meshes.
"""
from __future__ import annotations


import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from autodist_tpu.models.base import ModelSpec
from autodist_tpu.ops.chunked_xent import chunked_softmax_cross_entropy
from autodist_tpu.ops.sampled_xent import sampled_softmax_cross_entropy


class LSTMLM(nn.Module):
    vocab_size: int
    emb_dim: int
    hidden_dim: int
    num_layers: int

    def setup(self):
        init = nn.initializers.normal(0.1)
        self.embedding = self.param("embedding", init,
                                    (self.vocab_size, self.emb_dim))
        self.softmax_embedding = self.param("softmax_embedding", init,
                                            (self.vocab_size, self.emb_dim))
        for i in range(self.num_layers):
            setattr(self, f"lstm_{i}",
                    nn.RNN(nn.OptimizedLSTMCell(self.hidden_dim)))
        self.proj = nn.Dense(self.emb_dim)

    def features(self, tokens):
        """Pre-softmax activations ``[B, T, E]`` — the training loss pairs
        these with the softmax table through the chunked cross entropy so
        the ``[B, T, vocab]`` logits never materialize."""
        x = jnp.take(self.embedding, tokens, axis=0)  # [B, T, E]
        for i in range(self.num_layers):
            x = getattr(self, f"lstm_{i}")(x)
        return self.proj(x)

    def __call__(self, tokens):
        return jnp.einsum("bte,ve->btv", self.features(tokens),
                          self.softmax_embedding)


def lm1b(vocab_size: int = 793472, emb_dim: int = 512,
         hidden_dim: int = 2048, num_layers: int = 2,
         seq_len: int = 20, xent_chunk: int = 8192,
         sampled_softmax: int = 0) -> ModelSpec:
    model = LSTMLM(vocab_size, emb_dim, hidden_dim, num_layers)

    def init(rng):
        return model.init(rng, jnp.zeros((2, seq_len), jnp.int32))["params"]

    def apply_fn(params, tokens):
        return model.apply({"params": params}, tokens)

    def loss_fn(params, batch):
        feats = model.apply({"params": params}, batch["tokens"],
                            method=LSTMLM.features)
        if sampled_softmax:
            # The reference's actual lm1b loss (TF sampled_softmax_loss):
            # k negatives instead of the 793k-way softmax.  The sample
            # set is derived from the batch (deterministic per batch,
            # varying across batches) so loss_fn stays pure.
            rng = jax.random.fold_in(jax.random.PRNGKey(0),
                                     jnp.sum(batch["tokens"]) & 0x7FFFFFFF)
            return sampled_softmax_cross_entropy(
                feats[:, :-1], params["softmax_embedding"],
                batch["tokens"][:, 1:], rng, num_sampled=sampled_softmax)
        # Default: chunked-vocab EXACT loss — the [B, T, 793k] logits
        # (16 GB at batch 256) never materialize; unlike the reference,
        # no sampling bias.
        return chunked_softmax_cross_entropy(
            feats[:, :-1], params["softmax_embedding"],
            batch["tokens"][:, 1:], chunk=xent_chunk)

    def make_batch(rng: np.random.RandomState, batch_size: int):
        return {"tokens": rng.randint(
            0, vocab_size, (batch_size, seq_len)).astype(np.int32)}

    return ModelSpec(
        name="lm1b",
        init=init, loss_fn=loss_fn, apply_fn=apply_fn, make_batch=make_batch,
        sparse_vars=("embedding", "softmax_embedding"),
        config=dict(vocab_size=vocab_size, emb_dim=emb_dim,
                    hidden_dim=hidden_dim, num_layers=num_layers,
                    seq_len=seq_len),
    )
