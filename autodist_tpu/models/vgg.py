"""VGG16 (reference ``examples/benchmark/imagenet.py`` VGG16 benchmark —
the PartitionedPS showcase: its huge fc layers are what variable
partitioning was built for)."""
from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp

from autodist_tpu.models.base import ModelSpec
from autodist_tpu.models.resnet import _image_spec

_CFG16 = [64, 64, "M", 128, 128, "M", 256, 256, 256, "M",
          512, 512, 512, "M", 512, 512, 512, "M"]


class VGG(nn.Module):
    num_classes: int

    @nn.compact
    def __call__(self, x):
        conv_idx = 0
        for v in _CFG16:
            if v == "M":
                x = nn.max_pool(x, (2, 2), strides=(2, 2))
            else:
                x = nn.Conv(v, (3, 3), padding="SAME",
                            name=f"conv{conv_idx}")(x)
                x = nn.relu(x)
                conv_idx += 1
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(4096, name="fc1")(x))
        x = nn.relu(nn.Dense(4096, name="fc2")(x))
        return nn.Dense(self.num_classes, name="head")(x)


def vgg16(num_classes: int = 1000, image_size: int = 224) -> ModelSpec:
    return _image_spec("vgg16", VGG(num_classes), num_classes, image_size)
