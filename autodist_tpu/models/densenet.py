"""DenseNet121 (reference ``examples/benchmark/imagenet.py`` DenseNet121
benchmark).  GroupNorm for statelessness, as in resnet.py."""
from __future__ import annotations

from functools import partial
from typing import Sequence

import flax.linen as nn
import jax.numpy as jnp

from autodist_tpu.models.base import ModelSpec
from autodist_tpu.models.resnet import _image_spec

Conv = partial(nn.Conv, use_bias=False)


def _norm(name):
    return nn.GroupNorm(num_groups=32, name=name)


class DenseLayer(nn.Module):
    growth_rate: int

    @nn.compact
    def __call__(self, x):
        y = nn.relu(_norm("norm1")(x))
        y = Conv(4 * self.growth_rate, (1, 1), name="conv1")(y)
        y = nn.relu(_norm("norm2")(y))
        y = Conv(self.growth_rate, (3, 3), padding="SAME", name="conv2")(y)
        return jnp.concatenate([x, y], axis=-1)


class Transition(nn.Module):
    @nn.compact
    def __call__(self, x):
        x = nn.relu(_norm("norm")(x))
        x = Conv(x.shape[-1] // 2, (1, 1), name="conv")(x)
        return nn.avg_pool(x, (2, 2), strides=(2, 2))


class DenseNet(nn.Module):
    block_sizes: Sequence[int]
    growth_rate: int
    num_classes: int

    @nn.compact
    def __call__(self, x):
        x = Conv(2 * self.growth_rate, (7, 7), strides=(2, 2),
                 name="conv_init")(x)
        x = nn.relu(_norm("norm_init")(x))
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        for i, n in enumerate(self.block_sizes):
            for j in range(n):
                x = DenseLayer(self.growth_rate, name=f"block{i}_layer{j}")(x)
            if i != len(self.block_sizes) - 1:
                x = Transition(name=f"transition{i}")(x)
        x = nn.relu(_norm("norm_final")(x))
        x = jnp.mean(x, axis=(1, 2))
        return nn.Dense(self.num_classes, name="head")(x)


def densenet121(num_classes: int = 1000, image_size: int = 224) -> ModelSpec:
    return _image_spec("densenet121",
                       DenseNet([6, 12, 24, 16], 32, num_classes),
                       num_classes, image_size)
