"""User-facing facade.

Parity target: reference ``AutoDist`` (``autodist/autodist.py:297-322``) —
``AutoDist(resource_spec_file, strategy_builder)`` + ``scope()`` +
``create_distributed_session()`` / ``function()``.

TPU-native differences: the user *captures* the functional program explicitly
(``capture(params, optimizer, loss_fn)``) instead of the reference's implicit
graph+optimizer monkeypatch capture (``autodist/patch.py:40-116``); the
"session" holds sharded state and runs a jitted step rather than driving a TF
gRPC cluster.
"""
from __future__ import annotations

import contextlib
import itertools
from typing import Any, Callable, Dict, Optional, Sequence

from autodist_tpu.cluster import Cluster, make_cluster
from autodist_tpu.const import ENV
from autodist_tpu.coordinator import Coordinator
from autodist_tpu.graph_item import GraphItem
from autodist_tpu.kernel.graph_transformer import GraphTransformer
from autodist_tpu.mesh import build_mesh
from autodist_tpu.resource_spec import ResourceSpec
from autodist_tpu.runner import DistributedSession
from autodist_tpu.strategy.base import Strategy, StrategyBuilder
from autodist_tpu.strategy.compiler import StrategyCompiler
from autodist_tpu.utils import logging

_default_autodist: Optional["AutoDist"] = None


def get_default_autodist() -> Optional["AutoDist"]:
    return _default_autodist


def _set_default_autodist(ad: "AutoDist") -> None:
    """One AutoDist per process (reference autodist.py:46-51); the guard is
    relaxed under AUTODIST_IS_TESTING so test matrices can re-instantiate."""
    global _default_autodist
    if _default_autodist is not None and not ENV.AUTODIST_IS_TESTING.val:
        raise RuntimeError("Only one AutoDist instance is allowed per process")
    _default_autodist = ad


class AutoDist:
    """Facade: resource spec + strategy builder → compiled distributed step.

    Args:
      resource_spec_file: yaml path (or pass ``resource_spec``); omitting both
        auto-derives a single-node spec from local devices.
      strategy_builder: a :class:`StrategyBuilder`; defaults to
        ``PSLoadBalancing`` (the reference's default, autodist.py:70).
      mesh_axes: optional logical mesh shape override, e.g.
        ``{"data": 4, "model": 2}``.
    """

    def __init__(self, resource_spec_file: Optional[str] = None,
                 strategy_builder: Optional[StrategyBuilder] = None,
                 resource_spec: Optional[ResourceSpec] = None,
                 mesh_axes: Optional[Dict[str, int]] = None):
        _set_default_autodist(self)
        self._resource_spec = resource_spec or ResourceSpec(resource_spec_file)
        if strategy_builder is None:
            from autodist_tpu.strategy.ps_lb_strategy import PSLoadBalancing
            strategy_builder = PSLoadBalancing()
        self._strategy_builder = strategy_builder
        self._mesh_axes = mesh_axes
        self._graph_item: Optional[GraphItem] = None
        self._session: Optional[DistributedSession] = None
        self._strategy: Optional[Strategy] = None
        self._in_scope = False
        self._cluster: Cluster = make_cluster(self._resource_spec)
        self._coordinator: Optional[Coordinator] = None
        self._implicit_record = None  # patch.CaptureRecord from the scope

    # -- capture -----------------------------------------------------------
    @contextlib.contextmanager
    def scope(self):
        """Context for building/capturing the model (reference
        autodist.py:309-322).  Marks the capture region, enforces the
        build-before-run ordering, and — unless ``AUTODIST_PATCH=False`` —
        installs the implicit-capture patches so a plain optax script is
        captured without calling :meth:`capture`
        (``autodist_tpu/patch.py``; reference ``autodist/patch.py:40-116``)."""
        from autodist_tpu.patch import PatchOptax

        self._in_scope = True
        patched = ENV.AUTODIST_PATCH.val
        if patched:
            PatchOptax.patch()
        try:
            yield self
        finally:
            self._in_scope = False
            if patched:
                self._implicit_record = PatchOptax.unpatch()

    def capture(self, params: Any, optimizer: Any = None,
                loss_fn: Optional[Callable] = None,
                sparse_vars: Sequence[str] = (),
                untrainable_vars: Sequence[str] = (),
                pipeline_vars: Sequence[str] = (),
                expert_vars: Sequence[str] = (),
                remat: Optional[str] = None,
                has_aux: bool = False,
                metrics_fn: Optional[Callable] = None,
                grad_fn: Optional[Callable] = None,
                accum_steps: int = 1,
                numerics=None) -> GraphItem:
        """Capture the training program (the explicit analog of the
        reference's optimizer/gradient monkeypatch hooks,
        graph_item.py:72-108).  ``metrics_fn(params, batch) -> dict``
        merges extra metrics (e.g. accuracy) into every step's and
        ``evaluate``'s outputs — the reference's extra ``sess.run``
        fetches / Keras ``compile(metrics=...)``.  ``accum_steps=N``
        accumulates gradients over N microbatches per step (effective
        batch B at the live activation memory of B/N for the gradient
        pass; a ``metrics_fn`` still runs one full-batch forward).  With
        ``has_aux`` the per-step aux comes back STACKED along a leading
        ``[N]`` axis (one entry per microbatch).

        ``numerics`` enables the numerics guard (docs/numerics.md):
        ``True`` for defaults (fused non-finite detection + skip +
        auto loss scaling), an ``on_nonfinite`` string
        (``"skip"|"raise"|"rollback"``), a dict of
        :class:`~autodist_tpu.numerics.NumericsConfig` fields (e.g.
        ``{"clip_norm": 1.0}`` for exact global-norm clipping), or a
        config instance.  Default None — no guard, byte-identical
        steps."""
        if self.is_built():
            raise RuntimeError(
                "Cannot capture after the distributed session was created "
                "(reference graph-mutation guard, autodist.py:152-165)")
        self._graph_item = GraphItem(
            params, optimizer=optimizer, loss_fn=loss_fn,
            sparse_vars=sparse_vars, untrainable_vars=untrainable_vars,
            pipeline_vars=pipeline_vars, expert_vars=expert_vars,
            remat=remat, has_aux=has_aux, metrics_fn=metrics_fn,
            grad_fn=grad_fn, accum_steps=accum_steps, numerics=numerics)
        return self._graph_item

    @property
    def graph_item(self) -> Optional[GraphItem]:
        return self._graph_item

    @property
    def resource_spec(self) -> ResourceSpec:
        return self._resource_spec

    def is_built(self) -> bool:
        return self._session is not None

    # -- build pipeline (reference autodist.py:139-150) --------------------
    def _assemble_implicit_graph_item(self) -> None:
        """Build the GraphItem from the scope's implicit capture record when
        ``capture()`` was never called (the reference's zero-code-change
        path, ``autodist/patch.py:40-116``)."""
        rec = self._implicit_record
        if rec is None or (rec.params is None and rec.optimizer is None
                           and rec.loss_fn is None):
            raise RuntimeError(
                "capture() the program before building a strategy (or build "
                "the optimizer/opt.init(params)/jax.value_and_grad(loss_fn) "
                "inside ad.scope() for implicit capture)")
        if not rec.complete():
            raise RuntimeError(
                "implicit capture inside ad.scope() is incomplete; missing: "
                + "; ".join(rec.missing()))
        logging.info("implicit capture: params + optax.%s + loss_fn %r",
                     rec.optimizer_factory,
                     getattr(rec.loss_fn, "__name__", rec.loss_fn))
        self._graph_item = GraphItem(
            rec.params, optimizer=rec.optimizer, loss_fn=rec.loss_fn,
            has_aux=rec.has_aux)

    def build_strategy(self) -> Strategy:
        """Chief builds the strategy; workers deserialize the chief's by id
        (reference _build_or_load_strategy, autodist.py:100-109)."""
        if self._graph_item is None:
            self._assemble_implicit_graph_item()
        self._graph_item.prepare()
        strategy_id = ENV.AUTODIST_STRATEGY_ID.val
        if strategy_id:
            logging.info("worker: loading strategy %s", strategy_id)
            self._strategy = Strategy.deserialize(strategy_id)
        else:
            self._strategy = self._strategy_builder.build(
                self._graph_item, self._resource_spec)
            self._strategy.serialize()
        return self._strategy

    @property
    def cluster(self) -> Cluster:
        return self._cluster

    @property
    def coordinator(self) -> Optional[Coordinator]:
        return self._coordinator

    def _setup(self) -> None:
        """Chief-only multi-node bootstrap (reference _setup,
        autodist.py:120-128): fan the user script out to worker hosts, then
        join the distributed runtime.  Single-node: only Cluster.start()
        (a no-op)."""
        if (self._cluster.num_processes > 1
                and self._cluster.is_chief()
                and self._coordinator is None):
            self._coordinator = Coordinator(self._strategy, self._cluster)
            self._coordinator.launch_clients()
            import atexit
            # Chief reaps remote workers at exit (reference autodist worker
            # lifecycle, coordinator.py:92-110).  Bounded, so a chief-side
            # crash after launch terminates workers instead of hanging.
            atexit.register(self._coordinator.reap)
        self._cluster.start()

    def create_distributed_session(self, mesh=None,
                                   validate: Optional[bool] = None
                                   ) -> DistributedSession:
        """Full build pipeline: strategy → compile → transform → session
        (reference _create_distributed_session, autodist.py:167-185).

        ``mesh`` may be a Mesh or a zero-arg callable returning one: on
        multi-process runs the global device list only exists after the
        cluster rendezvous (``_setup`` → ``jax.distributed.initialize``),
        so a custom topology (e.g. ``build_hybrid_mesh``) must be built
        lazily — the callable runs after rendezvous.

        ``validate`` runs the static pre-flight analyzer
        (:mod:`autodist_tpu.analysis`) on the compiled strategy BEFORE
        any tracing: ERROR diagnostics raise
        :class:`~autodist_tpu.analysis.StrategyValidationError`
        immediately (a bad plan dies in milliseconds, not minutes into
        an XLA compile), WARNs log once.  Defaults to the
        ``AUTODIST_VALIDATE`` environment knob."""
        if self._session is not None:
            return self._session
        if self._strategy is None:
            self.build_strategy()
        self._setup()
        from jax.sharding import Mesh as _Mesh
        # NB: Mesh instances are themselves callable (context decorator),
        # so the factory check must exclude them explicitly.
        if mesh is not None and not isinstance(mesh, _Mesh) and callable(mesh):
            mesh = mesh()
        if mesh is None:
            mesh = build_mesh(self._mesh_axes, resource_spec=self._resource_spec)
        compiled = StrategyCompiler(
            mesh, resource_spec=self._resource_spec).compile(
                self._strategy, self._graph_item)
        if validate is None:
            validate = ENV.AUTODIST_VALIDATE.val
        if validate:
            from autodist_tpu.analysis import preflight

            preflight(compiled, self._graph_item,
                      resource_spec=self._resource_spec,
                      context=f"build:{self._strategy.id}")
        dist_step = GraphTransformer(compiled, self._graph_item).transform(
            extra_metrics_fn=self._graph_item.metrics_fn)
        self._session = DistributedSession(self._graph_item, dist_step)
        logging.info("distributed session created: strategy=%s mesh=%s",
                     self._strategy.id, dict(mesh.shape))
        try:
            from autodist_tpu.strategy.cost_model import estimate_cost
            logging.info("estimated sync cost: %s", estimate_cost(
                self._strategy, self._graph_item,
                self._resource_spec).summary())
        except Exception:  # pragma: no cover - advisory only
            pass
        return self._session

    # -- TF2-style one-liner (reference autodist.py:204-289) ---------------
    def function(self, fn: Optional[Callable] = None, *,
                 sync_every: int = 1):
        """Decorator parity with ``autodist.function``: wraps a per-batch
        step; the first call builds the session, later calls run steps.

        The decorated ``fn(batch)`` body is *declarative* in the reference
        (it defines the graph); here the captured loss_fn/optimizer define
        the step and ``fn``'s return value selects extra fetches from the
        metrics dict (or None for all metrics).

        Beyond fetch selection, the wrapper owns the hot-loop cadence the
        reference's remapper/session pairing owned: with ``sync_every=N``
        only every N-th call syncs metrics to host numpy; in between,
        steps dispatch back-to-back and return device arrays (JAX
        futures).  The per-step host round-trip is the classic accidental
        serializer on TPU (docs/performance.md); N≈10+ keeps dispatch
        ahead.  (Placement is already automatic: ``session.run`` places
        every batch, and placing a pre-placed/prefetched batch is a
        no-op.)

        Forms: bare ``@ad.function``, decorator factory
        ``@ad.function(sync_every=10)``, or ``ad.function()(None)`` /
        ``ad.function(sync_every=10)(None)`` for a plain step runner
        with no fetch selector.  (``ad.function()`` alone returns the
        decorator, not a runner — calling it with a batch raises.)
        """

        def wrap(user_fn):
            if user_fn is not None and not callable(user_fn):
                raise TypeError(
                    "ad.function()(...) expects a fetch-selector callable "
                    f"or None, got {type(user_fn).__name__}; to run a "
                    "step with no selector use ad.function()(None)")
            calls = itertools.count(1)

            def run_fn(batch):
                session = self.create_distributed_session()
                sync = sync_every <= 1 or next(calls) % sync_every == 0
                metrics = session.run(batch, sync=sync)
                out = user_fn(metrics) if user_fn is not None else metrics
                return out if out is not None else metrics
            return run_fn

        if fn is not None and not callable(fn):
            raise TypeError("ad.function expects a callable (or use @ad.function)")
        # Bare @ad.function gets the wrapped step directly; with only
        # kwargs (@ad.function(sync_every=N)) return the decorator.
        return wrap(fn) if fn is not None else wrap


def _reset_default_autodist_for_testing() -> None:
    global _default_autodist
    _default_autodist = None
