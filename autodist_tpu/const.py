"""Framework-wide constants and environment configuration.

TPU-native analog of the reference's ``autodist/const.py`` (reference
``autodist/const.py:32-89``): a working directory for run artifacts, name
prefixes, and a typed registry of environment variables.  Where the reference
needed gRPC port ranges and a TF collective group leader, we need none — the
JAX/PJRT distributed runtime handles rendezvous — so those knobs are replaced
by mesh-axis names and coordinator addresses.
"""
from __future__ import annotations

import enum
import os

# Root for all run artifacts (strategies, traces, graph dumps, logs).
# Reference: DEFAULT_WORKING_DIR = /tmp/autodist (autodist/const.py:32-36).
DEFAULT_WORKING_DIR = os.environ.get("AUTODIST_TPU_WORKDIR", "/tmp/autodist_tpu")
DEFAULT_STRATEGY_DIR = os.path.join(DEFAULT_WORKING_DIR, "strategies")
DEFAULT_TRACE_DIR = os.path.join(DEFAULT_WORKING_DIR, "traces")
DEFAULT_GRAPH_DIR = os.path.join(DEFAULT_WORKING_DIR, "graphs")
DEFAULT_LOG_DIR = os.path.join(DEFAULT_WORKING_DIR, "logs")
DEFAULT_CHECKPOINT_DIR = os.path.join(DEFAULT_WORKING_DIR, "checkpoints")
DEFAULT_TELEMETRY_DIR = os.path.join(DEFAULT_WORKING_DIR, "telemetry")

# Canonical mesh-axis names.  These are the TPU-native replacement for the
# reference's device lists in Strategy.graph_config.replicas: instead of
# enumerating device strings, a strategy names which mesh axes a tensor is
# partitioned over.
MESH_AXIS_DATA = "data"      # data parallelism (batch axis)
MESH_AXIS_MODEL = "model"    # tensor/model parallelism (partitioned variables)
MESH_AXIS_SEQ = "seq"        # sequence/context parallelism (ring attention)
MESH_AXIS_PIPE = "pipe"      # pipeline parallelism (stages)
MESH_AXIS_EXPERT = "expert"  # expert parallelism (MoE)

ALL_MESH_AXES = (
    MESH_AXIS_DATA,
    MESH_AXIS_MODEL,
    MESH_AXIS_SEQ,
    MESH_AXIS_PIPE,
    MESH_AXIS_EXPERT,
)

# Name-scope prefix used when the explicit (shard_map) execution path labels
# per-variable synchronization segments; analog of AUTODIST_PREFIX name scopes
# (autodist/const.py:41-49).
AUTODIST_PREFIX = "AutoDistTPU"


def _bool(v):
    return v in ("True", "true", "1")


def _str(v):
    return v or ""


def _int0(v):
    return int(v) if v else 0


def _int1(v):
    return int(v) if v else 1


def _loglevel(v):
    return v or "INFO"


def _bool_default_true(v):
    return v not in ("False", "false", "0")


def _float0(v):
    return float(v) if v else 0.0


def _int2(v):
    return int(v) if v else 2


class ENV(enum.Enum):
    """Typed environment-variable registry.

    Mirrors the reference's ``ENV`` enum (``autodist/const.py:55-89``):
    ``ENV.X.val`` returns the parsed value of environment variable ``X`` with
    a typed default.  Each member's value is ``(name, parser)`` so the
    registry is self-contained — a member cannot exist without its parser.
    (Plain-callable values don't work: functions in an Enum body become
    methods, not members.)
    """

    # non-empty ⇒ this process is a worker; value = its address
    AUTODIST_WORKER = ("AUTODIST_WORKER", _str)
    # strategy id to load instead of building (worker path)
    AUTODIST_STRATEGY_ID = ("AUTODIST_STRATEGY_ID", _str)
    AUTODIST_MIN_LOG_LEVEL = ("AUTODIST_MIN_LOG_LEVEL", _loglevel)
    # extra assertions during tests
    AUTODIST_IS_TESTING = ("AUTODIST_IS_TESTING", _bool)
    # implicit program capture inside ad.scope() (optax/jax.grad
    # interception, autodist_tpu/patch.py); analog of the reference's
    # AUTODIST_PATCH_TF gate (autodist/const.py:78)
    AUTODIST_PATCH = ("AUTODIST_PATCH", _bool_default_true)
    # print launch commands instead of executing them
    AUTODIST_DEBUG_REMOTE = ("AUTODIST_DEBUG_REMOTE", _bool)
    # profiler-trace the first N session steps (0 = off); SURVEY §5.1 parity
    # with the reference's RunOptions.trace_level timelines (runner.py:64-75)
    AUTODIST_TRACE_STEPS = ("AUTODIST_TRACE_STEPS", _int0)
    # re-armable capture windows: comma-separated step numbers at which a
    # profiler-trace window OPENS mid-run (each window spans
    # AUTODIST_TRACE_STEPS steps, min 1); windows never overlap — an open
    # window is flushed before the next one starts (utils/tracing.py)
    AUTODIST_TRACE_AT = ("AUTODIST_TRACE_AT", _str)
    # telemetry master switch (docs/observability.md): metrics registry,
    # per-step StepRecords, and the event journal.  Disabled paths are
    # near-zero-cost no-ops (BENCH_telemetry.json measures the enabled
    # overhead)
    AUTODIST_TELEMETRY = ("AUTODIST_TELEMETRY", _bool_default_true)
    # when set, StepRecord ring buffers and the event journal flush as
    # JSONL under this run directory (one writer per process;
    # chief-mergeable — `python -m autodist_tpu.telemetry <dir>`)
    AUTODIST_TELEMETRY_DIR = ("AUTODIST_TELEMETRY_DIR", _str)
    # leg-calibrated cost-model constants (docs/observability.md): path
    # to a calibration.json written by telemetry.calibration
    # .save_calibration / bench.py.  When set (or when
    # AUTODIST_TELEMETRY_DIR/calibration.json exists), estimate_ir_cost
    # and AutoStrategy(search=True) load the fitted constants
    # automatically — no flags.
    AUTODIST_CALIBRATION = ("AUTODIST_CALIBRATION", _str)
    # flight recorder (docs/observability.md "Flight recorder"): "0"
    # disables cursor recording entirely; "host" stamps host-phase
    # cursors only (step/checkpoint boundaries — the default
    # granularity off-TPU); "legs" additionally stamps leg-group
    # host-callbacks inside the explicit sync path; "auto" (default,
    # empty) resolves to "legs" on TPU backends (callbacks ride async
    # dispatch) and "host" elsewhere (CPU host-callbacks are not free —
    # BENCH_flightrec.json measures both).
    AUTODIST_FLIGHTREC = ("AUTODIST_FLIGHTREC", _str)
    # fused Pallas kernel opt-in (docs/kernels.md): "all" or a comma
    # list of guard,update,quant_hop,paged_attention.  Unset = every
    # path keeps its unfused lowering; requested-but-unsupported
    # configs fall back with a shared drop-reason WARN
    # (ops.fused_kernels.fused_drop_reason).
    AUTODIST_FUSED_KERNELS = ("AUTODIST_FUSED_KERNELS", _str)
    # force Pallas interpret mode off-TPU for the fused kernels —
    # the CPU test/bench escape hatch (slower than XLA; never default)
    AUTODIST_FUSED_INTERPRET = ("AUTODIST_FUSED_INTERPRET", _bool)
    # dump staged program snapshots (plan table, StableHLO, optimized HLO);
    # parity with the reference's per-stage graph dumps
    # (kernel/graph_transformer.py:62-90)
    AUTODIST_DUMP_GRAPHS = ("AUTODIST_DUMP_GRAPHS", _bool)
    # XLA compiler-option name for the all-reduce combiner threshold;
    # when set (and the strategy carries fusable groups), the group byte
    # size is passed through as that option's value — see
    # kernel/graph_transformer.py:_combiner_bytes
    AUTODIST_COMBINER_FLAG = ("AUTODIST_COMBINER_FLAG", _str)
    # pre-flight static strategy analysis (autodist_tpu.analysis) before
    # the session builds: ERROR diagnostics raise StrategyValidationError
    # before any tracing, WARNs log once.  Also reachable per-call via
    # create_distributed_session(validate=...) / fit(validate=...).
    AUTODIST_VALIDATE = ("AUTODIST_VALIDATE", _bool)
    # Cloud-TPU pod slice: rendezvous via TPU metadata (TPUPodCluster)
    AUTODIST_TPU_POD = ("AUTODIST_TPU_POD", _bool)
    # coordinator watcher behavior on worker death: fail_fast (default) |
    # ignore | restart | supervised (resilience.supervisor.policy_from_env)
    AUTODIST_FAILURE_POLICY = ("AUTODIST_FAILURE_POLICY", _str)
    # where a supervised job's failure markers + heartbeats live (set by
    # resilience.Supervisor for each attempt)
    AUTODIST_SUPERVISOR_DIR = ("AUTODIST_SUPERVISOR_DIR", _str)
    # deterministic fault-injection spec (resilience.chaos grammar)
    AUTODIST_CHAOS = ("AUTODIST_CHAOS", _str)
    # preemption grace window in seconds (docs/resilience.md): at a
    # preemption notice, fit compares the last measured persistent-save
    # time against this deadline and routes the emergency state to the
    # peer RAM tier when a durable save cannot finish.  0 = no deadline
    # (always attempt the persistent save — the pre-tier behavior)
    AUTODIST_PREEMPT_GRACE_S = ("AUTODIST_PREEMPT_GRACE_S", _float0)
    # RAM checkpoint tier (checkpoint/tiers.py): device→host snapshot
    # cadence in steps (0 = tier off), ring depth, and the peer-mirror
    # directory (a tmpfs path like /dev/shm/... in production; any
    # shared dir in tests).  fit() arguments override all three.
    AUTODIST_SNAPSHOT_EVERY = ("AUTODIST_SNAPSHOT_EVERY", _int0)
    AUTODIST_SNAPSHOT_KEEP = ("AUTODIST_SNAPSHOT_KEEP", _int2)
    AUTODIST_SNAPSHOT_DIR = ("AUTODIST_SNAPSHOT_DIR", _str)
    # buddy host address RAM snapshots mirror to (default: the next
    # host in the ResourceSpec ring — checkpoint.tiers.buddy_of)
    AUTODIST_BUDDY = ("AUTODIST_BUDDY", _str)
    # which supervisor attempt this process belongs to (chaos/test filters)
    AUTODIST_ATTEMPT = ("AUTODIST_ATTEMPT", _int0)
    # jax.distributed coordinator (host:port)
    AUTODIST_COORDINATOR_ADDRESS = ("AUTODIST_COORDINATOR_ADDRESS", _str)
    AUTODIST_NUM_PROCESSES = ("AUTODIST_NUM_PROCESSES", _int1)
    AUTODIST_PROCESS_ID = ("AUTODIST_PROCESS_ID", _int0)
    # MPMD pipeline runtime (parallel/mpmd, docs/pipeline.md): which
    # pipeline stage this process runs (stamped by StageRunner; the
    # chaos `stage=` filter and telemetry read it), the shared
    # activation-transport directory (a tmpfs path in production; any
    # shared dir in tests), and the transport recv deadline in seconds
    AUTODIST_STAGE = ("AUTODIST_STAGE", _str)
    AUTODIST_MPMD_DIR = ("AUTODIST_MPMD_DIR", _str)
    AUTODIST_MPMD_TIMEOUT_S = ("AUTODIST_MPMD_TIMEOUT_S", _float0)
    SYS_DATA_PATH = ("SYS_DATA_PATH", _str)
    SYS_RESOURCE_PATH = ("SYS_RESOURCE_PATH", _str)

    @property
    def val(self):
        """Parsed value of the environment variable, with the typed default."""
        return self.value[1](os.environ.get(self.name))


# Worker/chief role detection, mirroring autodist/autodist.py:40-41.
def is_worker() -> bool:
    return bool(ENV.AUTODIST_WORKER.val)


def is_chief() -> bool:
    return not is_worker()
