"""Supervised replica pool + request router: replica death is a
routing event, not an outage.

The PR 4 resilience story for training — a worker kill becomes a
supervised relaunch with exact resume — applied to serving:

* :class:`SupervisedReplicaPool` runs N serving replicas, each launched
  through the PR 4 :class:`~autodist_tpu.resilience.supervisor.Supervisor`
  in its own watch thread: the replica process is health-watched
  (process exit + heartbeat beacons, so a WEDGED replica — alive but
  stuck — is treated exactly like a dead one), terminated when bad, and
  relaunched with jittered backoff under the supervisor's restart
  budget.  Each attempt binds a fresh port and publishes it through an
  address file, so the pool's endpoints survive relaunches.
* :class:`Router` load-balances completions across live replicas by
  queue depth and block-pool headroom (the scheduler's
  ``/v1/stats`` surface), and re-routes on failure: a replica that
  refuses connections, times out, answers 503, or whose beacon verdict
  goes DEAD/WEDGED has its in-flight requests resubmitted to another
  live replica.  Re-admission recomputes prefix-cache state on the new
  replica (the trie warms itself); with greedy decode the re-routed
  output is token-identical to an uninterrupted run — the live drill
  in ``tests/test_serving_router.py`` pins it.
* 429 (:class:`~autodist_tpu.serving.engine.AdmissionError` surfaced by
  the replica) means route-elsewhere; only when EVERY live replica is
  at admission capacity does the router surface
  :class:`RouterBusy` with the largest ``Retry-After`` hint.

The router speaks the replicas' HTTP surface (``serving/server.py``)
through a tiny stdlib client, but takes any duck-typed endpoint —
the unit tests drive it with in-process fakes; the drill uses real
subprocess replicas.
"""
from __future__ import annotations

import http.client
import json
import os
import threading
import time
import uuid
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

from autodist_tpu.telemetry.registry import MetricsRegistry, \
    render_prometheus
from autodist_tpu.utils import logging


class RouterError(RuntimeError):
    """No live replica could serve the request."""


class RouterBusy(RouterError):
    """Every live replica rejected with 429; retry after the hint."""

    def __init__(self, message: str, retry_after_s: float = 1.0):
        super().__init__(message)
        self.retry_after_s = float(retry_after_s)


class RouterRequestError(RuntimeError):
    """The request itself is bad (4xx other than 429): re-routing
    would fail identically, so the error propagates with the replica's
    status and body."""

    def __init__(self, status: int, body: Dict[str, Any]):
        super().__init__(f"replica answered {status}: "
                         f"{body.get('error', body)}")
        self.status = int(status)
        self.body = body


class HTTPReplicaClient:
    """Minimal stdlib client for one EngineServer-compatible replica."""

    def __init__(self, host: str, port: int):
        self.host, self.port = host, int(port)

    def _request(self, method: str, path: str,
                 body: Optional[dict] = None,
                 timeout: float = 30.0,
                 headers: Optional[dict] = None) -> Tuple[int, Any]:
        conn = http.client.HTTPConnection(self.host, self.port,
                                          timeout=timeout)
        try:
            payload = json.dumps(body) if body is not None else None
            hdrs = dict(headers or {})
            if payload:
                hdrs.setdefault("Content-Type", "application/json")
            conn.request(method, path, payload, hdrs)
            resp = conn.getresponse()
            raw = resp.read()
            headers = dict(resp.getheaders())
            try:
                data = json.loads(raw) if raw else {}
            except ValueError:
                data = {"raw": raw.decode(errors="replace")}
            if isinstance(data, dict):
                data["_headers"] = headers
            return resp.status, data
        finally:
            conn.close()

    def post_completion(self, body: dict, timeout: float = 120.0,
                        trace_id: str = "") -> Tuple[int, dict]:
        # The trace id travels as an HTTP header (router -> replica ->
        # scheduler): the replica's request/queue-wait/prefill/decode
        # spans then carry the router's id, so one request correlates
        # across hosts in the exported trace (docs/observability.md).
        headers = {"X-Autodist-Trace": trace_id} if trace_id else None
        return self._request("POST", "/v1/completions", body, timeout,
                             headers=headers)

    def stats(self, timeout: float = 5.0) -> dict:
        status, data = self._request("GET", "/v1/stats", timeout=timeout)
        if status != 200:
            raise OSError(f"stats answered {status}")
        return data

    def healthz(self, timeout: float = 2.0) -> bool:
        try:
            status, data = self._request("GET", "/healthz",
                                         timeout=timeout)
        except OSError:
            return False
        return status == 200 and bool(data.get("ok"))


@dataclass
class ReplicaEndpoint:
    """One replica as the router sees it: a (relaunch-stable) address
    file plus optional heartbeat beacons.  ``address_file`` holds
    ``{"host": ..., "port": ...}`` rewritten by every attempt; the
    endpoint re-reads it when its mtime changes, so a relaunched
    replica on a fresh port is picked up without router restarts."""

    name: str
    address_file: str
    beacon_dir: Optional[str] = None
    beacon_timeout: float = 10.0
    _client: Optional[HTTPReplicaClient] = field(default=None, repr=False)
    _mtime: float = field(default=0.0, repr=False)
    _monitor: Any = field(default=None, repr=False)

    def client(self) -> Optional[HTTPReplicaClient]:
        try:
            mtime = os.stat(self.address_file).st_mtime
        except OSError:
            return None
        if self._client is None or mtime != self._mtime:
            try:
                with open(self.address_file, encoding="utf-8") as f:
                    addr = json.load(f)
                self._client = HTTPReplicaClient(addr["host"],
                                                 addr["port"])
                self._mtime = mtime
            except (OSError, ValueError, KeyError):
                return None
        return self._client

    def beacon_verdict(self) -> Optional[str]:
        """DEAD/WEDGED verdict from the replica's heartbeat beacons
        (None = healthy or no beacons configured)."""
        if self.beacon_dir is None:
            return None
        if self._monitor is None:
            from autodist_tpu.resilience.heartbeat import HeartbeatMonitor

            self._monitor = HeartbeatMonitor(self.beacon_dir,
                                             timeout=self.beacon_timeout)
        from autodist_tpu.resilience.heartbeat import DEAD, WEDGED

        for health in self._monitor.status().values():
            if health.state in (DEAD, WEDGED):
                return health.state
        return None

    # -- the duck-typed surface Router consumes ------------------------
    def probe(self, timeout: float = 2.0) -> bool:
        if self.beacon_verdict() is not None:
            return False
        cli = self.client()
        return cli is not None and cli.healthz(timeout=timeout)

    def fetch_stats(self) -> Optional[dict]:
        cli = self.client()
        if cli is None:
            return None
        try:
            return cli.stats()
        except OSError:
            return None

    def post(self, body: dict, timeout: float,
             trace_id: str = "") -> Tuple[int, dict]:
        cli = self.client()
        if cli is None:
            raise OSError(f"{self.name}: no address published")
        return cli.post_completion(body, timeout=timeout,
                                   trace_id=trace_id)


class Router:
    """Load-balancing, re-routing front over a set of endpoints.

    ``endpoints`` need ``name``, ``probe()``, ``fetch_stats()`` and
    ``post(body, timeout)`` (raising ``OSError`` on transport failure)
    — :class:`ReplicaEndpoint` for real replicas, fakes in the unit
    tests.  Load scoring prefers shallow queues and block headroom::

        score = outstanding + queue_depth_total
                + occupancy_weight * block_occupancy
                + draft_occupancy_weight * block_occupancy_draft

    ``draft_occupancy_weight`` (default 0: no behavior change) lets a
    mixed fleet penalize replicas whose pool pressure comes from
    speculative draft pages — draft KV is evictable only by finishing
    its request, so a draft-heavy replica has less admission headroom
    than its raw occupancy suggests.

    Routing policy per request: try live replicas in score order; on
    transport failure or 5xx mark the replica down (it re-enters
    rotation when a later probe passes) and try the next; on 429
    remember the Retry-After hint and try the next; other 4xx raise
    :class:`RouterRequestError` without re-routing."""

    def __init__(self, endpoints: Sequence[Any], *,
                 probe_ttl_s: float = 1.0, stats_ttl_s: float = 0.25,
                 occupancy_weight: float = 4.0,
                 draft_occupancy_weight: float = 0.0,
                 max_attempts: Optional[int] = None,
                 retry_wait_s: float = 0.25):
        if not endpoints:
            raise ValueError("Router needs at least one endpoint")
        self._eps = list(endpoints)
        self._probe_ttl = float(probe_ttl_s)
        self._stats_ttl = float(stats_ttl_s)
        self._occ_w = float(occupancy_weight)
        self._draft_occ_w = float(draft_occupancy_weight)
        self._max_attempts = (max_attempts if max_attempts is not None
                              else 2 * len(self._eps) + 2)
        self._retry_wait = float(retry_wait_s)
        self._lock = threading.Lock()
        self._down_until: Dict[str, float] = {}
        self._probed: Dict[str, Tuple[float, bool]] = {}
        self._scores: Dict[str, Tuple[float, float]] = {}
        self._inflight: Dict[str, int] = {}
        self.registry = MetricsRegistry()
        self._m_routed = {}
        self._m_reroutes = self.registry.counter(
            "autodist_router_reroutes_total",
            "requests re-routed after a replica failure")
        self._m_busy = self.registry.counter(
            "autodist_router_busy_rejects_total",
            "requests rejected because every live replica was at "
            "admission capacity")
        self._m_live = self.registry.gauge(
            "autodist_router_live_replicas",
            "replicas passing their latest health probe")

    # -- health / scoring --------------------------------------------------
    def _alive(self, ep) -> bool:
        now = time.monotonic()
        with self._lock:
            if self._down_until.get(ep.name, 0.0) > now:
                return False
            ts, ok = self._probed.get(ep.name, (0.0, False))
            if now - ts < self._probe_ttl:
                return ok
        ok = bool(ep.probe())
        with self._lock:
            self._probed[ep.name] = (time.monotonic(), ok)
            if ok:
                self._down_until.pop(ep.name, None)
        return ok

    def mark_down(self, ep, hold_s: float = 2.0) -> None:
        with self._lock:
            self._down_until[ep.name] = time.monotonic() + hold_s
            self._probed.pop(ep.name, None)

    def _score(self, ep) -> float:
        now = time.monotonic()
        with self._lock:
            ts, score = self._scores.get(ep.name, (0.0, 0.0))
            inflight = self._inflight.get(ep.name, 0)
            if now - ts < self._stats_ttl:
                return score + inflight
        st = ep.fetch_stats() or {}
        score = float(st.get("outstanding", 0))
        score += float(st.get("queue_depth_total", 0))
        score += self._occ_w * float(st.get("block_occupancy", 0.0))
        score += self._draft_occ_w * float(
            st.get("block_occupancy_draft", 0.0))
        with self._lock:
            self._scores[ep.name] = (time.monotonic(), score)
            inflight = self._inflight.get(ep.name, 0)
        return score + inflight

    def live_replicas(self) -> List[Any]:
        live = [ep for ep in self._eps if self._alive(ep)]
        self._m_live.set(len(live))
        return live

    # -- routing -----------------------------------------------------------
    def complete(self, body: dict, *, timeout_s: float = 120.0) -> dict:
        """Route one completion; returns the replica's 200 payload.
        Blocks its caller like a replica-local request would — the
        caller's thread IS the in-flight state, which is what makes
        re-routing safe: a failed attempt leaves nothing behind on the
        dead replica that the retry could double-serve."""
        deadline = time.monotonic() + timeout_s
        t0_unix = time.time()
        # One trace id per logical request — re-routes reuse it, so the
        # exported trace shows every attempt under one id.
        trace_id = uuid.uuid4().hex[:16]
        tried_busy: Dict[str, float] = {}
        attempts = 0
        first = True
        while attempts < self._max_attempts \
                and time.monotonic() < deadline:
            candidates = [ep for ep in self.live_replicas()
                          if ep.name not in tried_busy]
            if not candidates and tried_busy:
                self._m_busy.inc()
                raise RouterBusy(
                    "every live replica is at admission capacity",
                    retry_after_s=max(tried_busy.values()))
            if not candidates:
                attempts += 1
                time.sleep(self._retry_wait)   # a relaunch may be coming
                continue
            ep = min(candidates, key=self._score)
            attempts += 1
            if not first:
                self._m_reroutes.inc()
            first = False
            with self._lock:
                self._inflight[ep.name] = \
                    self._inflight.get(ep.name, 0) + 1
            try:
                try:
                    status, payload = ep.post(
                        body,
                        timeout=max(deadline - time.monotonic(), 1.0),
                        trace_id=trace_id)
                except TypeError:
                    # Duck-typed endpoints predating trace propagation
                    # (unit-test fakes, user endpoints) keep working;
                    # their replica spans are simply untagged.
                    status, payload = ep.post(
                        body, timeout=max(deadline - time.monotonic(),
                                          1.0))
            except OSError as e:
                logging.warning("router: replica %s failed mid-request "
                                "(%s) — re-routing", ep.name, e)
                self.mark_down(ep)
                continue
            finally:
                with self._lock:
                    self._inflight[ep.name] = \
                        max(self._inflight.get(ep.name, 1) - 1, 0)
            if status == 200:
                self._routed_counter(ep).inc()
                from autodist_tpu.telemetry.profiler import record_span
                record_span("route", start_unix=t0_unix,
                            dur_s=time.time() - t0_unix,
                            trace_id=trace_id, replica=ep.name,
                            attempts=attempts)
                return payload
            if status == 429:
                retry = _retry_after(payload)
                tried_busy[ep.name] = retry
                continue
            if 500 <= status < 600 or status == 503:
                logging.warning("router: replica %s answered %d — "
                                "re-routing", ep.name, status)
                self.mark_down(ep)
                continue
            raise RouterRequestError(status, payload)
        raise RouterError(
            f"no live replica served the request after {attempts} "
            f"attempt(s)")

    def _routed_counter(self, ep):
        c = self._m_routed.get(ep.name)
        if c is None:
            c = self.registry.counter(
                "autodist_router_requests_total",
                "completions served, by replica",
                labels={"replica": ep.name})
            self._m_routed[ep.name] = c
        return c

    def render_metrics(self) -> str:
        return render_prometheus(self.registry)

    def merged_replica_stats(self) -> Dict[str, Any]:
        """Per-replica ``/v1/stats`` snapshots keyed by name (the
        fleet-level observability roll-up; histograms merge exactly on
        the replicas' fixed bounds — docs/observability.md)."""
        return {ep.name: ep.fetch_stats() for ep in self._eps}


def _retry_after(payload: dict) -> float:
    headers = payload.get("_headers") or {}
    for k, v in headers.items():
        if k.lower() == "retry-after":
            try:
                return float(v)
            except ValueError:
                break
    return float(payload.get("retry_after_s", 1.0))


# ---------------------------------------------------------------------------
# supervised replica pool
# ---------------------------------------------------------------------------

class SupervisedReplicaPool:
    """N serving replicas, each under its own PR 4 Supervisor.

    ``launch(replica_index, attempt)`` starts one replica attempt and
    returns its ``subprocess.Popen`` (launched with
    ``start_new_session=True`` so straggler process groups die with
    it).  The replica must write ``{"host":..., "port":...}`` to
    ``address_file(replica_index)`` once it listens, and should write
    heartbeat beacons into ``attempt.heartbeat_dir`` — the supervisor
    then applies the training-side failure taxonomy: process exit,
    stale-beacon DEAD, fresh-beacon-no-progress WEDGED.

    A healthy serving replica never exits, so each supervisor's
    ``run()`` blocks in its watch loop for the pool's lifetime — each
    runs on a daemon thread.  ``stop()`` flips a flag that makes the
    next relaunch a no-op process exiting 0 (a clean completion ends
    the supervisor loop), then terminates the current replicas."""

    def __init__(self, n: int, launch, workdir: str, *,
                 policy=None):
        from autodist_tpu.resilience.supervisor import SupervisorPolicy

        if n < 1:
            raise ValueError("need n >= 1 replicas")
        self._n = n
        self._launch = launch
        self._workdir = workdir
        self._policy = policy or SupervisorPolicy(
            max_restarts=8, heartbeat_timeout=10.0, poll_interval=0.2)
        self._stopping = False
        self._threads: List[threading.Thread] = []
        self._procs: Dict[int, Any] = {}
        self._supervisors: List[Any] = []
        os.makedirs(workdir, exist_ok=True)

    def address_file(self, index: int) -> str:
        return os.path.join(self._workdir, f"replica_{index}.addr.json")

    def beacon_dir(self, index: int) -> str:
        return os.path.join(self._workdir, f"replica_{index}_hb")

    def endpoints(self) -> List[ReplicaEndpoint]:
        return [ReplicaEndpoint(
                    name=f"replica-{i}",
                    address_file=self.address_file(i),
                    beacon_dir=self.beacon_dir(i),
                    beacon_timeout=(self._policy.heartbeat_timeout
                                    or 10.0))
                for i in range(self._n)]

    def current_proc(self, index: int):
        """The replica's current attempt process (for drills that kill
        it)."""
        return self._procs.get(index)

    def start(self) -> "SupervisedReplicaPool":
        from autodist_tpu.resilience.supervisor import Supervisor

        for i in range(self._n):
            sup = Supervisor(
                self._policy, hosts=[f"replica-{i}"],
                workdir=os.path.join(self._workdir, f"sup_{i}"))
            self._supervisors.append(sup)

            def run(i=i, sup=sup):
                def launch_attempt(attempt):
                    if self._stopping:
                        import subprocess
                        import sys
                        return subprocess.Popen(
                            [sys.executable, "-c", "pass"])
                    # beacons live at a pool-stable path (the router's
                    # monitors watch one directory per replica, across
                    # attempts)
                    attempt.heartbeat_dir = self.beacon_dir(i)
                    os.makedirs(attempt.heartbeat_dir, exist_ok=True)
                    proc = self._launch(i, attempt)
                    self._procs[i] = proc
                    return proc

                report = sup.run(launch_attempt)
                if not report.ok and not self._stopping:
                    logging.error(
                        "replica pool: replica %d exhausted its restart "
                        "budget (%s)", i, report.gave_up)

            t = threading.Thread(target=run, daemon=True,
                                 name=f"replica-supervisor-{i}")
            t.start()
            self._threads.append(t)
        return self

    def stop(self, timeout: float = 20.0) -> None:
        import signal

        self._stopping = True
        for proc in self._procs.values():
            if proc is not None and proc.poll() is None:
                try:
                    os.killpg(os.getpgid(proc.pid), signal.SIGTERM)
                except (ProcessLookupError, PermissionError, OSError):
                    proc.terminate()
        deadline = time.monotonic() + timeout
        for t in self._threads:
            t.join(timeout=max(deadline - time.monotonic(), 0.1))

    def __enter__(self) -> "SupervisedReplicaPool":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()
