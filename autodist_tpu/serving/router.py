"""Supervised replica pool + request router: replica death is a
routing event, not an outage.

The PR 4 resilience story for training — a worker kill becomes a
supervised relaunch with exact resume — applied to serving:

* :class:`SupervisedReplicaPool` runs N serving replicas, each launched
  through the PR 4 :class:`~autodist_tpu.resilience.supervisor.Supervisor`
  in its own watch thread: the replica process is health-watched
  (process exit + heartbeat beacons, so a WEDGED replica — alive but
  stuck — is treated exactly like a dead one), terminated when bad, and
  relaunched with jittered backoff under the supervisor's restart
  budget.  Each attempt binds a fresh port and publishes it through an
  address file, so the pool's endpoints survive relaunches.
* :class:`Router` load-balances completions across live replicas by
  queue depth and block-pool headroom (the scheduler's
  ``/v1/stats`` surface), and re-routes on failure: a replica that
  refuses connections, times out, answers 503, or whose beacon verdict
  goes DEAD/WEDGED has its in-flight requests resubmitted to another
  live replica.  Re-admission recomputes prefix-cache state on the new
  replica (the trie warms itself); with greedy decode the re-routed
  output is token-identical to an uninterrupted run — the live drill
  in ``tests/test_serving_router.py`` pins it.
* 429 (:class:`~autodist_tpu.serving.engine.AdmissionError` surfaced by
  the replica) means route-elsewhere; only when EVERY live replica is
  at admission capacity does the router surface
  :class:`RouterBusy` with the largest ``Retry-After`` hint.

Fault tolerance on top of re-routing (docs/serving.md):

* **Token-exact recovery** — greedy ``prompt_tokens`` requests go out
  as SSE streams; the router records each delta, and when a replica
  dies mid-decode it resubmits ``prompt + partial`` so the survivor
  only prefills the carried tokens and decodes the REST.  The stitched
  result is bit-identical to an uninterrupted run, and carries
  ``recovered: true`` / ``resumed_tokens`` as evidence of
  resume-not-restart.
* **Drain awareness** — a 429 with ``draining: true`` (or a draining
  flag in ``/v1/stats``) takes the replica out of candidate rotation
  without marking it down: it is healthy, just leaving.
* **Circuit breaker** — ``breaker_threshold`` consecutive transport
  or 5xx failures open a per-replica breaker for ``breaker_hold_s``
  (doubling per re-open); expiry is the half-open probe.
* **Deadline shed** — a 503 with ``shed: true`` routes elsewhere
  without a health penalty; ``complete(timeout_s=...)`` itself raises
  :class:`RouterDeadlineError` the moment its budget is spent instead
  of posting with a floored timeout.
* **Hedging** (``hedge_after_s``) — a latency-class request still
  unanswered after the hint is mirrored to the next-best replica;
  first 200 wins, the loser is cancelled through ``POST /v1/cancel``
  with the request id from the stream's announce event.

The router speaks the replicas' HTTP surface (``serving/server.py``)
through a tiny stdlib client, but takes any duck-typed endpoint —
the unit tests drive it with in-process fakes; the drill uses real
subprocess replicas.
"""
from __future__ import annotations

import contextlib
import http.client
import json
import os
import threading
import time
import uuid
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

from autodist_tpu.telemetry.registry import MetricsRegistry, \
    render_prometheus
from autodist_tpu.utils import logging


class RouterError(RuntimeError):
    """No live replica could serve the request."""


class RouterBusy(RouterError):
    """Every live replica rejected with 429; retry after the hint."""

    def __init__(self, message: str, retry_after_s: float = 1.0):
        super().__init__(message)
        self.retry_after_s = float(retry_after_s)


class RouterDeadlineError(RouterError):
    """``complete(timeout_s=...)`` expired before any replica answered.
    No further attempts are made once the budget is spent — the old
    behavior posted one more request with a floored 1 s timeout, which
    both wasted replica work and lied to the caller."""


class RouterRequestError(RuntimeError):
    """The request itself is bad (4xx other than 429): re-routing
    would fail identically, so the error propagates with the replica's
    status and body."""

    def __init__(self, status: int, body: Dict[str, Any]):
        super().__init__(f"replica answered {status}: "
                         f"{body.get('error', body)}")
        self.status = int(status)
        self.body = body


class HTTPReplicaClient:
    """Minimal stdlib client for one EngineServer-compatible replica."""

    def __init__(self, host: str, port: int):
        self.host, self.port = host, int(port)

    def _request(self, method: str, path: str,
                 body: Optional[dict] = None,
                 timeout: float = 30.0,
                 headers: Optional[dict] = None) -> Tuple[int, Any]:
        conn = http.client.HTTPConnection(self.host, self.port,
                                          timeout=timeout)
        try:
            payload = json.dumps(body) if body is not None else None
            hdrs = dict(headers or {})
            if payload:
                hdrs.setdefault("Content-Type", "application/json")
            conn.request(method, path, payload, hdrs)
            resp = conn.getresponse()
            raw = resp.read()
            headers = dict(resp.getheaders())
            try:
                data = json.loads(raw) if raw else {}
            except ValueError:
                data = {"raw": raw.decode(errors="replace")}
            if isinstance(data, dict):
                data["_headers"] = headers
            return resp.status, data
        finally:
            conn.close()

    def post_completion(self, body: dict, timeout: float = 120.0,
                        trace_id: str = "") -> Tuple[int, dict]:
        # The trace id travels as an HTTP header (router -> replica ->
        # scheduler): the replica's request/queue-wait/prefill/decode
        # spans then carry the router's id, so one request correlates
        # across hosts in the exported trace (docs/observability.md).
        headers = {"X-Autodist-Trace": trace_id} if trace_id else None
        return self._request("POST", "/v1/completions", body, timeout,
                             headers=headers)

    def post_completion_stream(self, body: dict, timeout: float = 120.0,
                               trace_id: str = "",
                               on_event=None) -> Tuple[int, dict]:
        """POST a streaming completion and read the SSE events.

        Non-200 answers return ``(status, parsed_body)`` exactly like
        :meth:`post_completion`.  On 200 every ``data:`` event is
        handed to ``on_event`` as it arrives (the router's recovery
        ledger hangs off this callback) and the FINAL event is
        returned as the payload.  A connection that dies before the
        final event raises ``OSError`` — by then ``on_event`` has
        already seen every delta the replica managed to send, which is
        exactly the partial-progress record recovery needs."""
        conn = http.client.HTTPConnection(self.host, self.port,
                                          timeout=timeout)
        try:
            payload = json.dumps(body)
            hdrs = {"Content-Type": "application/json"}
            if trace_id:
                hdrs["X-Autodist-Trace"] = trace_id
            try:
                conn.request("POST", "/v1/completions", payload, hdrs)
                resp = conn.getresponse()
            except http.client.HTTPException as e:
                raise OSError(f"stream setup failed: {e}") from e
            if resp.status != 200:
                raw = resp.read()
                try:
                    data = json.loads(raw) if raw else {}
                except ValueError:
                    data = {"raw": raw.decode(errors="replace")}
                if isinstance(data, dict):
                    data["_headers"] = dict(resp.getheaders())
                return resp.status, data
            final: Optional[dict] = None
            try:
                for line in resp:
                    line = line.strip()
                    if not line.startswith(b"data: "):
                        continue
                    try:
                        ev = json.loads(line[len(b"data: "):])
                    except ValueError as e:
                        raise OSError(f"garbled stream event: {e}") from e
                    if on_event is not None:
                        on_event(ev)
                    if ev.get("done") or ev.get("error"):
                        final = ev
                        break
            except http.client.HTTPException as e:
                raise OSError(f"stream read failed: {e}") from e
            if final is None:
                raise OSError("stream severed before the final event")
            return 200, final
        finally:
            conn.close()

    def cancel(self, request_id: int, timeout: float = 5.0) -> bool:
        status, data = self._request("POST", "/v1/cancel",
                                     {"id": int(request_id)},
                                     timeout=timeout)
        return status == 200 and bool(data.get("cancelled"))

    def stats(self, timeout: float = 5.0) -> dict:
        status, data = self._request("GET", "/v1/stats", timeout=timeout)
        if status != 200:
            raise OSError(f"stats answered {status}")
        return data

    def healthz(self, timeout: float = 2.0) -> bool:
        try:
            status, data = self._request("GET", "/healthz",
                                         timeout=timeout)
        except OSError:
            return False
        return status == 200 and bool(data.get("ok"))


@dataclass
class ReplicaEndpoint:
    """One replica as the router sees it: a (relaunch-stable) address
    file plus optional heartbeat beacons.  ``address_file`` holds
    ``{"host": ..., "port": ...}`` rewritten by every attempt; the
    endpoint re-reads it when its mtime changes, so a relaunched
    replica on a fresh port is picked up without router restarts."""

    name: str
    address_file: str
    beacon_dir: Optional[str] = None
    beacon_timeout: float = 10.0
    _client: Optional[HTTPReplicaClient] = field(default=None, repr=False)
    _mtime: float = field(default=0.0, repr=False)
    _monitor: Any = field(default=None, repr=False)

    def client(self) -> Optional[HTTPReplicaClient]:
        try:
            mtime = os.stat(self.address_file).st_mtime
        except OSError:
            return None
        if self._client is None or mtime != self._mtime:
            try:
                with open(self.address_file, encoding="utf-8") as f:
                    addr = json.load(f)
                self._client = HTTPReplicaClient(addr["host"],
                                                 addr["port"])
                self._mtime = mtime
            except (OSError, ValueError, KeyError):
                return None
        return self._client

    def beacon_verdict(self) -> Optional[str]:
        """DEAD/WEDGED verdict from the replica's heartbeat beacons
        (None = healthy or no beacons configured)."""
        if self.beacon_dir is None:
            return None
        if self._monitor is None:
            from autodist_tpu.resilience.heartbeat import HeartbeatMonitor

            self._monitor = HeartbeatMonitor(self.beacon_dir,
                                             timeout=self.beacon_timeout)
        from autodist_tpu.resilience.heartbeat import DEAD, WEDGED

        for health in self._monitor.status().values():
            if health.state in (DEAD, WEDGED):
                return health.state
        return None

    # -- the duck-typed surface Router consumes ------------------------
    def probe(self, timeout: float = 2.0) -> bool:
        if self.beacon_verdict() is not None:
            return False
        cli = self.client()
        return cli is not None and cli.healthz(timeout=timeout)

    def fetch_stats(self) -> Optional[dict]:
        cli = self.client()
        if cli is None:
            return None
        try:
            return cli.stats()
        except OSError:
            return None

    def post(self, body: dict, timeout: float,
             trace_id: str = "") -> Tuple[int, dict]:
        cli = self.client()
        if cli is None:
            raise OSError(f"{self.name}: no address published")
        return cli.post_completion(body, timeout=timeout,
                                   trace_id=trace_id)

    def post_stream(self, body: dict, timeout: float,
                    trace_id: str = "", on_event=None) -> Tuple[int, dict]:
        cli = self.client()
        if cli is None:
            raise OSError(f"{self.name}: no address published")
        return cli.post_completion_stream(body, timeout=timeout,
                                          trace_id=trace_id,
                                          on_event=on_event)

    def cancel(self, request_id: int) -> bool:
        cli = self.client()
        if cli is None:
            raise OSError(f"{self.name}: no address published")
        return cli.cancel(request_id)


class Router:
    """Load-balancing, re-routing front over a set of endpoints.

    ``endpoints`` need ``name``, ``probe()``, ``fetch_stats()`` and
    ``post(body, timeout)`` (raising ``OSError`` on transport failure)
    — :class:`ReplicaEndpoint` for real replicas, fakes in the unit
    tests.  Load scoring prefers shallow queues and block headroom::

        score = outstanding + queue_depth_total
                + occupancy_weight * block_occupancy
                + draft_occupancy_weight * block_occupancy_draft

    ``draft_occupancy_weight`` (default 0: no behavior change) lets a
    mixed fleet penalize replicas whose pool pressure comes from
    speculative draft pages — draft KV is evictable only by finishing
    its request, so a draft-heavy replica has less admission headroom
    than its raw occupancy suggests.

    Routing policy per request: try live replicas in score order; on
    transport failure or 5xx mark the replica down (it re-enters
    rotation when a later probe passes) and try the next; on 429
    remember the Retry-After hint and try the next; other 4xx raise
    :class:`RouterRequestError` without re-routing.

    ``recover`` (default on) turns greedy ``prompt_tokens`` requests
    into SSE streams against endpoints exposing ``post_stream``, so a
    replica death mid-decode resumes token-exactly on a survivor
    instead of restarting.  ``breaker_threshold`` / ``breaker_hold_s``
    parameterize the per-replica circuit breaker (0 disables it).
    ``hedge_after_s`` (None = off) arms first-wins hedging for
    latency-class stragglers."""

    def __init__(self, endpoints: Sequence[Any], *,
                 probe_ttl_s: float = 1.0, stats_ttl_s: float = 0.25,
                 occupancy_weight: float = 4.0,
                 draft_occupancy_weight: float = 0.0,
                 max_attempts: Optional[int] = None,
                 retry_wait_s: float = 0.25,
                 recover: bool = True,
                 breaker_threshold: int = 3,
                 breaker_hold_s: float = 5.0,
                 hedge_after_s: Optional[float] = None):
        if not endpoints:
            raise ValueError("Router needs at least one endpoint")
        self._eps = list(endpoints)
        self._probe_ttl = float(probe_ttl_s)
        self._stats_ttl = float(stats_ttl_s)
        self._occ_w = float(occupancy_weight)
        self._draft_occ_w = float(draft_occupancy_weight)
        self._max_attempts = (max_attempts if max_attempts is not None
                              else 2 * len(self._eps) + 2)
        self._retry_wait = float(retry_wait_s)
        self._recover = bool(recover)
        self._breaker_threshold = int(breaker_threshold)
        self._breaker_hold_s = float(breaker_hold_s)
        self._hedge_after = (None if hedge_after_s is None
                             else float(hedge_after_s))
        self._lock = threading.Lock()
        self._down_until: Dict[str, float] = {}
        self._probed: Dict[str, Tuple[float, bool]] = {}
        self._scores: Dict[str, Tuple[float, float]] = {}
        self._inflight: Dict[str, int] = {}
        self._draining_until: Dict[str, float] = {}
        self._fails: Dict[str, int] = {}
        self._breaker_until: Dict[str, float] = {}
        self._breaker_hold: Dict[str, float] = {}
        self.registry = MetricsRegistry()
        self._m_routed = {}
        self._m_reroutes = self.registry.counter(
            "autodist_router_reroutes_total",
            "requests re-routed after a replica failure")
        self._m_busy = self.registry.counter(
            "autodist_router_busy_rejects_total",
            "requests rejected because every live replica was at "
            "admission capacity")
        self._m_live = self.registry.gauge(
            "autodist_router_live_replicas",
            "replicas passing their latest health probe")
        self._m_recovered = self.registry.counter(
            "autodist_router_recovered_total",
            "requests resumed token-exactly on a survivor after a "
            "replica died mid-decode")
        self._m_recovered_tokens = self.registry.counter(
            "autodist_router_recovered_tokens_total",
            "streamed tokens carried over (not re-decoded) by "
            "in-flight recovery")
        self._m_hedged = self.registry.counter(
            "autodist_router_hedged_total",
            "requests mirrored to a second replica after hedge_after_s")
        self._m_hedge_wins = self.registry.counter(
            "autodist_router_hedge_wins_total",
            "hedged requests won by the secondary replica")
        self._m_breaker = self.registry.counter(
            "autodist_router_breaker_open_total",
            "circuit-breaker opens (consecutive-failure threshold hit)")

    # -- health / scoring --------------------------------------------------
    def _alive(self, ep) -> bool:
        now = time.monotonic()
        with self._lock:
            if self._down_until.get(ep.name, 0.0) > now \
                    or self._breaker_until.get(ep.name, 0.0) > now:
                return False
            ts, ok = self._probed.get(ep.name, (0.0, False))
            if now - ts < self._probe_ttl:
                return ok
        ok = bool(ep.probe())
        with self._lock:
            self._probed[ep.name] = (time.monotonic(), ok)
            if ok:
                self._down_until.pop(ep.name, None)
        return ok

    def mark_down(self, ep, hold_s: float = 2.0) -> None:
        with self._lock:
            self._down_until[ep.name] = time.monotonic() + hold_s
            self._probed.pop(ep.name, None)

    def _note_failure(self, ep) -> None:
        """One consecutive-failure tick toward the replica's circuit
        breaker.  At ``breaker_threshold`` the breaker opens for the
        current hold (doubling per re-open, capped at 60 s); the count
        is NOT reset on open, so the half-open probe after expiry
        re-opens on its first failure instead of needing a fresh run
        of ``threshold`` failures."""
        if self._breaker_threshold <= 0:
            return
        opened = 0.0
        with self._lock:
            n = self._fails.get(ep.name, 0) + 1
            self._fails[ep.name] = n
            if n >= self._breaker_threshold:
                hold = self._breaker_hold.get(ep.name,
                                              self._breaker_hold_s)
                self._breaker_until[ep.name] = time.monotonic() + hold
                self._breaker_hold[ep.name] = min(hold * 2.0, 60.0)
                opened = hold
        if opened:
            self._m_breaker.inc()
            logging.warning("router: circuit breaker OPEN for %s "
                            "(%.1fs hold)", ep.name, opened)

    def _note_success(self, ep) -> None:
        with self._lock:
            self._fails.pop(ep.name, None)
            self._breaker_hold.pop(ep.name, None)
            self._breaker_until.pop(ep.name, None)

    def breaker_open(self, ep) -> bool:
        with self._lock:
            return self._breaker_until.get(ep.name, 0.0) \
                > time.monotonic()

    def _is_draining(self, ep) -> bool:
        with self._lock:
            return self._draining_until.get(ep.name, 0.0) \
                > time.monotonic()

    def _set_draining(self, ep, hold_s: float) -> None:
        with self._lock:
            self._draining_until[ep.name] = \
                time.monotonic() + max(float(hold_s), 0.5)

    def _score(self, ep) -> float:
        now = time.monotonic()
        with self._lock:
            ts, score = self._scores.get(ep.name, (0.0, 0.0))
            inflight = self._inflight.get(ep.name, 0)
            if now - ts < self._stats_ttl:
                return score + inflight
        st = ep.fetch_stats() or {}
        if st.get("draining"):
            # The stats surface says the replica is leaving rotation:
            # remember it so the NEXT candidate pass skips it without
            # burning an attempt on a guaranteed 429.
            self._set_draining(ep, 1.0)
        score = float(st.get("outstanding", 0))
        score += float(st.get("queue_depth_total", 0))
        score += self._occ_w * float(st.get("block_occupancy", 0.0))
        score += self._draft_occ_w * float(
            st.get("block_occupancy_draft", 0.0))
        with self._lock:
            self._scores[ep.name] = (time.monotonic(), score)
            inflight = self._inflight.get(ep.name, 0)
        return score + inflight

    def live_replicas(self) -> List[Any]:
        live = [ep for ep in self._eps if self._alive(ep)]
        self._m_live.set(len(live))
        return live

    # -- routing -----------------------------------------------------------
    def complete(self, body: dict, *, timeout_s: float = 120.0) -> dict:
        """Route one completion; returns the replica's 200 payload.
        Blocks its caller like a replica-local request would — the
        caller's thread IS the in-flight state, which is what makes
        re-routing safe: a failed attempt leaves nothing behind on the
        dead replica that the retry could double-serve.  With
        ``recover`` on and a greedy ``prompt_tokens`` body, a replica
        death mid-decode resumes on a survivor: the partial tokens the
        dead replica streamed become part of the retry's prompt, and
        the stitched payload carries ``recovered``/``resumed_tokens``."""
        deadline = time.monotonic() + timeout_s
        t0_unix = time.time()
        # One trace id per logical request — re-routes reuse it, so the
        # exported trace shows every attempt under one id.
        trace_id = uuid.uuid4().hex[:16]
        tried_busy: Dict[str, float] = {}
        attempts = 0
        first = True
        want_stream = bool(body.get("stream"))
        # Token-exact recovery needs (a) the exact prompt ids the
        # engine will see (a text prompt re-tokenizes identically, but
        # splicing partials into text cannot be exact) and (b) greedy
        # decode (resuming a sampled request re-rolls the dice).
        prompt = body.get("prompt_tokens")
        recover_ok = (self._recover
                      and isinstance(prompt, list) and prompt
                      and all(type(t) is int for t in prompt)
                      and type(body.get("max_new_tokens", 16)) is int
                      and body.get("temperature") in (None, 0, 0.0))
        base_prompt = list(prompt) if recover_ok else []
        orig_max_new = int(body.get("max_new_tokens", 16)) \
            if recover_ok else 16
        resumed: List[int] = []     # tokens carried across dead replicas
        cur_body = dict(body)
        while attempts < self._max_attempts \
                and time.monotonic() < deadline:
            candidates = [ep for ep in self.live_replicas()
                          if ep.name not in tried_busy
                          and not self._is_draining(ep)]
            if not candidates and tried_busy:
                self._m_busy.inc()
                raise RouterBusy(
                    "every live replica is at admission capacity",
                    retry_after_s=max(tried_busy.values()))
            if not candidates:
                attempts += 1
                time.sleep(self._retry_wait)   # a relaunch may be coming
                continue
            candidates.sort(key=self._score)
            ep = candidates[0]
            attempts += 1
            if not first:
                self._m_reroutes.inc()
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise RouterDeadlineError(
                    f"deadline ({timeout_s:.1f}s) exceeded after "
                    f"{attempts - 1} attempt(s)")
            use_stream = recover_ok and hasattr(ep, "post_stream")
            hedge_here = (self._hedge_after is not None and first
                          and use_stream and len(candidates) >= 2
                          and hasattr(candidates[1], "post_stream")
                          and body.get("slo") in (None, "latency"))
            first = False
            partial: List[int] = []

            def on_event(ev, _partial=partial):
                if not ev.get("done") and ev.get("new_tokens"):
                    _partial.extend(int(t) for t in ev["new_tokens"])

            with self._lock:
                self._inflight[ep.name] = \
                    self._inflight.get(ep.name, 0) + 1
            try:
                if hedge_here:
                    status, payload, ep = self._hedged_post(
                        cur_body, candidates[0], candidates[1],
                        timeout=remaining, trace_id=trace_id)
                elif use_stream:
                    status, payload = self._post_stream(
                        ep, cur_body, timeout=remaining,
                        trace_id=trace_id, on_event=on_event)
                else:
                    try:
                        status, payload = ep.post(
                            cur_body, timeout=remaining,
                            trace_id=trace_id)
                    except TypeError:
                        # Duck-typed endpoints predating trace
                        # propagation (unit-test fakes, user endpoints)
                        # keep working; their replica spans are simply
                        # untagged.
                        status, payload = ep.post(cur_body,
                                                  timeout=remaining)
            except OSError as e:
                logging.warning("router: replica %s failed mid-request "
                                "(%s) — re-routing", ep.name, e)
                self.mark_down(ep)
                self._note_failure(ep)
                if partial:
                    resumed.extend(partial)
                    done = self._finish_locally(body, base_prompt,
                                                resumed, orig_max_new)
                    if done is not None:
                        return self._stitched(done, [], ep, trace_id,
                                              t0_unix, attempts,
                                              resumed, want_stream)
                    cur_body = dict(body)
                    cur_body["prompt_tokens"] = base_prompt + resumed
                    cur_body["max_new_tokens"] = \
                        orig_max_new - len(resumed)
                continue
            finally:
                with self._lock:
                    self._inflight[ep.name] = \
                        max(self._inflight.get(ep.name, 1) - 1, 0)
            if status == -1:
                # Hedged request: both legs died transport-side.
                self.mark_down(ep)
                self._note_failure(ep)
                continue
            if status == 200:
                self._note_success(ep)
                return self._stitched(payload, resumed, ep, trace_id,
                                      t0_unix, attempts, resumed,
                                      want_stream)
            if status == 429:
                retry = _retry_after(payload)
                if payload.get("draining"):
                    # Healthy replica leaving rotation: skip it for a
                    # while, but neither mark it down nor let it count
                    # toward the all-busy verdict.
                    self._set_draining(ep, retry)
                    continue
                tried_busy[ep.name] = retry
                continue
            if status == 503 and payload.get("shed"):
                # Deadline shed is load signal, not ill health: another
                # replica may have the headroom this one lacks.
                tried_busy[ep.name] = _retry_after(payload)
                continue
            if 500 <= status < 600:
                logging.warning("router: replica %s answered %d — "
                                "re-routing", ep.name, status)
                self.mark_down(ep)
                self._note_failure(ep)
                continue
            raise RouterRequestError(status, payload)
        if time.monotonic() >= deadline:
            raise RouterDeadlineError(
                f"deadline ({timeout_s:.1f}s) exceeded after "
                f"{attempts} attempt(s)")
        raise RouterError(
            f"no live replica served the request after {attempts} "
            f"attempt(s)")

    # -- recovery / hedging helpers ---------------------------------------
    def _post_stream(self, ep, body: dict, *, timeout: float,
                     trace_id: str, on_event=None) -> Tuple[int, dict]:
        """Streaming post with the final SSE event mapped back onto the
        status codes ``complete`` already routes on (timeout/deadline →
        504, cancelled → 409, engine error → 503)."""
        sb = dict(body)
        sb["stream"] = True
        status, final = ep.post_stream(sb, timeout=timeout,
                                       trace_id=trace_id,
                                       on_event=on_event)
        if status != 200:
            return status, final
        if final.get("timeout") or final.get("deadline_exceeded"):
            return 504, final
        if final.get("cancelled"):
            return 409, final
        if final.get("error"):
            return 503, final
        return 200, final

    def _finish_locally(self, body: dict, base_prompt: List[int],
                        resumed: List[int],
                        orig_max_new: int) -> Optional[dict]:
        """The dead replica already streamed everything the request
        asked for (eos reached, or max_new_tokens exhausted): finish
        without a resubmit.  Returns None when decoding must continue
        on a survivor."""
        eos_id = body.get("eos_id")
        if eos_id is not None and int(eos_id) in resumed:
            del resumed[resumed.index(int(eos_id)) + 1:]
        elif len(resumed) < orig_max_new:
            return None
        return {"id": -1,
                "tokens": list(base_prompt) + list(resumed),
                "new_tokens": list(resumed)}

    def _stitched(self, payload: dict, prefix: List[int], ep,
                  trace_id: str, t0_unix: float, attempts: int,
                  resumed: List[int], want_stream: bool) -> dict:
        """Final bookkeeping for a served request: splice recovered
        tokens back in front of the survivor's continuation, stamp the
        evidence fields, count, and span."""
        if prefix:
            payload["new_tokens"] = \
                list(prefix) + list(payload.get("new_tokens") or [])
        if resumed:
            payload["recovered"] = True
            payload["resumed_tokens"] = len(resumed)
            self._m_recovered.inc()
            self._m_recovered_tokens.inc(len(resumed))
            from autodist_tpu.telemetry import emit_event
            emit_event("serving/recovered", trace_id=trace_id,
                       replica=ep.name, resumed_tokens=len(resumed),
                       attempts=attempts)
        if not want_stream:
            payload.pop("done", None)
        self._routed_counter(ep).inc()
        from autodist_tpu.telemetry.profiler import record_span
        record_span("route", start_unix=t0_unix,
                    dur_s=time.time() - t0_unix,
                    trace_id=trace_id, replica=ep.name,
                    attempts=attempts)
        if resumed:
            record_span("recover", start_unix=t0_unix,
                        dur_s=time.time() - t0_unix,
                        trace_id=trace_id, replica=ep.name,
                        resumed_tokens=len(resumed))
        return payload

    def _hedged_post(self, body: dict, primary, secondary, *,
                     timeout: float,
                     trace_id: str) -> Tuple[int, dict, Any]:
        """First-wins hedging: run the primary, and if it has not
        answered within ``hedge_after_s`` mirror the request to the
        secondary.  The first leg to return 200 wins; the loser is
        cancelled through the replica's cancel API using the request
        id from its stream's announce event.  Returns ``(status,
        payload, winner_ep)``; a transport failure on both legs comes
        back as status ``-1``.  Hedge legs do not splice partials —
        a failed hedge falls back to ``complete``'s standard retry
        path, where recovery applies."""
        cond = threading.Condition()
        outcome: List[Tuple[str, int, dict, Any]] = []
        rids: Dict[str, int] = {}
        deadline = time.monotonic() + timeout

        def leg(ep, tag):
            def on_event(ev):
                rid = ev.get("id")
                if isinstance(rid, int) and tag not in rids:
                    rids[tag] = rid
            try:
                status, payload = self._post_stream(
                    ep, body,
                    timeout=max(deadline - time.monotonic(), 0.1),
                    trace_id=trace_id, on_event=on_event)
            except OSError as e:
                status, payload = -1, {"error": str(e)}
            with cond:
                outcome.append((tag, status, payload, ep))
                cond.notify_all()

        threading.Thread(target=leg, args=(primary, "p"),
                         daemon=True,
                         name="router-hedge-primary").start()
        with cond:
            cond.wait_for(lambda: outcome, timeout=self._hedge_after)
            hedged = not outcome
        if hedged:
            self._m_hedged.inc()
            from autodist_tpu.telemetry import emit_event
            emit_event("serving/hedge", trace_id=trace_id,
                       primary=primary.name, secondary=secondary.name,
                       after_s=self._hedge_after)
            threading.Thread(target=leg, args=(secondary, "s"),
                             daemon=True,
                             name="router-hedge-secondary").start()
        legs = 2 if hedged else 1

        def settled():
            return (any(s == 200 for _, s, _, _ in outcome)
                    or len(outcome) >= legs)

        with cond:
            cond.wait_for(settled,
                          timeout=max(deadline - time.monotonic(), 0.1))
            snapshot = list(outcome)
        win = next(((t, s, p, e) for t, s, p, e in snapshot
                    if s == 200), None)
        if win is not None:
            tag, status, payload, ep = win
            if hedged:
                loser_tag = "s" if tag == "p" else "p"
                loser_ep = secondary if tag == "p" else primary
                lrid = rids.get(loser_tag)
                if lrid is not None and hasattr(loser_ep, "cancel"):
                    try:
                        loser_ep.cancel(lrid)
                    except (OSError, TypeError):
                        pass
                if tag == "s":
                    self._m_hedge_wins.inc()
            return status, payload, ep
        for tag, status, payload, ep in snapshot:
            if tag == "p":
                return status, payload, ep
        if snapshot:
            tag, status, payload, ep = snapshot[0]
            return status, payload, ep
        raise OSError("hedged request produced no outcome in time")

    def _routed_counter(self, ep):
        c = self._m_routed.get(ep.name)
        if c is None:
            c = self.registry.counter(
                "autodist_router_requests_total",
                "completions served, by replica",
                labels={"replica": ep.name})
            self._m_routed[ep.name] = c
        return c

    def render_metrics(self) -> str:
        return render_prometheus(self.registry)

    def merged_replica_stats(self) -> Dict[str, Any]:
        """Per-replica ``/v1/stats`` snapshots keyed by name (the
        fleet-level observability roll-up; histograms merge exactly on
        the replicas' fixed bounds — docs/observability.md)."""
        return {ep.name: ep.fetch_stats() for ep in self._eps}


def _retry_after(payload: dict) -> float:
    headers = payload.get("_headers") or {}
    for k, v in headers.items():
        if k.lower() == "retry-after":
            try:
                return float(v)
            except ValueError:
                break
    return float(payload.get("retry_after_s", 1.0))


# ---------------------------------------------------------------------------
# supervised replica pool
# ---------------------------------------------------------------------------

class SupervisedReplicaPool:
    """N serving replicas, each under its own PR 4 Supervisor.

    ``launch(replica_index, attempt)`` starts one replica attempt and
    returns its ``subprocess.Popen`` (launched with
    ``start_new_session=True`` so straggler process groups die with
    it).  The replica must write ``{"host":..., "port":...}`` to
    ``address_file(replica_index)`` once it listens, and should write
    heartbeat beacons into ``attempt.heartbeat_dir`` — the supervisor
    then applies the training-side failure taxonomy: process exit,
    stale-beacon DEAD, fresh-beacon-no-progress WEDGED.

    A healthy serving replica never exits, so each supervisor's
    ``run()`` blocks in its watch loop for the pool's lifetime — each
    runs on a daemon thread.  ``stop()`` flips a flag that makes the
    next relaunch a no-op process exiting 0 (a clean completion ends
    the supervisor loop), then terminates the current replicas."""

    def __init__(self, n: int, launch, workdir: str, *,
                 policy=None):
        from autodist_tpu.resilience.supervisor import SupervisorPolicy

        if n < 1:
            raise ValueError("need n >= 1 replicas")
        self._n = n
        self._launch = launch
        self._workdir = workdir
        self._policy = policy or SupervisorPolicy(
            max_restarts=8, heartbeat_timeout=10.0, poll_interval=0.2)
        self._stopping = False
        self._threads: List[threading.Thread] = []
        self._procs: Dict[int, Any] = {}
        self._supervisors: List[Any] = []
        os.makedirs(workdir, exist_ok=True)

    def address_file(self, index: int) -> str:
        return os.path.join(self._workdir, f"replica_{index}.addr.json")

    def beacon_dir(self, index: int) -> str:
        return os.path.join(self._workdir, f"replica_{index}_hb")

    def endpoints(self) -> List[ReplicaEndpoint]:
        return [ReplicaEndpoint(
                    name=f"replica-{i}",
                    address_file=self.address_file(i),
                    beacon_dir=self.beacon_dir(i),
                    beacon_timeout=(self._policy.heartbeat_timeout
                                    or 10.0))
                for i in range(self._n)]

    def current_proc(self, index: int):
        """The replica's current attempt process (for drills that kill
        it)."""
        return self._procs.get(index)

    def start(self) -> "SupervisedReplicaPool":
        from autodist_tpu.resilience.supervisor import Supervisor

        for i in range(self._n):
            sup = Supervisor(
                self._policy, hosts=[f"replica-{i}"],
                workdir=os.path.join(self._workdir, f"sup_{i}"))
            self._supervisors.append(sup)

            def run(i=i, sup=sup):
                def launch_attempt(attempt):
                    if self._stopping:
                        import subprocess
                        import sys
                        return subprocess.Popen(
                            [sys.executable, "-c", "pass"])
                    # beacons live at a pool-stable path (the router's
                    # monitors watch one directory per replica, across
                    # attempts)
                    attempt.heartbeat_dir = self.beacon_dir(i)
                    os.makedirs(attempt.heartbeat_dir, exist_ok=True)
                    # Drop beacons left by the previous attempt: the
                    # monitor judges staleness by file mtime, so a dead
                    # attempt's beacon would damn the fresh one before
                    # it finishes starting up (no-beacon-yet gets the
                    # grace window; a stale beacon gets none).
                    from autodist_tpu.resilience.heartbeat import \
                        BEAT_SUFFIX
                    try:
                        for fn in os.listdir(attempt.heartbeat_dir):
                            if fn.endswith(BEAT_SUFFIX):
                                with contextlib.suppress(OSError):
                                    os.unlink(os.path.join(
                                        attempt.heartbeat_dir, fn))
                    except OSError:
                        pass
                    proc = self._launch(i, attempt)
                    self._procs[i] = proc
                    return proc

                report = sup.run(launch_attempt)
                if not report.ok and not self._stopping:
                    logging.error(
                        "replica pool: replica %d exhausted its restart "
                        "budget (%s)", i, report.gave_up)

            t = threading.Thread(target=run, daemon=True,
                                 name=f"replica-supervisor-{i}")
            t.start()
            self._threads.append(t)
        return self

    def rolling_restart(self, *, drain_timeout_s: float = 30.0,
                        relaunch_timeout_s: float = 60.0) -> Dict[str, Any]:
        """Cycle every replica with zero failed requests: drain →
        wait-idle → SIGTERM → supervised relaunch → healthy, one
        replica at a time (the rest of the pool keeps serving).

        ``POST /admin/drain`` takes the replica out of admission (the
        router skips it on the draining flag); once ``/v1/stats``
        reports no outstanding work, SIGTERM fires the replica's drain
        handler, which exits with ``PREEMPTED_EXIT_CODE`` — the
        supervisor relaunches WITHOUT consuming restart budget.  The
        method then waits for the fresh attempt to publish an address
        and pass a health probe before moving on.  Returns a summary
        ``{"restarted": [...], "failed": [...]}``."""
        import signal

        from autodist_tpu.telemetry import emit_event

        summary: Dict[str, Any] = {"restarted": [], "failed": []}
        grace = float(getattr(self._policy, "kill_grace", None) or 3.0)
        for i in range(self._n):
            ep = ReplicaEndpoint(name=f"replica-{i}",
                                 address_file=self.address_file(i))
            old = self.current_proc(i)
            emit_event("serving/drain", phase="rolling", replica=i)
            cli = ep.client()
            drained = False
            if cli is not None:
                try:
                    cli._request("POST", "/admin/drain", {},
                                 timeout=5.0)
                except OSError:
                    pass   # already dead — the SIGTERM path handles it
                t_drain = time.monotonic() + drain_timeout_s
                while time.monotonic() < t_drain:
                    try:
                        st = cli.stats()
                    except OSError:
                        break
                    if int(st.get("outstanding", 0)) == 0:
                        drained = True
                        break
                    time.sleep(0.1)
            if old is not None and old.poll() is None:
                try:
                    os.killpg(os.getpgid(old.pid), signal.SIGTERM)
                except (ProcessLookupError, PermissionError, OSError):
                    old.terminate()
                t_kill = time.monotonic() + grace + drain_timeout_s
                while old.poll() is None \
                        and time.monotonic() < t_kill:
                    time.sleep(0.05)
                if old.poll() is None:
                    old.kill()
            ok = False
            t_up = time.monotonic() + relaunch_timeout_s
            while time.monotonic() < t_up:
                proc = self.current_proc(i)
                if proc is not None and proc is not old \
                        and proc.poll() is None and ep.probe():
                    ok = True
                    break
                time.sleep(0.1)
            (summary["restarted"] if ok
             else summary["failed"]).append(
                {"replica": i, "drained": drained})
            if not ok:
                logging.error("rolling restart: replica %d did not "
                              "come back healthy", i)
        return summary

    def stop(self, timeout: float = 20.0) -> None:
        import signal

        self._stopping = True
        for proc in self._procs.values():
            if proc is not None and proc.poll() is None:
                try:
                    os.killpg(os.getpgid(proc.pid), signal.SIGTERM)
                except (ProcessLookupError, PermissionError, OSError):
                    proc.terminate()
        deadline = time.monotonic() + timeout
        for t in self._threads:
            t.join(timeout=max(deadline - time.monotonic(), 0.1))

    def __enter__(self) -> "SupervisedReplicaPool":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()
